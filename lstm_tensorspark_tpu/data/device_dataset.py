"""Device-resident LM dataset: corpus staged in HBM, windows sliced on-device.

Reference parity + the TPU-native upgrade: Spark caches the RDD in executor
memory, so per-round the reference moves only params/grads — the *data* stays
resident with the workers (SURVEY.md §3.1). The host-fed JAX path regressed
that: every K-step dispatch shipped [K, B, T] token windows over PCIe/tunnel,
which measures as ~13x the step's actual compute time on this environment's
tunneled chip. This module restores the reference's data-locality property
the TPU way:

- the contiguous per-row token streams (`data.batching.lm_windows` layout:
  [B, n_windows*T] inputs + shifted targets) are `device_put` ONCE;
- the train step takes a scalar window index and `lax.dynamic_slice`s the
  [B, T] batch inside the jitted program (one slice per step of the K-step
  scan) — per-dispatch host traffic is one int32 scalar;
- under data parallelism the streams shard over the "data" mesh axis with
  `P("data", None)` — each chip holds only its batch rows, exactly like a
  Spark partition's cached shard; slicing is along time, so no collective
  is ever needed for the feed.

Stream order is identical to `lm_epoch_batches`, so stateful TBPTT carries
stay aligned and host-fed vs device-resident runs are bit-identical
(tests/test_device_data.py).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .batching import lm_windows


@dataclasses.dataclass(frozen=True)
class DeviceLMData:
    """HBM-staged LM corpus + static window geometry.

    ``arrays`` is a pytree of device arrays passed explicitly through jit
    (never closed over: closure constants can be baked into the executable,
    which would duplicate a large corpus into every compiled program).
    """

    arrays: dict  # {"streams": [B, n_windows*T], "shifted": same} int32
    batch_size: int
    seq_len: int
    n_windows: int

    @property
    def tokens_per_window(self) -> int:
        return self.batch_size * self.seq_len


def _placer(mesh: Mesh | None, spec: P | None = None):
    """One device_put closure for every stager: ``spec`` placement on the
    mesh (replicated when spec is None/P()), default device otherwise."""
    if mesh is None:
        return lambda a: jax.device_put(np.ascontiguousarray(a))
    sharding = NamedSharding(mesh, spec if spec is not None else P())
    return lambda a: jax.device_put(np.ascontiguousarray(a), sharding)


def stage_lm_data(
    tokens: np.ndarray,
    batch_size: int,
    seq_len: int,
    *,
    mesh: Mesh | None = None,
    axis: str = "data",
) -> DeviceLMData:
    """Build the [B, n_windows*T] streams host-side (pure reshape) and place
    them on device — batch rows sharded over ``axis`` when a mesh is given,
    single default device otherwise."""
    streams, shifted, n_windows = lm_windows(tokens, batch_size, seq_len)
    put = _placer(mesh, P(axis, None))
    return DeviceLMData(
        arrays={"streams": put(streams), "shifted": put(shifted)},
        batch_size=batch_size,
        seq_len=seq_len,
        n_windows=n_windows,
    )


def slice_window(arrays: dict, w: jax.Array, seq_len: int) -> dict:
    """Traced: window index (scalar int32) → {"inputs","targets"} [B, T]."""
    s = w * seq_len
    return {
        "inputs": lax.dynamic_slice_in_dim(arrays["streams"], s, seq_len, axis=1),
        "targets": lax.dynamic_slice_in_dim(arrays["shifted"], s, seq_len, axis=1),
    }


def window_index_stream(data: DeviceLMData, steps_per_call: int,
                        *, start_step: int = 0):
    """Host-side iterator of starting window indices, one per K-step dispatch
    (the entire per-call feed). Wraps around epochs forever, matching
    `lm_batch_stream`'s ordering. ``start_step`` fast-forwards to the window
    a resumed run would be at (data-exact resume)."""
    w = start_step % data.n_windows
    while True:
        yield np.int32(w)
        w = (w + steps_per_call) % data.n_windows


def stage_stacked_batches(batches, *, mesh: Mesh | None = None) -> dict:
    """Stack an iterator of equal-shape host batch dicts into ONE
    [n_batches, ...] pytree placed on device (replicated under a mesh) —
    the staging step for fused in-executable eval (train/device_step.py):
    the traced eval scans the leading axis, so the batches must be the
    EXACT ones the host eval loop would see."""
    ev_list = list(batches)
    if not ev_list:
        raise ValueError("stage_stacked_batches: empty batch iterator")
    put = _placer(mesh)
    return {k: put(np.stack([b[k] for b in ev_list])) for k in ev_list[0]}


# ---- generic per-example staging (classification: BASELINE.md config 2) ----


@dataclasses.dataclass(frozen=True)
class DeviceExamples:
    """HBM-staged fixed-shape example arrays ([N, ...] per key), batched
    on-device by row gather. Arrays are placed REPLICATED (every shard can
    gather any row); per-dispatch host traffic is the [K, B] index array."""

    arrays: dict
    num_examples: int


def stage_examples(host_arrays: dict, *, mesh: Mesh | None = None) -> DeviceExamples:
    n = next(iter(host_arrays.values())).shape[0]
    for k, a in host_arrays.items():
        if a.shape[0] != n:
            raise ValueError(
                f"leading dims differ: {k} has {a.shape[0]} rows, expected {n}"
            )
    put = _placer(mesh)
    return DeviceExamples(
        arrays={k: put(a) for k, a in host_arrays.items()}, num_examples=n
    )


def take_batch(arrays: dict, idx: jax.Array) -> dict:
    """Traced: row indices [B] → batch {key: [B, ...]}."""
    return {k: jnp.take(a, idx, axis=0) for k, a in arrays.items()}


# ---- series staging (forecasting: BASELINE.md config 4) ----


@dataclasses.dataclass(frozen=True)
class DeviceSeries:
    """HBM-staged [N, F] time series; (context, horizon) windows are sliced
    on-device from per-example start indices."""

    arrays: dict  # {"series": [N, F]}
    context_len: int
    horizon: int
    num_windows: int


def stage_series(
    series: np.ndarray, context_len: int, horizon: int,
    *, mesh: Mesh | None = None,
) -> DeviceSeries:
    n_windows = len(series) - context_len - horizon + 1
    if n_windows < 1:
        raise ValueError(
            f"series length {len(series)} < context {context_len} + horizon {horizon}"
        )
    put = _placer(mesh)
    return DeviceSeries(
        arrays={"series": put(series.astype(np.float32))},
        context_len=context_len,
        horizon=horizon,
        num_windows=n_windows,
    )


def slice_forecast_batch(
    arrays: dict, starts: jax.Array, context_len: int, horizon: int
) -> dict:
    """Traced: window starts [B] → {"context" [B,C,F], "targets" [B,H,F],
    "valid" [B]} — the exact layout of `batching.forecast_windows`."""
    series = arrays["series"]
    F = series.shape[-1]

    def one(s):
        ctx = lax.dynamic_slice(series, (s, 0), (context_len, F))
        tgt = lax.dynamic_slice(series, (s + context_len, 0), (horizon, F))
        return ctx, tgt

    ctx, tgt = jax.vmap(one)(starts)
    return {
        "context": ctx,
        "targets": tgt,
        "valid": jnp.ones(starts.shape[0], bool),
    }
