"""Batching: token streams → fixed [B, T] windows; padded/bucketed batches.

Reference parity: SURVEY.md §2 "Data pipeline" — the reference partitions an
RDD of (seq, label) pairs; each worker iterates its shard. Here batching is
host-side numpy producing static-shape arrays (XLA requirement), and the
device dimension is added by the parallel backend, not the data layer.

LM batching is the standard contiguous scheme: the token stream is split into
``batch_size`` parallel streams so that window t's final recurrent state can
seed window t+1 (stateful truncated BPTT — opt-in via the training loop's
``stateful`` mode / the CLI ``--stateful`` flag) — the reference's
fixed-unroll truncated-BPTT equivalent (SURVEY.md §5 "Long-context" row).
"""

from __future__ import annotations

from typing import Iterator

import numpy as np


def lm_windows(tokens: np.ndarray, batch_size: int, seq_len: int):
    """Arrange a token stream [N] into contiguous per-row streams.

    Returns ``(streams, shifted, n_windows)``: ``streams`` [B, n_windows*T]
    holds the inputs, ``shifted`` the same array offset by one token (the
    targets), so window w slices columns [w*T, (w+1)*T) of both."""
    n_windows = (len(tokens) - 1) // (batch_size * seq_len)
    if n_windows < 1:
        raise ValueError(
            f"corpus too small: {len(tokens)} tokens for B={batch_size} T={seq_len}"
        )
    usable = n_windows * batch_size * seq_len
    streams = tokens[:usable].reshape(batch_size, n_windows * seq_len)
    # targets need one extra token per stream: shift within the stream and
    # borrow the next token for the last position
    extra = tokens[1 : usable + 1].reshape(batch_size, n_windows * seq_len)
    return streams, extra, n_windows


def lm_epoch_batches(
    tokens: np.ndarray, batch_size: int, seq_len: int
) -> Iterator[dict]:
    """One epoch of contiguous LM windows: {"inputs","targets"} each [B,T]."""
    streams, shifted, n_windows = lm_windows(tokens, batch_size, seq_len)
    for w in range(n_windows):
        s = w * seq_len
        yield {
            "inputs": streams[:, s : s + seq_len],
            "targets": shifted[:, s : s + seq_len],
        }


def lm_batch_stream(
    tokens: np.ndarray,
    batch_size: int,
    seq_len: int,
    *,
    num_epochs: int | None = None,
) -> Iterator[dict]:
    """Repeat epochs (forever if num_epochs is None)."""
    epoch = 0
    while num_epochs is None or epoch < num_epochs:
        yield from lm_epoch_batches(tokens, batch_size, seq_len)
        epoch += 1


def stacked_batches(batches: Iterator[dict], k: int) -> Iterator[dict]:
    """Group k consecutive batches into one [k, ...]-leading pytree — the
    host-side feed for the K-steps-per-dispatch train step
    (train/multistep.py). Order is preserved, so contiguous LM streams stay
    contiguous across the stack (stateful TBPTT keeps working). A trailing
    group smaller than k is dropped (it would force a second XLA
    compilation for one partial call)."""
    group: list[dict] = []
    for b in batches:
        group.append(b)
        if len(group) == k:
            yield {key: np.stack([g[key] for g in group]) for key in group[0]}
            group = []


def padded_batches(
    sequences: list[np.ndarray],
    labels: np.ndarray,
    batch_size: int,
    max_len: int,
    *,
    bucket: bool = True,
    shuffle_seed: int | None = None,
    drop_remainder: bool = True,
) -> Iterator[dict]:
    """Variable-length classification batches: pad to max_len, emit lengths.

    ``bucket=True`` sorts by length first so co-batched sequences have similar
    lengths (minimal padding waste — SURVEY.md §7 "padding waste vs
    recompilation tradeoff": one static shape, bucketing only reorders).
    Yields {"tokens" [B,L], "lengths" [B], "labels" [B], "valid" [B]}.
    With ``drop_remainder=False`` the last short batch is padded with
    all-zero filler rows marked ``valid=False`` (lengths 0) so metric
    consumers can weight rows instead of double-counting examples.
    """
    order = np.arange(len(sequences))
    if shuffle_seed is not None:
        np.random.RandomState(shuffle_seed).shuffle(order)
    if bucket:
        order = order[np.argsort([len(sequences[i]) for i in order], kind="stable")]
    for start in range(0, len(order), batch_size):
        idx = order[start : start + batch_size]
        if len(idx) < batch_size and drop_remainder:
            break
        toks = np.zeros((batch_size, max_len), np.int32)
        lens = np.zeros((batch_size,), np.int32)
        labs = np.zeros((batch_size,), np.int32)
        valid = np.zeros((batch_size,), bool)
        for row, i in enumerate(idx):
            seq = sequences[i][:max_len]
            toks[row, : len(seq)] = seq
            lens[row] = len(seq)
            labs[row] = labels[i]
            valid[row] = True
        yield {"tokens": toks, "lengths": lens, "labels": labs, "valid": valid}


def forecast_windows(
    series: np.ndarray,
    context_len: int,
    horizon: int,
    batch_size: int,
    *,
    shuffle_seed: int | None = None,
    drop_remainder: bool = True,
) -> Iterator[dict]:
    """Slide (context, horizon) windows over a [N, F] series and batch them.

    Yields {"context" [B, context_len, F], "targets" [B, horizon, F],
    "valid" [B]}. With ``drop_remainder=False`` the last short batch keeps
    the static shape by repeating its final window as filler, marked
    ``valid=False`` — weight metrics by ``valid``; no window is ever
    double-counted as valid.
    """
    N = len(series)
    starts = np.arange(0, N - context_len - horizon + 1)
    if len(starts) == 0:
        raise ValueError(
            f"series length {N} < context {context_len} + horizon {horizon}"
        )
    if shuffle_seed is not None:
        np.random.RandomState(shuffle_seed).shuffle(starts)
    for b0 in range(0, len(starts), batch_size):
        idx = starts[b0 : b0 + batch_size]
        valid = np.ones((batch_size,), bool)
        if len(idx) < batch_size:
            if drop_remainder:
                break
            valid[len(idx):] = False
            idx = np.concatenate(
                [idx, np.repeat(idx[-1:], batch_size - len(idx))]
            )
        ctx = np.stack([series[i : i + context_len] for i in idx])
        tgt = np.stack(
            [series[i + context_len : i + context_len + horizon] for i in idx]
        )
        yield {
            "context": ctx.astype(np.float32),
            "targets": tgt.astype(np.float32),
            "valid": valid,
        }
