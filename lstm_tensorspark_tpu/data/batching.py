"""Batching: token streams → fixed [B, T] windows; padded/bucketed batches.

Reference parity: SURVEY.md §2 "Data pipeline" — the reference partitions an
RDD of (seq, label) pairs; each worker iterates its shard. Here batching is
host-side numpy producing static-shape arrays (XLA requirement), and the
device dimension is added by the parallel backend, not the data layer.

LM batching is the standard contiguous scheme: the token stream is split into
``batch_size`` parallel streams so that window t's final recurrent state can
seed window t+1 (stateful truncated BPTT — opt-in via the training loop's
``stateful`` mode / the CLI ``--stateful`` flag) — the reference's
fixed-unroll truncated-BPTT equivalent (SURVEY.md §5 "Long-context" row).
"""

from __future__ import annotations

import itertools
from typing import Iterator

import numpy as np


def epoch_stream(epoch_fn, *, steps_per_epoch: int, start_step: int = 0):
    """Endless epochs of ``epoch_fn(epoch)`` batches with data-exact resume:
    the epoch index (and therefore any per-epoch shuffle seed inside
    ``epoch_fn``) and the in-epoch offset follow ``start_step`` — shared by
    the classifier and forecaster task runners."""
    epoch, skip = divmod(start_step, steps_per_epoch) if start_step else (0, 0)
    while True:
        it = epoch_fn(epoch)
        if skip:
            it = itertools.islice(it, skip, None)
            skip = 0
        yield from it
        epoch += 1


def cap_batches(batches, n: int | None):
    """First ``n`` batches when set (the --eval-batches cost bound), else
    the full stream."""
    return itertools.islice(batches, n) if n else batches


def lm_windows(tokens: np.ndarray, batch_size: int, seq_len: int):
    """Arrange a token stream [N] into contiguous per-row streams.

    Returns ``(streams, shifted, n_windows)``: ``streams`` [B, n_windows*T]
    holds the inputs, ``shifted`` the same array offset by one token (the
    targets), so window w slices columns [w*T, (w+1)*T) of both."""
    n_windows = (len(tokens) - 1) // (batch_size * seq_len)
    if n_windows < 1:
        raise ValueError(
            f"corpus too small: {len(tokens)} tokens for B={batch_size} T={seq_len}"
        )
    usable = n_windows * batch_size * seq_len
    streams = tokens[:usable].reshape(batch_size, n_windows * seq_len)
    # targets need one extra token per stream: shift within the stream and
    # borrow the next token for the last position
    extra = tokens[1 : usable + 1].reshape(batch_size, n_windows * seq_len)
    return streams, extra, n_windows


def lm_epoch_batches(
    tokens: np.ndarray, batch_size: int, seq_len: int
) -> Iterator[dict]:
    """One epoch of contiguous LM windows: {"inputs","targets"} each [B,T]."""
    streams, shifted, n_windows = lm_windows(tokens, batch_size, seq_len)
    for w in range(n_windows):
        s = w * seq_len
        yield {
            "inputs": streams[:, s : s + seq_len],
            "targets": shifted[:, s : s + seq_len],
        }


def lm_batch_stream(
    tokens: np.ndarray,
    batch_size: int,
    seq_len: int,
    *,
    num_epochs: int | None = None,
    start_step: int = 0,
) -> Iterator[dict]:
    """Repeat epochs (forever if num_epochs is None).

    ``start_step`` fast-forwards the stream to the window a resumed run
    would be at (data-exact resume: each optimizer step consumes one
    window; epochs are identical — no shuffle — so only the in-epoch
    offset matters, and skipped epochs still count toward ``num_epochs``).
    """
    epoch, skip = 0, 0
    if start_step:
        _, _, n_windows = lm_windows(tokens, batch_size, seq_len)
        epoch, skip = divmod(start_step, n_windows)
    while num_epochs is None or epoch < num_epochs:
        it = lm_epoch_batches(tokens, batch_size, seq_len)
        if skip:
            it = itertools.islice(it, skip, None)
            skip = 0
        yield from it
        epoch += 1


def stacked_batches(batches: Iterator[dict], k: int) -> Iterator[dict]:
    """Group k consecutive batches into one [k, ...]-leading pytree — the
    host-side feed for the K-steps-per-dispatch train step
    (train/multistep.py). Order is preserved, so contiguous LM streams stay
    contiguous across the stack (stateful TBPTT keeps working). A trailing
    group smaller than k is dropped (it would force a second XLA
    compilation for one partial call)."""
    group: list[dict] = []
    for b in batches:
        group.append(b)
        if len(group) == k:
            yield {key: np.stack([g[key] for g in group]) for key in group[0]}
            group = []


def example_order(
    lengths: list[int],
    *,
    shuffle_seed: int | None = None,
    bucket: bool = True,
) -> np.ndarray:
    """THE example ordering (shuffle, then stable length-bucket sort) shared
    by the host-fed `padded_batches` and the device-resident gather path
    (tasks/classification.py) — one source so the two can never diverge."""
    order = np.arange(len(lengths))
    if shuffle_seed is not None:
        np.random.RandomState(shuffle_seed).shuffle(order)
    if bucket:
        order = order[np.argsort([lengths[i] for i in order], kind="stable")]
    return order


def forecast_starts(
    n_windows: int, *, shuffle_seed: int | None = None
) -> np.ndarray:
    """THE forecast window-start ordering shared by `forecast_windows` and
    the device-resident series path (tasks/forecasting.py)."""
    starts = np.arange(0, n_windows)
    if shuffle_seed is not None:
        np.random.RandomState(shuffle_seed).shuffle(starts)
    return starts


def index_groups(order_fn, batch_size: int, steps_per_call: int,
                 *, start_step: int = 0) -> Iterator[np.ndarray]:
    """Epochs of index batches packed into [K, B] dispatch groups — the
    index-stream sibling of `stacked_batches`. ``order_fn(epoch)`` returns
    that epoch's 1-D index order; full batches only (host-path parity),
    partial K-groups carry over into the next epoch.

    ``start_step`` fast-forwards to the batch a resumed run would be at:
    the epoch index advances (so ``order_fn``'s per-epoch shuffle seed
    matches the uninterrupted run) and the in-epoch batches already
    consumed are skipped — data-exact resume."""
    epoch, group, skip = 0, [], 0
    if start_step:
        per_epoch = max(len(order_fn(0)) // batch_size, 0)
        if per_epoch:
            epoch, skip = divmod(start_step, per_epoch)
    while True:
        order = order_fn(epoch)
        for b0 in range(skip * batch_size, len(order) - batch_size + 1,
                        batch_size):
            group.append(order[b0 : b0 + batch_size].astype(np.int32))
            if len(group) == steps_per_call:
                yield np.stack(group)
                group = []
        skip = 0
        epoch += 1


def padded_batches(
    sequences: list[np.ndarray],
    labels: np.ndarray,
    batch_size: int,
    max_len: int,
    *,
    bucket: bool = True,
    shuffle_seed: int | None = None,
    drop_remainder: bool = True,
) -> Iterator[dict]:
    """Variable-length classification batches: pad to max_len, emit lengths.

    ``bucket=True`` sorts by length first so co-batched sequences have similar
    lengths (minimal padding waste — SURVEY.md §7 "padding waste vs
    recompilation tradeoff": one static shape, bucketing only reorders).
    Yields {"tokens" [B,L], "lengths" [B], "labels" [B], "valid" [B]}.
    With ``drop_remainder=False`` the last short batch is padded with
    all-zero filler rows marked ``valid=False`` (lengths 0) so metric
    consumers can weight rows instead of double-counting examples.
    """
    order = example_order(
        [len(s) for s in sequences], shuffle_seed=shuffle_seed, bucket=bucket
    )
    for start in range(0, len(order), batch_size):
        idx = order[start : start + batch_size]
        if len(idx) < batch_size and drop_remainder:
            break
        toks = np.zeros((batch_size, max_len), np.int32)
        lens = np.zeros((batch_size,), np.int32)
        labs = np.zeros((batch_size,), np.int32)
        valid = np.zeros((batch_size,), bool)
        for row, i in enumerate(idx):
            seq = sequences[i][:max_len]
            toks[row, : len(seq)] = seq
            lens[row] = len(seq)
            labs[row] = labels[i]
            valid[row] = True
        yield {"tokens": toks, "lengths": lens, "labels": labs, "valid": valid}


def forecast_windows(
    series: np.ndarray,
    context_len: int,
    horizon: int,
    batch_size: int,
    *,
    shuffle_seed: int | None = None,
    drop_remainder: bool = True,
) -> Iterator[dict]:
    """Slide (context, horizon) windows over a [N, F] series and batch them.

    Yields {"context" [B, context_len, F], "targets" [B, horizon, F],
    "valid" [B]}. With ``drop_remainder=False`` the last short batch keeps
    the static shape by repeating its final window as filler, marked
    ``valid=False`` — weight metrics by ``valid``; no window is ever
    double-counted as valid.
    """
    N = len(series)
    n_windows = N - context_len - horizon + 1
    if n_windows < 1:
        raise ValueError(
            f"series length {N} < context {context_len} + horizon {horizon}"
        )
    starts = forecast_starts(n_windows, shuffle_seed=shuffle_seed)
    for b0 in range(0, len(starts), batch_size):
        idx = starts[b0 : b0 + batch_size]
        valid = np.ones((batch_size,), bool)
        if len(idx) < batch_size:
            if drop_remainder:
                break
            valid[len(idx):] = False
            idx = np.concatenate(
                [idx, np.repeat(idx[-1:], batch_size - len(idx))]
            )
        ctx = np.stack([series[i : i + context_len] for i in idx])
        tgt = np.stack(
            [series[i + context_len : i + context_len + horizon] for i in idx]
        )
        yield {
            "context": ctx.astype(np.float32),
            "targets": tgt.astype(np.float32),
            "valid": valid,
        }
