"""Dataset registry for the five baseline configs (BASELINE.md).

Each entry returns real data when files exist under ``data_path``, otherwise
a deterministic synthetic stand-in with identical interface — required by the
no-network environment (SURVEY.md §7 "Hard parts").

Returned dict: {"train","valid","test"} token arrays (LM) or
(sequences, labels) tuples (classification) or float arrays (forecasting),
plus "vocab" where applicable and "synthetic": bool.
"""

from __future__ import annotations

import numpy as np

from .corpus import (
    Vocab,
    build_char_vocab,
    build_word_vocab,
    load_text,
    resolve_split_files,
    synthetic_text,
)


def _lm_dataset(
    data_path: str | None,
    basenames: list[str],
    level: str,
    *,
    synthetic_tokens: int,
    max_vocab: int | None = None,
    seed: int = 0,
):
    files = resolve_split_files(data_path or "", basenames)
    synthetic = files is None
    if synthetic:
        texts = {
            "train": synthetic_text(synthetic_tokens, seed),
            "valid": synthetic_text(synthetic_tokens // 10, seed + 1),
            "test": synthetic_text(synthetic_tokens // 10, seed + 2),
        }
    else:
        texts = {s: load_text(p) for s, p in files.items()}

    if level == "char":
        vocab = build_char_vocab(texts["train"])
    else:
        vocab = build_word_vocab(texts["train"], max_vocab)

    out = {s: vocab.encode_text(t, level) for s, t in texts.items()}
    out["vocab"] = vocab
    out["synthetic"] = synthetic
    return out


def ptb_char(data_path=None, **kw):
    """BASELINE.md config 1: Penn Treebank char-level."""
    return _lm_dataset(
        data_path, ["ptb", "ptb.char"], "char", synthetic_tokens=200_000, **kw
    )


def wikitext2_word(data_path=None, **kw):
    """BASELINE.md config 3: WikiText-2 word-level."""
    return _lm_dataset(
        data_path, ["wiki", "wikitext-2"], "word",
        synthetic_tokens=400_000, max_vocab=33_278, **kw
    )


def wikitext103_word(data_path=None, **kw):
    """BASELINE.md config 5: WikiText-103 word-level (synthetic stand-in is
    deliberately larger)."""
    return _lm_dataset(
        data_path, ["wiki", "wikitext-103"], "word",
        synthetic_tokens=2_000_000, max_vocab=50_000, **kw
    )


def imdb(data_path=None, *, num_examples: int = 2000, max_len: int = 400, seed: int = 0):
    """BASELINE.md config 2: binary sentiment over variable-length sequences.

    Synthetic stand-in: two word distributions shifted by class, lengths
    drawn log-uniform in [20, max_len] — learnable by a bi-LSTM, label
    balance exact.
    """
    del data_path  # no standard offline layout; synthetic only for now
    rng = np.random.RandomState(seed)
    text = synthetic_text(50_000, seed)
    vocab = build_word_vocab(text)
    V = len(vocab)
    pos_words = np.arange(2, V, 2)
    neg_words = np.arange(3, V, 2)
    sequences, labels = [], []
    for i in range(num_examples):
        label = i % 2
        length = int(np.exp(rng.uniform(np.log(20), np.log(max_len))))
        base = pos_words if label else neg_words
        mix = rng.rand(length) < 0.7  # 70% class-specific, 30% shared noise
        seq = np.where(
            mix, base[rng.randint(len(base), size=length)],
            rng.randint(2, V, size=length),
        ).astype(np.int32)
        sequences.append(seq)
        labels.append(label)
    labels = np.asarray(labels, np.int32)
    n_train = int(num_examples * 0.8)
    n_valid = int(num_examples * 0.1)
    return {
        "train": (sequences[:n_train], labels[:n_train]),
        "valid": (sequences[n_train : n_train + n_valid], labels[n_train : n_train + n_valid]),
        "test": (sequences[n_train + n_valid :], labels[n_train + n_valid :]),
        "vocab": vocab,
        "num_classes": 2,
        "max_len": max_len,
        "synthetic": True,
    }


def uci_electricity(data_path=None, *, num_series: int = 8, length: int = 10_000, seed: int = 0):
    """BASELINE.md config 4: multivariate forecasting. Synthetic stand-in:
    mixtures of sinusoids (daily/weekly periods) + AR(1) noise, one column
    per 'customer', normalised per-series."""
    del data_path
    rng = np.random.RandomState(seed)
    t = np.arange(length, dtype=np.float32)
    series = []
    for i in range(num_series):
        daily = np.sin(2 * np.pi * t / 24 + rng.uniform(0, 6.28))
        weekly = 0.5 * np.sin(2 * np.pi * t / (24 * 7) + rng.uniform(0, 6.28))
        noise = np.zeros(length, np.float32)
        for k in range(1, length):
            noise[k] = 0.8 * noise[k - 1] + 0.1 * rng.randn()
        s = (1 + 0.3 * i) * daily + weekly + noise
        series.append((s - s.mean()) / (s.std() + 1e-6))
    data = np.stack(series, axis=1).astype(np.float32)  # [length, num_series]
    n_train = int(length * 0.8)
    n_valid = int(length * 0.1)
    return {
        "train": data[:n_train],
        "valid": data[n_train : n_train + n_valid],
        "test": data[n_train + n_valid :],
        "num_features": num_series,
        "synthetic": True,
    }


DATASETS = {
    "ptb_char": ptb_char,
    "wikitext2": wikitext2_word,
    "wikitext103": wikitext103_word,
    "imdb": imdb,
    "uci_electricity": uci_electricity,
}


def get_dataset(name: str, data_path: str | None = None, **kw):
    if name not in DATASETS:
        raise ValueError(f"unknown dataset {name!r}; have {sorted(DATASETS)}")
    return DATASETS[name](data_path, **kw)
