"""Dataset registry for the five baseline configs (BASELINE.md).

Each entry returns real data when files exist under ``data_path``, otherwise
a deterministic synthetic stand-in with identical interface — required by the
no-network environment (SURVEY.md §7 "Hard parts").

Returned dict: {"train","valid","test"} token arrays (LM) or
(sequences, labels) tuples (classification) or float arrays (forecasting),
plus "vocab" where applicable and "synthetic": bool.
"""

from __future__ import annotations

import os

import numpy as np

from .corpus import (
    build_char_vocab,
    build_word_vocab,
    load_text,
    resolve_split_files,
    synthetic_text,
)


# Version tag for the synthetic word-corpus CACHE FORMAT+ALGORITHM. Bump on
# any change to synthetic_word_corpus (or its defaults) so stale caches with
# a matching token count are never reused across generator versions.
_CORPUS_FMT = "v1"


# Stale-cache sweep age gate: entries from OTHER format versions are only
# deleted once untouched this long. A concurrent checkout of a different
# version (cross-version quality race) keeps refreshing its own entries'
# mtimes, so two live versions no longer delete and regenerate each
# other's multi-MB corpora on every leg (ADVICE r5 finding 3); genuinely
# orphaned versions still get cleaned up after the window passes.
_CACHE_STALE_AGE_S = 7 * 24 * 3600


def _sweep_stale_corpus_cache(cache_root: str) -> None:
    """Delete cache entries that belong to other format versions AND have
    not been touched for ``_CACHE_STALE_AGE_S``: version subdirectories
    other than the current ``_CORPUS_FMT`` one, plus legacy flat
    ``words_*`` files from the pre-namespaced layout."""
    import time

    # wall clock on purpose: the cutoff is compared against st_mtime
    # below, which is wall-clock time — monotonic would be wrong here
    cutoff = time.time() - _CACHE_STALE_AGE_S  # graftlint: disable=wallclock-timing
    try:
        entries = os.listdir(cache_root)
    except OSError:
        return
    for name in entries:
        if name == _CORPUS_FMT:
            continue
        p = os.path.join(cache_root, name)
        try:
            if os.path.isdir(p):
                for f in os.listdir(p):
                    fp = os.path.join(p, f)
                    if os.path.getmtime(fp) < cutoff:
                        os.remove(fp)
                if not os.listdir(p):
                    os.rmdir(p)
            elif name.startswith("words_") and os.path.getmtime(p) < cutoff:
                os.remove(p)
        except OSError:
            pass  # sweeping is best-effort housekeeping


def _cached_word_stream(n_tokens: int, vocab_size: int, seed: int,
                        noise: float, generate) -> list:
    """Token list of ``generate(n_tokens, vocab_size, seed=, noise=)``,
    cached as plain text under the system temp dir, keyed by every
    generation parameter, inside a per-``_CORPUS_FMT`` subdirectory (bump
    the tag whenever the generator algorithm changes, or a stale cache
    whose token count still matches silently skews cross-version
    quality-race comparisons — ADVICE r4). Namespacing by version means
    checkouts of different versions each keep their own cache instead of
    sweeping each other's (ADVICE r5 finding 3); other versions' entries
    are only removed once old (`_sweep_stale_corpus_cache`). A
    missing/corrupt/short cache regenerates silently — the cache is an
    optimization, never a correctness dependency (atomic tmp+rename
    write; concurrent legs at worst both generate and one rename wins)."""
    import tempfile

    cache_root = os.path.join(tempfile.gettempdir(), "lstm_tsp_corpus_cache")
    cache_dir = os.path.join(cache_root, _CORPUS_FMT)
    path = os.path.join(
        cache_dir, f"words_{n_tokens}_{vocab_size}_{seed}_{noise}.txt")
    try:
        # no exists() pre-check: another checkout's age-gated sweep can
        # remove the file between the stat and the open (the TOCTOU
        # class) — a missing cache is just the OSError miss below
        with open(path, "r", encoding="ascii") as f:
            stream = f.read().split()
    except OSError:
        stream = None  # no/unreadable cache: regenerate below
    if stream is not None and len(stream) == n_tokens:
        try:
            # a HIT must refresh mtime: reads alone don't, and the
            # age-gated sweep keys liveness off mtime — without this, a
            # daily-used foreign-version cache would still look stale
            # after the window and get swept
            os.utime(path, None)
        except OSError:
            pass
        return stream
    text = generate(n_tokens, vocab_size, seed=seed, noise=noise)
    try:
        os.makedirs(cache_dir, exist_ok=True)
        _sweep_stale_corpus_cache(cache_root)
        tmp = path + f".tmp{os.getpid()}"
        with open(tmp, "w", encoding="ascii") as f:
            f.write(text)
        os.replace(tmp, path)
    except OSError:
        pass  # cache write failure is not an error
    return text.split()


def _lm_dataset(
    data_path: str | None,
    basenames: list[str],
    level: str,
    *,
    synthetic_tokens: int,
    max_vocab: int | None = None,
    seed: int = 0,
    synthetic_vocab: int | None = None,
    synthetic_noise: float = 0.05,
):
    files = resolve_split_files(data_path or "", basenames)
    synthetic = files is None
    if synthetic:
        if synthetic_vocab is not None:
            # controlled-entropy stand-in (word LMs): the splits share the
            # SAME chain (same seed) — valid/test measure generalization
            # over held-out samples of one process, like real corpora.
            # The stream is cached on disk (keyed by every generation
            # parameter): the 2M-token chain costs ~1.2 s per process
            # launch, a pure fixed cost in the launch-to-quality races
            # that both platforms would otherwise re-pay every leg.
            from .corpus import synthetic_word_corpus

            # one long stream, sliced — cheaper than three generations
            stream = _cached_word_stream(
                int(synthetic_tokens * 1.2), synthetic_vocab, seed,
                synthetic_noise, synthetic_word_corpus,
            )
            n, tenth = synthetic_tokens, synthetic_tokens // 10
            texts = {
                "train": " ".join(stream[:n]),
                "valid": " ".join(stream[n:n + tenth]),
                "test": " ".join(stream[n + tenth:n + 2 * tenth]),
            }
        else:
            texts = {
                "train": synthetic_text(synthetic_tokens, seed),
                "valid": synthetic_text(synthetic_tokens // 10, seed + 1),
                "test": synthetic_text(synthetic_tokens // 10, seed + 2),
            }
    else:
        texts = {s: load_text(p) for s, p in files.items()}

    if level == "char":
        vocab = build_char_vocab(texts["train"])
    else:
        vocab = build_word_vocab(texts["train"], max_vocab)

    out = {s: vocab.encode_text(t, level) for s, t in texts.items()}
    out["vocab"] = vocab
    out["synthetic"] = synthetic
    return out


def ptb_char(data_path=None, **kw):
    """BASELINE.md config 1: Penn Treebank char-level."""
    return _lm_dataset(
        data_path, ["ptb", "ptb.char"], "char", synthetic_tokens=200_000, **kw
    )


def wikitext2_word(data_path=None, **kw):
    """BASELINE.md config 3: WikiText-2 word-level. Synthetic stand-in:
    controlled-entropy 1,000-word chain (synthetic_word_corpus) so the
    eval-ppl curve declines across hundreds of steps — the old
    seed-paragraph chain (~113 words) saturated by step ~20 and quality
    races measured launch costs (VERDICT r3 weak 2)."""
    kw.setdefault("synthetic_vocab", 1_000)
    kw.setdefault("synthetic_noise", 0.05)
    return _lm_dataset(
        data_path, ["wiki", "wikitext-2"], "word",
        synthetic_tokens=400_000, max_vocab=33_278, **kw
    )


def wikitext103_word(data_path=None, **kw):
    """BASELINE.md config 5: WikiText-103 word-level (synthetic stand-in is
    deliberately larger: a controlled-entropy 5,000-word chain — see
    wikitext2_word's note)."""
    kw.setdefault("synthetic_vocab", 5_000)
    kw.setdefault("synthetic_noise", 0.1)
    return _lm_dataset(
        data_path, ["wiki", "wikitext-103"], "word",
        synthetic_tokens=2_000_000, max_vocab=50_000, **kw
    )


def _resolve_imdb_root(data_path: str | None) -> str | None:
    """Locate the standard aclImdb directory layout: ``<root>/{train,test}/
    {pos,neg}/*.txt``. Accepts the aclImdb dir itself or a parent containing
    it; None when absent (synthetic fallback)."""
    if not data_path or not os.path.isdir(data_path):
        return None
    for root in (data_path, os.path.join(data_path, "aclImdb")):
        if all(
            os.path.isdir(os.path.join(root, split, label))
            for split in ("train", "test")
            for label in ("pos", "neg")
        ):
            return root
    return None


def _read_imdb_split(root: str, split: str, max_examples: int | None = None):
    """Read one aclImdb split into (texts, labels), deterministic order."""
    texts, labels = [], []
    for label_name, label in (("pos", 1), ("neg", 0)):
        d = os.path.join(root, split, label_name)
        names = [n for n in sorted(os.listdir(d)) if n.endswith(".txt")]
        if max_examples is not None:
            names = names[: max_examples // 2]
        for name in names:
            with open(os.path.join(d, name), encoding="utf-8",
                      errors="replace") as f:
                texts.append(f.read())
            labels.append(label)
    return texts, labels


def _imdb_real(root: str, *, max_len: int, max_vocab: int = 25_000,
               valid_frac: float = 0.1, max_examples: int | None = None,
               seed: int = 0):
    """aclImdb directory → the same dict interface as the synthetic path:
    word-id sequences clipped to ``max_len``, labels, train-split vocab."""
    train_texts, train_labels = _read_imdb_split(root, "train", max_examples)
    test_texts, test_labels = _read_imdb_split(root, "test", max_examples)
    vocab = build_word_vocab(" ".join(train_texts), max_vocab)

    def encode(texts, labels):
        seqs = [vocab.encode_text(t, "word")[:max_len] for t in texts]
        return seqs, np.asarray(labels, np.int32)

    # interleave pos/neg before the valid split so both splits stay balanced
    order = np.random.RandomState(seed).permutation(len(train_texts))
    train_texts = [train_texts[i] for i in order]
    train_labels = [train_labels[i] for i in order]
    n_valid = int(len(train_texts) * valid_frac)
    seqs, labels = encode(train_texts, train_labels)
    test_seqs, test_labels = encode(test_texts, test_labels)
    return {
        "train": (seqs[n_valid:], labels[n_valid:]),
        "valid": (seqs[:n_valid], labels[:n_valid]),
        "test": (test_seqs, test_labels),
        "vocab": vocab,
        "num_classes": 2,
        "max_len": max_len,
        "synthetic": False,
    }


def imdb(data_path=None, *, num_examples: int | None = None, max_len: int = 400,
         seed: int = 0, signal: float = 0.25):
    """BASELINE.md config 2: binary sentiment over variable-length sequences.

    Real data: point ``data_path`` at the aclImdb directory (or its parent) —
    standard ``{train,test}/{pos,neg}/*.txt`` layout. Synthetic stand-in
    otherwise: two word distributions shifted by class, lengths drawn
    log-uniform in [20, max_len] — learnable by a bi-LSTM, label balance
    exact. ``signal`` is the class-specific token fraction (the SNR knob):
    the old 0.7 made a seq-400 example carry ~hundreds of informative
    tokens, the model saturated accuracy 1.0 by step ~40, and the quality
    race measured launch costs instead of training (VERDICT r3 weak 2);
    0.25 leaves ~5-100 informative tokens per example (length-dependent)
    so the accuracy curve climbs over hundreds of steps.

    ``num_examples`` bounds BOTH paths (per split, balanced); the default
    loads everything real / 2000 synthetic.
    """
    root = _resolve_imdb_root(data_path)
    if root is not None:
        return _imdb_real(root, max_len=max_len, seed=seed,
                          max_examples=num_examples)
    num_examples = num_examples or 2000
    rng = np.random.RandomState(seed)
    text = synthetic_text(50_000, seed)
    vocab = build_word_vocab(text)
    V = len(vocab)
    pos_words = np.arange(2, V, 2)
    neg_words = np.arange(3, V, 2)
    sequences, labels = [], []
    for i in range(num_examples):
        label = i % 2
        length = int(np.exp(rng.uniform(np.log(20), np.log(max_len))))
        base = pos_words if label else neg_words
        mix = rng.rand(length) < signal  # class-specific vs shared noise
        seq = np.where(
            mix, base[rng.randint(len(base), size=length)],
            rng.randint(2, V, size=length),
        ).astype(np.int32)
        sequences.append(seq)
        labels.append(label)
    labels = np.asarray(labels, np.int32)
    n_train = int(num_examples * 0.8)
    n_valid = int(num_examples * 0.1)
    return {
        "train": (sequences[:n_train], labels[:n_train]),
        "valid": (sequences[n_train : n_train + n_valid], labels[n_train : n_train + n_valid]),
        "test": (sequences[n_train + n_valid :], labels[n_train + n_valid :]),
        "vocab": vocab,
        "num_classes": 2,
        "max_len": max_len,
        "synthetic": True,
    }


def _resolve_uci_file(data_path: str | None) -> str | None:
    """Locate the UCI ElectricityLoadDiagrams file (``LD2011_2014.txt``):
    accepts the file itself or a directory containing it."""
    if not data_path:
        return None
    if os.path.isfile(data_path):
        return data_path
    if os.path.isdir(data_path):
        p = os.path.join(data_path, "LD2011_2014.txt")
        if os.path.isfile(p):
            return p
    return None


def _uci_real(path: str, *, num_series: int):
    """Parse the UCI semicolon-separated CSV: first column is a timestamp,
    remaining columns are per-customer loads with DECIMAL COMMAS (European
    locale — the dataset's documented format). Keeps the first
    ``num_series`` customer columns, per-series normalised, 80/10/10
    time-ordered split — identical interface to the synthetic path.

    The per-value parse is the slowest host step on the real ~700 MB file,
    so it takes the C++ kernel (native/fastdata.cpp csv_decimal_comma)
    when available — byte-identical output (parse-to-double then cast,
    exactly like the Python loop; measured 2.9x end-to-end on a 39 MB
    synthetic file), pure-Python loop otherwise."""
    from .native import available, parse_decimal_comma_csv

    # header via TEXT mode: universal newlines, exactly like the fallback
    # loop below (a binary readline would mis-read CR-only files)
    with open(path, encoding="utf-8", errors="replace") as f:
        ncols = f.readline().count(";")
    take = min(num_series, ncols) if ncols else num_series
    data = None
    if available() and take > 0:
        with open(path, "rb") as fb:
            # locate the end of the header in a small prefix, then seek
            # and read ONLY the body — one copy of the ~700 MB file, for
            # the kernel alone (the fallback path streams line-by-line).
            # The skip stops at the FIRST line terminator of any style,
            # matching the text-mode sniff above (a binary readline would
            # eat the first data row of a \r-header/\n-body mixed file);
            # CR-only bodies then parse 0 rows (the kernel splits on \n)
            # or hit the -2 sentinel, and the text fallback handles them
            # as it always did.
            prefix = fb.read(1 << 20)  # headers are ~KBs; 1 MiB is ample
            i_r, i_n = prefix.find(b"\r"), prefix.find(b"\n")
            ends = [i for i in (i_r, i_n) if i >= 0]
            if ends:
                i = min(ends)
                i += 2 if prefix[i:i + 2] == b"\r\n" else 1
                fb.seek(i)
                body = fb.read()
                data = parse_decimal_comma_csv(body, take)
                del body
    if data is not None and not len(data):
        data = None  # empty parse: let the fallback raise the format error
    if data is None:
        rows = []
        with open(path, encoding="utf-8", errors="replace") as f:
            f.readline()  # header (column count already derived above)
            for line in f:
                parts = line.rstrip("\n").split(";")
                if len(parts) < take + 1:
                    continue
                rows.append(
                    [float(v.replace(",", ".") or 0.0)
                     for v in parts[1 : take + 1]]
                )
        if not rows:
            raise ValueError(
                f"{path} does not look like the UCI LD2011_2014 format "
                "(semicolon-separated, timestamp + per-customer columns)"
            )
        data = np.asarray(rows, np.float32)  # [length, take]
    n_train = int(len(data) * 0.8)
    n_valid = int(len(data) * 0.1)
    # normalise with TRAIN-split statistics only — using full-series stats
    # would leak valid/test information into the scored data
    mu = data[:n_train].mean(axis=0)
    sd = data[:n_train].std(axis=0)
    data = (data - mu) / (sd + 1e-6)
    return {
        "train": data[:n_train],
        "valid": data[n_train : n_train + n_valid],
        "test": data[n_train + n_valid :],
        "num_features": data.shape[1],
        "synthetic": False,
    }


def uci_electricity(data_path=None, *, num_series: int = 8, length: int = 10_000, seed: int = 0):
    """BASELINE.md config 4: multivariate forecasting.

    Real data: point ``data_path`` at ``LD2011_2014.txt`` (or a directory
    containing it) — the UCI ElectricityLoadDiagrams20112014 CSV. Synthetic
    stand-in otherwise: mixtures of sinusoids (daily/weekly periods) + AR(1)
    noise, one column per 'customer', normalised per-series."""
    uci_file = _resolve_uci_file(data_path)
    if uci_file is not None:
        return _uci_real(uci_file, num_series=num_series)
    rng = np.random.RandomState(seed)
    t = np.arange(length, dtype=np.float32)
    series = []
    for i in range(num_series):
        daily = np.sin(2 * np.pi * t / 24 + rng.uniform(0, 6.28))
        weekly = 0.5 * np.sin(2 * np.pi * t / (24 * 7) + rng.uniform(0, 6.28))
        noise = np.zeros(length, np.float32)
        for k in range(1, length):
            noise[k] = 0.8 * noise[k - 1] + 0.1 * rng.randn()
        s = (1 + 0.3 * i) * daily + weekly + noise
        series.append(s)
    data = np.stack(series, axis=1).astype(np.float32)  # [length, num_series]
    n_train = int(length * 0.8)
    n_valid = int(length * 0.1)
    # train-split statistics only (no valid/test leakage), as in _uci_real
    mu = data[:n_train].mean(axis=0)
    sd = data[:n_train].std(axis=0)
    data = (data - mu) / (sd + 1e-6)
    return {
        "train": data[:n_train],
        "valid": data[n_train : n_train + n_valid],
        "test": data[n_train + n_valid :],
        "num_features": num_series,
        "synthetic": True,
    }


DATASETS = {
    "ptb_char": ptb_char,
    "wikitext2": wikitext2_word,
    "wikitext103": wikitext103_word,
    "imdb": imdb,
    "uci_electricity": uci_electricity,
}


def get_dataset(name: str, data_path: str | None = None, **kw):
    if name not in DATASETS:
        raise ValueError(f"unknown dataset {name!r}; have {sorted(DATASETS)}")
    return DATASETS[name](data_path, **kw)
