from .corpus import Vocab, build_char_vocab, build_word_vocab, load_text
from .batching import (
    lm_batch_stream,
    lm_epoch_batches,
    padded_batches,
    stacked_batches,
)
from .datasets import get_dataset
from .prefetch import prefetch_to_device

__all__ = [
    "Vocab",
    "build_char_vocab",
    "build_word_vocab",
    "load_text",
    "lm_batch_stream",
    "lm_epoch_batches",
    "padded_batches",
    "stacked_batches",
    "get_dataset",
    "prefetch_to_device",
]
