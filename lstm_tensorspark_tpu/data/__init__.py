from .corpus import Vocab, build_char_vocab, build_word_vocab, load_text
from .batching import (
    lm_batch_stream,
    lm_epoch_batches,
    padded_batches,
    stacked_batches,
)
from .datasets import get_dataset
from .device_dataset import (
    DeviceLMData,
    DeviceExamples,
    DeviceSeries,
    stage_lm_data,
    stage_examples,
    stage_series,
    stage_stacked_batches,
    slice_window,
    slice_forecast_batch,
    take_batch,
    window_index_stream,
)
from .prefetch import prefetch_to_device

__all__ = [
    "Vocab",
    "build_char_vocab",
    "build_word_vocab",
    "load_text",
    "lm_batch_stream",
    "lm_epoch_batches",
    "padded_batches",
    "stacked_batches",
    "get_dataset",
    "DeviceLMData",
    "DeviceExamples",
    "DeviceSeries",
    "stage_lm_data",
    "stage_examples",
    "stage_series",
    "stage_stacked_batches",
    "slice_window",
    "slice_forecast_batch",
    "take_batch",
    "window_index_stream",
    "prefetch_to_device",
]
