from .corpus import Vocab, build_char_vocab, build_word_vocab, load_text
from .batching import (
    lm_batch_stream,
    lm_epoch_batches,
    padded_batches,
    stacked_batches,
)
from .datasets import get_dataset
from .device_dataset import (
    DeviceLMData,
    stage_lm_data,
    slice_window,
    window_index_stream,
)
from .prefetch import prefetch_to_device

__all__ = [
    "Vocab",
    "build_char_vocab",
    "build_word_vocab",
    "load_text",
    "lm_batch_stream",
    "lm_epoch_batches",
    "padded_batches",
    "stacked_batches",
    "get_dataset",
    "DeviceLMData",
    "stage_lm_data",
    "slice_window",
    "window_index_stream",
    "prefetch_to_device",
]
