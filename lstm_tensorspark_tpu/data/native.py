"""ctypes bindings for the native data-pipeline kernels (native/fastdata.cpp)
with transparent pure-Python fallback.

The .so is built on demand via the checked-in Makefile (g++ is part of the
toolchain); if the build or load fails, every entry point falls back to the
numpy/Python implementation with identical results — the native path is a
host-side throughput optimization, never a correctness dependency.
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_SO_PATH = os.path.join(_NATIVE_DIR, "libfastdata.so")

_lib = None
_load_attempted = False


def _load():
    global _lib, _load_attempted
    if _load_attempted:
        return _lib
    _load_attempted = True
    if os.environ.get("LSTM_TSP_NO_NATIVE") == "1":
        return None
    try:
        src = os.path.join(_NATIVE_DIR, "fastdata.cpp")
        stale = not os.path.exists(_SO_PATH) or (
            os.path.exists(src)
            and os.path.getmtime(src) > os.path.getmtime(_SO_PATH)
        )
        if stale:
            subprocess.run(
                ["make", "-C", _NATIVE_DIR, "-sB"],
                check=True, capture_output=True, timeout=120,
            )
        lib = ctypes.CDLL(_SO_PATH)
        lib.encode_bytes.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ]
        lib.count_words.restype = ctypes.c_int64
        lib.count_words.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.encode_words.restype = ctypes.c_int64
        lib.encode_words.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
        ]
        lib.vocab_build.restype = ctypes.c_void_p
        lib.vocab_build.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.vocab_size.restype = ctypes.c_int64
        lib.vocab_size.argtypes = [ctypes.c_void_p]
        lib.vocab_words_bytes.restype = ctypes.c_int64
        lib.vocab_words_bytes.argtypes = [ctypes.c_void_p]
        lib.vocab_fill.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
        ]
        lib.vocab_free.argtypes = [ctypes.c_void_p]
        lib.csv_decimal_comma.restype = ctypes.c_int64
        lib.csv_decimal_comma.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
        ]
        _lib = lib
    except Exception:
        _lib = None
    return _lib


def available() -> bool:
    return _load() is not None


def encode_chars(text: str, stoi: dict[str, int], unk_id: int) -> np.ndarray:
    """Char-level encoding. Only ASCII vocabularies take the native path
    (byte-level table); others fall back."""
    lib = _load()
    # The byte table only matches Python-level chars when text is pure ASCII
    # (1 byte == 1 char); multi-byte UTF-8 would change lengths and ids.
    # multi-char stoi entries (<pad>/<unk> specials) never appear in raw text.
    chars = {c: i for c, i in stoi.items() if len(c) == 1}
    if (
        lib is not None
        and text.isascii()
        and all(ord(c) < 128 for c in chars)
    ):
        data = text.encode("ascii")
        table = np.full(256, unk_id, np.int32)
        for ch, idx in chars.items():
            table[ord(ch)] = idx
        out = np.empty(len(data), np.int32)
        lib.encode_bytes(
            data, len(data),
            table.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        return out
    return np.asarray([stoi.get(c, unk_id) for c in text], np.int32)


def _ascii_splittable(text: str) -> bool:
    """True when str.split() and the C tokenizer agree: pure-ASCII text
    (the C side matches Python's ASCII whitespace set exactly)."""
    return text.isascii()


def encode_words(
    text: str, itos: list[str], unk_id: int, id_base: int = 0
) -> np.ndarray:
    """Word-level encoding of a whitespace-tokenized text.

    itos: words in id order STARTING at id_base (specials excluded when
    id_base covers them). Tokens not in itos — including literal special
    strings like "<pad>" appearing in raw text — map to unk_id on BOTH
    paths (reserved ids are never reachable from raw text)."""
    lib = _load()
    if (
        lib is not None
        and _ascii_splittable(text)
        # a NUL inside a vocab token would corrupt the \0-delimited buffer
        and all("\0" not in w for w in itos)
    ):
        data = text.encode("ascii")
        vocab_buf = b"\0".join(w.encode("utf-8") for w in itos) + b"\0"
        n_words = lib.count_words(data, len(data))
        out = np.empty(max(n_words, 1), np.int32)
        written = lib.encode_words(
            data, len(data), vocab_buf, len(vocab_buf), len(itos),
            id_base, unk_id,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), len(out),
        )
        return out[:written]
    lookup = {w: id_base + i for i, w in enumerate(itos)}
    return np.asarray(
        [lookup.get(w, unk_id) for w in text.split()], np.int32
    )


def parse_decimal_comma_csv(body: bytes, take: int) -> np.ndarray | None:
    """Parse the body (header already stripped) of a semicolon-separated
    decimal-comma CSV (UCI LD2011_2014 format) into a [rows, take] float32
    array: per line, skip the timestamp field, convert the next ``take``
    values. Returns None when the native library is unavailable OR when
    the C parser hits a value Python's float() might treat differently
    (caller falls back to the pure loop, which keeps the exact historical
    semantics, including its ValueError on garbage)."""
    lib = _load()
    if lib is None or take <= 0:
        return None
    # capacity bound must count every terminator the kernel honors:
    # '\n', lone '\r', and '\r\n' (which would be double-counted by the
    # two substring counts, hence the subtraction)
    max_rows = (body.count(b"\n") + body.count(b"\r")
                - body.count(b"\r\n") + 1)
    out = np.empty((max_rows, take), np.float32)
    rows = lib.csv_decimal_comma(
        body, len(body), take,
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        out.size,
    )
    if rows < 0:
        return None
    return out[:rows]


def most_common_words(text: str, max_size: int | None = None) -> list[str]:
    """Whitespace-tokenized vocabulary in ``Counter.most_common`` order
    (count desc, first-occurrence tie-break) — C++ hash-count+sort for ASCII
    text, Python Counter fallback, identical results."""
    if max_size is not None and max_size <= 0:
        return []  # Counter.most_common(n <= 0) semantics on both paths
    lib = _load()
    # NUL gate: a token containing '\0' would corrupt the \0-joined words
    # buffer returned from C++ (one counted word parsed back as two).
    if lib is not None and _ascii_splittable(text) and "\0" not in text:
        data = text.encode("ascii")
        handle = lib.vocab_build(data, len(data))
        try:
            n = lib.vocab_size(handle)
            nbytes = lib.vocab_words_bytes(handle)
            words_buf = ctypes.create_string_buffer(max(nbytes, 1))
            counts = np.empty(max(n, 1), np.int64)
            lib.vocab_fill(
                handle, words_buf,
                counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            )
            words = words_buf.raw[: max(nbytes - 1, 0)].decode("ascii")
            out = words.split("\0") if words else []
        finally:
            lib.vocab_free(handle)
        return out[:max_size] if max_size is not None else out
    from collections import Counter

    most = Counter(text.split()).most_common(max_size)
    return [w for w, _ in most]
