"""Device prefetch: overlap host batch preparation with device compute.

The reference's input path is Spark's lazily-materialised RDD iterator inside
each executor (SURVEY.md §3.2) — batch prep and compute are serialized per
worker. Here the host thread stacks/transfers the NEXT batch while the device
runs the CURRENT step: `jax.device_put` is async, so keeping a small window
of in-flight transfers ahead of the compute stream hides host time entirely
(double/triple buffering). With a sharding, the put lands shards directly on
their devices — this is also the DP feed path.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator

import jax


def prefetch_to_device(batches: Iterator, size: int = 2, *, sharding=None) -> Iterator:
    """Yield batches already transferred to device, ``size`` ahead.

    A daemon thread pulls from ``batches`` (host numpy work — stacking,
    tokenization — happens there, off the dispatch thread) and device_puts
    into a bounded queue. ``sharding`` (e.g. NamedSharding(mesh, P("data")))
    places each leaf; None uses the default device.
    """
    if size < 1:
        raise ValueError(f"prefetch size must be >= 1, got {size}")
    q: queue.Queue = queue.Queue(maxsize=size)
    END = object()
    stop = threading.Event()  # consumer-gone signal: unpin HBM + exit thread

    def put(x):
        if sharding is None:
            return jax.device_put(x)
        return jax.tree.map(lambda a: jax.device_put(a, sharding), x)

    def producer():
        try:
            for b in batches:
                if stop.is_set():
                    return
                q.put(put(b))
        except Exception as e:  # surface in the consumer, not the thread
            if not stop.is_set():
                q.put(e)
            return
        q.put(END)

    threading.Thread(target=producer, daemon=True).start()
    try:
        while True:
            item = q.get()
            if item is END:
                return
            if isinstance(item, Exception):
                raise item
            yield item
    finally:
        # Abandoned mid-stream (train_loop breaking at num_steps is the
        # normal case): tell the producer to quit and drain the queue so a
        # blocked q.put unblocks — otherwise the thread pins size+1
        # device-resident batches for the rest of the process.
        stop.set()
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass
