from .lstm_lm import LMConfig, init_lm, lm_forward, lm_loss
from .generate import generate, make_generate_fn, sample_logits
from .classifier import (
    ClassifierConfig,
    init_classifier,
    classifier_forward,
    classifier_loss,
)
from .seq2seq import (
    Seq2SeqConfig,
    init_seq2seq,
    seq2seq_loss,
    forecast,
)

__all__ = [
    "LMConfig",
    "init_lm",
    "lm_forward",
    "lm_loss",
    "generate",
    "make_generate_fn",
    "sample_logits",
    "ClassifierConfig",
    "init_classifier",
    "classifier_forward",
    "classifier_loss",
    "Seq2SeqConfig",
    "init_seq2seq",
    "seq2seq_loss",
    "forecast",
]
