from .lstm_lm import LMConfig, init_lm, lm_forward, lm_loss

__all__ = ["LMConfig", "init_lm", "lm_forward", "lm_loss"]
