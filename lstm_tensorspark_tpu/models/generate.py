"""Autoregressive text generation for the LSTM LM.

Reference parity: SURVEY.md §2 "Eval / inference" [P] — the reference's
inference surface is a forward-only predict path. For a language model the
natural predict operation is sampling continuations; this module supplies it
TPU-natively: one jitted program containing the prompt prefill (batched
`lm_forward` over [B, T0]) and the decode loop (`lax.scan` over new tokens,
recurrent carries threaded on-device). No per-token host round-trips — the
host sees only the final [B, T0 + N] token array.

Sampling modes (all static at trace time): greedy argmax, temperature
scaling, top-k truncation, top-p (nucleus) truncation.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.lstm_cell import fuse_params, lstm_step
from .lstm_lm import LMConfig, init_carries, lm_forward


def sample_logits(
    rng: jax.Array,
    logits: jax.Array,
    *,
    temperature: float = 1.0,
    top_k: int | None = None,
    top_p: float | None = None,
    greedy: bool = False,
) -> jax.Array:
    """Sample token ids [B] from logits [B, V]. ``top_k`` and ``top_p``
    (nucleus) truncation compose: k-truncation first, then the smallest
    prefix of the remaining distribution whose mass reaches ``top_p``."""
    logits = logits.astype(jnp.float32)
    if greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if temperature != 1.0:
        logits = logits / jnp.maximum(temperature, 1e-6)
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if top_k is not None and top_k < logits.shape[-1]:
        kth = lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p is not None and top_p < 1.0:
        desc = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(desc, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # keep tokens whose EXCLUSIVE cumulative mass is < top_p (the
        # highest-probability token always survives)
        keep = (cum - probs) < top_p
        cutoff = jnp.min(
            jnp.where(keep, desc, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def fuse_layers(params, cfg: LMConfig):
    """Fuse every layer's gate matrices ONCE (outside the decode scan) — per
    lstm_cell.py's contract that fusing happens once per forward pass.

    Shared with the serving engine (serve/engine.py), which fuses once at
    engine construction and reuses the result for every decode batch."""
    cdtype = None if cfg.cdtype == jnp.float32 else cfg.cdtype
    return [fuse_params(layer, compute_dtype=cdtype) for layer in params["layers"]]


def decode_one(params, fused_layers, cfg: LMConfig, carries, token: jax.Array):
    """One decode step: token [B] int32 → (logits [B, V], new carries).

    Shares the exact cell math with training (`lstm_step` on fused kernels) —
    the decode path cannot drift from the train path.
    """
    x = jnp.take(params["embedding"], token, axis=0)
    new_carries = []
    for fused, carry in zip(fused_layers, carries):
        carry, x = lstm_step(fused, carry, x)
        new_carries.append(carry)
    head = params["head"]
    kernel = params["embedding"].T if cfg.tie_embeddings else head["kernel"]
    # cfg.ldtype, NOT hardcoded f32: the prefill's logits come from
    # lm_forward at cfg.ldtype, and sampling from the prefill's last
    # position must match sampling from a decode step over the same
    # prefix — same precision or near-tied logits argmax differently
    logits = (
        jnp.dot(x.astype(kernel.dtype), kernel,
                preferred_element_type=cfg.ldtype)
        + head["bias"].astype(cfg.ldtype)
    )
    return logits, new_carries


def generate(
    params,
    prompt: jax.Array,
    cfg: LMConfig,
    rng: jax.Array,
    *,
    max_new_tokens: int,
    temperature: float = 1.0,
    top_k: int | None = None,
    top_p: float | None = None,
    greedy: bool = False,
) -> jax.Array:
    """Generate continuations: prompt [B, T0] int32 → [B, T0 + N] int32.

    Pure function of (params, prompt, rng) — jit with static
    (cfg, max_new_tokens, temperature, top_k, greedy) via
    :func:`make_generate_fn`.
    """
    B = prompt.shape[0]
    # Inference needs no rematerialisation: remat_chunk is a training-memory
    # device and would reject prompt lengths not divisible by the chunk.
    if cfg.remat_chunk is not None:
        cfg = dataclasses.replace(cfg, remat_chunk=None)
    logits, carries = lm_forward(
        params, prompt, cfg, carries=init_carries(cfg, B)
    )
    rng, sub = jax.random.split(rng)
    token = sample_logits(
        sub, logits[:, -1, :], temperature=temperature, top_k=top_k,
        top_p=top_p, greedy=greedy,
    )

    fused_layers = fuse_layers(params, cfg)

    def step(carry, _):
        rng, token, carries = carry
        logits, carries = decode_one(params, fused_layers, cfg, carries, token)
        rng, sub = jax.random.split(rng)
        nxt = sample_logits(
            sub, logits, temperature=temperature, top_k=top_k,
            top_p=top_p, greedy=greedy,
        )
        return (rng, nxt, carries), token

    if max_new_tokens > 1:
        (_, last, _), toks = lax.scan(
            step, (rng, token, carries), None, length=max_new_tokens - 1
        )
        new = jnp.concatenate([jnp.moveaxis(toks, 0, 1), last[:, None]], axis=1)
    else:
        new = token[:, None]
    return jnp.concatenate([prompt, new], axis=1)


def make_generate_fn(
    cfg: LMConfig,
    *,
    max_new_tokens: int,
    temperature: float = 1.0,
    top_k: int | None = None,
    top_p: float | None = None,
    greedy: bool = False,
):
    """Jitted generate: fn(params, prompt [B, T0], rng) -> [B, T0 + N]."""

    def fn(params, prompt, rng):
        return generate(
            params, prompt, cfg, rng,
            max_new_tokens=max_new_tokens,
            temperature=temperature, top_k=top_k, top_p=top_p, greedy=greedy,
        )

    return jax.jit(fn)
