"""Seq2seq encoder-decoder LSTM forecaster (BASELINE.md config 4:
UCI-Electricity multivariate forecasting).

Reference parity: part of the driver-defined capability envelope
(SURVEY.md §6: "seq2seq" row); the reference itself ships only one task, so
this is new capability built from the same cell/scan primitives.

Encoder: stacked LSTM over the context window; its final per-layer (h, c)
carries initialize the decoder stack. Decoder: teacher-forced `lstm_scan`
during training (one compiled scan over the horizon — MXU-friendly), and an
autoregressive `lax.scan` feeding back its own projections for inference.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.lstm_cell import fuse_params, init_lstm_params, lstm_step
from ..ops.scan import auto_lstm_scan, stacked_lstm_scan


@dataclasses.dataclass(frozen=True)
class Seq2SeqConfig:
    num_features: int
    hidden_size: int = 128
    num_layers: int = 1
    horizon: int = 24
    compute_dtype: str = "float32"
    remat_chunk: int | None = None
    # fused Pallas recurrence for the encoder scan AND the teacher-forced
    # decoder scan (the autoregressive inference decode stays a lax.scan —
    # its per-step projection feedback cannot be hoisted into one kernel)
    use_pallas: bool = False
    # BPTT mode for the encoder scan (ops/parallel_scan.py); the decoder
    # scans stay sequential — the forecast horizon is short, below any
    # shape where the assoc backward pays
    bptt: str = "sequential"

    @property
    def cdtype(self):
        return jnp.dtype(self.compute_dtype)


def init_seq2seq(key: jax.Array, cfg: Seq2SeqConfig):
    keys = jax.random.split(key, 2 * cfg.num_layers + 1)
    enc, dec = [], []
    for i in range(cfg.num_layers):
        enc_in = cfg.num_features if i == 0 else cfg.hidden_size
        dec_in = cfg.num_features if i == 0 else cfg.hidden_size
        enc.append(init_lstm_params(keys[2 * i], enc_in, cfg.hidden_size))
        dec.append(init_lstm_params(keys[2 * i + 1], dec_in, cfg.hidden_size))
    proj = {
        "kernel": jax.nn.initializers.glorot_uniform()(
            keys[-1], (cfg.hidden_size, cfg.num_features), jnp.float32
        ),
        "bias": jnp.zeros((cfg.num_features,), jnp.float32),
    }
    return {"encoder": enc, "decoder": dec, "proj": proj}


def _project(proj, h):
    return (
        jnp.dot(h.astype(proj["kernel"].dtype), proj["kernel"],
                preferred_element_type=jnp.float32)
        + proj["bias"]
    )


def encode(params, context: jax.Array, cfg: Seq2SeqConfig):
    """context [B, T, F] → per-layer final carries for the decoder."""
    cdtype = None if cfg.cdtype == jnp.float32 else cfg.cdtype
    carries, _ = stacked_lstm_scan(
        params["encoder"], context,
        compute_dtype=cdtype, remat_chunk=cfg.remat_chunk,
        use_pallas=cfg.use_pallas, bptt=cfg.bptt,
    )
    return carries


def decode_teacher_forced(params, carries, decoder_inputs, cfg: Seq2SeqConfig):
    """Training decode: decoder_inputs [B, H, F] (last context step + shifted
    targets) → predictions [B, H, F]. One compiled scan per layer."""
    cdtype = None if cfg.cdtype == jnp.float32 else cfg.cdtype
    ys = decoder_inputs
    # no remat on the decoder: the horizon is short (remat_chunk targets the
    # long encoder context and generally does not divide the horizon)
    for p, c0 in zip(params["decoder"], carries):
        _, ys = auto_lstm_scan(p, ys, c0, compute_dtype=cdtype,
                               use_pallas=cfg.use_pallas)
    return _project(params["proj"], ys)


def decode_autoregressive(params, carries, first_input, cfg: Seq2SeqConfig):
    """Inference decode: feed back own projections for ``horizon`` steps.
    first_input [B, F] (the last observed step). Returns [B, horizon, F]."""
    cdtype = None if cfg.cdtype == jnp.float32 else cfg.cdtype
    fused = [fuse_params(p, compute_dtype=cdtype) for p in params["decoder"]]

    def step(carry, _):
        layer_carries, x = carry
        new_carries = []
        h = x
        for f, c in zip(fused, layer_carries):
            c_new, h = lstm_step(f, c, h)
            new_carries.append(c_new)
        y = _project(params["proj"], h)
        return (new_carries, y), y

    (_, _), ys = lax.scan(step, (carries, first_input), None, length=cfg.horizon)
    return jnp.moveaxis(ys, 0, 1)


def seq2seq_loss(params, batch, cfg: Seq2SeqConfig, *, dropout_rng=None,
                 deterministic: bool = True):
    """batch: {"context" [B,T,F], "targets" [B,H,F]}. Teacher-forced MSE.

    Decoder input at step t is the previous ground-truth step (context's last
    step at t=0) — the standard teacher-forcing scheme.
    """
    del dropout_rng, deterministic
    carries = encode(params, batch["context"], cfg)
    last = batch["context"][:, -1:, :]
    dec_in = jnp.concatenate([last, batch["targets"][:, :-1, :]], axis=1)
    preds = decode_teacher_forced(params, carries, dec_in, cfg)
    err = (preds - batch["targets"]) ** 2
    loss = jnp.mean(err)
    return loss, {"loss": loss, "mae": jnp.mean(jnp.abs(preds - batch["targets"]))}


def forecast(params, context: jax.Array, cfg: Seq2SeqConfig):
    """Free-running forecast: [B,T,F] → [B,horizon,F]."""
    carries = encode(params, context, cfg)
    return decode_autoregressive(params, carries, context[:, -1, :], cfg)
