"""Bidirectional LSTM sequence classifier (BASELINE.md config 2: IMDB
sentiment, hidden=256, seq-len=400).

Reference parity: the reference's network wrapper supports a classification
head (SURVEY.md §2 "Multi-layer / network wrapper", §6 capability envelope:
"uni/bi-directional ... classification + LM heads, variable-length
batching"). Bi-direction and masking are capability extensions the baseline
configs demand.

Design: each bi-layer runs the SAME `lstm_scan` twice — forward, and
reverse=True with the carry-freeze mask (correct over right-padded batches:
the reversed scan walks padding first with a frozen zero carry, so its final
state is the state at t=0 over the valid prefix). Outputs concat to [B,T,2H].
The classifier head consumes the concat of both directions' final states.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from ..ops.embedding import embed_lookup
from ..ops.lstm_cell import init_lstm_params
from ..ops.masking import dropout, sequence_mask
from ..ops.scan import bidir_lstm_scan


@dataclasses.dataclass(frozen=True)
class ClassifierConfig:
    vocab_size: int
    num_classes: int = 2
    hidden_size: int = 256
    num_layers: int = 1
    embed_size: int | None = None
    dropout: float = 0.0
    compute_dtype: str = "float32"
    remat_chunk: int | None = None
    # fused Pallas recurrence (ops/pallas_lstm.py) — covers the masked
    # forward AND reversed scans of the bi-LSTM; falls back per-layer when
    # shapes/platform don't fit the kernel's VMEM cost model
    use_pallas: bool = False
    # BPTT mode for both directions' scans (ops/parallel_scan.py):
    # "sequential" | "assoc" | "auto" — the T=400 IMDB config is exactly
    # the long-chain shape the assoc backward targets
    bptt: str = "sequential"

    @property
    def embed(self) -> int:
        return self.embed_size or self.hidden_size

    @property
    def cdtype(self):
        return jnp.dtype(self.compute_dtype)


def init_classifier(key: jax.Array, cfg: ClassifierConfig):
    keys = jax.random.split(key, 2 * cfg.num_layers + 2)
    embedding = (
        jax.random.normal(keys[0], (cfg.vocab_size, cfg.embed)) * 0.02
    ).astype(jnp.float32)
    fwd, bwd = [], []
    for i in range(cfg.num_layers):
        in_size = cfg.embed if i == 0 else 2 * cfg.hidden_size
        fwd.append(init_lstm_params(keys[1 + 2 * i], in_size, cfg.hidden_size))
        bwd.append(init_lstm_params(keys[2 + 2 * i], in_size, cfg.hidden_size))
    head = {
        "kernel": jax.nn.initializers.glorot_uniform()(
            keys[-1], (2 * cfg.hidden_size, cfg.num_classes), jnp.float32
        ),
        "bias": jnp.zeros((cfg.num_classes,), jnp.float32),
    }
    return {"embedding": embedding, "fwd": fwd, "bwd": bwd, "head": head}


def classifier_forward(
    params,
    tokens: jax.Array,
    lengths: jax.Array,
    cfg: ClassifierConfig,
    *,
    dropout_rng: jax.Array | None = None,
    deterministic: bool = True,
):
    """tokens [B,T] int32, lengths [B] → logits [B, num_classes]."""
    cdtype = None if cfg.cdtype == jnp.float32 else cfg.cdtype
    mask = sequence_mask(lengths, tokens.shape[1])
    xs = embed_lookup(params["embedding"], tokens)
    h_fwd = h_bwd = None
    for i, (pf, pb) in enumerate(zip(params["fwd"], params["bwd"])):
        # both directions in one dispatch: the stacked-direction fused
        # kernel when its plan fits, else two auto_lstm_scan calls
        ((h_fwd, _), ys_f), ((h_bwd, _), ys_b) = bidir_lstm_scan(
            pf, pb, xs, mask=mask, compute_dtype=cdtype,
            remat_chunk=cfg.remat_chunk, use_pallas=cfg.use_pallas,
            bptt=cfg.bptt,
        )
        xs = jnp.concatenate([ys_f, ys_b], axis=-1)
        if i < cfg.num_layers - 1 and cfg.dropout > 0.0 and not deterministic:
            dropout_rng, xs = dropout(dropout_rng, cfg.dropout, xs)
    final = jnp.concatenate([h_fwd, h_bwd], axis=-1)  # [B, 2H]
    if cfg.dropout > 0.0 and not deterministic:
        dropout_rng, final = dropout(dropout_rng, cfg.dropout, final)
    head = params["head"]
    return (
        jnp.dot(final.astype(head["kernel"].dtype), head["kernel"],
                preferred_element_type=jnp.float32)
        + head["bias"]
    )


def classifier_loss(
    params,
    batch,
    cfg: ClassifierConfig,
    *,
    dropout_rng=None,
    deterministic: bool = True,
):
    """batch: {"tokens" [B,T], "lengths" [B], "labels" [B], "valid" [B]}.
    Mean softmax cross-entropy over valid rows; aux carries accuracy."""
    logits = classifier_forward(
        params, batch["tokens"], batch["lengths"], cfg,
        dropout_rng=dropout_rng, deterministic=deterministic,
    )
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    nll = -jnp.take_along_axis(logp, batch["labels"][:, None], axis=-1)[:, 0]
    w = batch.get("valid")
    w = jnp.ones_like(nll) if w is None else w.astype(nll.dtype)
    denom = jnp.maximum(w.sum(), 1.0)
    loss = (nll * w).sum() / denom
    acc = ((jnp.argmax(logits, axis=-1) == batch["labels"]) * w).sum() / denom
    return loss, {"loss": loss, "accuracy": acc}
