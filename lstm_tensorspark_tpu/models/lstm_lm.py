"""LSTM language model: embedding → stacked LSTM → softmax head.

Reference parity: SURVEY.md §2 "Multi-layer / network wrapper" [P] — stacks
cells over layers, unrolls over time, projection + softmax head,
cross-entropy loss. Covers BASELINE.md configs 1 (PTB char, 1×128),
3 (WikiText-2 word, 2×650) and 5 (WikiText-103, 4×1024) by hyperparameters.

Params are a plain pytree (dict of arrays / LSTMParams), the step is a pure
function — this is what lets the same code run under jit, grad, shard_map and
the multi-chip dry-run without modification.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from ..ops.embedding import embed_lookup, selected_logits
from ..ops.lstm_cell import LSTMParams, init_lstm_params, zero_carry
from ..ops.scan import stacked_lstm_scan

# Above this vocab size lm_loss switches to the vocab-chunked cross-entropy
# (ops/xent.py), which bounds loss memory at O(N·Vc) instead of O(N·V).
# MEASURED on v5e: at V=33k/50k the chunked path is 16-18% SLOWER than the
# plain logsumexp loss (XLA already fuses the head matmul + reduction well;
# the scan serializes chunk matmuls and doubles the exp work), so the
# threshold sits ABOVE those configs — the chunked path is a memory
# capability for vocabularies whose [B,T,V] logits would not fit HBM,
# not a throughput optimisation.
_CHUNKED_XENT_MIN_V = 2**17


@dataclasses.dataclass(frozen=True)
class LMConfig:
    vocab_size: int
    hidden_size: int = 128
    num_layers: int = 1
    embed_size: int | None = None  # defaults to hidden_size
    dropout: float = 0.0
    tie_embeddings: bool = False
    compute_dtype: str = "float32"  # "bfloat16" for MXU-friendly matmuls
    remat_chunk: int | None = None
    scan_unroll: int = 1
    # fused Pallas recurrence kernel (ops/pallas_lstm.py) when shapes/platform
    # allow; falls back to lax.scan per layer otherwise
    use_pallas: bool = False
    # BPTT mode for the recurrence (ops/parallel_scan.py): "sequential",
    # "assoc" (parallel-scan backward), or "auto" (assoc when the memory
    # plan fits and T is long enough). Library default stays sequential;
    # `cli train --bptt-mode` defaults to auto.
    bptt: str = "sequential"
    # dtype of the materialized [B,T,V] logits array. At the word-LM vocab
    # sizes every pass over that array is an HBM-bandwidth cost (fwd write,
    # logsumexp read, dlogits write + three backward reads — ~300 MB each
    # at V=33k); "bfloat16" halves all of them (+25% measured on config 3)
    # while the logsumexp/NLL itself still runs in f32 over the upcast
    # values. Default float32 — opt-in numerics trade. No effect on the
    # chunked-xent path (V >= _CHUNKED_XENT_MIN_V), which never
    # materializes the array this flag exists to shrink.
    logits_dtype: str = "float32"

    @property
    def embed(self) -> int:
        return self.embed_size or self.hidden_size

    @property
    def cdtype(self):
        return jnp.dtype(self.compute_dtype)

    @property
    def ldtype(self):
        return jnp.dtype(self.logits_dtype)


def init_lm(key: jax.Array, cfg: LMConfig):
    """Initialize the LM parameter pytree."""
    if cfg.tie_embeddings and cfg.embed != cfg.hidden_size:
        raise ValueError("tie_embeddings requires embed_size == hidden_size")
    keys = jax.random.split(key, cfg.num_layers + 2)
    embedding = (
        jax.random.normal(keys[0], (cfg.vocab_size, cfg.embed)) * 0.02
    ).astype(jnp.float32)
    layers = []
    for i in range(cfg.num_layers):
        in_size = cfg.embed if i == 0 else cfg.hidden_size
        layers.append(init_lstm_params(keys[1 + i], in_size, cfg.hidden_size))
    params = {"embedding": embedding, "layers": layers}
    if not cfg.tie_embeddings:
        params["head"] = {
            "kernel": jax.nn.initializers.glorot_uniform()(
                keys[-1], (cfg.hidden_size, cfg.vocab_size), jnp.float32
            ),
            "bias": jnp.zeros((cfg.vocab_size,), jnp.float32),
        }
    else:
        params["head"] = {"bias": jnp.zeros((cfg.vocab_size,), jnp.float32)}
    return params


def init_carries(cfg: LMConfig, batch: int):
    return [zero_carry(batch, cfg.hidden_size) for _ in range(cfg.num_layers)]


def lm_backbone(
    params,
    tokens: jax.Array,
    cfg: LMConfig,
    *,
    carries=None,
    mask: jax.Array | None = None,
    dropout_rng: jax.Array | None = None,
    deterministic: bool = True,
):
    """tokens [B, T] int32 → (per-layer final carries, pre-head
    activations [B, T, H]).

    ``mask`` [B, T] bool (optional) freezes the recurrent carries at False
    steps (ops/scan.py), so right-padded batches end with each row's true
    final state — the serving engine's bucket-padded prefill (serve/).
    """
    cdtype = cfg.cdtype
    # embed_lookup: gather forward; at small V the gradient is an MXU
    # matmul, not a scatter (ops/embedding.py — measured 28 us/step saved
    # at the config-1 shape)
    xs = embed_lookup(params["embedding"], tokens)
    return stacked_lstm_scan(
        params["layers"],
        xs,
        carries,
        mask=mask,
        dropout_rate=cfg.dropout,
        dropout_rng=dropout_rng,
        deterministic=deterministic,
        compute_dtype=None if cdtype == jnp.float32 else cdtype,
        remat_chunk=cfg.remat_chunk,
        unroll=cfg.scan_unroll,
        use_pallas=cfg.use_pallas,
        bptt=cfg.bptt,
    )


def _head_kernel(params, cfg: LMConfig):
    head = params["head"]
    kernel = params["embedding"].T if cfg.tie_embeddings else head["kernel"]
    return kernel, head["bias"]


def lm_forward(
    params,
    tokens: jax.Array,
    cfg: LMConfig,
    *,
    carries=None,
    dropout_rng: jax.Array | None = None,
    deterministic: bool = True,
):
    """tokens [B, T] int32 → (logits [B, T, V], final per-layer carries)."""
    finals, ys = lm_backbone(
        params, tokens, cfg, carries=carries, dropout_rng=dropout_rng,
        deterministic=deterministic,
    )
    kernel, bias = _head_kernel(params, cfg)
    logits = (
        jnp.dot(ys.astype(kernel.dtype), kernel,
                preferred_element_type=cfg.ldtype)
        + bias.astype(cfg.ldtype)
    )
    return logits, finals


def lm_loss(
    params,
    batch,
    cfg: LMConfig,
    *,
    carries=None,
    dropout_rng=None,
    deterministic: bool = True,
):
    """Next-token cross-entropy (mean over B*T tokens), as in the reference's
    ``xent(softmax(h·W_out), y)`` head (SURVEY.md §3.2).

    batch: dict with "inputs" [B,T] and "targets" [B,T] int32.
    Returns (loss, aux) with aux = {"loss", "tokens", "carries"}.
    """
    if cfg.vocab_size >= _CHUNKED_XENT_MIN_V:
        # big-vocab path: vocab-chunked cross-entropy (ops/xent.py) — the
        # [B,T,V] logits/dlogits arrays (~300-400 MB at V=33k/50k) never
        # exist in HBM; head matmul recomputed chunk-wise in the backward
        finals, ys = lm_backbone(
            params, batch["inputs"], cfg, carries=carries,
            dropout_rng=dropout_rng, deterministic=deterministic,
        )
        kernel, bias = _head_kernel(params, cfg)
        from ..ops.xent import chunked_xent_mean

        loss = chunked_xent_mean(ys.astype(jnp.float32), kernel, bias,
                                 batch["targets"])
        nll_size = batch["targets"].size
    else:
        logits, finals = lm_forward(
            params,
            batch["inputs"],
            cfg,
            carries=carries,
            dropout_rng=dropout_rng,
            deterministic=deterministic,
        )
        # nll via logsumexp, NOT log_softmax: identical math
        # (nll = lse - z_t) without the full [B,T,V] log-prob array.
        # selected_logits: one-hot multiply-reduce at small V (bit-equal to
        # the gather — the sum has one nonzero term — but fused and
        # scatter-free in the backward; 43 us/step at the config-1 shape)
        logits_f = logits.astype(jnp.float32)
        lse = jax.nn.logsumexp(logits_f, axis=-1)
        tgt = selected_logits(logits_f, batch["targets"])
        loss = jnp.mean(lse - tgt)
        nll_size = batch["targets"].size
    aux = {
        "loss": loss,
        "tokens": jnp.array(nll_size, jnp.float32),
        "carries": finals,
    }
    return loss, aux
