"""Crash supervisor: relaunch training until completion, resuming from the
latest checkpoint.

Reference parity: SURVEY.md §5 "Failure detection / elastic recovery" — the
reference inherits lineage-based task retry from Spark (a failed partition's
task is re-run automatically) but loses the whole run on a driver crash.
XLA has no partition-retry equivalent (the step is one program), so the
rebuild's fault story is checkpoint-restart; this module closes the loop by
supervising the process the way Spark's driver supervises tasks:

    python -m lstm_tensorspark_tpu.supervise --max-restarts 3 -- \
        --dataset ptb_char --num-steps 10000 \
        --checkpoint-dir ckpt --checkpoint-every 50

The child is the normal CLI (same flags). On a nonzero exit the supervisor
relaunches it with ``--resume`` injected, so the run continues from the
last checkpoint; ``--num-steps`` is resume-inclusive (cli.py), so the total
step budget holds across restarts. Exit code: the child's final exit code —
0 on success, or the LAST failing child's code when restarts are exhausted
(so callers can still distinguish failure classes, e.g. OOM kills).

Stall detection (``--stall-timeout N``): crashes are not the only failure
mode — this environment's tunneled TPU backend has been observed to WEDGE
(a dispatch that never returns; the child hangs forever without exiting).
With a stall timeout the supervisor watches the child's output: if no line
arrives for N seconds it terminates the child (SIGTERM, then SIGKILL) and
treats it like a signal death — retryable, relaunched with ``--resume``.
Size N well above the longest silent phase of the run (first XLA compile +
the --log-every cadence).

Serving children: ``supervise -- serve --http --session-dir d ...``
relaunches a crashed server WITHOUT injecting ``--resume`` (a training
flag serve's parser rejects); clients' kept sessions survive the restart
through serve's own disk tier (``--session-dir``), resuming
token-identically from their last completed request.

Self-healing (resilience plane): restart delays back off exponentially
with jitter (--restart-delay is the base, --max-delay the cap); known
retryable exit codes (resilience/exit_codes.py: anomaly aborts, injected
crash drills) always relaunch; and a forward-progress check declares the
run POISONED (dedicated exit code) when consecutive failures stop
advancing the latest checkpoint step — the crash-loop case a fixed retry
budget would grind through pointlessly. Drills: arm
``--faults``/``LSTM_TSP_FAULTS`` on the child (resilience/faults.py) or
run tools/chaos_smoke.py.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import threading
import time

from . import obs
from .resilience import ckpt_layout
from .resilience.backoff import backoff_delay
from .resilience.exit_codes import POISON_RC, RETRYABLE_RCS, USAGE_RC

__all__ = ["backoff_delay", "supervise", "main"]  # backoff_delay is
# re-exported on purpose: it moved to resilience/backoff.py (the serve
# loadgen's 429 retry path shares the one implementation) and existing
# callers/tests keep importing it from here.


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="lstm_tensorspark_tpu.supervise",
        description="relaunch-on-crash wrapper around the training CLI",
    )
    p.add_argument("--max-restarts", type=int, default=3,
                   help="restarts after the first attempt (default 3)")
    p.add_argument("--restart-delay", type=float, default=1.0,
                   help="BASE restart delay in seconds; attempts back off "
                        "exponentially (base * 2^(attempt-1), capped by "
                        "--max-delay) with up to +50%% jitter so a fleet of "
                        "supervisors never relaunches in lockstep")
    p.add_argument("--max-delay", type=float, default=30.0,
                   help="exponential-backoff cap in seconds (default 30)")
    p.add_argument("--no-progress-limit", type=int, default=2,
                   help="give up with the poison exit code "
                        f"({POISON_RC}) after this many CONSECUTIVE "
                        "failures during which the latest checkpoint step "
                        "did not advance — a crash loop that replays the "
                        "same step forever is unrecoverable by restarting. "
                        "Signal deaths (preemption/OOM-kill/stall-kill) "
                        "never count: two preemptions inside one long "
                        "checkpoint interval is bad luck, not poison. "
                        "0 disables (needs --checkpoint-dir to measure)")
    p.add_argument("--stall-timeout", type=float, default=None,
                   help="kill + relaunch the child if it prints NOTHING for "
                        "this many seconds (hang/wedge detection; size it "
                        "above first-compile time + the log cadence; must "
                        "be > 0; NOTE: the watchdog merges the child's "
                        "stderr into stdout so one stream carries the "
                        "liveness signal)")
    p.add_argument("--registry-dir", default=None,
                   help="model registry directory (serve/registry.py): "
                        "after every child exit, promote the run's best "
                        "checkpoint (best.msgpack, versioned by its step) "
                        "into the registry so a serving fleet can roll it "
                        "without a restart; requires --checkpoint-dir in "
                        "the child's flags")
    p.add_argument("--registry-model", default="default",
                   help="model id to publish under (default: 'default' — "
                        "the serve engine's boot model id, so rollouts "
                        "reach existing sessions)")
    p.add_argument("--rollout-url", default=None,
                   help="serve fleet base URL (e.g. http://host:8000): "
                        "POST /rollout after each NEW publication so the "
                        "fleet rolls the fresh best automatically; best "
                        "effort — an unreachable fleet only loses the "
                        "trigger, not the artifact")
    p.add_argument("cli_args", nargs=argparse.REMAINDER,
                   help="-- followed by the training CLI flags")
    return p


def latest_checkpoint_step(directory: str) -> int | None:
    """Newest restorable checkpoint step in ``directory`` (None when the
    directory is missing/empty) — the forward-progress signal: a restart
    that cannot advance this number is a crash loop. Filename patterns
    come from resilience/ckpt_layout.py, the jax-free naming authority
    shared with train/checkpoint.py."""
    try:
        names = os.listdir(directory)
    except OSError:
        return None
    steps = [int(m.group(1)) for n in names
             if (m := ckpt_layout.RESTORABLE_PAT.match(n))]
    return max(steps, default=None)


def _deterministic_failure(rc, lifetime: float, subprocess_runner: bool) -> bool:
    """Deterministic failures can never be fixed by a retry: argparse usage
    errors exit 2, and flag-validation SystemExits die within well under a
    second (before any training state exists). Retrying those burns the
    whole restart budget on a run that cannot succeed. The lifetime
    heuristic only applies to real child processes — injected test runners
    return instantly by construction — never to signal deaths (rc >= 128):
    an early OOM-kill or preemption is exactly the transient class the
    supervisor exists to retry; and never to the KNOWN-retryable codes
    (RETRYABLE_RCS: anomaly aborts, injected crash drills), which are
    emitted deliberately by code that expects a restart-from-checkpoint to
    help."""
    if rc == USAGE_RC:
        return True
    return (subprocess_runner and rc is not None and 0 < rc < 128
            and rc not in RETRYABLE_RCS and lifetime < 1.0)


def _checkpoint_dir_of(cli_args: list[str]) -> str | None:
    for i, a in enumerate(cli_args):
        if a == "--checkpoint-dir" and i + 1 < len(cli_args):
            return cli_args[i + 1]
        if a.startswith("--checkpoint-dir="):
            return a.split("=", 1)[1]
    return None


def _publish_best(ckpt_dir: str, registry_dir: str, model_id: str, *,
                  rollout_url: str | None = None) -> dict | None:
    """Promote the run's best checkpoint into a model registry
    (serve/registry.py) so the serving side can roll it out without a
    restart. The raw ``best.msgpack`` bytes are published VERBATIM as a
    ``best_state`` artifact versioned by its step — the supervisor never
    deserializes multi-MB weights, and re-publication of an already-
    promoted step is a no-op (registry versions are immutable). Returns
    the published metadata record, or None when there was nothing new
    (or nothing valid) to promote. Sharded bests (``best.complete``
    marker sets) are skipped: promotion needs the single-artifact form a
    1-process training run writes."""
    import json

    meta_path = os.path.join(ckpt_dir, "best.json")
    try:
        with open(meta_path) as f:
            best = json.load(f)
        step = int(best["step"])
    except (OSError, ValueError, KeyError, TypeError):
        return None  # no best yet — nothing to promote
    # heavy imports stay OUT of module scope: the supervisor is
    # import-light by contract (no jax/backend init) unless publication
    # is armed and a best checkpoint actually exists
    from .serve.registry import ModelRegistry
    from .train.checkpoint import CorruptCheckpointError, read_verified

    path = os.path.join(ckpt_dir, "best.msgpack")
    try:
        payload = read_verified(path)
    except (CorruptCheckpointError, OSError) as e:
        print(f"supervise: best checkpoint not publishable ({e})",
              file=sys.stderr)
        return None
    reg = ModelRegistry(registry_dir)
    try:
        meta = reg.publish(model_id, payload, kind="best_state",
                           version=step,
                           parent=f"best.msgpack @ step {step}")
    except ValueError:
        return None  # this step is already in the registry
    print(f"supervise: published {model_id} v{step} "
          f"({len(payload)} bytes) to {registry_dir}", file=sys.stderr)
    if rollout_url:
        _trigger_rollout(rollout_url, model_id, step)
    return meta


def _trigger_rollout(url: str, model_id: str, version: int) -> None:
    """Ask a serve fleet (``POST /rollout``) to roll the version that was
    just published. Best effort: an unreachable fleet only loses the
    TRIGGER — the artifact is in the registry, and an operator (or the
    next publication) can roll it later."""
    import json
    import urllib.request

    body = json.dumps({"model": model_id, "version": version}).encode()
    req = urllib.request.Request(
        url.rstrip("/") + "/rollout", data=body,
        headers={"Content-Type": "application/json"}, method="POST")
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            print(f"supervise: rollout of {model_id} v{version} accepted "
                  f"({resp.status})", file=sys.stderr)
    except OSError as e:
        print(f"supervise: rollout trigger failed ({e}) — artifact is "
              "published; roll it manually via POST /rollout",
              file=sys.stderr)


def run_with_stall_watch(cmd: list[str], stall_timeout: float) -> int:
    """Run ``cmd``, relaying its output line-by-line; if NO line arrives for
    ``stall_timeout`` seconds, terminate (then kill) it. Returns the exit
    code — negative (signal death) when the watchdog fired, so the caller's
    retry logic treats a stall exactly like a crash-by-signal."""
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    last = [time.monotonic()]

    def pump():
        for line in proc.stdout:
            last[0] = time.monotonic()
            print(line, end="", flush=True)
        proc.stdout.close()

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    while True:
        rc = proc.poll()
        if rc is not None:
            t.join(timeout=5)
            return rc
        if time.monotonic() - last[0] > stall_timeout:
            print(f"supervise: child silent for >{stall_timeout:.0f}s — "
                  "stalled; terminating", file=sys.stderr)
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
            t.join(timeout=5)
            return proc.returncode
        time.sleep(min(1.0, stall_timeout / 4))


def supervise(cli_args: list[str], *, max_restarts: int = 3,
              restart_delay: float = 1.0, max_delay: float = 30.0,
              no_progress_limit: int = 2,
              stall_timeout: float | None = None,
              registry_dir: str | None = None,
              registry_model: str = "default",
              rollout_url: str | None = None,
              runner=None, rand=None) -> int:
    """Run the CLI (as a subprocess by default); relaunch with --resume on
    failure. ``runner(argv) -> int`` is injectable for tests; ``rand``
    feeds the backoff jitter (tests pass ``lambda: 0.0``).

    Self-healing contract (resilience/exit_codes.py): restart delays back
    off exponentially with jitter; a known-retryable child exit
    (``RETRYABLE_RCS`` — injected crash drills, anomaly aborts) is always
    relaunched even when the child died fast; and when ``--checkpoint-dir``
    is visible in the child's flags, the latest checkpoint step must
    ADVANCE between failures — ``no_progress_limit`` consecutive
    no-progress failures end the run with ``POISON_RC`` instead of
    replaying the same doomed step until the restart budget burns out."""
    if stall_timeout is not None and stall_timeout <= 0:
        # 0 would silently mean "no watchdog" and a negative value would
        # kill every healthy child at launch — both are operator mistakes
        raise SystemExit(
            f"--stall-timeout must be > 0, got {stall_timeout}"
        )
    # a supervised SERVE child (``supervise -- serve --http ...``) is the
    # serve-session resilience drill: relaunches must NOT inject --resume
    # (the serve parser has no such flag — argparse would exit 2 and the
    # deterministic-failure check would give up on a perfectly retryable
    # server), and checkpoint-step forward progress is a training notion
    # (serve's --checkpoint-dir is read-only params restore). Session
    # continuity across the restart comes from serve's own disk tier
    # (--session-dir, serve/state_cache.py SessionTiers).
    serve_child = bool(cli_args) and cli_args[0] == "serve"
    ckpt_dir = None if serve_child else _checkpoint_dir_of(cli_args)
    if ckpt_dir is None and not serve_child:
        print("supervise: warning: no --checkpoint-dir — a crash will "
              "restart from step 0 (and forward-progress poison detection "
              "is off)", file=sys.stderr)
    subprocess_runner = runner is None
    if runner is None:
        def runner(argv):
            cmd = [sys.executable, "-m", "lstm_tensorspark_tpu.cli", *argv]
            if stall_timeout:
                return run_with_stall_watch(cmd, stall_timeout)
            return subprocess.run(cmd).returncode

    # telemetry (obs/): restart/backoff accounting in the process-wide
    # registry — a long-lived supervisor's churn becomes scrapeable (and a
    # MetricsLogger.log_registry snapshot in any co-resident run carries it)
    m_restarts = obs.REGISTRY.counter(
        "supervise_restarts_total", "child relaunches after failure")
    m_backoff = obs.REGISTRY.counter(
        "supervise_backoff_seconds_total", "total time slept backing off")
    m_verdicts = obs.REGISTRY.counter(
        "supervise_terminal_total",
        "terminal supervisor verdicts (poisoned/deterministic/exhausted)",
        labelnames=("verdict",))
    attempt = 0
    _UNSET = object()
    prev_ckpt_step = _UNSET  # latest checkpoint step at the PREVIOUS failure
    no_progress = 0
    while True:
        argv = list(cli_args)
        if attempt > 0 and not serve_child:
            # --resume-best is a ONE-TIME rewind (and mutually exclusive
            # with --resume in the CLI): after the first attempt performed
            # it, relaunches must continue the fine-tune's own lineage
            argv = [a for a in argv if a != "--resume-best"]
            if "--resume" not in argv:
                argv.append("--resume")
        start = time.monotonic()
        rc = runner(argv)
        lifetime = time.monotonic() - start
        if rc is not None and rc < 0:
            rc = 128 - rc  # signal death -> conventional 128+signum status
        if registry_dir is not None and ckpt_dir is not None:
            # promotion runs on EVERY exit, not just success: a crashed
            # attempt may still have improved the best checkpoint, and
            # serving the newest best should not wait out the restart
            # budget. Already-published steps no-op inside.
            try:
                _publish_best(ckpt_dir, registry_dir, registry_model,
                              rollout_url=rollout_url)
            except Exception as e:  # registry trouble must not eat the
                # supervisor's retry loop — the child's lifecycle wins
                print(f"supervise: registry publication failed: {e}",
                      file=sys.stderr)
        if rc == 0:
            if attempt > 0:
                print(f"supervise: succeeded after {attempt} restart(s)",
                      file=sys.stderr)
            return 0
        if _deterministic_failure(rc, lifetime, subprocess_runner):
            print(f"supervise: child failed deterministically (exit {rc} "
                  f"after {lifetime:.2f}s) — not retrying", file=sys.stderr)
            m_verdicts.labels(verdict="deterministic").inc()
            return rc
        # Forward-progress check: between consecutive FAILURES the latest
        # restorable checkpoint step must advance, or the restarts are a
        # crash loop replaying the same step (poisoned data window, broken
        # model, corrupt-beyond-fallback checkpoints). Declaring poison
        # needs `no_progress_limit` consecutive stalls — a single repeat is
        # legitimate (e.g. a crash landing just before the next save).
        # Signal deaths (rc >= 128: preemption, OOM-kill, the stall
        # watchdog) never count toward poison — two preemptions landing
        # inside one long checkpoint interval is bad luck, not a doomed
        # step, and the transient class gets the full restart budget.
        # Also requires an actual checkpoint to exist (cur is not None):
        # a run that has not saved yet — first checkpoint interval still
        # open, or --checkpoint-every 0 with the dir used only for
        # keep-best/fault markers — has nothing to measure progress BY,
        # and transient early crashes must get the full restart budget.
        if (ckpt_dir is not None and no_progress_limit > 0
                and rc is not None and rc < 128):
            cur = latest_checkpoint_step(ckpt_dir)
            if (prev_ckpt_step is not _UNSET and cur is not None
                    and cur == prev_ckpt_step):
                no_progress += 1
                if no_progress >= no_progress_limit:
                    print(f"supervise: POISONED — {no_progress} consecutive "
                          f"failures without checkpoint progress (stuck at "
                          f"step {cur}); giving up (exit {POISON_RC})",
                          file=sys.stderr)
                    m_verdicts.labels(verdict="poisoned").inc()
                    return POISON_RC
            else:
                no_progress = 0
            prev_ckpt_step = cur
        if attempt >= max_restarts:
            print(f"supervise: giving up after {attempt} restart(s) "
                  f"(last exit code {rc})", file=sys.stderr)
            m_verdicts.labels(verdict="exhausted").inc()
            return rc
        attempt += 1
        delay = backoff_delay(restart_delay, attempt, cap=max_delay,
                              rand=rand)
        m_restarts.inc()
        m_backoff.inc(delay)
        print(f"supervise: child exited {rc}; restart {attempt}/"
              f"{max_restarts} in {delay:.1f}s", file=sys.stderr)
        time.sleep(delay)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    cli_args = args.cli_args
    if cli_args and cli_args[0] == "--":
        cli_args = cli_args[1:]
    if not cli_args:
        raise SystemExit("usage: ... supervise [--max-restarts N] -- <cli flags>")
    return supervise(
        cli_args,
        max_restarts=args.max_restarts,
        restart_delay=args.restart_delay,
        max_delay=args.max_delay,
        no_progress_limit=args.no_progress_limit,
        stall_timeout=args.stall_timeout,
        registry_dir=args.registry_dir,
        registry_model=args.registry_model,
        rollout_url=args.rollout_url,
    )


if __name__ == "__main__":
    raise SystemExit(main())
