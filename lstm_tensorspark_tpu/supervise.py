"""Crash supervisor: relaunch training until completion, resuming from the
latest checkpoint.

Reference parity: SURVEY.md §5 "Failure detection / elastic recovery" — the
reference inherits lineage-based task retry from Spark (a failed partition's
task is re-run automatically) but loses the whole run on a driver crash.
XLA has no partition-retry equivalent (the step is one program), so the
rebuild's fault story is checkpoint-restart; this module closes the loop by
supervising the process the way Spark's driver supervises tasks:

    python -m lstm_tensorspark_tpu.supervise --max-restarts 3 -- \
        --dataset ptb_char --num-steps 10000 \
        --checkpoint-dir ckpt --checkpoint-every 50

The child is the normal CLI (same flags). On a nonzero exit the supervisor
relaunches it with ``--resume`` injected, so the run continues from the
last checkpoint; ``--num-steps`` is resume-inclusive (cli.py), so the total
step budget holds across restarts. Exit code: the child's final exit code —
0 on success, or the LAST failing child's code when restarts are exhausted
(so callers can still distinguish failure classes, e.g. OOM kills).

Stall detection (``--stall-timeout N``): crashes are not the only failure
mode — this environment's tunneled TPU backend has been observed to WEDGE
(a dispatch that never returns; the child hangs forever without exiting).
With a stall timeout the supervisor watches the child's output: if no line
arrives for N seconds it terminates the child (SIGTERM, then SIGKILL) and
treats it like a signal death — retryable, relaunched with ``--resume``.
Size N well above the longest silent phase of the run (first XLA compile +
the --log-every cadence).
"""

from __future__ import annotations

import argparse
import subprocess
import sys
import threading
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="lstm_tensorspark_tpu.supervise",
        description="relaunch-on-crash wrapper around the training CLI",
    )
    p.add_argument("--max-restarts", type=int, default=3,
                   help="restarts after the first attempt (default 3)")
    p.add_argument("--restart-delay", type=float, default=1.0,
                   help="seconds between attempts")
    p.add_argument("--stall-timeout", type=float, default=None,
                   help="kill + relaunch the child if it prints NOTHING for "
                        "this many seconds (hang/wedge detection; size it "
                        "above first-compile time + the log cadence; must "
                        "be > 0; NOTE: the watchdog merges the child's "
                        "stderr into stdout so one stream carries the "
                        "liveness signal)")
    p.add_argument("cli_args", nargs=argparse.REMAINDER,
                   help="-- followed by the training CLI flags")
    return p


def run_with_stall_watch(cmd: list[str], stall_timeout: float) -> int:
    """Run ``cmd``, relaying its output line-by-line; if NO line arrives for
    ``stall_timeout`` seconds, terminate (then kill) it. Returns the exit
    code — negative (signal death) when the watchdog fired, so the caller's
    retry logic treats a stall exactly like a crash-by-signal."""
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    last = [time.monotonic()]

    def pump():
        for line in proc.stdout:
            last[0] = time.monotonic()
            print(line, end="", flush=True)
        proc.stdout.close()

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    while True:
        rc = proc.poll()
        if rc is not None:
            t.join(timeout=5)
            return rc
        if time.monotonic() - last[0] > stall_timeout:
            print(f"supervise: child silent for >{stall_timeout:.0f}s — "
                  "stalled; terminating", file=sys.stderr)
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
            t.join(timeout=5)
            return proc.returncode
        time.sleep(min(1.0, stall_timeout / 4))


def supervise(cli_args: list[str], *, max_restarts: int = 3,
              restart_delay: float = 1.0, stall_timeout: float | None = None,
              runner=None) -> int:
    """Run the CLI (as a subprocess by default); relaunch with --resume on
    failure. ``runner(argv) -> int`` is injectable for tests."""
    if stall_timeout is not None and stall_timeout <= 0:
        # 0 would silently mean "no watchdog" and a negative value would
        # kill every healthy child at launch — both are operator mistakes
        raise SystemExit(
            f"--stall-timeout must be > 0, got {stall_timeout}"
        )
    if not any(a == "--checkpoint-dir" or a.startswith("--checkpoint-dir=")
               for a in cli_args):
        print("supervise: warning: no --checkpoint-dir — a crash will "
              "restart from step 0", file=sys.stderr)
    subprocess_runner = runner is None
    if runner is None:
        def runner(argv):
            cmd = [sys.executable, "-m", "lstm_tensorspark_tpu.cli", *argv]
            if stall_timeout:
                return run_with_stall_watch(cmd, stall_timeout)
            return subprocess.run(cmd).returncode

    attempt = 0
    while True:
        argv = list(cli_args)
        if attempt > 0:
            # --resume-best is a ONE-TIME rewind (and mutually exclusive
            # with --resume in the CLI): after the first attempt performed
            # it, relaunches must continue the fine-tune's own lineage
            argv = [a for a in argv if a != "--resume-best"]
            if "--resume" not in argv:
                argv.append("--resume")
        start = time.monotonic()
        rc = runner(argv)
        lifetime = time.monotonic() - start
        if rc is not None and rc < 0:
            rc = 128 - rc  # signal death -> conventional 128+signum status
        if rc == 0:
            if attempt > 0:
                print(f"supervise: succeeded after {attempt} restart(s)",
                      file=sys.stderr)
            return 0
        # Deterministic failures can never be fixed by a retry: argparse
        # usage errors exit 2, and flag-validation SystemExits die within
        # well under a second (before any training state exists). Retrying
        # those burns the whole restart budget on a run that cannot succeed.
        # The lifetime heuristic only applies to real child processes —
        # injected test runners return instantly by construction — and never
        # to signal deaths (rc >= 128): an early OOM-kill or preemption is
        # exactly the transient class the supervisor exists to retry.
        if rc == 2 or (subprocess_runner and rc is not None and 0 < rc < 128
                       and lifetime < 1.0):
            print(f"supervise: child failed deterministically (exit {rc} "
                  f"after {lifetime:.2f}s) — not retrying", file=sys.stderr)
            return rc
        if attempt >= max_restarts:
            print(f"supervise: giving up after {attempt} restart(s) "
                  f"(last exit code {rc})", file=sys.stderr)
            return rc
        attempt += 1
        print(f"supervise: child exited {rc}; restart {attempt}/"
              f"{max_restarts} in {restart_delay}s", file=sys.stderr)
        time.sleep(restart_delay)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    cli_args = args.cli_args
    if cli_args and cli_args[0] == "--":
        cli_args = cli_args[1:]
    if not cli_args:
        raise SystemExit("usage: ... supervise [--max-restarts N] -- <cli flags>")
    return supervise(
        cli_args,
        max_restarts=args.max_restarts,
        restart_delay=args.restart_delay,
        stall_timeout=args.stall_timeout,
    )


if __name__ == "__main__":
    raise SystemExit(main())
