"""Vocabulary indexing tuned for TPU: gather/scatter vs one-hot matmul.

Reference parity: SURVEY.md §2 "Data pipeline" / "Multi-layer network
wrapper" rows — the reference vectorizes tokens by index and trains an
embedding + softmax head; HOW the lookup runs is an implementation choice
the TPU makes differently.

Why this module exists (measured, not guessed): profiling the config-1
train step on v5e showed 48% of device time in two vocabulary-indexing
kernels — the cross-entropy target-logit gather (43 us/step) and the
embedding-gradient scatter-add (28 us/step) — while the fused Pallas
recurrence pair ran at its roofline (29 us/step combined). TPU gathers and
scatter-adds over the minor dimension serialize; at small vocabularies the
same operation expressed as a one-hot contraction runs on the MXU in ~1 us.

Two helpers, both gated on vocab size:

- ``embed_lookup``: forward stays the bit-identical row gather; at
  V <= _MM_GRAD_MAX_V a custom VJP computes the embedding gradient as
  ``one_hot(tokens)^T @ g`` (an MXU matmul) instead of XLA's scatter-add.
  Above the threshold the one-hot factor itself would dominate (e.g.
  273 MB at V=50k for a 4096-token batch), so the scatter stays.

- ``selected_logits``: ``logits[..., target]`` as a one-hot
  multiply-reduce at small V. XLA fuses the iota/compare one-hot into the
  reduction loop (nothing materializes in HBM) and the backward is
  elementwise — no gather forward, no scatter backward. Above the
  threshold the take_along_axis gather stays: its cost is bounded by
  token count while a second full read of [N, V] logits is not.

Thresholds are conservative 2^11; the configs that matter sit far on
either side (V=26..370 vs V=25k..50k).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# Above this vocab size the one-hot contraction's [N, V] factor costs more
# (FLOPs and/or HBM traffic) than the serialized gather/scatter it replaces.
_MM_GRAD_MAX_V = 2048
_SELECT_MAX_V = 2048


@functools.partial(jax.custom_vjp, nondiff_argnums=())
def _embed_mm_grad(embedding: jax.Array, tokens: jax.Array) -> jax.Array:
    return jnp.take(embedding, tokens, axis=0)


def _embed_mm_grad_fwd(embedding, tokens):
    return jnp.take(embedding, tokens, axis=0), (tokens, embedding.shape)


def _embed_mm_grad_bwd(res, g):
    tokens, (V, E) = res
    # dE[v, e] = sum_n 1[tokens_n == v] * g[n, e]: contraction over the
    # flattened token axis on the MXU. The one-hot factor holds exact 0/1
    # in any float dtype; products are g or 0, so the result differs from
    # the scatter-add only by float summation order — PROVIDED the MXU
    # does not first round f32 cotangents to bf16 (TPU's DEFAULT matmul
    # precision does exactly that; measured 1.7e-2 max abs error vs the
    # scatter at H=128). HIGHEST keeps f32 operand fidelity, and the
    # matmul is ~1 us at the V<=2048 gate, so exactness is free.
    n = tokens.size
    oh = jax.nn.one_hot(tokens.reshape(n), V, dtype=g.dtype)
    dE = jax.lax.dot_general(
        oh, g.reshape(n, E),
        (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    ).astype(g.dtype)
    return dE, None


_embed_mm_grad.defvjp(_embed_mm_grad_fwd, _embed_mm_grad_bwd)


def embed_lookup(embedding: jax.Array, tokens: jax.Array) -> jax.Array:
    """``embedding[tokens]`` — row gather forward everywhere (bit-identical
    to ``jnp.take``); matmul-backward custom VJP at small vocab."""
    if embedding.shape[0] <= _MM_GRAD_MAX_V:
        return _embed_mm_grad(embedding, tokens)
    return jnp.take(embedding, tokens, axis=0)


def selected_logits(logits: jax.Array, targets: jax.Array) -> jax.Array:
    """``logits[..., targets]`` over the trailing vocab axis: one-hot
    multiply-reduce where it wins, gather elsewhere. targets has logits'
    shape minus the last axis.

    The two forms are BIT-EXACT equal (the sum has one nonzero term, and
    the one-hot backward writes exactly one cotangent per position), so
    the dispatch is pure performance policy: on TPU the one-hot fuses
    into the surrounding loss reduction at ANY vocab size and keeps the
    backward elementwise — measured at V=33k it is neutral with f32
    logits and +20% with bf16 logits, where the gather's backward scatter
    forces an f32 dlogits materialization. On CPU the fused one-hot pass
    costs real work at large V while the gather is a cheap row lookup, so
    large-V CPU keeps the gather (identical values either way)."""
    V = logits.shape[-1]
    if V <= _SELECT_MAX_V or jax.default_backend() == "tpu":
        oh = jax.nn.one_hot(targets, V, dtype=logits.dtype)
        return jnp.sum(logits * oh, axis=-1)
    return jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
