"""Sequence unrolling of the LSTM cell with `jax.lax.scan`.

Reference parity: SURVEY.md §3.2 — the reference unrolls the recurrence in a
Python ``for t in 1..T`` loop re-executed per batch through TF ``session.run``.
TPU-native replacement: the recurrence is a `lax.scan`, traced once and
compiled by XLA into a single on-device loop (static shapes, no per-step host
round-trips).

Long-sequence memory (SURVEY.md §7 "Hard parts"): BPTT through T steps stores
O(T) activations; ``remat_chunk`` wraps fixed-size chunks of the scan in
`jax.checkpoint`, storing only O(T/chunk) boundary carries and recomputing
inside chunks during the backward pass — the scan-with-remat crux kernel.

Variable-length sequences (SURVEY.md §7): a boolean ``mask`` freezes the carry
at padded steps, so the final (h, c) is each sequence's state at its true end,
and reversed scans over right-padded batches stay correct.

BPTT modes (``bptt=``): ``"sequential"`` (default) differentiates through
the scan with the ordinary reverse-mode transpose — a T-deep chain;
``"assoc"`` swaps in the parallel-scan backward of ops/parallel_scan.py
(BPPSA-style: the adjoint chain is an associative scan of per-step
Jacobian operators, O(log T) depth); ``"auto"`` picks assoc only when the
`parallel_scan.plan_bytes` memory model fits and T >= its threshold,
counting every fallback. Forward values are identical in every mode.

Masked + remat interaction: both the mask reshape and the chunked scan
require ``T % remat_chunk == 0`` — a silent tail chunk would give the two
bptt modes different step groupings for the same inputs, so indivisible
T raises instead (same error from `parallel_scan.assoc_lstm_scan`).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from jax import lax

from .lstm_cell import (
    LSTMParams,
    fuse_params,
    lstm_step_hoisted,
    zero_carry,
)


def lstm_scan(
    params: LSTMParams,
    xs: jax.Array,
    carry: tuple[jax.Array, jax.Array] | None = None,
    *,
    mask: jax.Array | None = None,
    reverse: bool = False,
    remat_chunk: int | None = None,
    compute_dtype=None,
    unroll: int = 1,
    bptt: str = "sequential",
):
    """Run the LSTM over a batch of sequences.

    Args:
      params: per-gate `LSTMParams` (fused once here, outside the scan).
      xs: inputs ``[B, T, D]`` (batch-major).
      carry: optional initial ``(h, c)`` each ``[B, H]``; zeros if None.
      mask: optional bool ``[B, T]``; False steps leave the carry unchanged.
      reverse: scan right-to-left (for the backward direction of a bi-LSTM).
      remat_chunk: if set, chunk size for `jax.checkpoint` rematerialisation
        (T must be divisible by it).
      compute_dtype: e.g. ``jnp.bfloat16`` for the matmuls; cell state and
        accumulation stay float32.
      unroll: `lax.scan` unroll factor (amortises loop overhead on TPU).
      bptt: ``"sequential"`` | ``"assoc"`` | ``"auto"`` — how the backward
        pass runs (module docstring; ops/parallel_scan.py). Values are
        mode-independent; gradients agree to numerical tolerance
        (tests/test_parallel_scan.py, tests/test_property_scan.py).

    Returns:
      ``((h_T, c_T), ys)`` with ``ys`` ``[B, T, H]`` (hidden state per step).
    """
    B, T, _ = xs.shape
    if bptt != "sequential":
        from .parallel_scan import assoc_lstm_scan, resolve_bptt

        if resolve_bptt(bptt, B, T, params.hidden_size,
                        remat_chunk=remat_chunk) == "assoc":
            return assoc_lstm_scan(
                params, xs, carry, mask=mask, reverse=reverse,
                remat_chunk=remat_chunk, compute_dtype=compute_dtype,
                unroll=unroll,
            )
    fused = fuse_params(params, compute_dtype=compute_dtype)
    if carry is None:
        carry = zero_carry(B, params.hidden_size)

    xs_t = jnp.moveaxis(xs, 0, 1)  # [T, B, D] — scan runs over the leading axis

    def project(x_td):
        # Input projection for a whole [t, B, D] block in ONE MXU matmul —
        # hoisted out of the scan so the sequential loop only carries the
        # unavoidable h @ recurrent (cuDNN-style split). float32 out.
        z = jnp.dot(
            x_td.astype(fused.kernel.dtype),
            fused.kernel,
            preferred_element_type=jnp.float32,
        )
        return z + fused.bias

    def step(c, inp):
        if mask is None:
            new_carry, y = lstm_step_hoisted(fused, c, inp)
        else:
            zx, m = inp
            (h_new, c_new), _ = lstm_step_hoisted(fused, c, zx)
            h = jnp.where(m, h_new, c[0])
            cc = jnp.where(m, c_new, c[1])
            new_carry, y = (h, cc), h
        return new_carry, y

    def with_mask(zx_t):
        if mask is None:
            return zx_t
        return (zx_t, jnp.moveaxis(mask, 0, 1)[..., None])

    if remat_chunk is None:
        final, ys = lax.scan(
            step, carry, with_mask(project(xs_t)), reverse=reverse, unroll=unroll
        )
    else:
        if T % remat_chunk != 0:
            raise ValueError(
                f"T={T} not divisible by remat_chunk={remat_chunk} — a "
                "tail chunk would silently change remat (and bptt-mode) "
                "semantics; pad or pick a divisor")
        n_chunks = T // remat_chunk

        def chunk_fn(c, chunk_inputs):
            # project per chunk, INSIDE the checkpoint: the [chunk, B, 4H]
            # activations are rematerialised, not stored — keeps the remat
            # memory bound at O(T/chunk) carries.
            x_td, m = chunk_inputs if mask is not None else (chunk_inputs, None)
            zx = project(x_td)
            inp = zx if m is None else (zx, m)
            return lax.scan(step, c, inp, reverse=reverse, unroll=unroll)

        chunk_fn = jax.checkpoint(chunk_fn, prevent_cse=False)
        inputs = xs_t if mask is None else (xs_t, jnp.moveaxis(mask, 0, 1)[..., None])
        chunked = jax.tree.map(
            lambda a: a.reshape(n_chunks, remat_chunk, *a.shape[1:]), inputs
        )
        final, ys = lax.scan(chunk_fn, carry, chunked, reverse=reverse)
        ys = ys.reshape(T, B, ys.shape[-1])

    return final, jnp.moveaxis(ys, 0, 1)


def auto_lstm_scan(
    params: LSTMParams,
    xs: jax.Array,
    carry: tuple[jax.Array, jax.Array] | None = None,
    *,
    mask: jax.Array | None = None,
    reverse: bool = False,
    use_pallas: bool = False,
    compute_dtype=None,
    remat_chunk: int | None = None,
    unroll: int = 1,
    bptt: str = "sequential",
):
    """`lstm_scan` with optional fused-Pallas dispatch.

    When ``use_pallas`` and the shapes/platform pass the kernel's VMEM cost
    model (`pallas_lstm.supported`), runs the fused `pallas_lstm_scan` —
    which now covers masked AND reversed scans, so the bi-LSTM classifier
    and seq2seq decoder recurrences take the fused path too; otherwise
    falls back to the plain `lax.scan`. Same signature contract as
    `lstm_scan`; returns ``((hT, cT), ys)``.

    Precedence with ``bptt``: an EXPLICIT ``bptt="assoc"`` wins over the
    Pallas forward dispatch (the caller asked for the parallel-scan
    backward, which the fused forward kernel does not provide);
    ``bptt="auto"`` defers to the Pallas kernel when it engages — pinning
    one fast path must not silently disable the other — and only
    consults the assoc plan on the `lstm_scan` fallback.
    """
    if bptt == "assoc":
        return lstm_scan(
            params, xs, carry, mask=mask, reverse=reverse,
            compute_dtype=compute_dtype, remat_chunk=remat_chunk,
            unroll=unroll, bptt=bptt,
        )
    if use_pallas:
        from .pallas_lstm import pallas_lstm_scan, supported

        pbytes = 2 if compute_dtype == jnp.bfloat16 else 4
        if supported(xs.shape[0], params.hidden_size,
                     param_dtype_bytes=pbytes, has_mask=mask is not None):
            return pallas_lstm_scan(
                params, xs, carry, mask=mask, reverse=reverse,
                compute_dtype=compute_dtype, remat_chunk=remat_chunk,
                unroll=unroll,
            )
    return lstm_scan(
        params, xs, carry, mask=mask, reverse=reverse,
        compute_dtype=compute_dtype, remat_chunk=remat_chunk, unroll=unroll,
        bptt=bptt,
    )


def bidir_lstm_scan(
    params_fwd: LSTMParams,
    params_bwd: LSTMParams,
    xs: jax.Array,
    *,
    mask: jax.Array | None = None,
    use_pallas: bool = False,
    compute_dtype=None,
    remat_chunk: int | None = None,
    unroll: int = 1,
    bptt: str = "sequential",
):
    """Both directions of one bi-LSTM layer (VERDICT r3 item 2).

    When ``use_pallas`` and the stacked-direction kernel's plan fits
    (`pallas_bilstm.bilstm_supported` — residentx-class shapes: long T,
    VMEM/HBM budgets, no remat memory priority), BOTH chains advance in
    ONE fused `pallas_call`, halving the serialized chain count per
    layer. Otherwise: two `auto_lstm_scan` calls (which keep the full
    per-direction strategy lattice, including the recompute fallback).
    ``LSTM_TSP_NO_BIDIR_FUSE=1`` disables the stacked path (A/B lever
    for benchmarking the fusion itself).

    Returns ``(((hT_f, cT_f), ys_f), ((hT_b, cT_b), ys_b))``.
    """
    import os

    # explicit assoc wins over the stacked-direction fused forward, same
    # precedence as auto_lstm_scan (auto defers to the kernels)
    if (use_pallas and remat_chunk is None and bptt != "assoc"
            and os.environ.get("LSTM_TSP_NO_BIDIR_FUSE") != "1"):
        from .pallas_bilstm import bilstm_supported, pallas_bilstm_scan

        pbytes = 2 if compute_dtype == jnp.bfloat16 else 4
        B, T, D = xs.shape
        if (params_fwd.hidden_size == params_bwd.hidden_size
                and bilstm_supported(B, params_fwd.hidden_size, D, T,
                                     param_dtype_bytes=pbytes,
                                     has_mask=mask is not None)):
            return pallas_bilstm_scan(
                params_fwd, params_bwd, xs, mask=mask,
                compute_dtype=compute_dtype,
            )
    out_f = auto_lstm_scan(
        params_fwd, xs, mask=mask, use_pallas=use_pallas,
        compute_dtype=compute_dtype, remat_chunk=remat_chunk, unroll=unroll,
        bptt=bptt,
    )
    out_b = auto_lstm_scan(
        params_bwd, xs, mask=mask, reverse=True, use_pallas=use_pallas,
        compute_dtype=compute_dtype, remat_chunk=remat_chunk, unroll=unroll,
        bptt=bptt,
    )
    return out_f, out_b


def stacked_lstm_scan(
    layer_params: Sequence[LSTMParams],
    xs: jax.Array,
    carries: Sequence[tuple[jax.Array, jax.Array]] | None = None,
    *,
    mask: jax.Array | None = None,
    dropout_rate: float = 0.0,
    dropout_rng: jax.Array | None = None,
    deterministic: bool = True,
    **scan_kwargs,
):
    """Stack LSTM layers over the same time axis (SURVEY.md §2 "Multi-layer").

    Inter-layer dropout is applied to the full ``[B, T, H]`` output between
    layers (not on the recurrent path). Returns (list of per-layer final
    carries, top-layer outputs ``[B, T, H]``).
    """
    use_pallas = scan_kwargs.pop("use_pallas", False)
    ys = xs
    finals = []
    n = len(layer_params)
    for idx, p in enumerate(layer_params):
        c0 = None if carries is None else carries[idx]
        final, ys = auto_lstm_scan(
            p, ys, c0, mask=mask, use_pallas=use_pallas,
            reverse=scan_kwargs.get("reverse", False),
            compute_dtype=scan_kwargs.get("compute_dtype"),
            remat_chunk=scan_kwargs.get("remat_chunk"),
            unroll=scan_kwargs.get("unroll", 1),
            bptt=scan_kwargs.get("bptt", "sequential"),
        )
        finals.append(final)
        if idx < n - 1 and dropout_rate > 0.0 and not deterministic:
            if dropout_rng is None:
                raise ValueError("dropout_rng required when deterministic=False")
            from .masking import dropout

            dropout_rng, ys = dropout(dropout_rng, dropout_rate, ys)
    return finals, ys
