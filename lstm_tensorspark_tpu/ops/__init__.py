from .lstm_cell import (
    LSTMParams,
    init_lstm_params,
    fuse_params,
    lstm_step,
    lstm_step_unfused,
)
from .embedding import embed_lookup, selected_logits
from .scan import (auto_lstm_scan, bidir_lstm_scan, lstm_scan,
                   stacked_lstm_scan)
from .parallel_scan import assoc_lstm_scan, resolve_bptt
from .masking import sequence_mask, masked_mean, reverse_sequences

__all__ = [
    "LSTMParams",
    "init_lstm_params",
    "fuse_params",
    "lstm_step",
    "lstm_step_unfused",
    "assoc_lstm_scan",
    "auto_lstm_scan",
    "bidir_lstm_scan",
    "resolve_bptt",
    "embed_lookup",
    "selected_logits",
    "lstm_scan",
    "stacked_lstm_scan",
    "sequence_mask",
    "masked_mean",
    "reverse_sequences",
]
