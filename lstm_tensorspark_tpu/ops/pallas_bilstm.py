"""Stacked-direction fused Pallas kernel for one bi-LSTM layer.

Motivation (VERDICT r3 item 2): the bi-LSTM classifier (BASELINE.md
config 2) ran its forward and reverse directions as TWO sequential
`pallas_lstm_scan` invocations — 2T serialized chain steps per layer —
even though the two chains are completely data-independent until the
output concat (models/classifier.py). The strategy-aware roofline
(`bench.py _impl_bound`) identified that serialization as config 2's
binding constraint (41% of the strategy-aware bound in round 3).

Design: ONE `pallas_call` advances BOTH chains in every sub-step. The
reverse direction is realised exactly as in `pallas_lstm_scan` — a
forward-in-time scan over time-flipped inputs and mask (flips live
outside the custom VJP, so autodiff transposes them for free) — which
makes the two directions the SAME computation with different weights.
Operands are batch-stacked (rows 0:B = forward, B:2B = reverse, so all
VPU gate algebra vectorizes over 2B rows unchanged) while the weights
carry a leading direction axis ([2, Dp, 4H] W, [2, H, 4H] U): each
sub-step issues the two directions' ``h_d @ U_d`` back-to-back. The two
matmuls are data-independent, so the MXU pipelines the second behind
the first instead of waiting a full chain-step latency — the serialized
chain count per layer drops from 2 (fwd direction then rev direction)
to ~1 (both at once).

Strategy: the residentx (fully-fused, recompute-z backward) pair only —
the plan config 2's shape selects. Everything else (short T, VMEM
overflow, remat_chunk memory priority, recompute fallback) falls back
to two single-direction calls at the dispatch layer
(`ops.scan.bidir_lstm_scan`), which keeps its own full strategy
lattice. VMEM planning reuses `pallas_lstm`'s per-buffer cost model at
2B rows plus the second direction's weight copies.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import pallas_lstm as _pl
from .lstm_cell import LSTMParams, fuse_params
from .pallas_lstm import (_LANE, _chunk_for, _pad_params_lane, _pad_to_lane,
                          _residual_dtype)


def _bi_fwd_vmem(B2: int, H: int, Dp: int, pbytes: int, save_c: bool,
                 has_mask: bool, c: int) -> int:
    """Stacked forward = the residentx forward at 2B rows plus the second
    direction's W/U/bias copies (streamed blocks already scale with B2)."""
    return (_pl._residentx_fwd_vmem(B2, H, Dp, pbytes, save_c, has_mask, c)
            + 4 * H * H * pbytes + Dp * 4 * H * pbytes + 4 * H * 4)


def _bi_bwd_vmem(B2: int, H: int, Dp: int, pbytes: int, has_mask: bool,
                 c: int) -> int:
    """Stacked backward = residentx backward at 2B rows plus the second
    direction's W, U (z recompute), U^T (dh carry) and bias copies."""
    return (_pl._residentx_bwd_vmem(B2, H, Dp, pbytes, has_mask, c)
            + 2 * 4 * H * H * pbytes + Dp * 4 * H * pbytes + 4 * H * 4)


def _bi_plan(B: int, H: int, Dp: int, pbytes: int,
             has_mask: bool) -> int | None:
    """Largest VMEM-feasible time chunk for the stacked pair (the TRAIN
    shape: residual-saving forward AND the recompute-z backward must both
    fit at the same chunk), or None when nothing fits."""
    for c in (8, 4, 2, 1):
        if (_bi_fwd_vmem(2 * B, H, Dp, pbytes, True, has_mask,
                         c) <= _pl._VMEM_BUDGET
                and _bi_bwd_vmem(2 * B, H, Dp, pbytes, has_mask,
                                 c) <= _pl._VMEM_BUDGET):
            return c
    return None


def bilstm_supported(batch: int, hidden: int, d_in: int, seq_len: int,
                     platform: str | None = None, *,
                     param_dtype_bytes: int = 4,
                     has_mask: bool = False) -> bool:
    """Can the stacked-direction kernel run this layer? Mirrors
    `pallas_lstm.supported` but for the TRAIN pair at 2B rows, gated on
    the fusedx sequence-length threshold (short sequences prefer the
    hoisted-xproj single-direction kernels — same trade as the
    single-direction `_FUSEDX_MIN_T` gate) and the O(T) cs residual
    fitting the HBM budget at 2B rows."""
    if platform is None:
        platform = jax.default_backend()
    hp = _pad_to_lane(hidden)
    return (
        platform == "tpu"
        and batch % 8 == 0
        and hidden >= 1
        and seq_len >= _pl._FUSEDX_MIN_T
        and _bi_plan(batch, hp, _pad_to_lane(d_in), param_dtype_bytes,
                     has_mask) is not None
        and (seq_len * 2 * batch * hp * 4) <= _pl._RESIDUAL_HBM_BUDGET
    )


# ---------------------------------------------------------------------------
# Kernels. Batch-stacked values (2B rows), direction-stacked weights.
# ---------------------------------------------------------------------------


def _bi_fwdx_kernel(*refs, hidden: int, dpad: int, chunk: int, batch: int,
                    save_c: bool, has_mask: bool):
    """Stacked residentx forward: per grid step, TWO chunk-batched xproj
    matmuls (one per direction's W), then each sequential sub-step issues
    the two directions' ``h_d @ U_d`` back-to-back — independent MXU ops
    the hardware pipelines — and runs the gate algebra once over all 2B
    rows. With ``save_c`` only the cell states stream out (the
    recompute-z backward's sole residual)."""
    n_in = 6 + has_mask
    xs_ref, w_ref, b_ref, u_ref, h0_ref, c0_ref = refs[:6]
    mask_ref = refs[6] if has_mask else None
    ys_ref, hT_ref, cT_ref = refs[n_in:n_in + 3]
    rest = refs[n_in + 3:]
    if save_c:
        cs_ref, h_scr, c_scr = rest
    else:
        h_scr, c_scr = rest
    t = pl.program_id(0)
    T = pl.num_programs(0)
    H = hidden
    B = batch

    @pl.when(t == 0)
    def _():
        h_scr[:] = h0_ref[:]
        c_scr[:] = c0_ref[:]

    xs = xs_ref[:]  # [C, 2B, Dp]
    zx = []
    for d in range(2):
        zd = jnp.dot(
            xs[:, d * B:(d + 1) * B].reshape(-1, dpad).astype(w_ref.dtype),
            w_ref[d], preferred_element_type=jnp.float32,
        ) + b_ref[d]
        zx.append(zd.reshape(chunk, -1, 4 * H))
    h = h_scr[:]
    c = c_scr[:]
    for s in range(chunk):
        z = jnp.concatenate(
            [zx[d][s] + jnp.dot(
                h[d * B:(d + 1) * B].astype(u_ref.dtype), u_ref[d],
                preferred_element_type=jnp.float32,
            ) for d in range(2)],
            axis=0,
        )  # [2B, 4H]
        i = jax.nn.sigmoid(z[:, :H])
        f = jax.nn.sigmoid(z[:, H:2 * H])
        g = jnp.tanh(z[:, 2 * H:3 * H])
        o = jax.nn.sigmoid(z[:, 3 * H:])
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        if has_mask:
            m = mask_ref[s][:, :1]
            c = m * c_new + (1.0 - m) * c
            h = m * h_new + (1.0 - m) * h
        else:
            c = c_new
            h = h_new
        ys_ref[s] = h
        if save_c:
            cs_ref[s] = c
    h_scr[:] = h
    c_scr[:] = c

    @pl.when(t == T - 1)
    def _():
        hT_ref[:] = h
        cT_ref[:] = c


def _bi_bwdx_kernel(*refs, hidden: int, dpad: int, chunk: int, batch: int,
                    has_mask: bool):
    """Stacked recompute-z BPTT: rebuilds both directions' z in-kernel
    (two xproj matmuls per chunk, two ``h_prev_d @ U_d`` per sub-step —
    bit-identical to the forward's f32 values), runs the cotangent
    algebra once over 2B rows, and carries dh through two back-to-back
    ``dz_d @ U_d^T`` matmuls. dU/dW/db/dxs are contracted OUTSIDE per
    direction (`_bi_backward`) — same split as the single-direction
    kernels (`pallas_lstm._lstm_bwdx_kernel`'s rationale)."""
    n_in = 10 + has_mask
    xs_ref, dys_ref, cprev_ref, hprev_ref = refs[:4]
    mask_ref = refs[4] if has_mask else None
    w_ref, b_ref, u_ref, ut_ref, dhT_ref, dcT_ref = refs[4 + has_mask:n_in]
    dz_ref, dh0_ref, dc0_ref = refs[n_in:n_in + 3]
    dh_scr, dc_scr = refs[n_in + 3:]
    t = pl.program_id(0)
    T = pl.num_programs(0)
    H = hidden
    B = batch

    @pl.when(t == 0)
    def _():
        dh_scr[:] = dhT_ref[:]
        dc_scr[:] = dcT_ref[:]

    xs = xs_ref[:]  # [C, 2B, Dp]
    zx = []
    for d in range(2):
        zd = jnp.dot(
            xs[:, d * B:(d + 1) * B].reshape(-1, dpad).astype(w_ref.dtype),
            w_ref[d], preferred_element_type=jnp.float32,
        ) + b_ref[d]
        zx.append(zd.reshape(chunk, -1, 4 * H))
    dh = dh_scr[:]
    dc = dc_scr[:]
    for s in range(chunk - 1, -1, -1):
        hp = hprev_ref[s]
        z = jnp.concatenate(
            [zx[d][s] + jnp.dot(
                hp[d * B:(d + 1) * B].astype(u_ref.dtype), u_ref[d],
                preferred_element_type=jnp.float32,
            ) for d in range(2)],
            axis=0,
        )
        i = jax.nn.sigmoid(z[:, :H])
        f = jax.nn.sigmoid(z[:, H:2 * H])
        g = jnp.tanh(z[:, 2 * H:3 * H])
        o = jax.nn.sigmoid(z[:, 3 * H:])
        c_prev = cprev_ref[s]
        tc = jnp.tanh(f * c_prev + i * g)  # tanh(c_new), recomputed
        dh_tot = dh + dys_ref[s]
        dc_in = dc
        if has_mask:
            m = mask_ref[s][:, :1]
            dh_eff = m * dh_tot
            dc_eff = m * dc_in
        else:
            dh_eff = dh_tot
            dc_eff = dc_in
        dc_new = dc_eff + dh_eff * o * (1.0 - tc * tc)
        do = dh_eff * tc * o * (1.0 - o)
        di = dc_new * g * i * (1.0 - i)
        df = dc_new * c_prev * f * (1.0 - f)
        dg = dc_new * i * (1.0 - g * g)
        dz = jnp.concatenate([di, df, dg, do], axis=1)  # [2B, 4H] f32
        dz_ref[s] = dz.astype(dz_ref.dtype)  # stream dtype
        dh = jnp.concatenate(
            [jnp.dot(
                dz[d * B:(d + 1) * B].astype(ut_ref.dtype), ut_ref[d],
                preferred_element_type=jnp.float32,
            ) for d in range(2)],
            axis=0,
        )
        dc = dc_new * f
        if has_mask:
            # frozen fraction of the cotangents bypasses the gates
            dh = dh + (1.0 - m) * dh_tot
            dc = dc + (1.0 - m) * dc_in
    dh_scr[:] = dh
    dc_scr[:] = dc

    @pl.when(t == T - 1)
    def _():
        dh0_ref[:] = dh
        dc0_ref[:] = dc


# ---------------------------------------------------------------------------
# pallas_call wrappers
# ---------------------------------------------------------------------------


def _stack_weights(fused_f, fused_b, Dp: int):
    """Direction-stacked W [2, Dp, 4H] (rows zero-padded to Dp — exact,
    they multiply zero xs lanes), bias [2, 4H] f32, U [2, H, 4H]."""
    D = fused_f.kernel.shape[0]
    pad = ((0, Dp - D), (0, 0))
    w2 = jnp.stack([jnp.pad(fused_f.kernel, pad),
                    jnp.pad(fused_b.kernel, pad)])
    b2 = jnp.stack([fused_f.bias, fused_b.bias]).astype(jnp.float32)
    u2 = jnp.stack([fused_f.recurrent, fused_b.recurrent])
    return w2, b2, u2


def _bi_forward(fused_f, fused_b, xs2, h0, c0, mask_tbl=None, *,
                save_c: bool = False, interpret: bool = False):
    """xs2 [2B, T, D] (rows B: = the time-flipped reverse direction) →
    (ys2 [2B, T, H], hT [2B, H], cT[, cs]). Residentx strategy only."""
    B2, T, D = xs2.shape
    B = B2 // 2
    H = fused_f.hidden_size
    pbytes = 2 if fused_f.kernel.dtype == jnp.bfloat16 else 4
    has_mask = mask_tbl is not None
    Dp = _pad_to_lane(D)
    cap = _bi_plan(B, H, Dp, pbytes, has_mask)
    if cap is None:
        raise ValueError(f"no stacked bilstm plan for B={B}, H={H}, D={D}")
    C = _chunk_for(T, cap)

    sdtype = _residual_dtype(fused_f.kernel.dtype)
    xs_t = jnp.moveaxis(xs2, 0, 1).astype(sdtype)  # [T, 2B, D]
    if Dp != D:
        xs_t = jnp.pad(xs_t, ((0, 0), (0, 0), (0, Dp - D)))
    w2, b2, u2 = _stack_weights(fused_f, fused_b, Dp)

    in_specs = [
        pl.BlockSpec((C, B2, Dp), lambda t: (t, 0, 0),
                     memory_space=pltpu.VMEM),  # xs
        pl.BlockSpec(memory_space=pltpu.VMEM),  # W [2, Dp, 4H]
        pl.BlockSpec(memory_space=pltpu.VMEM),  # bias [2, 4H]
        pl.BlockSpec(memory_space=pltpu.VMEM),  # U [2, H, 4H]
        pl.BlockSpec(memory_space=pltpu.VMEM),  # h0
        pl.BlockSpec(memory_space=pltpu.VMEM),  # c0
    ]
    operands = [xs_t, w2, b2, u2,
                h0.astype(jnp.float32), c0.astype(jnp.float32)]
    if has_mask:
        in_specs.append(
            pl.BlockSpec((C, B2, _LANE), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM))
        operands.append(mask_tbl)
    out_specs = [
        pl.BlockSpec((C, B2, H), lambda t: (t, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec(memory_space=pltpu.VMEM),
        pl.BlockSpec(memory_space=pltpu.VMEM),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((T, B2, H), jnp.float32),
        jax.ShapeDtypeStruct((B2, H), jnp.float32),
        jax.ShapeDtypeStruct((B2, H), jnp.float32),
    ]
    if save_c:
        out_specs.append(
            pl.BlockSpec((C, B2, H), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM))
        out_shape.append(jax.ShapeDtypeStruct((T, B2, H), jnp.float32))
    out = pl.pallas_call(
        functools.partial(
            _bi_fwdx_kernel, hidden=H, dpad=Dp, chunk=C, batch=B,
            save_c=save_c, has_mask=has_mask,
        ),
        grid=(T // C,),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((B2, H), jnp.float32),  # h
            pltpu.VMEM((B2, H), jnp.float32),  # c
        ],
        interpret=interpret,
    )(*operands)
    ys2 = jnp.moveaxis(out[0], 0, 1)
    if save_c:
        return ys2, out[1], out[2], out[3]
    return ys2, out[1], out[2]


def _bi_backward(fused_f, fused_b, params_f, params_b, xs2, h0, c0,
                 mask_tbl, ys2, cs, dys2, dhT, dcT, *,
                 interpret: bool = False):
    """Stacked recompute-z BPTT + per-direction outside contractions.
    Returns (dparams_f, dparams_b, dxs2, dh0, dc0)."""
    B2, T, D = xs2.shape
    B = B2 // 2
    H = fused_f.hidden_size
    dtype = fused_f.kernel.dtype
    pbytes = 2 if dtype == jnp.bfloat16 else 4
    has_mask = mask_tbl is not None
    Dp = _pad_to_lane(D)
    cap = _bi_plan(B, H, Dp, pbytes, has_mask)
    if cap is None:
        raise ValueError(f"no stacked bilstm plan for B={B}, H={H}, D={D}")
    C = _chunk_for(T, cap)
    n = T // C
    rev = lambda t: (n - 1 - t, 0, 0)  # noqa: E731 — reverse-time grid

    ys_t = jnp.moveaxis(ys2, 0, 1)  # [T, 2B, H] f32
    h_prev = jnp.concatenate(
        [h0.astype(jnp.float32)[None], ys_t[:-1]], axis=0)
    c_prev = jnp.concatenate(
        [c0.astype(jnp.float32)[None], cs[:-1]], axis=0)
    dys_t = jnp.moveaxis(dys2.astype(jnp.float32), 0, 1)
    sdtype = _residual_dtype(dtype)
    xs_t = jnp.moveaxis(xs2, 0, 1).astype(sdtype)
    if Dp != D:
        xs_t_pad = jnp.pad(xs_t, ((0, 0), (0, 0), (0, Dp - D)))
    else:
        xs_t_pad = xs_t
    w2, b2, u2 = _stack_weights(fused_f, fused_b, Dp)
    ut2 = jnp.stack([fused_f.recurrent.T, fused_b.recurrent.T])

    in_specs = [
        pl.BlockSpec((C, B2, Dp), rev, memory_space=pltpu.VMEM),  # xs
        pl.BlockSpec((C, B2, H), rev, memory_space=pltpu.VMEM),   # dys
        pl.BlockSpec((C, B2, H), rev, memory_space=pltpu.VMEM),   # c_prev
        pl.BlockSpec((C, B2, H), rev, memory_space=pltpu.VMEM),   # h_prev
    ]
    operands = [xs_t_pad, dys_t, c_prev, h_prev]
    if has_mask:
        in_specs.append(
            pl.BlockSpec((C, B2, _LANE), rev, memory_space=pltpu.VMEM))
        operands.append(mask_tbl)
    in_specs += [pl.BlockSpec(memory_space=pltpu.VMEM)] * 6  # w/b/u/ut/dhT/dcT
    operands += [w2, b2, u2, ut2,
                 dhT.astype(jnp.float32), dcT.astype(jnp.float32)]
    dz, dh0, dc0 = pl.pallas_call(
        functools.partial(_bi_bwdx_kernel, hidden=H, dpad=Dp, chunk=C,
                          batch=B, has_mask=has_mask),
        grid=(n,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((C, B2, 4 * H), rev, memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, B2, 4 * H), sdtype),  # dz stream
            jax.ShapeDtypeStruct((B2, H), jnp.float32),
            jax.ShapeDtypeStruct((B2, H), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((B2, H), jnp.float32),
            pltpu.VMEM((B2, H), jnp.float32),
        ],
        interpret=interpret,
    )(*operands)

    # per-direction weight/input cotangents: large MXU contractions over
    # all T·B outside the sequential kernel (same split as pallas_lstm)
    dparams = []
    dxs_parts = []
    for d, (fused, params) in enumerate(
            ((fused_f, params_f), (fused_b, params_b))):
        rows = slice(d * B, (d + 1) * B)
        dz_d = dz[:, rows]
        dz_c = dz_d.astype(dtype)
        dU = jnp.einsum("tbh,tbk->hk", h_prev[:, rows].astype(dtype), dz_c,
                        preferred_element_type=jnp.float32)
        dW = jnp.einsum("tbd,tbk->dk", xs_t[:, rows].astype(dtype), dz_c,
                        preferred_element_type=jnp.float32)
        db = jnp.sum(dz_d, axis=(0, 1), dtype=jnp.float32)
        dxs_parts.append(jnp.moveaxis(
            jnp.einsum("tbk,dk->tbd", dz_c, fused.kernel,
                       preferred_element_type=jnp.float32),
            0, 1,
        ).astype(xs2.dtype))
        Ws = jnp.split(dW, 4, axis=1)
        Us = jnp.split(dU, 4, axis=1)
        bs = jnp.split(db, 4)
        dp = LSTMParams(*Ws, *Us, *bs)
        dparams.append(jax.tree.map(lambda g, p: g.astype(p.dtype),
                                    dp, params))
    dxs2 = jnp.concatenate(dxs_parts, axis=0)
    return (dparams[0], dparams[1], dxs2,
            dh0.astype(h0.dtype), dc0.astype(c0.dtype))


# ---------------------------------------------------------------------------
# custom-VJP core + public entry
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8))
def _bi_core(params_f, params_b, xs2, h0, c0, mask_tbl, compute_dtype,
             interpret, has_mask):
    fused_f = fuse_params(params_f, compute_dtype=compute_dtype)
    fused_b = fuse_params(params_b, compute_dtype=compute_dtype)
    ys2, hT, cT = _bi_forward(
        fused_f, fused_b, xs2, h0, c0, mask_tbl if has_mask else None,
        interpret=interpret,
    )
    return ys2, hT, cT


def _bi_core_fwd(params_f, params_b, xs2, h0, c0, mask_tbl, compute_dtype,
                 interpret, has_mask):
    fused_f = fuse_params(params_f, compute_dtype=compute_dtype)
    fused_b = fuse_params(params_b, compute_dtype=compute_dtype)
    ys2, hT, cT, cs = _bi_forward(
        fused_f, fused_b, xs2, h0, c0, mask_tbl if has_mask else None,
        save_c=True, interpret=interpret,
    )
    return (ys2, hT, cT), (params_f, params_b, xs2, h0, c0, mask_tbl,
                           ys2, cs)


def _bi_core_bwd(compute_dtype, interpret, has_mask, residuals, cotangents):
    params_f, params_b, xs2, h0, c0, mask_tbl, ys2, cs = residuals
    fused_f = fuse_params(params_f, compute_dtype=compute_dtype)
    fused_b = fuse_params(params_b, compute_dtype=compute_dtype)
    dys2, dhT, dcT = cotangents
    dpf, dpb, dxs2, dh0, dc0 = _bi_backward(
        fused_f, fused_b, params_f, params_b, xs2, h0, c0,
        mask_tbl if has_mask else None, ys2, cs, dys2, dhT, dcT,
        interpret=interpret,
    )
    return dpf, dpb, dxs2, dh0, dc0, jnp.zeros_like(mask_tbl)


_bi_core.defvjp(_bi_core_fwd, _bi_core_bwd)


def pallas_bilstm_scan(
    params_fwd: LSTMParams,
    params_bwd: LSTMParams,
    xs: jax.Array,
    *,
    mask: jax.Array | None = None,
    compute_dtype=None,
    interpret: bool = False,
):
    """Both directions of one bi-LSTM layer in ONE fused kernel pass.

    Equivalent to
    ``pallas_lstm_scan(params_fwd, xs, mask=mask)`` and
    ``pallas_lstm_scan(params_bwd, xs, mask=mask, reverse=True)`` — the
    reverse direction walks right-padded tails first with a frozen zero
    carry, exactly like `lstm_scan(reverse=True)` — but with the two
    serialized chains advanced together (module docstring). Zero initial
    carries (the bi-LSTM layer contract; models/classifier.py never
    seeds carries).

    Returns ``(((hT_f, cT_f), ys_f), ((hT_b, cT_b), ys_b))``.
    """
    B, T, _ = xs.shape
    H = params_fwd.hidden_size
    if params_bwd.hidden_size != H:
        raise ValueError("direction hidden sizes differ")
    hp = _pad_to_lane(H)
    pf = _pad_params_lane(params_fwd, hp) if hp != H else params_fwd
    pb = _pad_params_lane(params_bwd, hp) if hp != H else params_bwd
    # rows B:2B are the time-flipped reverse direction; the flips sit
    # OUTSIDE the custom VJP so autodiff transposes them automatically
    xs2 = jnp.concatenate([xs, jnp.flip(xs, axis=1)], axis=0)
    has_mask = mask is not None
    if has_mask:
        m2 = jnp.concatenate([mask, jnp.flip(mask, axis=1)], axis=0)
        mask_tbl = jnp.broadcast_to(
            jnp.moveaxis(m2, 0, 1).astype(jnp.float32)[:, :, None],
            (T, 2 * B, _LANE),
        )
    else:
        mask_tbl = jnp.zeros((1, 1, _LANE), jnp.float32)  # unused dummy
    h0 = jnp.zeros((2 * B, hp), jnp.float32)
    c0 = jnp.zeros((2 * B, hp), jnp.float32)
    ys2, hT, cT = _bi_core(pf, pb, xs2, h0, c0, mask_tbl, compute_dtype,
                           interpret, has_mask)
    if hp != H:
        ys2, hT, cT = ys2[..., :H], hT[:, :H], cT[:, :H]
    ys_f = ys2[:B]
    ys_b = jnp.flip(ys2[B:], axis=1)
    return ((hT[:B], cT[:B]), ys_f), ((hT[B:], cT[B:]), ys_b)
