"""Vocab-chunked cross-entropy: the [N, V] logits never exist in HBM.

Motivation (BASELINE.md configs 3/5): at V = 33k/50k the LM softmax head's
logits array is 300–400 MB; a train step writes it (head matmul), reads it
(logsumexp + target gather), writes the same-sized dlogits in the backward
and reads it twice more (dW and dys matmuls) — ~1.5–2 GB of HBM traffic per
step that dwarfs the head's actual FLOPs. This module computes the exact
same mean-NLL with the vocabulary processed in `chunk`-column tiles:

- forward: one pass of ONLINE logsumexp (flash-attention-style running
  (m, s) accumulators) + in-chunk target-logit gather — the only [N, Vc]
  tile alive is the current one;
- backward (custom VJP): recompute each chunk's logits, form its dlogits
  tile, and immediately contract it into dys / dW / db accumulators.

The trade is the standard recompute-vs-traffic one: head matmul FLOPs ×2
(the backward re-projects each chunk) against deleting ~5 full-logits HBM
round-trips. XLA's job remains the matmuls; this is pure jax-level
restructuring (lax.scan over weight column tiles), no Pallas needed —
the tiles are large MXU-friendly matmuls already.

Reference parity note: the reference computes a plain softmax cross-entropy
(SURVEY.md §3.2 ``xent(softmax(h·W_out), y)``); this is the same math to
float rounding (exactness tests in tests/test_xent.py), restructured for
HBM economics.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def _pad_vocab(kernel, bias, chunk):
    """Pad V up to a multiple of ``chunk``. Padded columns get bias -1e30,
    so their softmax mass underflows to exactly 0 and the online logsumexp
    ignores them (no target ever points at a padded id)."""
    V = kernel.shape[1]
    pad = -V % chunk
    if pad:
        kernel = jnp.pad(kernel, ((0, 0), (0, pad)))
        bias = jnp.pad(bias, (0, pad), constant_values=-1e30)
    return kernel, bias, V + pad


def _chunk_logits(ys, k_tile, b_tile):
    return (
        jnp.dot(ys.astype(k_tile.dtype), k_tile,
                preferred_element_type=jnp.float32)
        + b_tile
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def chunked_xent_mean(ys, kernel, bias, targets, chunk: int = 8192):
    """Mean next-token NLL over all N = B·T positions, logits never
    materialised. ``ys`` [B, T, H] (float), ``kernel`` [H, V], ``bias``
    [V], ``targets`` [B, T] int32. Returns a scalar; grads flow to
    ys/kernel/bias via the recompute backward."""
    loss, _ = _xent_fwd_pass(ys, kernel, bias, targets, chunk)
    return loss


def _xent_fwd_pass(ys, kernel, bias, targets, chunk):
    B, T, H = ys.shape
    N = B * T
    ys_f = ys.reshape(N, H)
    tgt = targets.reshape(N)
    kernel_p, bias_p, Vp = _pad_vocab(kernel, bias, chunk)
    K = Vp // chunk
    k_tiles = kernel_p.T.reshape(K, chunk, H)  # [K, Vc, H] (scan-sliced)
    b_tiles = bias_p.reshape(K, chunk)

    def body(carry, tile):
        m, s, tl = carry
        k_t, b_t, c0 = tile
        logits = _chunk_logits(ys_f, k_t.T, b_t)  # [N, Vc]
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))
        s = s * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1
        )
        idx = tgt - c0
        in_chunk = (idx >= 0) & (idx < chunk)
        got = jnp.take_along_axis(
            logits, jnp.clip(idx, 0, chunk - 1)[:, None], axis=-1
        )[:, 0]
        tl = jnp.where(in_chunk, got, tl)
        return (m_new, s, tl), None

    init = (
        jnp.full((N,), -jnp.inf, jnp.float32),
        jnp.zeros((N,), jnp.float32),
        jnp.zeros((N,), jnp.float32),
    )
    c0s = jnp.arange(K, dtype=jnp.int32) * chunk
    (m, s, tl), _ = lax.scan(body, init, (k_tiles, b_tiles, c0s))
    lse = m + jnp.log(s)
    loss = jnp.mean(lse - tl)
    return loss, (ys_f, tgt, lse, (B, T, H))


def _xent_fwd(ys, kernel, bias, targets, chunk):
    loss, (ys_f, tgt, lse, dims) = _xent_fwd_pass(ys, kernel, bias, targets,
                                                  chunk)
    return loss, (ys_f, kernel, bias, tgt, lse, dims)


def _xent_bwd(chunk, residuals, g):
    ys_f, kernel, bias, tgt, lse, (B, T, H) = residuals
    N = B * T
    kernel_p, bias_p, Vp = _pad_vocab(kernel, bias, chunk)
    K = Vp // chunk
    k_tiles = kernel_p.T.reshape(K, chunk, H)
    b_tiles = bias_p.reshape(K, chunk)
    gN = (g / N).astype(jnp.float32)  # d(mean)/d(per-token nll)
    cdtype = kernel.dtype

    def body(dys, tile):
        k_t, b_t, c0 = tile
        logits = _chunk_logits(ys_f, k_t.T, b_t)
        # dlogits tile = (softmax - onehot) * g/N; padded cols: softmax
        # underflows to 0 and no target points there, so exactly 0
        p = jnp.exp(logits - lse[:, None])
        idx = tgt - c0
        in_chunk = (idx >= 0) & (idx < chunk)
        onehot = (
            jax.nn.one_hot(jnp.clip(idx, 0, chunk - 1), chunk,
                           dtype=jnp.float32)
            * in_chunk[:, None]
        )
        dlog = (p - onehot) * gN
        dlog_c = dlog.astype(cdtype)
        dk_t = jnp.dot(ys_f.astype(cdtype).T, dlog_c,
                       preferred_element_type=jnp.float32)  # [H, Vc]
        db_t = jnp.sum(dlog, axis=0)
        dys = dys + jnp.dot(dlog_c, k_t.astype(cdtype),
                            preferred_element_type=jnp.float32)
        return dys, (dk_t, db_t)

    c0s = jnp.arange(K, dtype=jnp.int32) * chunk
    dys, (dk_tiles, db_tiles) = lax.scan(
        body, jnp.zeros((N, H), jnp.float32), (k_tiles, b_tiles, c0s)
    )
    V = kernel.shape[1]
    dkernel = jnp.moveaxis(dk_tiles, 0, 1).reshape(H, Vp)[:, :V]
    dbias = db_tiles.reshape(Vp)[:V]
    return (
        dys.reshape(B, T, H).astype(ys_f.dtype),
        dkernel.astype(kernel.dtype),
        dbias.astype(bias.dtype),
        np.zeros((B, T), dtype=jax.dtypes.float0),  # int targets
    )


chunked_xent_mean.defvjp(_xent_fwd, _xent_bwd)
