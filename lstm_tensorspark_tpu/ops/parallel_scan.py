"""Parallel-scan BPTT for the LSTM recurrence (BPPSA-style).

The training bottleneck for long sequences is not the matmuls — it is the
T-deep sequential dependency chain that `lax.scan` (ops/scan.py) and its
reverse-mode transpose walk step by step (BENCH_TABLE.json: the T=400
rows sit at ~20-25% MFU; the roofline section shows the chain latency,
not FLOPs, as the binding constraint). *BPPSA: Scaling Back-propagation
by Parallel Scan Algorithm* (PAPERS.md) observes that even though the
forward cell is nonlinear, **backprop through a recurrence is a linear
chain of per-step Jacobian operators**:

    lambda_{t-1} = A_t^T (lambda_t + e_t)

with ``lambda_t`` the adjoint of the carry ``(h_t, c_t)``, ``e_t`` the
cotangent injected by the step's output ``y_t = h_t``, and ``A_t`` the
per-step carry Jacobian. Affine operators compose associatively, so the
whole backward pass is an associative scan — O(log T) depth instead of
O(T) — of MXU-friendly composes.

Three-phase tiled backward (the chunking of `remat_chunk` /
`parallel/sequence_parallel.py` is the natural tile for the scan tree):

1. **Tile build** (depth = tile): within each of the T/tile chunks —
   all chunks advancing together in ONE `lax.scan` of length ``tile`` —
   compose the per-step operators into one dense affine chunk operator
   ``(M_c, d_c)``. The per-step operator is *never* materialized as a
   dense [2H, 2H] block: it is applied in factored form — gate-local
   diagonal terms (``sigma'``/``tanh'`` products) plus ONE shared
   ``[*, 4H] @ [4H, H]`` matmul against the fused recurrent kernel — to
   the 2H+1 columns of the accumulating chunk operator at once.
2. **Tree compose** (depth = log2(T/tile)): `jax.lax.associative_scan`
   over the chunk operators (dense ``[B, 2H, 2H]`` batched matmuls —
   the only place dense blocks exist, which is what the `plan_bytes`
   memory model below prices) yields the adjoint at every chunk
   boundary.
3. **Interior replay** (depth = tile): all chunks again advance in one
   scan from their boundary adjoints, emitting the per-step gate
   cotangents ``gz_t``; parameter and input gradients then come from
   three large batched matmuls over the whole [T, B, 4H] block.

Residual policy mirrors `remat_chunk`'s recompute trade: the forward
stores only the ``h``/``c`` sequences (2 x [T, B, H]); the backward
rebuilds every gate in ONE fused [T*B, 4H] matmul instead of storing
per-step activations.

The FLOP trade is real and priced honestly: the dense tile/tree
composes do O(H) more arithmetic than sequential BPTT's vector chain.
On a latency-bound accelerator chain (small per-step matmuls, T deep)
the log-depth tree wins; on a throughput-bound CPU it usually does not
— `tools/bench_train_scan.py` records the honest CPU ratio and
`tests_tpu/test_parallel_scan_tpu.py` is the hardware >= 1.0x gate.

``resolve_bptt`` implements the ``bptt="auto"`` policy (ops/scan.py):
assoc only when the `plan_bytes` memory model fits the budget AND
T >= `AUTO_MIN_T`; every auto resolution that falls back to sequential
bumps a trace-time counter surfaced in the run's ``metrics_snapshot``
record (train/loop.py) so supervised restarts can detect a mode flip
between resume legs.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax

from .lstm_cell import LSTMParams, fuse_params, lstm_step_hoisted, zero_carry

#: minimum T for ``bptt="auto"`` to pick the assoc path: below this the
#: sequential chain is short enough that the tree's extra FLOPs and the
#: dense-block traffic cannot pay for the saved depth.
AUTO_MIN_T = 128

#: default budget for the dense chunk-operator working set (HBM-level —
#: the training twin of ops/pallas_decode's VMEM plan, at the memory
#: tier this path actually pressures). Override: LSTM_TSP_ASSOC_BUDGET_MB.
_DEFAULT_BUDGET_MB = 1024

#: trace-time counters (bumped when a scan RESOLVES, i.e. once per XLA
#: trace, not per step): ``assoc_traces`` = scans that took the assoc
#: path; ``sequential_fallbacks`` = ``auto`` requests the memory plan or
#: T-threshold pushed back to sequential. train/loop.py mirrors the
#: fallback delta into obs and cli.py stamps both into metrics_snapshot.
_STATS = {"assoc_traces": 0, "sequential_fallbacks": 0}

BPTT_MODES = ("sequential", "assoc", "auto")


def assoc_stats() -> dict:
    """Snapshot of the trace-time resolution counters (copies)."""
    return dict(_STATS)


def reset_stats() -> None:
    for k in _STATS:
        _STATS[k] = 0


def _budget_bytes() -> int:
    return int(os.environ.get(
        "LSTM_TSP_ASSOC_BUDGET_MB", _DEFAULT_BUDGET_MB)) * 2**20


def pick_tile(T: int, remat_chunk: int | None = None, *,
              target: int = 16) -> int:
    """Tile (chunk) length for the scan tree: `remat_chunk` when it
    divides T (the forward's chunking IS the tree's natural tile),
    else the divisor of T closest to ``target``."""
    if remat_chunk and T % remat_chunk == 0:
        return remat_chunk
    divisors = [d for d in range(1, T + 1) if T % d == 0]
    return min(divisors, key=lambda d: (abs(d - target), d))


def plan_bytes(batch: int, T: int, hidden: int, *,
               tile: int | None = None) -> int:
    """Working-set bytes of the assoc backward (f32 throughout).

    Dominant term: the dense chunk operators — [T/tile, B, 2H, 2H+1]
    augmented blocks, counted x3 for the associative-scan combine tree's
    intermediate copies. Plus the tile-build scan's double-buffered
    carry, the gate recompute / cotangent block ([T, B, 4H] x2), the
    factor tensors, and the h/c residuals. Mirrors the
    `ops/pallas_decode.plan_bytes` cost-model style: count every live
    operand once, prefer over-counting to an OOM surprise.
    """
    tile = tile or pick_tile(T)
    n_chunks = max(T // tile, 1)
    H = hidden
    K = 2 * H + 1
    v = 3 * n_chunks * batch * 2 * H * K * 4      # chunk ops through the tree
    v += 2 * n_chunks * batch * 2 * H * K * 4     # build-scan carry (dbl buf)
    v += 2 * T * batch * 4 * H * 4                # gate recompute + gz block
    v += 6 * T * batch * H * 4                    # per-step factor tensors
    v += 3 * T * batch * H * 4                    # h/c residuals + ys cotangent
    return v


def plan_fits(batch: int, T: int, hidden: int, *,
              tile: int | None = None) -> bool:
    return plan_bytes(batch, T, hidden, tile=tile) <= _budget_bytes()


def resolve_bptt(mode: str, batch: int, T: int, hidden: int, *,
                 remat_chunk: int | None = None) -> str:
    """Resolve a ``bptt=`` knob value to a concrete path at trace time.

    ``sequential``/``assoc`` are honored as written (explicit ``assoc``
    trusts the caller — parity tests need a deterministic path);
    ``auto`` takes assoc only when T >= `AUTO_MIN_T` AND `plan_fits`,
    else falls back to sequential and counts the fallback.
    """
    if mode not in BPTT_MODES:
        raise ValueError(
            f"bptt={mode!r} not in {BPTT_MODES} — pick 'sequential' "
            "(reverse-mode through the scan), 'assoc' (parallel-scan "
            "adjoint chain), or 'auto' (assoc when the memory plan fits "
            f"and T >= {AUTO_MIN_T})")
    if mode == "auto":
        tile = pick_tile(T, remat_chunk)
        if T >= AUTO_MIN_T and plan_fits(batch, T, hidden, tile=tile):
            return "assoc"
        _STATS["sequential_fallbacks"] += 1
        return "sequential"
    return mode


# ---- the custom-VJP core (forward time order; wrapper handles reverse) ----


def _project(fused, xs_t):
    """Input projection for the whole [T, B, D] block in one MXU matmul —
    same hoisting as ops/scan.py `lstm_scan.project` (float32 out)."""
    z = jnp.dot(xs_t.astype(fused.kernel.dtype), fused.kernel,
                preferred_element_type=jnp.float32)
    return z + fused.bias


def _apply_adjoint(U_T, coeff, gh, gc):
    """Apply one step's adjoint operator ``A_t^T`` (factored form — the
    gate-local diagonals plus one shared matmul against the fused
    recurrent kernel; dense [2H, 2H] blocks never appear here) to a
    stack of K adjoint vectors.

    ``coeff`` = (q, ci, cf, cg, co, f, m) each [..., H] (m [..., 1] or
    None); ``gh``/``gc`` [..., K, H]. Returns (gh_prev, gc_prev, gz)
    with ``gz`` [..., K, 4H] the pre-activation cotangents (gate order
    i, f, g, o — `ops/lstm_cell.GATE_ORDER`).
    """
    q, ci, cf, cg, co, f, m = coeff
    col = lambda a: a[..., None, :]  # noqa: E731 — broadcast over K
    if m is not None:
        mm = col(m)
        gh_m = gh * mm
        gc_m = gc * mm
    else:
        gh_m, gc_m = gh, gc
    gc_hat = gc_m + gh_m * col(q)
    gz = jnp.concatenate([
        gc_hat * col(ci),
        gc_hat * col(cf),
        gc_hat * col(cg),
        gh_m * col(co),
    ], axis=-1)
    gh_prev = jnp.dot(gz, U_T)
    gc_prev = gc_hat * col(f)
    if m is not None:
        inv = 1.0 - mm
        gh_prev = gh_prev + inv * gh
        gc_prev = gc_prev + inv * gc
    return gh_prev, gc_prev, gz


def _forward_scan(fused, xs_t, carry, mask_t):
    """The sequential forward (identical step math to ops/scan.py),
    additionally emitting the c sequence next to ys — the only
    residuals the assoc backward needs (gates rebuild in one matmul)."""

    def step(c, inp):
        if mask_t is None:
            new_carry, _ = lstm_step_hoisted(fused, c, inp)
        else:
            zx, mb = inp
            (h_new, c_new), _ = lstm_step_hoisted(fused, c, zx)
            h = jnp.where(mb, h_new, c[0])
            cc = jnp.where(mb, c_new, c[1])
            new_carry = (h, cc)
        return new_carry, new_carry

    inp = _project(fused, xs_t)
    if mask_t is not None:
        inp = (inp, mask_t)
    (hT, cT), (hs, cs) = lax.scan(step, carry, inp)
    return (hT, cT), hs, cs


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _assoc_core(tile, compute_dtype, params, xs, carry, mask_f):
    out, _ = _assoc_core_fwd(tile, compute_dtype, params, xs, carry, mask_f)
    return out


def _assoc_core_fwd(tile, compute_dtype, params, xs, carry, mask_f):
    fused = fuse_params(params, compute_dtype=compute_dtype)
    xs_t = jnp.moveaxis(xs, 0, 1)  # [T, B, D]
    mask_t = None
    if mask_f is not None:
        mask_t = jnp.moveaxis(mask_f, 0, 1)[..., None] != 0
    (hT, cT), hs, cs = _forward_scan(fused, xs_t, carry, mask_t)
    out = ((hT, cT), jnp.moveaxis(hs, 0, 1))
    return out, (params, xs, carry, mask_f, hs, cs)


def _assoc_core_bwd(tile, compute_dtype, res, ct):
    params, xs, carry, mask_f, hs, cs = res
    (ghT, gcT), gys_bm = ct
    fused = fuse_params(params, compute_dtype=compute_dtype)
    B, T, _ = xs.shape
    H = params.hidden_size
    n_chunks = T // tile
    K = 2 * H + 1
    f32 = jnp.float32

    xs_t = jnp.moveaxis(xs, 0, 1)
    gys = jnp.moveaxis(gys_bm, 0, 1).astype(f32)          # [T, B, H]
    h0, c0 = carry
    h_prev = jnp.concatenate([h0[None].astype(hs.dtype), hs[:-1]], axis=0)
    c_prev = jnp.concatenate([c0[None].astype(cs.dtype), cs[:-1]], axis=0)

    # gate recompute: ONE fused matmul over all T steps (the remat-style
    # trade — h/c residuals in, every sigma/tanh activation back out)
    z = _project(fused, xs_t) + jnp.dot(
        h_prev.astype(fused.recurrent.dtype), fused.recurrent,
        preferred_element_type=f32)
    zi, zf, zg, zo = jnp.split(z, 4, axis=-1)
    gi = jax.nn.sigmoid(zi)
    gf = jax.nn.sigmoid(zf)
    gg = jnp.tanh(zg)
    go = jax.nn.sigmoid(zo)
    # tanh of the UNFROZEN cell update (== cs at unmasked steps; at
    # masked steps the factors are m-zeroed, but rebuilding from the
    # gates keeps them exact regardless)
    tc = jnp.tanh(gf * c_prev + gi * gg)

    # gate-local diagonal factors of A_t^T
    q = go * (1.0 - tc * tc)
    ci = gg * gi * (1.0 - gi)
    cf = c_prev * gf * (1.0 - gf)
    cg = gi * (1.0 - gg * gg)
    co = tc * go * (1.0 - go)
    m = None
    if mask_f is not None:
        m = jnp.moveaxis(mask_f, 0, 1).astype(f32)[..., None]  # [T, B, 1]
    U_T = fused.recurrent.astype(f32).T                        # [4H, H]

    def chunkify(a):  # [T, ...] -> [tile, NC, ...] (local time leading)
        return a.reshape(n_chunks, tile, *a.shape[1:]).swapaxes(0, 1)

    coeffs = tuple(chunkify(a) for a in (q, ci, cf, cg, co, gf))
    coeffs = coeffs + ((chunkify(m) if m is not None else None),)
    gys_ch = chunkify(gys)                                     # [tile, NC, B, H]

    # ---- phase 1: build each chunk's affine operator (all chunks in
    # one scan; per-step op applied in factored form to the K columns) --
    eyeh = jnp.eye(H, dtype=f32)
    zrow = jnp.zeros((1, H), f32)
    Mgh0 = jnp.concatenate([eyeh, jnp.zeros((H, H), f32), zrow], axis=0)
    Mgc0 = jnp.concatenate([jnp.zeros((H, H), f32), eyeh, zrow], axis=0)
    Mgh0 = jnp.broadcast_to(Mgh0, (n_chunks, B, K, H))
    Mgc0 = jnp.broadcast_to(Mgc0, (n_chunks, B, K, H))

    def build_step(acc, inp):
        Mgh, Mgc = acc
        coeff, gy = inp
        # fold this step's output cotangent into the affine column
        Mgh = Mgh.at[..., K - 1, :].add(gy)
        gh2, gc2, _ = _apply_adjoint(U_T, coeff, Mgh, Mgc)
        return (gh2, gc2), None

    (Mgh, Mgc), _ = lax.scan(build_step, (Mgh0, Mgc0), (coeffs, gys_ch),
                             reverse=True)

    # ---- phase 2: log-depth tree over the chunk operators -------------
    # row convention: lambda_prev = lambda_next @ M + d
    M_blocks = jnp.concatenate([Mgh[:, :, :2 * H, :], Mgc[:, :, :2 * H, :]],
                               axis=-1)                    # [NC, B, 2H, 2H]
    d_vecs = jnp.concatenate([Mgh[:, :, K - 1, :], Mgc[:, :, K - 1, :]],
                             axis=-1)                      # [NC, B, 2H]

    def combine(a, b):
        # suffix composition in row convention (lambda' = lambda @ M + d):
        # under associative_scan(reverse=True) the FIRST argument holds
        # the later-in-time (applied-first) side, so the composed map is
        # lambda @ M_a @ M_b + d_a @ M_b + d_b (validated against a
        # step-at-a-time reference in tests/test_parallel_scan.py)
        Ma, da = a
        Mb, db = b
        return (jnp.matmul(Ma, Mb),
                jnp.einsum("cbi,cbio->cbo", da, Mb) + db)

    S_M, S_d = lax.associative_scan(combine, (M_blocks, d_vecs),
                                    reverse=True, axis=0)
    lam_fin = jnp.concatenate([ghT.astype(f32), gcT.astype(f32)], axis=-1)
    applied = jnp.einsum("bi,cbio->cbo", lam_fin, S_M) + S_d   # [NC, B, 2H]
    # adjoint entering chunk c from the right = suffix over chunks > c
    lam_end = jnp.concatenate([applied[1:], lam_fin[None]], axis=0)

    # ---- phase 3: interior replay (all chunks in one scan), emitting
    # the per-step gate cotangents -------------------------------------
    def replay_step(acc, inp):
        gh, gc = acc
        coeff, gy = inp
        gh = gh + gy
        gh2, gc2, gz = _apply_adjoint(
            U_T, coeff, gh[..., None, :], gc[..., None, :])
        return (gh2[..., 0, :], gc2[..., 0, :]), gz[..., 0, :]

    (gh_in, gc_in), gz_ch = lax.scan(
        replay_step, (lam_end[..., :H], lam_end[..., H:]),
        (coeffs, gys_ch), reverse=True)
    gz = gz_ch.swapaxes(0, 1).reshape(T, B, 4 * H)             # [T, B, 4H]

    # ---- gradients: three large batched matmuls ----------------------
    dt = fused.kernel.dtype
    g_kernel = jnp.einsum("tbd,tbk->dk", xs_t.astype(dt), gz).astype(f32)
    g_recur = jnp.einsum("tbh,tbk->hk", h_prev.astype(dt), gz).astype(f32)
    g_bias = gz.sum(axis=(0, 1))
    g_xs = jnp.einsum("tbk,dk->tbd", gz, fused.kernel.astype(f32))
    g_xs = jnp.moveaxis(g_xs, 0, 1).astype(xs.dtype)
    gW = jnp.split(g_kernel, 4, axis=1)
    gU = jnp.split(g_recur, 4, axis=1)
    gb = jnp.split(g_bias, 4)
    g_params = LSTMParams(*gW, *gU, *gb)
    g_params = jax.tree.map(lambda g, p: g.astype(p.dtype), g_params, params)
    g_carry = (gh_in[0].astype(h0.dtype), gc_in[0].astype(c0.dtype))
    g_mask = None if mask_f is None else jnp.zeros_like(mask_f)
    return g_params, g_xs, g_carry, g_mask


_assoc_core.defvjp(_assoc_core_fwd, _assoc_core_bwd)


def assoc_lstm_scan(
    params: LSTMParams,
    xs: jax.Array,
    carry: tuple[jax.Array, jax.Array] | None = None,
    *,
    mask: jax.Array | None = None,
    reverse: bool = False,
    remat_chunk: int | None = None,
    compute_dtype=None,
    unroll: int = 1,
    tile: int | None = None,
):
    """`ops/scan.lstm_scan` with the associative-scan backward.

    Same signature and return contract — ``((h_T, c_T), ys)``, ys
    [B, T, H] — and the same forward values (the forward is the same
    hoisted-projection scan); only the VJP differs. ``unroll`` is
    accepted for signature parity and ignored (the backward's depth
    comes from the tile/tree split, not loop unrolling). ``tile``
    defaults to `pick_tile` (remat_chunk when it divides T).
    """
    B, T, _ = xs.shape
    if remat_chunk is not None and T % remat_chunk != 0:
        raise ValueError(
            f"T={T} not divisible by remat_chunk={remat_chunk} — a tail "
            "chunk would silently change remat (and bptt-mode) semantics; "
            "pad or pick a divisor")
    del unroll
    if carry is None:
        carry = zero_carry(B, params.hidden_size)
    if tile is None:
        tile = pick_tile(T, remat_chunk)
    if T % tile != 0:
        raise ValueError(f"T={T} not divisible by assoc tile={tile}")
    mask_f = None if mask is None else mask.astype(jnp.float32)
    if reverse:
        xs = jnp.flip(xs, axis=1)
        mask_f = None if mask_f is None else jnp.flip(mask_f, axis=1)
    _STATS["assoc_traces"] += 1
    (hT, cT), ys = _assoc_core(int(tile), compute_dtype, params, xs, carry,
                               mask_f)
    if reverse:
        ys = jnp.flip(ys, axis=1)
    return (hT, cT), ys
