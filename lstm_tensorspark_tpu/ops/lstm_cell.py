"""Hand-rolled LSTM cell as pure functions on an explicit parameter pytree.

Reference parity: SURVEY.md §2 "LSTM cell (hand-rolled)" [D] — per-gate affine
transforms + nonlinearities (input i, forget f, output o, cell-candidate g;
``c' = f*c + i*g``, ``h' = o*tanh(c')``) with explicit gate weight matrices
``W_i, W_f, W_g, W_o`` (+ recurrent ``U_*``, biases ``b_*``). The reference
mount was empty during the survey (SURVEY.md §0), so the gate math follows the
driver-confirmed description [D] with standard defaults (forget-gate bias 1.0).

TPU-first design (NOT a translation of the reference's per-gate TF matmuls):
parameters are *stored* per-gate for parity and inspection, but *fused* into a
single ``(D, 4H)`` input kernel / ``(H, 4H)`` recurrent kernel before the
sequence scan, so each recurrence step is two MXU-shaped matmuls instead of
eight small ones. Cell state ``c`` stays float32; matmuls optionally run in
bfloat16 with float32 accumulation (``preferred_element_type``).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

GATE_ORDER = ("i", "f", "g", "o")  # input, forget, cell-candidate, output


class LSTMParams(NamedTuple):
    """Per-gate LSTM parameters (the reference's explicit gate matrices).

    Shapes: W_* (input_size, hidden), U_* (hidden, hidden), b_* (hidden,).
    """

    W_i: jax.Array
    W_f: jax.Array
    W_g: jax.Array
    W_o: jax.Array
    U_i: jax.Array
    U_f: jax.Array
    U_g: jax.Array
    U_o: jax.Array
    b_i: jax.Array
    b_f: jax.Array
    b_g: jax.Array
    b_o: jax.Array

    @property
    def input_size(self) -> int:
        return self.W_i.shape[0]

    @property
    def hidden_size(self) -> int:
        return self.W_i.shape[1]


class FusedLSTMParams(NamedTuple):
    """Gate-fused view: kernel (D, 4H), recurrent (H, 4H), bias (4H,)."""

    kernel: jax.Array
    recurrent: jax.Array
    bias: jax.Array

    @property
    def hidden_size(self) -> int:
        return self.recurrent.shape[0]


def _orthogonal(key: jax.Array, shape, dtype) -> jax.Array:
    return jax.nn.initializers.orthogonal()(key, shape, dtype)


def _glorot(key: jax.Array, shape, dtype) -> jax.Array:
    return jax.nn.initializers.glorot_uniform()(key, shape, dtype)


def init_lstm_params(
    key: jax.Array,
    input_size: int,
    hidden_size: int,
    *,
    dtype=jnp.float32,
    forget_bias: float = 1.0,
) -> LSTMParams:
    """Initialize per-gate parameters.

    Glorot-uniform input kernels, orthogonal recurrent kernels, zero biases
    except the forget gate (``forget_bias``, default 1.0 — the standard
    default assumed for the reference per SURVEY.md §7 "Hard parts").
    """
    kW = jax.random.split(key, 8)
    Ws = [_glorot(kW[j], (input_size, hidden_size), dtype) for j in range(4)]
    Us = [_orthogonal(kW[4 + j], (hidden_size, hidden_size), dtype) for j in range(4)]
    zeros = jnp.zeros((hidden_size,), dtype)
    biases = [zeros, jnp.full((hidden_size,), forget_bias, dtype), zeros, zeros]
    return LSTMParams(*Ws, *Us, *biases)


def fuse_params(params: LSTMParams, *, compute_dtype=None) -> FusedLSTMParams:
    """Concatenate per-gate matrices into MXU-shaped fused kernels.

    Done once per forward pass (outside the scan), so the per-step work is a
    single ``x @ (D,4H)`` plus ``h @ (H,4H)``. Gate order is i, f, g, o.
    """
    kernel = jnp.concatenate([params.W_i, params.W_f, params.W_g, params.W_o], axis=1)
    recurrent = jnp.concatenate([params.U_i, params.U_f, params.U_g, params.U_o], axis=1)
    bias = jnp.concatenate([params.b_i, params.b_f, params.b_g, params.b_o])
    if compute_dtype is not None:
        kernel = kernel.astype(compute_dtype)
        recurrent = recurrent.astype(compute_dtype)
    return FusedLSTMParams(kernel, recurrent, bias)


def lstm_step(
    fused: FusedLSTMParams,
    carry: tuple[jax.Array, jax.Array],
    x: jax.Array,
) -> tuple[tuple[jax.Array, jax.Array], jax.Array]:
    """One recurrence step on fused params.

    carry = (h, c) each [B, H] (h stored in compute dtype, c in float32);
    x is [B, D]. Returns ((h', c'), h').
    """
    h, c = carry
    dtype = fused.kernel.dtype
    z = jnp.dot(x.astype(dtype), fused.kernel, preferred_element_type=jnp.float32)
    z = z + jnp.dot(h.astype(dtype), fused.recurrent, preferred_element_type=jnp.float32)
    z = z + fused.bias
    i, f, g, o = jnp.split(z, 4, axis=-1)
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f)
    g = jnp.tanh(g)
    o = jax.nn.sigmoid(o)
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    return (h_new, c_new), h_new


def lstm_step_hoisted(
    fused: FusedLSTMParams,
    carry: tuple[jax.Array, jax.Array],
    zx: jax.Array,
) -> tuple[tuple[jax.Array, jax.Array], jax.Array]:
    """Recurrence step on a PRE-PROJECTED input: ``zx = x @ kernel + bias``
    [B, 4H] float32, computed for all T steps in one MXU matmul before the
    scan (ops/scan.py). Leaves only the unavoidable sequential work —
    ``h @ recurrent`` + gate nonlinearities — inside the loop, halving the
    per-iteration matmul count (the standard cuDNN-style LSTM split)."""
    h, c = carry
    dtype = fused.recurrent.dtype
    z = zx + jnp.dot(
        h.astype(dtype), fused.recurrent, preferred_element_type=jnp.float32
    )
    i, f, g, o = jnp.split(z, 4, axis=-1)
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f)
    g = jnp.tanh(g)
    o = jax.nn.sigmoid(o)
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    return (h_new, c_new), h_new


def lstm_step_unfused(
    params: LSTMParams,
    carry: tuple[jax.Array, jax.Array],
    x: jax.Array,
) -> tuple[tuple[jax.Array, jax.Array], jax.Array]:
    """Reference-shaped step: eight per-gate matmuls (SURVEY.md §3.2).

    Kept as the parity/readability form and as the oracle for tests; the
    production path is :func:`lstm_step` on fused kernels — both compute the
    same math.
    """
    h, c = carry
    i = jax.nn.sigmoid(x @ params.W_i + h @ params.U_i + params.b_i)
    f = jax.nn.sigmoid(x @ params.W_f + h @ params.U_f + params.b_f)
    g = jnp.tanh(x @ params.W_g + h @ params.U_g + params.b_g)
    o = jax.nn.sigmoid(x @ params.W_o + h @ params.U_o + params.b_o)
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    return (h_new, c_new), h_new


def zero_carry(batch: int, hidden_size: int, dtype=jnp.float32):
    h = jnp.zeros((batch, hidden_size), dtype)
    c = jnp.zeros((batch, hidden_size), jnp.float32)
    return (h, c)
