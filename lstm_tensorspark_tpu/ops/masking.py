"""Variable-length sequence utilities (SURVEY.md §7 "Hard parts": bucketing +
padding + masked loss under XLA's static shapes).

The reference handles only fixed unroll lengths within one worker
(SURVEY.md §5 "Long-context" row); variable-length batches (IMDB seq-400
config, BASELINE.md config 2) are new capability and need masking throughout.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sequence_mask(lengths: jax.Array, maxlen: int) -> jax.Array:
    """Bool mask [B, maxlen]: True where position < length."""
    return jnp.arange(maxlen)[None, :] < lengths[:, None]


def masked_mean(x: jax.Array, mask: jax.Array) -> jax.Array:
    """Mean of x over True mask positions (mask broadcast against x)."""
    mask = mask.astype(x.dtype)
    total = jnp.sum(x * mask)
    count = jnp.maximum(jnp.sum(mask), 1.0)
    return total / count


def dropout_with_key(key: jax.Array, rate: float, x: jax.Array) -> jax.Array:
    """Inverted dropout with a caller-derived key (no split chain): the ONE
    mask/scale implementation — the SP/PP parallel backends call this with
    deterministically folded per-(shard, microbatch, layer) keys."""
    if rate <= 0.0:
        return x
    keep = jax.random.bernoulli(key, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0.0)


def dropout(rng: jax.Array, rate: float, x: jax.Array):
    """Inverted dropout. Returns (next_rng, dropped_x); identity at rate 0."""
    if rate <= 0.0:
        return rng, x
    rng, sub = jax.random.split(rng)
    return rng, dropout_with_key(sub, rate, x)


def reverse_sequences(x: jax.Array, lengths: jax.Array) -> jax.Array:
    """Reverse each row's first ``length`` elements, leaving padding in place.

    x: [B, T, ...], lengths: [B]. Used to feed the backward direction of a
    bi-LSTM when not using the mask-freeze reversed scan.
    """
    T = x.shape[1]
    t = jnp.arange(T)[None, :]
    src = jnp.where(t < lengths[:, None], lengths[:, None] - 1 - t, t)
    return jnp.take_along_axis(
        x, src.reshape(src.shape + (1,) * (x.ndim - 2)), axis=1
    )
