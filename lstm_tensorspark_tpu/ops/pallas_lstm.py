"""Fused Pallas TPU kernel for the LSTM recurrence.

Motivation (SURVEY.md §2 native-capability table: "optional Pallas kernel
for the fused cell if XLA fusion is insufficient"): under `lax.scan` XLA
executes T small programs, each round-tripping h/c and the gate activations
through HBM. This kernel runs the WHOLE sequence in one `pallas_call`:

- the input projection ``X @ W + b`` for all T steps is hoisted OUT of the
  recurrence into one large MXU matmul (XLA does this part best);
- the serial part — ``z_t = Xproj_t + h @ U``, gates, state update — runs
  over a sequential grid of T steps with h and c RESIDENT IN VMEM scratch
  (TPU grids execute in order, so scratch carries state between steps);
- per step the kernel touches HBM only for its Xproj block (streamed in)
  and its ys block (streamed out): 2*B*H + B*4H floats instead of the
  scan's intermediates.

Training support: `pallas_lstm_scan` carries a custom VJP with TWO backward
strategies:
- default: a hand-written FUSED BPTT kernel (`_lstm_bwd_kernel`) — reverse
  sequential grid with dh/dc carries and the dU accumulator resident in
  VMEM, consuming the z/c trajectories the train-mode forward streams out.
  Gate math recomputes from saved f32 z, but the two backward matmuls run
  in the compute dtype, so bf16 grads agree with the scan reference only to
  bf16 tolerance (not bit-exact);
- fallback (when `remat_chunk` is set — memory priority — or the backward's
  VMEM residents don't fit): re-run the pure-jax scan under `jax.vjp`
  (full-recompute, remat-style), bit-exact with the reference BPTT.

Tiling constraints (pallas_guide.md): last dim 128 lanes; float32 sublane 8.
`supported()` gates on B % 8 == 0 and H % 128 == 0; callers fall back to
`lstm_scan` otherwise.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .lstm_cell import LSTMParams, fuse_params
from .scan import lstm_scan


_VMEM_BUDGET = 12 * 2**20  # bytes; conservative vs ~16 MiB/core


def supported(
    batch: int,
    hidden: int,
    platform: str | None = None,
    *,
    param_dtype_bytes: int = 4,
) -> bool:
    """Can the fused kernel run these shapes on this platform?

    Besides tiling divisibility, checks VMEM feasibility: the kernel keeps
    the recurrent matrix U (H, 4H) plus h/c state, carry in/out blocks and
    the streamed xproj/ys blocks resident in VMEM. Shapes that would blow
    the budget (e.g. H=1024 f32: U alone is 16 MiB) fall back to lstm_scan
    instead of failing Mosaic compilation.
    """
    if platform is None:
        platform = jax.default_backend()
    resident = (
        4 * hidden * hidden * param_dtype_bytes  # U (H, 4H)
        + 8 * batch * 4 * hidden * 4  # xproj block (worst-case chunk=8), f32
        + (8 + 6) * batch * hidden * 4  # ys block + h0/c0/hT/cT + h/c scratch
    )
    return (
        platform == "tpu"
        and batch % 8 == 0
        and hidden % 128 == 0
        and resident <= _VMEM_BUDGET
    )


def _lstm_kernel(xproj_ref, u_ref, h0_ref, c0_ref, ys_ref, hT_ref, cT_ref,
                 *rest, hidden: int, chunk: int, save_residuals: bool):
    """Forward recurrence. With ``save_residuals`` the kernel additionally
    streams out the gate pre-activations z_t and cell states c_t — the
    residuals `_lstm_bwd_kernel` consumes (no recompute in the backward)."""
    if save_residuals:
        z_ref, cs_ref, h_scr, c_scr = rest
    else:
        h_scr, c_scr = rest
    t = pl.program_id(0)
    T = pl.num_programs(0)

    @pl.when(t == 0)
    def _():
        h_scr[:] = h0_ref[:]
        c_scr[:] = c0_ref[:]

    H = hidden
    h = h_scr[:]
    c = c_scr[:]
    # ``chunk`` sequential time-steps per grid step (python-unrolled): the
    # per-grid-step overhead (block index bookkeeping, DMA setup) amortises
    # over the chunk while h/c stay in registers/VMEM between sub-steps.
    for s in range(chunk):
        z = xproj_ref[s] + jnp.dot(
            h.astype(u_ref.dtype), u_ref[:], preferred_element_type=jnp.float32
        )
        if save_residuals:
            z_ref[s] = z
        i = jax.nn.sigmoid(z[:, :H])
        f = jax.nn.sigmoid(z[:, H : 2 * H])
        g = jnp.tanh(z[:, 2 * H : 3 * H])
        o = jax.nn.sigmoid(z[:, 3 * H :])
        c = f * c + i * g
        h = o * jnp.tanh(c)
        ys_ref[s] = h
        if save_residuals:
            cs_ref[s] = c
    h_scr[:] = h
    c_scr[:] = c

    @pl.when(t == T - 1)
    def _():
        hT_ref[:] = h
        cT_ref[:] = c


def _time_chunk(T: int) -> int:
    """Largest chunk (≤8) dividing T — python-unrolled inside the kernel."""
    for c in (8, 4, 2):
        if T % c == 0:
            return c
    return 1


def _bwd_supported(batch: int, hidden: int, param_dtype_bytes: int) -> bool:
    """Can the FUSED backward kernel hold its residents in VMEM?

    Residents: U^T (4H, H), the f32 dU accumulator (H, 4H) TWICE (scratch +
    whole-array output block), dh/dc scratch, and the streamed per-chunk
    blocks (z, dys, c, c_prev, h_prev in; dz out) — counted ×2 for the
    pipeline's double-buffering. Falls back to the remat-recompute backward
    otherwise — a memory/speed trade, never a capability loss."""
    streamed = (
        8 * batch * 4 * hidden * 4 * 2  # z in + dz out blocks (chunk<=8)
        + 8 * batch * hidden * 4 * 4  # dys/c/c_prev/h_prev blocks
    )
    resident = (
        4 * hidden * hidden * param_dtype_bytes  # U^T
        + 2 * 4 * hidden * hidden * 4  # dU: f32 scratch + output block
        + streamed * 2  # double-buffered pipelining
        + 4 * batch * hidden * 4  # dh/dc scratch + dh0/dc0 out
    )
    return resident <= _VMEM_BUDGET


def _lstm_bwd_kernel(z_ref, dys_ref, c_ref, cprev_ref, hprev_ref, ut_ref,
                     dhT_ref, dcT_ref,
                     dz_ref, du_ref, dh0_ref, dc0_ref,
                     dh_scr, dc_scr, du_scr, *, hidden: int, chunk: int):
    """Fused BPTT: reverse sequential grid; dh/dc carries and the dU
    accumulator live in VMEM scratch across grid steps. Per time-step:
    gate recompute from saved z (VPU), cotangent algebra (VPU), and two
    MXU matmuls — dz @ U^T for the carry, h_prev^T @ dz into dU."""
    t = pl.program_id(0)
    T = pl.num_programs(0)
    H = hidden

    @pl.when(t == 0)
    def _():
        dh_scr[:] = dhT_ref[:]
        dc_scr[:] = dcT_ref[:]
        du_scr[:] = jnp.zeros_like(du_scr)

    dh = dh_scr[:]
    dc = dc_scr[:]
    du = du_scr[:]
    for s in range(chunk - 1, -1, -1):
        z = z_ref[s]
        i = jax.nn.sigmoid(z[:, :H])
        f = jax.nn.sigmoid(z[:, H : 2 * H])
        g = jnp.tanh(z[:, 2 * H : 3 * H])
        o = jax.nn.sigmoid(z[:, 3 * H :])
        c = c_ref[s]
        c_prev = cprev_ref[s]
        tc = jnp.tanh(c)
        dh = dh + dys_ref[s]
        dc = dc + dh * o * (1.0 - tc * tc)
        do = dh * tc * o * (1.0 - o)
        di = dc * g * i * (1.0 - i)
        df = dc * c_prev * f * (1.0 - f)
        dg = dc * i * (1.0 - g * g)
        dz = jnp.concatenate([di, df, dg, do], axis=1)  # [B, 4H] f32
        dz_ref[s] = dz
        dz_c = dz.astype(ut_ref.dtype)
        du = du + jax.lax.dot_general(
            hprev_ref[s].astype(ut_ref.dtype), dz_c,
            (((0,), (0,)), ((), ())),  # contract batch -> [H, 4H]
            preferred_element_type=jnp.float32,
        )
        dh = jnp.dot(dz_c, ut_ref[:], preferred_element_type=jnp.float32)
        dc = dc * f
    dh_scr[:] = dh
    dc_scr[:] = dc
    du_scr[:] = du

    @pl.when(t == T - 1)
    def _():
        dh0_ref[:] = dh
        dc0_ref[:] = dc
        du_ref[:] = du


def _pallas_forward(fused, xs, h0, c0, *, interpret: bool = False,
                    save_residuals: bool = False):
    """xs [B,T,D] -> (ys [B,T,H], hT, cT[, z, cs]). fused: FusedLSTMParams.

    ``save_residuals`` additionally returns the z/c trajectories ([T,B,...])
    for the fused backward."""
    B, T, _ = xs.shape
    H = fused.hidden_size
    dtype = fused.kernel.dtype
    # one big MXU matmul for every step's input projection
    xproj = (
        jnp.einsum(
            "btd,dk->btk", xs.astype(dtype), fused.kernel,
            preferred_element_type=jnp.float32,
        )
        + fused.bias
    )  # [B, T, 4H] f32
    xproj = jnp.moveaxis(xproj, 0, 1)  # [T, B, 4H]
    C = _time_chunk(T)

    out_specs = [
        pl.BlockSpec((C, B, H), lambda t: (t, 0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec(memory_space=pltpu.VMEM),
        pl.BlockSpec(memory_space=pltpu.VMEM),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((T, B, H), jnp.float32),
        jax.ShapeDtypeStruct((B, H), jnp.float32),
        jax.ShapeDtypeStruct((B, H), jnp.float32),
    ]
    if save_residuals:
        out_specs += [
            pl.BlockSpec((C, B, 4 * H), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((C, B, H), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
        ]
        out_shape += [
            jax.ShapeDtypeStruct((T, B, 4 * H), jnp.float32),
            jax.ShapeDtypeStruct((T, B, H), jnp.float32),
        ]

    kernel = functools.partial(
        _lstm_kernel, hidden=H, chunk=C, save_residuals=save_residuals
    )
    out = pl.pallas_call(
        kernel,
        grid=(T // C,),
        in_specs=[
            pl.BlockSpec((C, B, 4 * H), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),  # U resident
            pl.BlockSpec(memory_space=pltpu.VMEM),  # h0
            pl.BlockSpec(memory_space=pltpu.VMEM),  # c0
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((B, H), jnp.float32),
            pltpu.VMEM((B, H), jnp.float32),
        ],
        interpret=interpret,
    )(xproj, fused.recurrent, h0.astype(jnp.float32), c0.astype(jnp.float32))
    ys = jnp.moveaxis(out[0], 0, 1)
    if save_residuals:
        return ys, out[1], out[2], out[3], out[4]
    return ys, out[1], out[2]


def _pallas_backward(fused, params, xs, h0, c0, ys, z, cs, dys, dhT, dcT,
                     *, interpret: bool = False):
    """Fused BPTT via `_lstm_bwd_kernel` + two big MXU matmuls outside.

    Returns per-gate grads in the LSTMParams structure plus (dxs, dh0, dc0).
    """
    B, T, _ = xs.shape
    H = fused.hidden_size
    dtype = fused.kernel.dtype
    C = _time_chunk(T)

    ys_t = jnp.moveaxis(ys, 0, 1)  # [T, B, H] f32
    h_prev = jnp.concatenate([h0.astype(jnp.float32)[None], ys_t[:-1]], axis=0)
    c_prev = jnp.concatenate([c0.astype(jnp.float32)[None], cs[:-1]], axis=0)
    dys_t = jnp.moveaxis(dys.astype(jnp.float32), 0, 1)
    u_t = fused.recurrent.T  # [4H, H], compute dtype

    kernel = functools.partial(_lstm_bwd_kernel, hidden=H, chunk=C)
    n = T // C
    rev = lambda t: (n - 1 - t, 0, 0)  # reverse-time grid
    dz, dU, dh0, dc0 = pl.pallas_call(
        kernel,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((C, B, 4 * H), rev, memory_space=pltpu.VMEM),  # z
            pl.BlockSpec((C, B, H), rev, memory_space=pltpu.VMEM),      # dys
            pl.BlockSpec((C, B, H), rev, memory_space=pltpu.VMEM),      # c
            pl.BlockSpec((C, B, H), rev, memory_space=pltpu.VMEM),      # c_prev
            pl.BlockSpec((C, B, H), rev, memory_space=pltpu.VMEM),      # h_prev
            pl.BlockSpec(memory_space=pltpu.VMEM),                      # U^T
            pl.BlockSpec(memory_space=pltpu.VMEM),                      # dhT
            pl.BlockSpec(memory_space=pltpu.VMEM),                      # dcT
        ],
        out_specs=[
            pl.BlockSpec((C, B, 4 * H), rev, memory_space=pltpu.VMEM),  # dz
            pl.BlockSpec(memory_space=pltpu.VMEM),                      # dU
            pl.BlockSpec(memory_space=pltpu.VMEM),                      # dh0
            pl.BlockSpec(memory_space=pltpu.VMEM),                      # dc0
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, B, 4 * H), jnp.float32),
            jax.ShapeDtypeStruct((H, 4 * H), jnp.float32),
            jax.ShapeDtypeStruct((B, H), jnp.float32),
            jax.ShapeDtypeStruct((B, H), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((B, H), jnp.float32),
            pltpu.VMEM((B, H), jnp.float32),
            pltpu.VMEM((H, 4 * H), jnp.float32),
        ],
        interpret=interpret,
    )(z, dys_t, cs, c_prev, h_prev, u_t,
      dhT.astype(jnp.float32), dcT.astype(jnp.float32))

    # input-projection cotangents: one MXU matmul each (XLA's job)
    xs_t = jnp.moveaxis(xs, 0, 1).astype(dtype)  # [T, B, D]
    dz_c = dz.astype(dtype)
    dW = jnp.einsum(
        "tbd,tbk->dk", xs_t, dz_c, preferred_element_type=jnp.float32
    )
    db = jnp.sum(dz, axis=(0, 1))
    dxs = jnp.moveaxis(
        jnp.einsum(
            "tbk,dk->tbd", dz_c, fused.kernel,
            preferred_element_type=jnp.float32,
        ),
        0, 1,
    ).astype(xs.dtype)

    Ws = jnp.split(dW, 4, axis=1)
    Us = jnp.split(dU, 4, axis=1)
    bs = jnp.split(db, 4)
    dparams = LSTMParams(*Ws, *Us, *bs)
    dparams = jax.tree.map(lambda g, p: g.astype(p.dtype), dparams, params)
    return dparams, dxs, dh0.astype(h0.dtype), dc0.astype(c0.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _scan_core(params, xs, h0, c0, compute_dtype, interpret, remat_chunk,
               unroll):
    fused = fuse_params(params, compute_dtype=compute_dtype)
    ys, hT, cT = _pallas_forward(fused, xs, h0, c0, interpret=interpret)
    return ys, hT, cT


def _reference(params, xs, h0, c0, compute_dtype, remat_chunk, unroll):
    (hT, cT), ys = lstm_scan(
        params, xs, (h0, c0),
        compute_dtype=compute_dtype, remat_chunk=remat_chunk, unroll=unroll,
    )
    return ys, hT, cT


def _scan_core_fwd(params, xs, h0, c0, compute_dtype, interpret, remat_chunk,
                   unroll):
    fused = fuse_params(params, compute_dtype=compute_dtype)
    pbytes = 2 if fused.kernel.dtype == jnp.bfloat16 else 4
    # Fused Pallas backward when its residents fit VMEM and no remat was
    # requested (remat_chunk is the memory-over-speed signal: the recompute
    # backward stores O(T/chunk) carries, the fused one stores z/cs O(T)).
    if remat_chunk is None and _bwd_supported(xs.shape[0], fused.hidden_size,
                                              pbytes):
        ys, hT, cT, z, cs = _pallas_forward(
            fused, xs, h0, c0, interpret=interpret, save_residuals=True
        )
        return (ys, hT, cT), (params, xs, h0, c0, ys, z, cs)
    out = _scan_core(
        params, xs, h0, c0, compute_dtype, interpret, remat_chunk, unroll
    )
    return out, (params, xs, h0, c0, None, None, None)


def _scan_core_bwd(compute_dtype, interpret, remat_chunk, unroll, residuals,
                   cotangents):
    params, xs, h0, c0, ys, z, cs = residuals
    if z is not None:
        # Fused Pallas BPTT (see _lstm_bwd_kernel).
        fused = fuse_params(params, compute_dtype=compute_dtype)
        dys, dhT, dcT = cotangents
        return _pallas_backward(
            fused, params, xs, h0, c0, ys, z, cs, dys, dhT, dcT,
            interpret=interpret,
        )
    # Remat-style backward: recompute the forward with the pure-jax scan and
    # pull gradients through it — bit-exact with the reference BPTT.
    # remat_chunk bounds the recompute's own residual memory to O(T/chunk)
    # carries, so --use-pallas composes with --remat-chunk on long sequences.
    _, vjp = jax.vjp(
        lambda p, x, h, c: _reference(
            p, x, h, c, compute_dtype, remat_chunk, unroll
        ),
        params, xs, h0, c0,
    )
    return vjp(cotangents)


_scan_core.defvjp(_scan_core_fwd, _scan_core_bwd)


def pallas_lstm_scan(
    params: LSTMParams,
    xs: jax.Array,
    carry: tuple[jax.Array, jax.Array] | None = None,
    *,
    compute_dtype=None,
    remat_chunk: int | None = None,
    unroll: int = 1,
    interpret: bool = False,
):
    """Drop-in fused-kernel variant of `lstm_scan` (no mask/reverse support).

    Backward strategy (module docstring): fused BPTT kernel by default;
    setting ``remat_chunk`` selects the recompute backward (bounded residual
    memory), where ``remat_chunk``/``unroll`` apply to its recompute scan
    exactly as in `lstm_scan`. Returns ``((hT, cT), ys)``.
    """
    B, _, _ = xs.shape
    H = params.hidden_size
    if carry is None:
        h0 = jnp.zeros((B, H), jnp.float32)
        c0 = jnp.zeros((B, H), jnp.float32)
    else:
        h0, c0 = carry
    ys, hT, cT = _scan_core(params, xs, h0, c0, compute_dtype, interpret,
                            remat_chunk, unroll)
    return (hT, cT), ys
