"""Fused Pallas TPU kernels for the LSTM recurrence.

Motivation (SURVEY.md §2 native-capability table: "optional Pallas kernel
for the fused cell if XLA fusion is insufficient"): under `lax.scan` XLA
executes T small programs, each round-tripping h/c and the gate activations
through HBM. These kernels run the WHOLE sequence in one `pallas_call`:

- the input projection ``X @ W + b`` for all T steps is hoisted OUT of the
  recurrence into one large MXU matmul (XLA does this part best);
- the serial part — ``z_t = Xproj_t + h @ U``, gates, state update — runs
  over a sequential grid with h and c RESIDENT IN VMEM scratch (TPU grids
  execute in order, so scratch carries state between steps).

Two kernel strategies, chosen by a single VMEM cost model (`_plan_fwd` /
`_plan_bwd` — both gates derive from the same per-buffer accounting):

- **resident** (small H): the recurrent matrix U lives in VMEM for the whole
  sequence; the grid is time-chunked (``chunk`` steps python-unrolled per
  grid step). Minimum HBM traffic.
- **tiled** (big H, e.g. configs 3/5 at H=650/1024): U cannot fit VMEM, so
  the grid is ``(T, K)`` with U streamed in K row-tiles per step and the
  pre-gate activations accumulated f32 in a full-width VMEM scratch; h is
  kept twice (tile-major for the matmul reads, full-width for the update).
  U streams from HBM once per step — the same per-step U traffic `lax.scan`
  pays — while still deleting the scan's h/c round-trips and per-step
  dispatch overhead.

Hidden sizes that are not lane-aligned (H % 128 != 0, e.g. 650) are
zero-PADDED to the next multiple of 128 per gate block. Padding is exactly
gradient-neutral: padded U/W columns and biases are zero, so padded
pre-activations are z=0, padded gates are (i,f,o)=σ(0)=½, g=tanh(0)=0, and
padded h/c lanes stay exactly 0 through the whole recurrence; all padded
cotangents vanish identically (dz_pad = 0), so sliced gradients equal the
unpadded ones. The pad/slice lives OUTSIDE the custom VJP, so JAX transposes
it automatically.

Variable-length and bidirectional support (the bi-LSTM / seq2seq configs):

- ``mask`` ([B, T] bool) freezes the carry at padded steps exactly as in
  `lstm_scan`: the kernels stream a lane-broadcast f32 mask and blend
  ``m*new + (1-m)*old`` into h and c. The backward applies the transposed
  blend: the skipped cotangent ``(1-m)*dh`` bypasses the gate algebra into
  the previous step.
- ``reverse`` is implemented by flipping the time axis OUTSIDE the custom
  VJP (inputs and mask in, outputs back), so the kernels always run
  forward-in-time and autodiff transposes the flips for free. The flip is a
  strided HBM read XLA fuses into the input projection.

Training support: `pallas_lstm_scan` carries a custom VJP with THREE
backward strategies:
- **resident fused BPTT** (`_lstm_bwd_kernel`): reverse sequential grid with
  dh/dc carries resident in VMEM, consuming the z/c trajectories the
  train-mode forward streams out; the cell state c_t is RECOMPUTED from
  (z_t, c_{t-1}) in-kernel — bit-identical in f32 — so the backward
  streams one fewer [T,B,H] tensor than a save-everything design;
- **tiled fused BPTT** (`_lstm_bwd_tiled_kernel`): the sequential kernel
  computes only dz (streaming U^T in tiles for the dh carry);
- in EVERY strategy the weight cotangents dU/dW/db and dxs are single
  large MXU matmuls OUTSIDE the kernel (XLA's job — they contract over
  T·B at once; an in-kernel dU accumulate would serialize one more MXU
  op with the reverse dependent chain, measured real time on v5e);
- **recompute fallback** (when `remat_chunk` is set — memory priority — or
  the O(T) f32 residuals would exceed `_RESIDUAL_HBM_BUDGET`, or no fused
  kernel fits): re-run the pure-jax scan under `jax.vjp` (remat-style),
  bit-exact with the reference BPTT.

Tiling constraints (pallas_guide.md): last dim 128 lanes; float32 sublane 8.
`supported()` gates on B % 8 == 0 plus the cost model; callers fall back to
`lstm_scan` otherwise.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .lstm_cell import LSTMParams, fuse_params
from .scan import lstm_scan


_VMEM_BUDGET = 12 * 2**20  # bytes; conservative vs ~16 MiB/core
_LANE = 128
# The fused backward saves O(T) f32 residuals (z [T,B,4H] + cs [T,B,H]) in
# HBM. Above this budget the recompute backward is selected instead — the
# memory/speed trade ADVICE.md flagged, now an explicit heuristic
# (override with LSTM_TSP_RESIDUAL_HBM_MB).
_RESIDUAL_HBM_BUDGET = int(os.environ.get("LSTM_TSP_RESIDUAL_HBM_MB", 4096)) * 2**20
# The fully-fused residentx strategy trades the [T,B,4H] xproj/z HBM
# round-trips for in-kernel projection matmuls serialized with the chain.
# Measured on v5e: +28% at T=400 (config 2), −3% at T=64..192 (configs
# 1/4) — the traffic saved scales with T while the serialization cost is
# per-step. Only prefer it for long sequences (tests override to 0).
_FUSEDX_MIN_T = 256


def _pad_to_lane(h: int) -> int:
    return h + (-h % _LANE)


def _residual_dtype(kernel_dtype):
    """Dtype of the big [*, 4H] HBM streams (xproj in, z residual, dz out).

    r4 bandwidth analysis (DESIGN.md): at config-1 class shapes one
    optimizer step moves ~40 copies of T·B·H·4 bytes through HBM when
    every stream is f32 — more than the chip's HBM bandwidth over the
    measured step time, i.e. these configs are STREAM-bound, not
    chain-bound, and that is the missing ~2x between the measured step
    and the chain-latency roofline. Storing the 4H-wide streams in the
    compute dtype halves the dominant traffic. The cell state (cs),
    carries, and ys stay f32 (the recurrence trajectory's precision);
    gate math still runs f32 in-kernel — only the STORED copies round.
    f32 compute keeps f32 streams (bit-exact parity tests unchanged);
    LSTM_TSP_RESIDUAL_F32=1 forces f32 streams under bf16 compute (the
    A/B lever for measuring the saving)."""
    if (kernel_dtype == jnp.bfloat16
            and os.environ.get("LSTM_TSP_RESIDUAL_F32") != "1"):
        return jnp.bfloat16
    return jnp.float32


def _rbytes(pbytes: int) -> int:
    """Cost-model mirror of `_residual_dtype` (pbytes encodes the kernel
    dtype: 2 = bf16, 4 = f32)."""
    if pbytes == 2 and os.environ.get("LSTM_TSP_RESIDUAL_F32") != "1":
        return 2
    return 4


# ---------------------------------------------------------------------------
# Unified VMEM cost model. Every supported()/strategy decision reads these
# four functions; there is no second, implicit accounting (ADVICE.md #1).
# Streamed blocks are counted ×2 for the pipeline's double-buffering.
# ---------------------------------------------------------------------------


def _residentx_fwd_vmem(B: int, H: int, Dp: int, pbytes: int,
                        save_c: bool, has_mask: bool = False,
                        c: int = 8) -> int:
    """Fully-fused resident forward: W AND U live in VMEM, the input
    projection happens in-kernel (one chunk-batched MXU matmul per grid
    step), and nothing but ys/cs ever leaves — the [T,B,4H] xproj and z
    arrays the hoisted variants round-trip through HBM do not exist.
    ``c`` is the time chunk — the planner shrinks it when the streamed
    blocks would not fit at 8."""
    r = _rbytes(pbytes)
    v = 4 * H * H * pbytes  # U resident
    v += Dp * 4 * H * pbytes  # W resident
    v += 4 * H * 4  # bias
    v += 2 * c * B * Dp * r  # xs blocks (double-buffered, stream dtype)
    v += c * B * 4 * H * 4  # in-kernel zx chunk (live value)
    v += 2 * c * B * H * 4  # ys out blocks
    v += 6 * B * H * 4  # h0/c0 in, hT/cT out, h/c scratch
    if has_mask:
        v += 2 * c * B * _LANE * 4  # mask blocks
    if save_c:
        v += 2 * c * B * H * 4  # cs out blocks (the ONLY residual)
    return v


def _residentx_bwd_vmem(B: int, H: int, Dp: int, pbytes: int,
                        has_mask: bool = False, c: int = 8) -> int:
    """Recompute-z fused BPTT: z_t is rebuilt in-kernel from the streamed
    xs/h_prev (W, U resident) instead of being read back from HBM — the
    forward never saved it. ``c`` as in `_residentx_fwd_vmem`."""
    r = _rbytes(pbytes)
    streamed = (
        c * B * Dp * r  # xs blocks (stream dtype)
        + c * B * 4 * H * r  # dz out blocks (stream dtype)
        + c * B * H * 4 * 3  # dys/c_prev/h_prev blocks
    )
    if has_mask:
        streamed += c * B * _LANE * 4  # mask blocks
    return (
        2 * 4 * H * H * pbytes  # U (z recompute) + U^T (dh carry) resident
        + Dp * 4 * H * pbytes  # W resident
        + 4 * H * 4  # bias
        + c * B * 4 * H * 4  # in-kernel zx chunk (live value)
        + streamed * 2  # double-buffered pipelining
        + 4 * B * H * 4  # dh/dc scratch + dh0/dc0 out
    )  # (dU lives outside: contracted from the streamed dz, no accumulator)


def _resident_fwd_vmem(B: int, H: int, pbytes: int, save_residuals: bool,
                       has_mask: bool = False, c: int = 8) -> int:
    """``c`` is the time chunk — r4: the planner shrinks it when the
    streamed blocks would not fit at 8 (previously resident was
    evaluated at the worst-case chunk only, so H=650/1024 fell through
    to the tiled strategy and paid its per-timestep U re-stream — the
    dominant cost the bandwidth analysis exposed; a smaller chunk trades
    some grid-step overhead for keeping U resident)."""
    r = _rbytes(pbytes)
    v = 4 * H * H * pbytes  # U resident
    v += 2 * c * B * 4 * H * r  # xproj blocks (double-buffered, stream dtype)
    v += 2 * c * B * H * 4  # ys out blocks
    v += 6 * B * H * 4  # h0/c0 in, hT/cT out, h/c scratch
    if has_mask:
        v += 2 * c * B * _LANE * 4  # mask blocks
    if save_residuals:
        v += 2 * c * B * 4 * H * r  # z out blocks (stream dtype)
        v += 2 * c * B * H * 4  # cs out blocks
    return v


def _resident_bwd_vmem(B: int, H: int, pbytes: int,
                       has_mask: bool = False, c: int = 8) -> int:
    """``c`` as in `_resident_fwd_vmem` (r4 chunk-flexible planning)."""
    r = _rbytes(pbytes)
    streamed = (
        c * B * 4 * H * r * 2  # z in + dz out blocks (stream dtype)
        + c * B * H * 4 * 2  # dys/c_prev blocks (c_t recomputed; h_prev
                             # not read — dU is contracted outside)
    )
    if has_mask:
        streamed += c * B * _LANE * 4  # mask blocks
    return (
        4 * H * H * pbytes  # U^T resident
        + streamed * 2  # double-buffered pipelining
        + 4 * B * H * 4  # dh/dc scratch + dh0/dc0 out
    )


def _tiled_fwd_vmem(B: int, H: int, pbytes: int, save_residuals: bool,
                    htile: int, has_mask: bool = False) -> int:
    r = _rbytes(pbytes)
    v = 2 * htile * 4 * H * pbytes  # U row-tile (streamed every step)
    v += 2 * B * 4 * H * r  # xproj block (stream dtype)
    v += B * 4 * H * 4  # z accumulator scratch (f32)
    v += 2 * B * H * 4  # h tiles scratch + c scratch
    v += 2 * B * H * 4  # ys out block
    v += 4 * B * H * 4  # h0/c0 in, hT/cT out
    if has_mask:
        v += 2 * B * _LANE * 4  # mask block
    if save_residuals:
        v += 2 * B * 4 * H * r  # z out block (stream dtype)
        v += 2 * B * H * 4  # cs out block
    return v


def _tiled_bwd_vmem(B: int, H: int, pbytes: int, ttile: int,
                    has_mask: bool = False) -> int:
    r = _rbytes(pbytes)
    v = 2 * ttile * H * pbytes  # U^T row-tile
    v += 2 * B * 4 * H * r  # z in block (stream dtype)
    v += 2 * 2 * B * H * 4  # dys/c_prev in blocks (c_t recomputed)
    v += 2 * B * 4 * H * r  # dz out block (stream dtype)
    v += B * 4 * H * 4  # dz tiles scratch
    v += 3 * B * H * 4  # dh/dc/dh-accumulator scratch
    v += 4 * B * H * 4  # dhT/dcT in, dh0/dc0 out
    if has_mask:
        v += 2 * B * _LANE * 4  # mask block
        v += B * H * 4  # dh-skip scratch
    return v


def _plan_fwd(B: int, H: int, pbytes: int, *, save_residuals: bool,
              has_mask: bool = False,
              Dp: int | None = None) -> tuple[str, int] | None:
    """(strategy, htile) for the forward kernel at PADDED hidden size H,
    or None when nothing fits. Preference order = least HBM traffic:
    fully-fused residentx (needs the padded input width ``Dp``; with
    residuals it saves cs ONLY — callers must pair it with the residentx
    backward), then hoisted-projection resident, then the largest feasible
    U row-tile."""
    if Dp is not None:
        for c in (8, 4, 2, 1):
            if _residentx_fwd_vmem(B, H, Dp, pbytes, save_residuals,
                                   has_mask, c) <= _VMEM_BUDGET:
                return ("residentx", c)
    # resident at ANY feasible chunk before tiled (r4): a chunk-1 resident
    # kernel reads U once per pallas_call; tiled re-streams U every
    # timestep — T x 4H x H x pbytes of pure HBM traffic per scan
    for c in (8, 4, 2, 1):
        if _resident_fwd_vmem(B, H, pbytes, save_residuals, has_mask,
                              c) <= _VMEM_BUDGET:
            return ("resident", c)
    for htile in (512, 256, 128):
        if H % htile == 0 and _tiled_fwd_vmem(
                B, H, pbytes, save_residuals, htile, has_mask) <= _VMEM_BUDGET:
            return ("tiled", htile)
    return None


def _plan_bwd(B: int, H: int, pbytes: int, has_mask: bool = False,
              Dp: int | None = None) -> tuple[str, int] | None:
    """(strategy, ttile) for the fused backward kernel, or None → recompute
    fallback. ttile tiles U^T's leading (4H) dim. The residentx strategy
    (recompute-z) is only offered when the matching residentx FORWARD also
    fits — its cs-only residual contract requires the pair."""
    if Dp is not None and _residentx_fwd_vmem(
            B, H, Dp, pbytes, True, has_mask, 1) <= _VMEM_BUDGET:
        for c in (8, 4, 2, 1):
            if _residentx_bwd_vmem(B, H, Dp, pbytes, has_mask,
                                   c) <= _VMEM_BUDGET:
                return ("residentx", c)
    # resident at any feasible chunk before tiled (see _plan_fwd's note);
    # the MATCHING residual-saving forward must also fit, else the pair
    # would plan inconsistently (fwd tiled + bwd resident is fine — both
    # consume/produce the same z/cs streams — but prefer coherent pairs)
    for c in (8, 4, 2, 1):
        if _resident_bwd_vmem(B, H, pbytes, has_mask, c) <= _VMEM_BUDGET:
            return ("resident", c)
    for ttile in (1024, 512, 256, 128):
        if (4 * H) % ttile == 0 and _tiled_bwd_vmem(
                B, H, pbytes, ttile, has_mask) <= _VMEM_BUDGET:
            return ("tiled", ttile)
    return None


def _residual_bytes(T: int, B: int, H: int, bwd_strategy: str = "resident",
                    pbytes: int = 4) -> int:
    if bwd_strategy == "residentx":
        return T * B * H * 4  # cs only (z recomputed in-kernel), f32
    # z [T,B,4H] in the stream dtype + cs [T,B,H] f32
    return T * B * H * (4 * _rbytes(pbytes) + 4)


def chosen_bwd_strategy(B: int, T: int, H: int, pbytes: int, *,
                        has_mask: bool = False, Dp: int | None = None,
                        remat_chunk: int | None = None) -> str:
    """The SINGLE backward-strategy decision: which gradient path a
    `pallas_lstm_scan` at PADDED hidden size ``H`` (and padded input width
    ``Dp``, None when the xproj is hoisted) will actually take —
    ``"residentx"`` / ``"resident"`` / ``"tiled"`` fused kernels, or
    ``"recompute"`` (the pure-jax remat fallback). Both `_scan_core_fwd`
    and bench.py's strategy-aware roofline read THIS function, so the
    published `impl_bwd_strategy` can never diverge from the path that
    ran. Gates, in order: remat_chunk is the explicit memory-priority
    signal; a backward kernel must plan; its O(T) residuals must fit the
    HBM budget; and the matching residual-saving forward must also fit
    (residentx bwd consumes the residentx fwd's cs-only residuals; the
    legacy bwds need z, so their fwd must not take the fusedx path)."""
    plan_b = _plan_bwd(B, H, pbytes, has_mask, Dp)
    if remat_chunk is not None or plan_b is None:
        return "recompute"
    fusedx = plan_b[0] == "residentx"
    ok = (
        _residual_bytes(T, B, H, plan_b[0], pbytes) <= _RESIDUAL_HBM_BUDGET
        and _plan_fwd(B, H, pbytes, save_residuals=True, has_mask=has_mask,
                      Dp=Dp if fusedx else None) is not None
    )
    return plan_b[0] if ok else "recompute"


def supported(
    batch: int,
    hidden: int,
    platform: str | None = None,
    *,
    param_dtype_bytes: int = 4,
    has_mask: bool = False,
) -> bool:
    """Can a fused kernel run these shapes on this platform?

    Hidden sizes are padded to the 128-lane multiple internally, so any H is
    lane-feasible; the gate is batch sublane alignment (B % 8) plus the VMEM
    cost model (`_plan_fwd`) at the padded size — H=650/1024 now plan onto
    the tiled kernel instead of falling back to lstm_scan. ``has_mask``
    accounts for the streamed mask operand of variable-length scans.
    """
    if platform is None:
        platform = jax.default_backend()
    hp = _pad_to_lane(hidden)
    return (
        platform == "tpu"
        and batch % 8 == 0
        and hidden >= 1
        and _plan_fwd(batch, hp, param_dtype_bytes,
                      save_residuals=False, has_mask=has_mask) is not None
    )


# ---------------------------------------------------------------------------
# Fully-fused resident kernels (W AND U in VMEM; xproj in-kernel; the
# backward RECOMPUTES z — neither xproj nor z ever exists in HBM)
# ---------------------------------------------------------------------------


def _lstm_fwdx_kernel(*refs, hidden: int, dpad: int, chunk: int,
                      save_c: bool, has_mask: bool):
    """Fully-fused forward: per grid step, ONE chunk-batched MXU matmul
    ``[C·B, Dp] @ [Dp, 4H]`` projects the whole chunk's inputs into a live
    VMEM value, then the sequential sub-steps add ``h @ U`` and the gates.
    With ``save_c`` only the cell states stream out (the residentx
    backward's sole residual); z is never materialised."""
    n_in = 6 + has_mask
    xs_ref, w_ref, b_ref, u_ref, h0_ref, c0_ref = refs[:6]
    mask_ref = refs[6] if has_mask else None
    ys_ref, hT_ref, cT_ref = refs[n_in:n_in + 3]
    rest = refs[n_in + 3:]
    if save_c:
        cs_ref, h_scr, c_scr = rest
    else:
        h_scr, c_scr = rest
    t = pl.program_id(0)
    T = pl.num_programs(0)
    H = hidden

    @pl.when(t == 0)
    def _():
        h_scr[:] = h0_ref[:]
        c_scr[:] = c0_ref[:]

    zx = jnp.dot(
        xs_ref[:].reshape(-1, dpad).astype(w_ref.dtype), w_ref[:],
        preferred_element_type=jnp.float32,
    ) + b_ref[:]
    zx = zx.reshape(chunk, -1, 4 * H)
    h = h_scr[:]
    c = c_scr[:]
    for s in range(chunk):
        z = zx[s] + jnp.dot(
            h.astype(u_ref.dtype), u_ref[:], preferred_element_type=jnp.float32
        )
        i = jax.nn.sigmoid(z[:, :H])
        f = jax.nn.sigmoid(z[:, H : 2 * H])
        g = jnp.tanh(z[:, 2 * H : 3 * H])
        o = jax.nn.sigmoid(z[:, 3 * H :])
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        if has_mask:
            m = mask_ref[s][:, :1]
            c = m * c_new + (1.0 - m) * c
            h = m * h_new + (1.0 - m) * h
        else:
            c = c_new
            h = h_new
        ys_ref[s] = h
        if save_c:
            cs_ref[s] = c
    h_scr[:] = h
    c_scr[:] = c

    @pl.when(t == T - 1)
    def _():
        hT_ref[:] = h
        cT_ref[:] = c


def _lstm_bwdx_kernel(*refs, hidden: int, dpad: int, chunk: int,
                      has_mask: bool):
    """Recompute-z fused BPTT: the forward saved ONLY the cell states; this
    kernel rebuilds ``z_t = x_t@W + b + h_{t-1}@U`` in-kernel (chunk-batched
    x@W, per-step h_prev@U — bit-identical to the forward's f32 values) and
    runs the same reverse cotangent algebra as `_lstm_bwd_kernel`. Costs one
    extra matmul per step; deletes the [T,B,4H] z round-trip entirely.

    The weight cotangent dU = Σ_t h_{t-1}^T dz_t is NOT accumulated here:
    dz streams out anyway, so `_pallas_backward` contracts it against
    h_prev over all T·B in one large MXU matmul outside — the same split
    the tiled backward uses. That keeps the sequential chain to two MXU
    ops per step (z recompute, dh carry) instead of three — the per-step
    accumulate serialized real MXU issue slots with the chain."""
    n_in = 10 + has_mask
    xs_ref, dys_ref, cprev_ref, hprev_ref = refs[:4]
    mask_ref = refs[4] if has_mask else None
    w_ref, b_ref, u_ref, ut_ref, dhT_ref, dcT_ref = refs[4 + has_mask:n_in]
    dz_ref, dh0_ref, dc0_ref = refs[n_in:n_in + 3]
    dh_scr, dc_scr = refs[n_in + 3:]
    t = pl.program_id(0)
    T = pl.num_programs(0)
    H = hidden

    @pl.when(t == 0)
    def _():
        dh_scr[:] = dhT_ref[:]
        dc_scr[:] = dcT_ref[:]

    zx = jnp.dot(
        xs_ref[:].reshape(-1, dpad).astype(w_ref.dtype), w_ref[:],
        preferred_element_type=jnp.float32,
    ) + b_ref[:]
    zx = zx.reshape(chunk, -1, 4 * H)
    dh = dh_scr[:]
    dc = dc_scr[:]
    for s in range(chunk - 1, -1, -1):
        z = zx[s] + jnp.dot(
            hprev_ref[s].astype(u_ref.dtype), u_ref[:],
            preferred_element_type=jnp.float32,
        )
        i = jax.nn.sigmoid(z[:, :H])
        f = jax.nn.sigmoid(z[:, H : 2 * H])
        g = jnp.tanh(z[:, 2 * H : 3 * H])
        o = jax.nn.sigmoid(z[:, 3 * H :])
        c_prev = cprev_ref[s]
        tc = jnp.tanh(f * c_prev + i * g)  # tanh(c_new), recomputed
        dh_tot = dh + dys_ref[s]
        dc_in = dc
        if has_mask:
            m = mask_ref[s][:, :1]
            dh_eff = m * dh_tot
            dc_eff = m * dc_in
        else:
            dh_eff = dh_tot
            dc_eff = dc_in
        dc_new = dc_eff + dh_eff * o * (1.0 - tc * tc)
        do = dh_eff * tc * o * (1.0 - o)
        di = dc_new * g * i * (1.0 - i)
        df = dc_new * c_prev * f * (1.0 - f)
        dg = dc_new * i * (1.0 - g * g)
        dz = jnp.concatenate([di, df, dg, do], axis=1)  # [B, 4H] f32
        dz_ref[s] = dz.astype(dz_ref.dtype)  # stored in the stream dtype
        dh = jnp.dot(dz.astype(ut_ref.dtype), ut_ref[:],
                     preferred_element_type=jnp.float32)
        dc = dc_new * f
        if has_mask:
            # frozen fraction of the cotangents bypasses the gates
            dh = dh + (1.0 - m) * dh_tot
            dc = dc + (1.0 - m) * dc_in
    dh_scr[:] = dh
    dc_scr[:] = dc

    @pl.when(t == T - 1)
    def _():
        dh0_ref[:] = dh
        dc0_ref[:] = dc


# ---------------------------------------------------------------------------
# Resident kernels (U lives in VMEM; time-chunked grid)
# ---------------------------------------------------------------------------


def _lstm_kernel(*refs, hidden: int, chunk: int, save_residuals: bool,
                 has_mask: bool):
    """Forward recurrence. With ``save_residuals`` the kernel additionally
    streams out the gate pre-activations z_t and cell states c_t — the
    residuals `_lstm_bwd_kernel` consumes (no recompute in the backward).
    With ``has_mask`` a lane-broadcast f32 mask freezes h/c at padded
    steps (carry blend ``m*new + (1-m)*old``, matching `lstm_scan`)."""
    n_in = 4 + has_mask
    xproj_ref, u_ref, h0_ref, c0_ref = refs[:4]
    mask_ref = refs[4] if has_mask else None
    ys_ref, hT_ref, cT_ref = refs[n_in:n_in + 3]
    rest = refs[n_in + 3:]
    if save_residuals:
        z_ref, cs_ref, h_scr, c_scr = rest
    else:
        h_scr, c_scr = rest
    t = pl.program_id(0)
    T = pl.num_programs(0)

    @pl.when(t == 0)
    def _():
        h_scr[:] = h0_ref[:]
        c_scr[:] = c0_ref[:]

    H = hidden
    h = h_scr[:]
    c = c_scr[:]
    # ``chunk`` sequential time-steps per grid step (python-unrolled): the
    # per-grid-step overhead (block index bookkeeping, DMA setup) amortises
    # over the chunk while h/c stay in registers/VMEM between sub-steps.
    for s in range(chunk):
        z = xproj_ref[s].astype(jnp.float32) + jnp.dot(
            h.astype(u_ref.dtype), u_ref[:], preferred_element_type=jnp.float32
        )
        if save_residuals:
            z_ref[s] = z.astype(z_ref.dtype)  # stored in the stream dtype
        i = jax.nn.sigmoid(z[:, :H])
        f = jax.nn.sigmoid(z[:, H : 2 * H])
        g = jnp.tanh(z[:, 2 * H : 3 * H])
        o = jax.nn.sigmoid(z[:, 3 * H :])
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        if has_mask:
            m = mask_ref[s][:, :1]  # [B, 1] f32, lane-broadcasts against H
            c = m * c_new + (1.0 - m) * c
            h = m * h_new + (1.0 - m) * h
        else:
            c = c_new
            h = h_new
        ys_ref[s] = h
        if save_residuals:
            cs_ref[s] = c
    h_scr[:] = h
    c_scr[:] = c

    @pl.when(t == T - 1)
    def _():
        hT_ref[:] = h
        cT_ref[:] = c


def _chunk_for(T: int, cap: int) -> int:
    """Largest chunk ≤ the planner's VMEM-feasible cap that divides T."""
    for c in (8, 4, 2):
        if c <= cap and T % c == 0:
            return c
    return 1


def _lstm_bwd_kernel(*refs, hidden: int, chunk: int, has_mask: bool):
    """Fused BPTT: reverse sequential grid; dh/dc carries live in VMEM
    scratch across grid steps. Per time-step: gate recompute from saved z
    (VPU), cell-state recompute ``c_t = f*c_{t-1} + i*g`` (bit-identical
    f32 — saves streaming c_t), cotangent algebra (VPU), and ONE MXU
    matmul — dz @ U^T for the carry. dU is contracted outside the kernel
    from the streamed dz (see `_lstm_bwdx_kernel`'s note). With
    ``has_mask`` the frozen fraction of the incoming cotangents bypasses
    the gate algebra straight into the previous step (the transpose of
    the forward's carry blend). h_prev is not read at all — it only ever
    fed the dU accumulate — so that input stream is gone too."""
    n_in = 6 + has_mask
    z_ref, dys_ref, cprev_ref = refs[:3]
    mask_ref = refs[3] if has_mask else None
    ut_ref, dhT_ref, dcT_ref = refs[3 + has_mask:n_in]
    dz_ref, dh0_ref, dc0_ref = refs[n_in:n_in + 3]
    dh_scr, dc_scr = refs[n_in + 3:]
    t = pl.program_id(0)
    T = pl.num_programs(0)
    H = hidden

    @pl.when(t == 0)
    def _():
        dh_scr[:] = dhT_ref[:]
        dc_scr[:] = dcT_ref[:]

    dh = dh_scr[:]
    dc = dc_scr[:]
    for s in range(chunk - 1, -1, -1):
        z = z_ref[s].astype(jnp.float32)
        i = jax.nn.sigmoid(z[:, :H])
        f = jax.nn.sigmoid(z[:, H : 2 * H])
        g = jnp.tanh(z[:, 2 * H : 3 * H])
        o = jax.nn.sigmoid(z[:, 3 * H :])
        c_prev = cprev_ref[s]
        tc = jnp.tanh(f * c_prev + i * g)  # tanh(c_new), recomputed
        dh_tot = dh + dys_ref[s]
        dc_in = dc  # incoming dc carry at step t (pre-mask split)
        if has_mask:
            m = mask_ref[s][:, :1]
            dh_eff = m * dh_tot
            dc_eff = m * dc_in
        else:
            dh_eff = dh_tot
            dc_eff = dc_in
        dc_new = dc_eff + dh_eff * o * (1.0 - tc * tc)
        do = dh_eff * tc * o * (1.0 - o)
        di = dc_new * g * i * (1.0 - i)
        df = dc_new * c_prev * f * (1.0 - f)
        dg = dc_new * i * (1.0 - g * g)
        dz = jnp.concatenate([di, df, dg, do], axis=1)  # [B, 4H] f32
        dz_ref[s] = dz.astype(dz_ref.dtype)  # stored in the stream dtype
        dh = jnp.dot(dz.astype(ut_ref.dtype), ut_ref[:],
                     preferred_element_type=jnp.float32)
        dc = dc_new * f
        if has_mask:
            # frozen fraction of the cotangents bypasses the gates
            dh = dh + (1.0 - m) * dh_tot
            dc = dc + (1.0 - m) * dc_in
    dh_scr[:] = dh
    dc_scr[:] = dc

    @pl.when(t == T - 1)
    def _():
        dh0_ref[:] = dh
        dc0_ref[:] = dc


# ---------------------------------------------------------------------------
# Tiled kernels (U streamed in tiles; grid (T, K), chunk = 1)
# ---------------------------------------------------------------------------


def _lstm_tiled_kernel(*refs, hidden: int, htile: int, save_residuals: bool,
                       has_mask: bool):
    """Forward recurrence with U streamed in [htile, 4H] row-tiles.

    Grid (T, K), K = H/htile, k fastest. Per (t, k): accumulate
    ``z += h[:, k-tile] @ U[k-tile, :]`` into the full-width f32 z scratch;
    at the last tile, apply the gates and advance h/c. h is kept twice —
    tile-major ([K, B, htile] scratch, dynamically indexed by k for the
    matmul) and rebuilt with static slices after each step. With
    ``has_mask`` the previous full-width h is reassembled from the tiles
    for the carry blend."""
    n_in = 4 + has_mask
    xproj_ref, u_ref, h0_ref, c0_ref = refs[:4]
    mask_ref = refs[4] if has_mask else None
    ys_ref, hT_ref, cT_ref = refs[n_in:n_in + 3]
    rest = refs[n_in + 3:]
    if save_residuals:
        z_out_ref, cs_ref, h_tiles, c_scr, z_scr = rest
    else:
        h_tiles, c_scr, z_scr = rest
    t = pl.program_id(0)
    k = pl.program_id(1)
    T = pl.num_programs(0)
    K = pl.num_programs(1)
    H = hidden

    @pl.when((t == 0) & (k == 0))
    def _():
        for j in range(K):
            h_tiles[j] = h0_ref[:, j * htile : (j + 1) * htile]
        c_scr[:] = c0_ref[:]

    @pl.when(k == 0)
    def _():
        z_scr[:] = xproj_ref[0].astype(jnp.float32)

    z_scr[:] = z_scr[:] + jnp.dot(
        h_tiles[k].astype(u_ref.dtype), u_ref[:],
        preferred_element_type=jnp.float32,
    )

    @pl.when(k == K - 1)
    def _():
        z = z_scr[:]
        i = jax.nn.sigmoid(z[:, :H])
        f = jax.nn.sigmoid(z[:, H : 2 * H])
        g = jnp.tanh(z[:, 2 * H : 3 * H])
        o = jax.nn.sigmoid(z[:, 3 * H :])
        c_new = f * c_scr[:] + i * g
        h_new = o * jnp.tanh(c_new)
        if has_mask:
            m = mask_ref[0][:, :1]
            h_prev = jnp.concatenate(
                [h_tiles[j] for j in range(K)], axis=1
            )  # previous step's full-width h
            c = m * c_new + (1.0 - m) * c_scr[:]
            h = m * h_new + (1.0 - m) * h_prev
        else:
            c = c_new
            h = h_new
        c_scr[:] = c
        ys_ref[0] = h
        if save_residuals:
            z_out_ref[0] = z.astype(z_out_ref.dtype)  # stream dtype
            cs_ref[0] = c
        for j in range(K):
            h_tiles[j] = h[:, j * htile : (j + 1) * htile]

        @pl.when(t == T - 1)
        def _():
            hT_ref[:] = h
            cT_ref[:] = c


def _lstm_bwd_tiled_kernel(*refs, hidden: int, ttile: int, has_mask: bool):
    """Tiled BPTT: computes ONLY the sequential part — dz_t and the dh/dc
    carries — streaming U^T in [ttile, H] row-tiles for the carry matmul.
    The weight cotangents (dU, dW, db) and dxs contract over all T·B outside
    the kernel as single large MXU matmuls (`_pallas_backward`). The cell
    state c_t is recomputed from (z_t, c_{t-1}). With ``has_mask`` the
    skipped cotangent ``(1-m)*dh_tot`` is staged in a scratch at the first
    tile and added to the carry at the last tile."""
    n_in = 6 + has_mask
    z_ref, dys_ref, cprev_ref = refs[:3]
    mask_ref = refs[3] if has_mask else None
    ut_ref, dhT_ref, dcT_ref = refs[3 + has_mask:n_in]
    dz_ref, dh0_ref, dc0_ref = refs[n_in:n_in + 3]
    scratch = refs[n_in + 3:]
    if has_mask:
        dh_scr, dc_scr, dhacc_scr, dz_tiles, dhskip_scr = scratch
    else:
        dh_scr, dc_scr, dhacc_scr, dz_tiles = scratch
    t = pl.program_id(0)
    k = pl.program_id(1)
    T = pl.num_programs(0)
    K = pl.num_programs(1)
    H = hidden

    @pl.when((t == 0) & (k == 0))
    def _():
        dh_scr[:] = dhT_ref[:]
        dc_scr[:] = dcT_ref[:]

    @pl.when(k == 0)
    def _():
        z = z_ref[0].astype(jnp.float32)
        i = jax.nn.sigmoid(z[:, :H])
        f = jax.nn.sigmoid(z[:, H : 2 * H])
        g = jnp.tanh(z[:, 2 * H : 3 * H])
        o = jax.nn.sigmoid(z[:, 3 * H :])
        c_prev = cprev_ref[0]
        tc = jnp.tanh(f * c_prev + i * g)  # tanh(c_new), recomputed
        dh_tot = dh_scr[:] + dys_ref[0]
        if has_mask:
            m = mask_ref[0][:, :1]
            dh_eff = m * dh_tot
            dc_eff = m * dc_scr[:]
            dhskip_scr[:] = (1.0 - m) * dh_tot
        else:
            dh_eff = dh_tot
            dc_eff = dc_scr[:]
        dc_new = dc_eff + dh_eff * o * (1.0 - tc * tc)
        do = dh_eff * tc * o * (1.0 - o)
        di = dc_new * g * i * (1.0 - i)
        df = dc_new * c_prev * f * (1.0 - f)
        dg = dc_new * i * (1.0 - g * g)
        dz = jnp.concatenate([di, df, dg, do], axis=1)  # [B, 4H] f32
        dz_ref[0] = dz.astype(dz_ref.dtype)  # stream dtype
        for j in range(K):
            dz_tiles[j] = dz[:, j * ttile : (j + 1) * ttile]
        if has_mask:
            dc_scr[:] = dc_new * f + (1.0 - m) * dc_scr[:]
        else:
            dc_scr[:] = dc_new * f
        dhacc_scr[:] = jnp.zeros_like(dhacc_scr)

    dhacc_scr[:] = dhacc_scr[:] + jnp.dot(
        dz_tiles[k].astype(ut_ref.dtype), ut_ref[:],
        preferred_element_type=jnp.float32,
    )

    @pl.when(k == K - 1)
    def _():
        if has_mask:
            dh_scr[:] = dhacc_scr[:] + dhskip_scr[:]
        else:
            dh_scr[:] = dhacc_scr[:]

        @pl.when(t == T - 1)
        def _():
            dh0_ref[:] = dh_scr[:]
            dc0_ref[:] = dc_scr[:]


# ---------------------------------------------------------------------------
# pallas_call wrappers
# ---------------------------------------------------------------------------


def _pad_inputs_lane(xs, kernel, Dp: int, sdtype=jnp.float32):
    """Time-major xs (in the STREAM dtype ``sdtype`` — `_residual_dtype`)
    and W with the input width zero-padded to ``Dp`` (shared by the
    residentx forward AND backward, which must recompute z from
    bit-identical inputs — both call this with the same sdtype). Zero W
    rows multiply zero xs lanes: exact."""
    xs_t = jnp.moveaxis(xs, 0, 1).astype(sdtype)  # [T, B, D]
    D = xs_t.shape[-1]
    if Dp != D:
        xs_t = jnp.pad(xs_t, ((0, 0), (0, 0), (0, Dp - D)))
        kernel = jnp.pad(kernel, ((0, Dp - D), (0, 0)))
    return xs_t, kernel


def _pallas_forward(fused, xs, h0, c0, mask_tbl=None, *,
                    interpret: bool = False, save_residuals: bool = False,
                    allow_fusedx: bool = True):
    """xs [B,T,D] -> (ys [B,T,H], hT, cT[, z, cs]). fused: FusedLSTMParams.

    ``mask_tbl`` (optional) is the lane-broadcast f32 mask [T, B, LANE].
    ``save_residuals`` additionally returns residuals for the fused
    backward: the residentx strategy saves cs ONLY (z is recomputed in its
    backward; the z slot returns None), the others save z AND cs. Callers
    pairing a non-residentx backward must pass ``allow_fusedx=False`` so
    the z residual exists. Strategy comes from the shared cost model."""
    B, T, D = xs.shape
    H = fused.hidden_size
    dtype = fused.kernel.dtype
    pbytes = 2 if dtype == jnp.bfloat16 else 4
    has_mask = mask_tbl is not None
    Dp = (_pad_to_lane(D)
          if allow_fusedx and T >= _FUSEDX_MIN_T else None)
    plan = _plan_fwd(B, H, pbytes, save_residuals=save_residuals,
                     has_mask=has_mask, Dp=Dp)
    if plan is None:  # callers gate via supported(); belt-and-braces
        raise ValueError(f"no pallas forward plan for B={B}, H={H}")
    strategy, parg = plan
    htile = parg  # (tiled strategy; for resident[x] parg is the chunk cap)
    if strategy in ("residentx", "resident"):
        C = _chunk_for(T, parg)
    else:
        C = 1
    mask_spec = pl.BlockSpec((C, B, _LANE), lambda t, *k: (t, 0, 0),
                             memory_space=pltpu.VMEM)

    if strategy == "residentx":
        Dp = _pad_to_lane(D)
        xs_t, w = _pad_inputs_lane(xs, fused.kernel, Dp, _residual_dtype(dtype))
        in_specs = [
            pl.BlockSpec((C, B, Dp), lambda t, *k: (t, 0, 0),
                         memory_space=pltpu.VMEM),  # xs
            pl.BlockSpec(memory_space=pltpu.VMEM),  # W resident
            pl.BlockSpec(memory_space=pltpu.VMEM),  # bias
            pl.BlockSpec(memory_space=pltpu.VMEM),  # U resident
            pl.BlockSpec(memory_space=pltpu.VMEM),  # h0
            pl.BlockSpec(memory_space=pltpu.VMEM),  # c0
        ]
        operands = [xs_t, w, fused.bias.reshape(1, -1).astype(jnp.float32),
                    fused.recurrent, h0.astype(jnp.float32),
                    c0.astype(jnp.float32)]
        if has_mask:
            in_specs.append(mask_spec)
            operands.append(mask_tbl)
        out_specs = [
            pl.BlockSpec((C, B, H), lambda t, *k: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ]
        out_shape = [
            jax.ShapeDtypeStruct((T, B, H), jnp.float32),
            jax.ShapeDtypeStruct((B, H), jnp.float32),
            jax.ShapeDtypeStruct((B, H), jnp.float32),
        ]
        if save_residuals:
            out_specs.append(
                pl.BlockSpec((C, B, H), lambda t, *k: (t, 0, 0),
                             memory_space=pltpu.VMEM)
            )
            out_shape.append(jax.ShapeDtypeStruct((T, B, H), jnp.float32))
        out = pl.pallas_call(
            functools.partial(
                _lstm_fwdx_kernel, hidden=H, dpad=Dp, chunk=C,
                save_c=save_residuals, has_mask=has_mask,
            ),
            grid=(T // C,),
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shape,
            scratch_shapes=[
                pltpu.VMEM((B, H), jnp.float32),  # h
                pltpu.VMEM((B, H), jnp.float32),  # c
            ],
            interpret=interpret,
        )(*operands)
        ys = jnp.moveaxis(out[0], 0, 1)
        if save_residuals:
            return ys, out[1], out[2], None, out[3]
        return ys, out[1], out[2]

    # one big MXU matmul for every step's input projection, accumulated
    # f32 then STORED in the stream dtype (the r4 bandwidth analysis: the
    # [T,B,4H] xproj round-trip is a dominant HBM stream)
    sdtype = _residual_dtype(dtype)
    xproj = (
        jnp.einsum(
            "btd,dk->btk", xs.astype(dtype), fused.kernel,
            preferred_element_type=jnp.float32,
        )
        + fused.bias
    ).astype(sdtype)  # [B, T, 4H]
    xproj = jnp.moveaxis(xproj, 0, 1)  # [T, B, 4H]

    out_specs = [
        pl.BlockSpec((C, B, H), lambda t, *k: (t, 0, 0),
                     memory_space=pltpu.VMEM),
        pl.BlockSpec(memory_space=pltpu.VMEM),
        pl.BlockSpec(memory_space=pltpu.VMEM),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((T, B, H), jnp.float32),
        jax.ShapeDtypeStruct((B, H), jnp.float32),
        jax.ShapeDtypeStruct((B, H), jnp.float32),
    ]
    if save_residuals:
        out_specs += [
            pl.BlockSpec((C, B, 4 * H), lambda t, *k: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((C, B, H), lambda t, *k: (t, 0, 0),
                         memory_space=pltpu.VMEM),
        ]
        out_shape += [
            jax.ShapeDtypeStruct((T, B, 4 * H), sdtype),  # z: stream dtype
            jax.ShapeDtypeStruct((T, B, H), jnp.float32),
        ]

    xproj_spec = pl.BlockSpec((C, B, 4 * H), lambda t, *k: (t, 0, 0),
                              memory_space=pltpu.VMEM)
    if strategy == "resident":
        kernel = functools.partial(
            _lstm_kernel, hidden=H, chunk=C, save_residuals=save_residuals,
            has_mask=has_mask,
        )
        grid = (T // C,)
        u_spec = pl.BlockSpec(memory_space=pltpu.VMEM)  # U resident
        scratch = [
            pltpu.VMEM((B, H), jnp.float32),  # h
            pltpu.VMEM((B, H), jnp.float32),  # c
        ]
    else:
        K = H // htile
        kernel = functools.partial(
            _lstm_tiled_kernel, hidden=H, htile=htile,
            save_residuals=save_residuals, has_mask=has_mask,
        )
        grid = (T, K)
        u_spec = pl.BlockSpec((htile, 4 * H), lambda t, k: (k, 0),
                              memory_space=pltpu.VMEM)  # U streamed
        scratch = [
            pltpu.VMEM((K, B, htile), jnp.float32),  # h, tile-major
            pltpu.VMEM((B, H), jnp.float32),  # c
            pltpu.VMEM((B, 4 * H), jnp.float32),  # z accumulator
        ]

    in_specs = [
        xproj_spec,
        u_spec,
        pl.BlockSpec(memory_space=pltpu.VMEM),  # h0
        pl.BlockSpec(memory_space=pltpu.VMEM),  # c0
    ]
    operands = [xproj, fused.recurrent,
                h0.astype(jnp.float32), c0.astype(jnp.float32)]
    if has_mask:
        in_specs.append(mask_spec)
        operands.append(mask_tbl)

    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=interpret,
    )(*operands)
    ys = jnp.moveaxis(out[0], 0, 1)
    if save_residuals:
        return ys, out[1], out[2], out[3], out[4]
    return ys, out[1], out[2]


def _pallas_backward(fused, params, xs, h0, c0, mask_tbl, ys, z, cs,
                     dys, dhT, dcT, *, interpret: bool = False):
    """Fused BPTT via `_lstm_bwd_kernel` / `_lstm_bwd_tiled_kernel` + big
    MXU matmuls outside.

    Returns per-gate grads in the LSTMParams structure plus (dxs, dh0, dc0).
    """
    B, T, D = xs.shape
    H = fused.hidden_size
    dtype = fused.kernel.dtype
    pbytes = 2 if dtype == jnp.bfloat16 else 4
    sdtype = _residual_dtype(dtype)  # dtype of the z/dz/xs HBM streams
    has_mask = mask_tbl is not None
    # z is None ⇔ the forward ran residentx and saved cs only — the
    # recompute-z backward is then the ONLY strategy whose residual
    # contract matches (the planner guarantees it fits in that case)
    Dp = _pad_to_lane(D) if z is None else None
    plan = _plan_bwd(B, H, pbytes, has_mask, Dp)
    if plan is None or (z is None and plan[0] != "residentx"):
        raise ValueError(f"no pallas backward plan for B={B}, H={H}")
    strategy, parg = plan
    ttile = parg  # (tiled strategy; for residentx parg is the chunk cap)

    ys_t = jnp.moveaxis(ys, 0, 1)  # [T, B, H] f32
    h_prev = jnp.concatenate([h0.astype(jnp.float32)[None], ys_t[:-1]], axis=0)
    c_prev = jnp.concatenate([c0.astype(jnp.float32)[None], cs[:-1]], axis=0)
    dys_t = jnp.moveaxis(dys.astype(jnp.float32), 0, 1)
    u_t = fused.recurrent.T  # [4H, H], compute dtype

    if strategy == "residentx":
        C = _chunk_for(T, parg)
        n = T // C
        rev = lambda t: (n - 1 - t, 0, 0)  # reverse-time grid
        xs_t, w = _pad_inputs_lane(xs, fused.kernel, Dp, sdtype)
        in_specs = [
            pl.BlockSpec((C, B, Dp), rev, memory_space=pltpu.VMEM),  # xs
            pl.BlockSpec((C, B, H), rev, memory_space=pltpu.VMEM),   # dys
            pl.BlockSpec((C, B, H), rev, memory_space=pltpu.VMEM),   # c_prev
            pl.BlockSpec((C, B, H), rev, memory_space=pltpu.VMEM),   # h_prev
        ]
        operands = [xs_t, dys_t, c_prev, h_prev]
        if has_mask:
            in_specs.append(
                pl.BlockSpec((C, B, _LANE), rev, memory_space=pltpu.VMEM)
            )
            operands.append(mask_tbl)
        in_specs += [
            pl.BlockSpec(memory_space=pltpu.VMEM),                   # W
            pl.BlockSpec(memory_space=pltpu.VMEM),                   # bias
            pl.BlockSpec(memory_space=pltpu.VMEM),                   # U
            pl.BlockSpec(memory_space=pltpu.VMEM),                   # U^T
            pl.BlockSpec(memory_space=pltpu.VMEM),                   # dhT
            pl.BlockSpec(memory_space=pltpu.VMEM),                   # dcT
        ]
        operands += [w, fused.bias.reshape(1, -1).astype(jnp.float32),
                     fused.recurrent, u_t,
                     dhT.astype(jnp.float32), dcT.astype(jnp.float32)]
        dz, dh0, dc0 = pl.pallas_call(
            functools.partial(_lstm_bwdx_kernel, hidden=H, dpad=Dp,
                              chunk=C, has_mask=has_mask),
            grid=(n,),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((C, B, 4 * H), rev, memory_space=pltpu.VMEM),  # dz
                pl.BlockSpec(memory_space=pltpu.VMEM),                   # dh0
                pl.BlockSpec(memory_space=pltpu.VMEM),                   # dc0
            ],
            out_shape=[
                jax.ShapeDtypeStruct((T, B, 4 * H), sdtype),  # dz stream
                jax.ShapeDtypeStruct((B, H), jnp.float32),
                jax.ShapeDtypeStruct((B, H), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((B, H), jnp.float32),
                pltpu.VMEM((B, H), jnp.float32),
            ],
            interpret=interpret,
        )(*operands)
    elif strategy == "resident":
        C = _chunk_for(T, parg)
        n = T // C
        rev = lambda t: (n - 1 - t, 0, 0)  # reverse-time grid
        kernel = functools.partial(_lstm_bwd_kernel, hidden=H, chunk=C,
                                   has_mask=has_mask)
        in_specs = [
            pl.BlockSpec((C, B, 4 * H), rev, memory_space=pltpu.VMEM),  # z
            pl.BlockSpec((C, B, H), rev, memory_space=pltpu.VMEM),   # dys
            pl.BlockSpec((C, B, H), rev, memory_space=pltpu.VMEM),   # c_prev
        ]
        operands = [z, dys_t, c_prev]
        if has_mask:
            in_specs.append(
                pl.BlockSpec((C, B, _LANE), rev, memory_space=pltpu.VMEM)
            )
            operands.append(mask_tbl)
        in_specs += [
            pl.BlockSpec(memory_space=pltpu.VMEM),                   # U^T
            pl.BlockSpec(memory_space=pltpu.VMEM),                   # dhT
            pl.BlockSpec(memory_space=pltpu.VMEM),                   # dcT
        ]
        operands += [u_t, dhT.astype(jnp.float32), dcT.astype(jnp.float32)]
        dz, dh0, dc0 = pl.pallas_call(
            kernel,
            grid=(n,),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((C, B, 4 * H), rev, memory_space=pltpu.VMEM),  # dz
                pl.BlockSpec(memory_space=pltpu.VMEM),                   # dh0
                pl.BlockSpec(memory_space=pltpu.VMEM),                   # dc0
            ],
            out_shape=[
                jax.ShapeDtypeStruct((T, B, 4 * H), sdtype),  # dz stream
                jax.ShapeDtypeStruct((B, H), jnp.float32),
                jax.ShapeDtypeStruct((B, H), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((B, H), jnp.float32),
                pltpu.VMEM((B, H), jnp.float32),
            ],
            interpret=interpret,
        )(*operands)
    else:
        K = 4 * H // ttile
        rev1 = lambda t, k: (T - 1 - t, 0, 0)
        kernel = functools.partial(_lstm_bwd_tiled_kernel, hidden=H,
                                   ttile=ttile, has_mask=has_mask)
        in_specs = [
            pl.BlockSpec((1, B, 4 * H), rev1, memory_space=pltpu.VMEM),  # z
            pl.BlockSpec((1, B, H), rev1, memory_space=pltpu.VMEM),  # dys
            pl.BlockSpec((1, B, H), rev1, memory_space=pltpu.VMEM),  # c_prev
        ]
        operands = [z, dys_t, c_prev]
        if has_mask:
            in_specs.append(
                pl.BlockSpec((1, B, _LANE), rev1, memory_space=pltpu.VMEM)
            )
            operands.append(mask_tbl)
        in_specs += [
            pl.BlockSpec((ttile, H), lambda t, k: (k, 0),
                         memory_space=pltpu.VMEM),                   # U^T tile
            pl.BlockSpec(memory_space=pltpu.VMEM),                   # dhT
            pl.BlockSpec(memory_space=pltpu.VMEM),                   # dcT
        ]
        operands += [u_t, dhT.astype(jnp.float32), dcT.astype(jnp.float32)]
        scratch = [
            pltpu.VMEM((B, H), jnp.float32),          # dh carry
            pltpu.VMEM((B, H), jnp.float32),          # dc carry
            pltpu.VMEM((B, H), jnp.float32),          # dh accumulator
            pltpu.VMEM((K, B, ttile), jnp.float32),   # dz, tile-major
        ]
        if has_mask:
            scratch.append(pltpu.VMEM((B, H), jnp.float32))  # dh skip
        dz, dh0, dc0 = pl.pallas_call(
            kernel,
            grid=(T, K),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, B, 4 * H), rev1, memory_space=pltpu.VMEM),  # dz
                pl.BlockSpec(memory_space=pltpu.VMEM),                   # dh0
                pl.BlockSpec(memory_space=pltpu.VMEM),                   # dc0
            ],
            out_shape=[
                jax.ShapeDtypeStruct((T, B, 4 * H), sdtype),  # dz stream
                jax.ShapeDtypeStruct((B, H), jnp.float32),
                jax.ShapeDtypeStruct((B, H), jnp.float32),
            ],
            scratch_shapes=scratch,
            interpret=interpret,
        )(*operands)

    # dU contracts over all T·B at once — one large MXU matmul for EVERY
    # strategy (the sequential kernels emit dz anyway; accumulating dU
    # in-kernel would serialize an extra MXU op with the reverse chain).
    dU = jnp.einsum(
        "tbh,tbk->hk", h_prev.astype(dtype), dz.astype(dtype),
        preferred_element_type=jnp.float32,
    )

    # input-projection cotangents: one MXU matmul each (XLA's job)
    xs_t = jnp.moveaxis(xs, 0, 1).astype(dtype)  # [T, B, D]
    dz_c = dz.astype(dtype)
    dW = jnp.einsum(
        "tbd,tbk->dk", xs_t, dz_c, preferred_element_type=jnp.float32
    )
    db = jnp.sum(dz, axis=(0, 1), dtype=jnp.float32)
    dxs = jnp.moveaxis(
        jnp.einsum(
            "tbk,dk->tbd", dz_c, fused.kernel,
            preferred_element_type=jnp.float32,
        ),
        0, 1,
    ).astype(xs.dtype)

    Ws = jnp.split(dW, 4, axis=1)
    Us = jnp.split(dU, 4, axis=1)
    bs = jnp.split(db, 4)
    dparams = LSTMParams(*Ws, *Us, *bs)
    dparams = jax.tree.map(lambda g, p: g.astype(p.dtype), dparams, params)
    return dparams, dxs, dh0.astype(h0.dtype), dc0.astype(c0.dtype)


# ---------------------------------------------------------------------------
# custom-VJP core + public entry
# ---------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _scan_core(params, xs, h0, c0, mask_tbl, compute_dtype, interpret,
               remat_chunk, unroll, has_mask):
    fused = fuse_params(params, compute_dtype=compute_dtype)
    ys, hT, cT = _pallas_forward(
        fused, xs, h0, c0, mask_tbl if has_mask else None, interpret=interpret
    )
    return ys, hT, cT


def _mask_bt(mask_tbl):
    """Recover the [B, T] bool mask from the lane-broadcast [T, B, LANE]."""
    return jnp.moveaxis(mask_tbl[:, :, 0] > 0, 0, 1)


def _reference(params, xs, h0, c0, mask, compute_dtype, remat_chunk, unroll):
    (hT, cT), ys = lstm_scan(
        params, xs, (h0, c0), mask=mask,
        compute_dtype=compute_dtype, remat_chunk=remat_chunk, unroll=unroll,
    )
    return ys, hT, cT


def _scan_core_fwd(params, xs, h0, c0, mask_tbl, compute_dtype, interpret,
                   remat_chunk, unroll, has_mask):
    fused = fuse_params(params, compute_dtype=compute_dtype)
    B, T, D = xs.shape
    H = fused.hidden_size
    pbytes = 2 if fused.kernel.dtype == jnp.bfloat16 else 4
    Dp = _pad_to_lane(D) if T >= _FUSEDX_MIN_T else None
    # gate rationale lives on chosen_bwd_strategy — the one decision both
    # this path and bench.py's strategy-aware roofline read
    strategy = chosen_bwd_strategy(B, T, H, pbytes, has_mask=has_mask, Dp=Dp,
                                   remat_chunk=remat_chunk)
    fusedx = strategy == "residentx"
    use_fused_bwd = strategy != "recompute"
    if use_fused_bwd:
        ys, hT, cT, z, cs = _pallas_forward(
            fused, xs, h0, c0, mask_tbl if has_mask else None,
            interpret=interpret, save_residuals=True, allow_fusedx=fusedx,
        )
        return (ys, hT, cT), (params, xs, h0, c0, mask_tbl, ys, z, cs)
    out = _scan_core(
        params, xs, h0, c0, mask_tbl, compute_dtype, interpret, remat_chunk,
        unroll, has_mask,
    )
    return out, (params, xs, h0, c0, mask_tbl, None, None, None)


def _scan_core_bwd(compute_dtype, interpret, remat_chunk, unroll, has_mask,
                   residuals, cotangents):
    params, xs, h0, c0, mask_tbl, ys, z, cs = residuals
    if cs is not None:
        # Fused Pallas BPTT; z is None ⇔ the residentx pair (recompute-z).
        fused = fuse_params(params, compute_dtype=compute_dtype)
        dys, dhT, dcT = cotangents
        dparams, dxs, dh0, dc0 = _pallas_backward(
            fused, params, xs, h0, c0, mask_tbl if has_mask else None,
            ys, z, cs, dys, dhT, dcT, interpret=interpret,
        )
        return dparams, dxs, dh0, dc0, jnp.zeros_like(mask_tbl)
    # Remat-style backward: recompute the forward with the pure-jax scan and
    # pull gradients through it — bit-exact with the reference BPTT.
    # remat_chunk bounds the recompute's own residual memory to O(T/chunk)
    # carries, so --use-pallas composes with --remat-chunk on long sequences.
    mask = _mask_bt(mask_tbl) if has_mask else None
    _, vjp = jax.vjp(
        lambda p, x, h, c: _reference(
            p, x, h, c, mask, compute_dtype, remat_chunk, unroll
        ),
        params, xs, h0, c0,
    )
    dparams, dxs, dh0, dc0 = vjp(cotangents)
    return dparams, dxs, dh0, dc0, jnp.zeros_like(mask_tbl)


_scan_core.defvjp(_scan_core_fwd, _scan_core_bwd)


def _pad_params_lane(params: LSTMParams, hp: int) -> LSTMParams:
    """Zero-pad every gate block from H to hp (lane alignment). Exactly
    gradient-neutral — see the module docstring's padding analysis."""
    pad = hp - params.hidden_size
    pw = lambda a: jnp.pad(a, ((0, 0), (0, pad)))
    pu = lambda a: jnp.pad(a, ((0, pad), (0, pad)))
    pb = lambda a: jnp.pad(a, (0, pad))
    return LSTMParams(
        pw(params.W_i), pw(params.W_f), pw(params.W_g), pw(params.W_o),
        pu(params.U_i), pu(params.U_f), pu(params.U_g), pu(params.U_o),
        pb(params.b_i), pb(params.b_f), pb(params.b_g), pb(params.b_o),
    )


def pallas_lstm_scan(
    params: LSTMParams,
    xs: jax.Array,
    carry: tuple[jax.Array, jax.Array] | None = None,
    *,
    mask: jax.Array | None = None,
    reverse: bool = False,
    compute_dtype=None,
    remat_chunk: int | None = None,
    unroll: int = 1,
    interpret: bool = False,
):
    """Drop-in fused-kernel variant of `lstm_scan` (mask + reverse included).

    ``mask`` ([B, T] bool) freezes the carry at False steps; ``reverse``
    scans right-to-left. Reverse is realised by flipping the time axis
    outside the custom VJP (the kernels always run forward), so a reversed
    masked scan over a right-padded batch — the bi-LSTM's backward direction
    — walks the padding first with a frozen carry, exactly like `lstm_scan`.

    Backward strategy (module docstring): fused BPTT kernel by default;
    setting ``remat_chunk`` selects the recompute backward (bounded residual
    memory), where ``remat_chunk``/``unroll`` apply to its recompute scan
    exactly as in `lstm_scan`. Returns ``((hT, cT), ys)``.

    Hidden sizes off the 128-lane grid (e.g. 650) are padded internally;
    the pad/slice sits outside the custom VJP, so gradients transpose
    through it automatically and exactly.
    """
    B, T, _ = xs.shape
    H = params.hidden_size
    hp = _pad_to_lane(H)
    if reverse:
        xs = jnp.flip(xs, axis=1)
        if mask is not None:
            mask = jnp.flip(mask, axis=1)
    if carry is None:
        h0 = jnp.zeros((B, hp), jnp.float32)
        c0 = jnp.zeros((B, hp), jnp.float32)
    else:
        h0, c0 = carry
        if hp != H:
            h0 = jnp.pad(h0, ((0, 0), (0, hp - H)))
            c0 = jnp.pad(c0, ((0, 0), (0, hp - H)))
    run_params = _pad_params_lane(params, hp) if hp != H else params
    has_mask = mask is not None
    if has_mask:
        mask_tbl = jnp.broadcast_to(
            jnp.moveaxis(mask, 0, 1).astype(jnp.float32)[:, :, None],
            (T, B, _LANE),
        )
    else:
        mask_tbl = jnp.zeros((1, 1, _LANE), jnp.float32)  # unused dummy
    ys, hT, cT = _scan_core(run_params, xs, h0, c0, mask_tbl, compute_dtype,
                            interpret, remat_chunk, unroll, has_mask)
    if hp != H:
        ys, hT, cT = ys[..., :H], hT[:, :H], cT[:, :H]
    if reverse:
        ys = jnp.flip(ys, axis=1)
    return (hT, cT), ys
