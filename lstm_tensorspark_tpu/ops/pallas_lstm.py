"""Fused Pallas TPU kernel for the LSTM recurrence.

Motivation (SURVEY.md §2 native-capability table: "optional Pallas kernel
for the fused cell if XLA fusion is insufficient"): under `lax.scan` XLA
executes T small programs, each round-tripping h/c and the gate activations
through HBM. This kernel runs the WHOLE sequence in one `pallas_call`:

- the input projection ``X @ W + b`` for all T steps is hoisted OUT of the
  recurrence into one large MXU matmul (XLA does this part best);
- the serial part — ``z_t = Xproj_t + h @ U``, gates, state update — runs
  over a sequential grid of T steps with h and c RESIDENT IN VMEM scratch
  (TPU grids execute in order, so scratch carries state between steps);
- per step the kernel touches HBM only for its Xproj block (streamed in)
  and its ys block (streamed out): 2*B*H + B*4H floats instead of the
  scan's intermediates.

Training support: `pallas_lstm_scan` carries a custom VJP whose backward
re-runs the pure-jax scan under `jax.vjp` (full-recompute, remat-style) —
gradients are exactly the reference implementation's, and the fast kernel
needs no hand-written backward.

Tiling constraints (pallas_guide.md): last dim 128 lanes; float32 sublane 8.
`supported()` gates on B % 8 == 0 and H % 128 == 0; callers fall back to
`lstm_scan` otherwise.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .lstm_cell import LSTMParams, fuse_params
from .scan import lstm_scan


_VMEM_BUDGET = 12 * 2**20  # bytes; conservative vs ~16 MiB/core


def supported(
    batch: int,
    hidden: int,
    platform: str | None = None,
    *,
    param_dtype_bytes: int = 4,
) -> bool:
    """Can the fused kernel run these shapes on this platform?

    Besides tiling divisibility, checks VMEM feasibility: the kernel keeps
    the recurrent matrix U (H, 4H) plus h/c state, carry in/out blocks and
    the streamed xproj/ys blocks resident in VMEM. Shapes that would blow
    the budget (e.g. H=1024 f32: U alone is 16 MiB) fall back to lstm_scan
    instead of failing Mosaic compilation.
    """
    if platform is None:
        platform = jax.default_backend()
    resident = (
        4 * hidden * hidden * param_dtype_bytes  # U (H, 4H)
        + 8 * batch * 4 * hidden * 4  # xproj block (worst-case chunk=8), f32
        + (8 + 6) * batch * hidden * 4  # ys block + h0/c0/hT/cT + h/c scratch
    )
    return (
        platform == "tpu"
        and batch % 8 == 0
        and hidden % 128 == 0
        and resident <= _VMEM_BUDGET
    )


def _lstm_kernel(xproj_ref, u_ref, h0_ref, c0_ref, ys_ref, hT_ref, cT_ref,
                 h_scr, c_scr, *, hidden: int, chunk: int):
    t = pl.program_id(0)
    T = pl.num_programs(0)

    @pl.when(t == 0)
    def _():
        h_scr[:] = h0_ref[:]
        c_scr[:] = c0_ref[:]

    H = hidden
    h = h_scr[:]
    c = c_scr[:]
    # ``chunk`` sequential time-steps per grid step (python-unrolled): the
    # per-grid-step overhead (block index bookkeeping, DMA setup) amortises
    # over the chunk while h/c stay in registers/VMEM between sub-steps.
    for s in range(chunk):
        z = xproj_ref[s] + jnp.dot(
            h.astype(u_ref.dtype), u_ref[:], preferred_element_type=jnp.float32
        )
        i = jax.nn.sigmoid(z[:, :H])
        f = jax.nn.sigmoid(z[:, H : 2 * H])
        g = jnp.tanh(z[:, 2 * H : 3 * H])
        o = jax.nn.sigmoid(z[:, 3 * H :])
        c = f * c + i * g
        h = o * jnp.tanh(c)
        ys_ref[s] = h
    h_scr[:] = h
    c_scr[:] = c

    @pl.when(t == T - 1)
    def _():
        hT_ref[:] = h
        cT_ref[:] = c


def _time_chunk(T: int) -> int:
    """Largest chunk (≤8) dividing T — python-unrolled inside the kernel."""
    for c in (8, 4, 2):
        if T % c == 0:
            return c
    return 1


def _pallas_forward(fused, xs, h0, c0, *, interpret: bool = False):
    """xs [B,T,D] -> (ys [B,T,H], hT, cT). fused: FusedLSTMParams."""
    B, T, _ = xs.shape
    H = fused.hidden_size
    dtype = fused.kernel.dtype
    # one big MXU matmul for every step's input projection
    xproj = (
        jnp.einsum(
            "btd,dk->btk", xs.astype(dtype), fused.kernel,
            preferred_element_type=jnp.float32,
        )
        + fused.bias
    )  # [B, T, 4H] f32
    xproj = jnp.moveaxis(xproj, 0, 1)  # [T, B, 4H]
    C = _time_chunk(T)

    kernel = functools.partial(_lstm_kernel, hidden=H, chunk=C)
    ys, hT, cT = pl.pallas_call(
        kernel,
        grid=(T // C,),
        in_specs=[
            pl.BlockSpec((C, B, 4 * H), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),  # U resident
            pl.BlockSpec(memory_space=pltpu.VMEM),  # h0
            pl.BlockSpec(memory_space=pltpu.VMEM),  # c0
        ],
        out_specs=[
            pl.BlockSpec((C, B, H), lambda t: (t, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, B, H), jnp.float32),
            jax.ShapeDtypeStruct((B, H), jnp.float32),
            jax.ShapeDtypeStruct((B, H), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((B, H), jnp.float32),
            pltpu.VMEM((B, H), jnp.float32),
        ],
        interpret=interpret,
    )(xproj, fused.recurrent, h0.astype(jnp.float32), c0.astype(jnp.float32))
    return jnp.moveaxis(ys, 0, 1), hT, cT


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _scan_core(params, xs, h0, c0, compute_dtype, interpret, remat_chunk,
               unroll):
    fused = fuse_params(params, compute_dtype=compute_dtype)
    ys, hT, cT = _pallas_forward(fused, xs, h0, c0, interpret=interpret)
    return ys, hT, cT


def _reference(params, xs, h0, c0, compute_dtype, remat_chunk, unroll):
    (hT, cT), ys = lstm_scan(
        params, xs, (h0, c0),
        compute_dtype=compute_dtype, remat_chunk=remat_chunk, unroll=unroll,
    )
    return ys, hT, cT


def _scan_core_fwd(params, xs, h0, c0, compute_dtype, interpret, remat_chunk,
                   unroll):
    out = _scan_core(
        params, xs, h0, c0, compute_dtype, interpret, remat_chunk, unroll
    )
    return out, (params, xs, h0, c0)


def _scan_core_bwd(compute_dtype, interpret, remat_chunk, unroll, residuals,
                   cotangents):
    # Remat-style backward: recompute the forward with the pure-jax scan and
    # pull gradients through it — bit-exact with the reference BPTT.
    # remat_chunk bounds the recompute's own residual memory to O(T/chunk)
    # carries, so --use-pallas composes with --remat-chunk on long sequences.
    params, xs, h0, c0 = residuals
    _, vjp = jax.vjp(
        lambda p, x, h, c: _reference(
            p, x, h, c, compute_dtype, remat_chunk, unroll
        ),
        params, xs, h0, c0,
    )
    return vjp(cotangents)


_scan_core.defvjp(_scan_core_fwd, _scan_core_bwd)


def pallas_lstm_scan(
    params: LSTMParams,
    xs: jax.Array,
    carry: tuple[jax.Array, jax.Array] | None = None,
    *,
    compute_dtype=None,
    remat_chunk: int | None = None,
    unroll: int = 1,
    interpret: bool = False,
):
    """Drop-in fused-kernel variant of `lstm_scan` (no mask/reverse support).

    ``remat_chunk``/``unroll`` apply to the backward's recompute scan,
    bounding its residual memory / loop overhead exactly as in `lstm_scan`.
    Returns ``((hT, cT), ys)``.
    """
    B, _, _ = xs.shape
    H = params.hidden_size
    if carry is None:
        h0 = jnp.zeros((B, H), jnp.float32)
        c0 = jnp.zeros((B, H), jnp.float32)
    else:
        h0, c0 = carry
    ys, hT, cT = _scan_core(params, xs, h0, c0, compute_dtype, interpret,
                            remat_chunk, unroll)
    return (hT, cT), ys
