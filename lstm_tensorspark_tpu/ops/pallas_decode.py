"""Fused Pallas TPU decode-window kernel for the serve plane.

The serve engine's windowed decode (`serve/engine.py decode_window`) is a
`lax.scan` over the fused cell + head + sampler: XLA executes K small
programs per window, each round-tripping the [B, H] carries and the
[B, V] logits through HBM, plus a gather/scatter pair at the window
boundaries. This kernel runs the WHOLE window in one `pallas_call`:

- the h/c carries of every layer stay RESIDENT IN VMEM across the K
  steps (the paper's O(1) recurrent-state thesis applied to serving:
  an LSTM session's entire decode state is [L, H] — it fits VMEM with
  room to spare, unlike a transformer's KV cache);
- the per-row EOS / budget / finished latches live in VMEM registers
  across the steps — exactly the `decode_window` latch algebra, so a
  window is always safe to run past a row's end (frozen carries, PAD
  output);
- the embedding lookup is a one-hot MXU matmul (the standard TPU
  gather-free embedding — ops/embedding.py does the same for training),
  the gates are the fused-kernel matmuls of `ops/lstm_cell.lstm_step`,
  and the head + sampler run in-kernel, so the ONLY HBM traffic per
  window is weights in (once), token block + row summary out.

**Token-identical sampling.** Greedy is an in-kernel argmax over the
f32-cast logits — bit-identical to `models/generate.sample_logits`.
Temperature sampling uses the Gumbel-argmax identity that
`jax.random.categorical` itself is built on: the (traced) wrapper draws
``gumbel(rng_k, [B, V])`` noise per step with the SAME split chain the
scan path feeds `sample_logits`, and the kernel computes
``argmax(logits/max(t, 1e-6) + noise)`` — float addition is commutative
bit-exactly, so the sampled tokens match the scan window token for
token (tests/test_pallas_decode.py). Top-k / top-p truncation would
need an in-kernel sort; those configs fall back to the scan window
(`ServeEngine` counts the fallback honestly).

**Interpreter-mode fallback**: off-TPU the kernel runs under
``interpret=True`` — the same kernel body executed by XLA on CPU — so
tier-1 proves token parity vs the scan window and `models/generate.py`
without hardware; `tests_tpu/test_pallas_decode_tpu.py` is the
compiled-Mosaic parity + perf gate. Interpreted execution is SLOWER
than the scan path (it exists for correctness coverage, not speed) —
`--decode-kernel auto` therefore resolves to ``scan`` off-TPU.

VMEM plan (`plan_fits` — the serve twin of `ops/pallas_lstm.py`'s
`_plan_fwd` accounting, same 12 MiB budget): weights (embedding, L
fused layer kernels, head) + carries + the [K, B, V] noise block
(sampled mode only) + the [B, V] logits/one-hot working set must fit;
shapes that do not (huge vocab x large batch bucket x deep window)
fall back to the scan window per compile key. docs/OPERATIONS.md
carries the budget table.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: emitted for a dead row's steps — MUST equal serve/engine.py PAD_TOKEN
#: (imported there and asserted equal at engine init; kept literal here
#: so ops/ stays import-independent of serve/)
PAD_TOKEN = -1

_VMEM_BUDGET = 12 * 2**20  # bytes; conservative vs ~16 MiB/core


def sampling_supported(temperature: float, top_k, top_p, greedy: bool) -> bool:
    """Which sampling configs the kernel reproduces bit-exactly: greedy
    (in-kernel argmax) and pure temperature sampling (Gumbel-argmax with
    wrapper-drawn noise). Top-k/top-p need an in-kernel sort — those
    dispatch the scan window instead."""
    if greedy:
        return True
    return top_k is None and top_p is None


def plan_bytes(batch_b: int, window: int, num_layers: int, hidden: int,
               embed: int, vocab: int, *, sampled: bool,
               pbytes: int = 4) -> int:
    """VMEM bytes the kernel needs resident (no grid — one invocation
    holds everything). Mirrors the `ops/pallas_lstm.py` cost-model
    style: every operand + output + the [B, V] working set, counted
    once (nothing streams)."""
    v = vocab * embed * pbytes                      # embedding table
    v += (embed + (num_layers - 1) * hidden) * 4 * hidden * pbytes  # Ws
    v += num_layers * hidden * 4 * hidden * pbytes  # Us
    v += num_layers * 4 * hidden * 4                # biases (f32)
    v += hidden * vocab * pbytes + vocab * 4        # head kernel + bias
    v += 4 * num_layers * batch_b * hidden * 4      # h/c in + out
    v += window * batch_b * 4                       # token block out
    v += 4 * batch_b * 4 * 4                        # row vectors (latches)
    if sampled:
        v += window * batch_b * vocab * 4           # gumbel noise block
    # working set: one-hot + logits + gate pre-activations (live values)
    v += 2 * batch_b * vocab * 4
    v += batch_b * 4 * hidden * 4
    return v


def plan_fits(batch_b: int, window: int, num_layers: int, hidden: int,
              embed: int, vocab: int, *, sampled: bool,
              pbytes: int = 4) -> bool:
    return plan_bytes(batch_b, window, num_layers, hidden, embed, vocab,
                      sampled=sampled, pbytes=pbytes) <= _VMEM_BUDGET


def _decode_window_kernel(*refs, num_layers: int, hidden: int, vocab: int,
                          window: int, temperature: float, greedy: bool,
                          sampled: bool, ldtype):
    """One fused decode window. Carries, latches and the token block all
    live in VMEM for the K python-unrolled steps; the latch algebra is
    the scan window's, verbatim (serve/engine.py `window_fn.step`):

    - rows alive at step entry emit this step's token and commit its
      carry update (the EOS-emitting step still writes carries);
    - dead rows emit PAD_TOKEN, keep frozen carries, and feed token 0
      forward (the value never matters — but a PAD embedding one-hot
      would be all-zeros, which is equally harmless and exactly what
      the comparison produces for -1).
    """
    L = num_layers
    H = hidden
    idx = 0
    emb_ref = refs[idx]; idx += 1
    layer_refs = []
    for _ in range(L):
        layer_refs.append((refs[idx], refs[idx + 1], refs[idx + 2]))
        idx += 3
    head_ref = refs[idx]; idx += 1
    hb_ref = refs[idx]; idx += 1
    h0_ref = refs[idx]; idx += 1
    c0_ref = refs[idx]; idx += 1
    tok_ref = refs[idx]; idx += 1
    alive_ref = refs[idx]; idx += 1
    rem_ref = refs[idx]; idx += 1
    eos_ref = refs[idx]; idx += 1
    noise_ref = None
    if sampled:
        noise_ref = refs[idx]; idx += 1
    (toks_ref, next_ref, alive_out_ref, rem_out_ref,
     h_out_ref, c_out_ref) = refs[idx:idx + 6]

    tok = tok_ref[0]                  # [B] int32
    alive = alive_ref[0] != 0         # [B] bool
    rem = rem_ref[0]                  # [B] int32
    eos = eos_ref[0]                  # [B] int32 (-1 = none)
    B = tok.shape[0]
    hs = [h0_ref[l] for l in range(L)]
    cs = [c0_ref[l] for l in range(L)]

    for k in range(window):
        # embedding gather as a one-hot MXU matmul (exact: 1.0 * row +
        # zeros — bit-identical to jnp.take's row copy)
        onehot = (jax.lax.broadcasted_iota(jnp.int32, (B, vocab), 1)
                  == tok[:, None]).astype(jnp.float32)
        x = jnp.dot(onehot, emb_ref[:].astype(jnp.float32),
                    preferred_element_type=jnp.float32)
        if emb_ref.dtype != jnp.float32:
            # mirror decode_one: jnp.take yields the embedding's dtype,
            # and lstm_step casts x to the kernel dtype from THERE —
            # narrow back so the downstream cast chain is identical
            x = x.astype(emb_ref.dtype)
        new_hs, new_cs = [], []
        for l, (w_ref, u_ref, b_ref) in enumerate(layer_refs):
            # ops/lstm_cell.lstm_step on fused kernels, op for op
            dtype = w_ref.dtype
            z = jnp.dot(x.astype(dtype), w_ref[:],
                        preferred_element_type=jnp.float32)
            z = z + jnp.dot(hs[l].astype(dtype), u_ref[:],
                            preferred_element_type=jnp.float32)
            z = z + b_ref[0]
            i, f, g, o = jnp.split(z, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            f = jax.nn.sigmoid(f)
            g = jnp.tanh(g)
            o = jax.nn.sigmoid(o)
            c_new = f * cs[l] + i * g
            h_new = o * jnp.tanh(c_new)
            new_hs.append(h_new)
            new_cs.append(c_new)
            x = h_new
        # head + sampler (models/generate.decode_one + sample_logits):
        # same dtype chain — near-tied logits must argmax identically
        logits = (
            jnp.dot(x.astype(head_ref.dtype), head_ref[:],
                    preferred_element_type=ldtype)
            + hb_ref[0].astype(ldtype)
        ).astype(jnp.float32)
        if greedy:
            nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        else:
            if temperature != 1.0:
                logits = logits / max(temperature, 1e-6)
            # Gumbel-argmax == jax.random.categorical (float addition is
            # commutative bit-exactly; the wrapper drew noise with the
            # scan path's exact split chain)
            nxt = jnp.argmax(logits + noise_ref[k], axis=-1).astype(jnp.int32)
        # the scan window's latch algebra, verbatim
        emit = alive
        out_tok = jnp.where(emit, nxt, PAD_TOKEN).astype(jnp.int32)
        new_rem = rem - emit.astype(rem.dtype)
        hit_eos = emit & (eos >= 0) & (nxt == eos)
        new_alive = emit & ~hit_eos & (new_rem > 0)
        hs = [jnp.where(emit[:, None], hn, ho)
              for ho, hn in zip(hs, new_hs)]
        cs = [jnp.where(emit[:, None], cn, co)
              for co, cn in zip(cs, new_cs)]
        tok = jnp.where(new_alive, nxt, 0).astype(jnp.int32)
        alive = new_alive
        rem = new_rem
        toks_ref[k] = out_tok

    # the per-row summary the scheduler tick reads (one tiny readback
    # per window instead of Python bookkeeping per row)
    next_ref[0] = tok
    alive_out_ref[0] = alive.astype(jnp.int32)
    rem_out_ref[0] = rem
    for l in range(L):
        h_out_ref[l] = hs[l].astype(jnp.float32)
        c_out_ref[l] = cs[l].astype(jnp.float32)


def decode_window_call(params, fused_layers, cfg, h_in, c_in, tokens,
                       alive, remaining, eos_ids, noise, *, window: int,
                       temperature: float, greedy: bool,
                       interpret: bool):
    """Trace-level entry (called inside the engine's jitted wrapper):
    run one fused decode window over the GATHERED carries.

    ``h_in``/``c_in`` [L, B, H] f32; ``tokens``/``remaining``/``eos_ids``
    [B] int32; ``alive`` [B] bool; ``noise`` [K, B, V] f32 gumbel draws
    (None when greedy). Returns ``(h_out, c_out, toks [K, B] int32,
    next_tok [B] int32, alive_out [B] bool, rem_out [B] int32)`` — the
    exact shapes/dtypes the scan window produces, so the two kernels are
    interchangeable behind one `DecodeWindow`."""
    L, B, H = h_in.shape
    V = cfg.vocab_size
    E = cfg.embed
    sampled = not greedy
    head = params["head"]
    head_kernel = (params["embedding"].T if cfg.tie_embeddings
                   else head["kernel"])

    operands = [params["embedding"]]
    in_specs = [pl.BlockSpec(memory_space=pltpu.VMEM)]
    for fused in fused_layers:
        operands += [fused.kernel, fused.recurrent,
                     fused.bias.reshape(1, -1)]
        in_specs += [pl.BlockSpec(memory_space=pltpu.VMEM)] * 3
    operands += [
        head_kernel, head["bias"].reshape(1, -1),
        h_in, c_in,
        tokens.reshape(1, -1).astype(jnp.int32),
        alive.reshape(1, -1).astype(jnp.int32),
        remaining.reshape(1, -1).astype(jnp.int32),
        eos_ids.reshape(1, -1).astype(jnp.int32),
    ]
    in_specs += [pl.BlockSpec(memory_space=pltpu.VMEM)] * 8
    if sampled:
        operands.append(noise)
        in_specs.append(pl.BlockSpec(memory_space=pltpu.VMEM))

    out_shape = [
        jax.ShapeDtypeStruct((window, B), jnp.int32),   # token block
        jax.ShapeDtypeStruct((1, B), jnp.int32),        # next token
        jax.ShapeDtypeStruct((1, B), jnp.int32),        # alive summary
        jax.ShapeDtypeStruct((1, B), jnp.int32),        # remaining summary
        jax.ShapeDtypeStruct((L, B, H), jnp.float32),   # h out
        jax.ShapeDtypeStruct((L, B, H), jnp.float32),   # c out
    ]
    out_specs = [pl.BlockSpec(memory_space=pltpu.VMEM)] * 6

    toks, next_tok, alive_out, rem_out, h_out, c_out = pl.pallas_call(
        functools.partial(
            _decode_window_kernel, num_layers=L, hidden=H, vocab=V,
            window=window, temperature=temperature, greedy=greedy,
            sampled=sampled, ldtype=cfg.ldtype,
        ),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(*operands)
    # E is only consulted by plan_fits; asserted here so a config whose
    # layer-0 width disagrees with the embedding table fails loudly at
    # trace time instead of producing shape errors inside the kernel
    assert params["embedding"].shape == (V, E), (params["embedding"].shape,
                                                 (V, E))
    return (h_out, c_out, toks, next_tok[0],
            alive_out[0].astype(bool), rem_out[0])


# ---- speculative verify window (draft + target, fused) -----------------


def spec_plan_bytes(batch_b: int, k_draft: int, num_layers: int,
                    hidden: int, embed: int, vocab: int,
                    draft_layers: int, draft_hidden: int,
                    draft_embed: int, *, pbytes: int = 4) -> int:
    """VMEM plan for the fused spec window: BOTH models' weights and
    carries are resident for the whole propose+verify pass. Composed
    from two greedy `plan_bytes` plans (target at W = K+1, draft
    likewise — the draft runs every verify step teacher-forced) plus
    the proposal block; the double-counted [B, V] working set is kept
    as slack (the two models step sequentially, so the true live set is
    smaller — overcounting only ever falls back to the scan window)."""
    w = k_draft + 1
    v = plan_bytes(batch_b, w, num_layers, hidden, embed, vocab,
                   sampled=False, pbytes=pbytes)
    v += plan_bytes(batch_b, w, draft_layers, draft_hidden, draft_embed,
                    vocab, sampled=False, pbytes=pbytes)
    v += k_draft * batch_b * 4  # proposal block
    return v


def spec_plan_fits(batch_b: int, k_draft: int, num_layers: int,
                   hidden: int, embed: int, vocab: int,
                   draft_layers: int, draft_hidden: int,
                   draft_embed: int, *, pbytes: int = 4) -> bool:
    return spec_plan_bytes(
        batch_b, k_draft, num_layers, hidden, embed, vocab,
        draft_layers, draft_hidden, draft_embed,
        pbytes=pbytes) <= _VMEM_BUDGET


def _model_step(tok, hs, cs, emb_ref, layer_refs, head_ref, hb_ref, *,
                vocab: int, ldtype):
    """One greedy decode step of one model inside the kernel — the
    `_decode_window_kernel` per-step body, factored so the spec kernel
    runs it for the target AND the draft. Returns ``(logits_f32,
    new_hs, new_cs)`` (uncommitted — the caller latches)."""
    B = tok.shape[0]
    onehot = (jax.lax.broadcasted_iota(jnp.int32, (B, vocab), 1)
              == tok[:, None]).astype(jnp.float32)
    x = jnp.dot(onehot, emb_ref[:].astype(jnp.float32),
                preferred_element_type=jnp.float32)
    if emb_ref.dtype != jnp.float32:
        x = x.astype(emb_ref.dtype)
    new_hs, new_cs = [], []
    for l, (w_ref, u_ref, b_ref) in enumerate(layer_refs):
        dtype = w_ref.dtype
        z = jnp.dot(x.astype(dtype), w_ref[:],
                    preferred_element_type=jnp.float32)
        z = z + jnp.dot(hs[l].astype(dtype), u_ref[:],
                        preferred_element_type=jnp.float32)
        z = z + b_ref[0]
        i, f, g, o = jnp.split(z, 4, axis=-1)
        i = jax.nn.sigmoid(i)
        f = jax.nn.sigmoid(f)
        g = jnp.tanh(g)
        o = jax.nn.sigmoid(o)
        c_new = f * cs[l] + i * g
        h_new = o * jnp.tanh(c_new)
        new_hs.append(h_new)
        new_cs.append(c_new)
        x = h_new
    logits = (
        jnp.dot(x.astype(head_ref.dtype), head_ref[:],
                preferred_element_type=ldtype)
        + hb_ref[0].astype(ldtype)
    ).astype(jnp.float32)
    return logits, new_hs, new_cs


def _spec_window_kernel(*refs, num_layers: int, hidden: int,
                        draft_layers: int, draft_hidden: int, vocab: int,
                        k_draft: int, ldtype, dldtype):
    """The fused speculative step, greedy-only. Phase 1: the draft
    decodes ``k_draft`` proposals from its VMEM-resident carries (the
    propose-time carries are discarded). Phase 2: ``W = k_draft + 1``
    joint verify steps run the TARGET teacher-forced over [last_token,
    proposals...] with the DRAFT stepping alongside on the same inputs;
    both models' carries latch on the scan spec window's exact ``emit``
    mask (serve/engine.py `_get_spec_window_fn`), the emitted prefix is
    the plain greedy sequence by construction, and the disagreement-
    detecting step emits the target's own argmax as the correction
    token. The returned ``alive`` is the SESSION latch (EOS/budget) —
    a draft miss ends the window, never the conversation."""
    L, Ld = num_layers, draft_layers
    idx = 0
    emb_ref = refs[idx]; idx += 1
    layer_refs = []
    for _ in range(L):
        layer_refs.append((refs[idx], refs[idx + 1], refs[idx + 2]))
        idx += 3
    head_ref = refs[idx]; idx += 1
    hb_ref = refs[idx]; idx += 1
    demb_ref = refs[idx]; idx += 1
    dlayer_refs = []
    for _ in range(Ld):
        dlayer_refs.append((refs[idx], refs[idx + 1], refs[idx + 2]))
        idx += 3
    dhead_ref = refs[idx]; idx += 1
    dhb_ref = refs[idx]; idx += 1
    h0_ref = refs[idx]; idx += 1
    c0_ref = refs[idx]; idx += 1
    dh0_ref = refs[idx]; idx += 1
    dc0_ref = refs[idx]; idx += 1
    tok_ref = refs[idx]; idx += 1
    alive_ref = refs[idx]; idx += 1
    rem_ref = refs[idx]; idx += 1
    eos_ref = refs[idx]; idx += 1
    (toks_ref, next_ref, alive_out_ref, rem_out_ref,
     h_out_ref, c_out_ref, dh_out_ref, dc_out_ref) = refs[idx:idx + 8]

    tok = tok_ref[0]                  # [B] int32
    alive = alive_ref[0] != 0         # [B] bool — window latch, step 0
    rem = rem_ref[0]                  # [B] int32
    eos = eos_ref[0]                  # [B] int32 (-1 = none)
    hs = [h0_ref[l] for l in range(L)]
    cs = [c0_ref[l] for l in range(L)]
    dhs0 = [dh0_ref[l] for l in range(Ld)]
    dcs0 = [dc0_ref[l] for l in range(Ld)]

    # phase 1 — draft proposes K greedy tokens; its propose-time carries
    # are discarded (the verify phase re-runs the draft teacher-forced,
    # which is the state commit)
    props = []
    dhs, dcs = list(dhs0), list(dcs0)
    ptok = tok
    for _ in range(k_draft):
        dlogits, dhs, dcs = _model_step(
            ptok, dhs, dcs, demb_ref, dlayer_refs, dhead_ref, dhb_ref,
            vocab=vocab, ldtype=dldtype)
        ptok = jnp.argmax(dlogits, axis=-1).astype(jnp.int32)
        props.append(ptok)

    # phase 2 — W joint teacher-forced verify steps
    dhs, dcs = list(dhs0), list(dcs0)
    sess_alive = alive
    final_tok = tok
    for i in range(k_draft + 1):
        inp = tok if i == 0 else props[i - 1]
        logits, new_hs, new_cs = _model_step(
            inp, hs, cs, emb_ref, layer_refs, head_ref, hb_ref,
            vocab=vocab, ldtype=ldtype)
        _, new_dhs, new_dcs = _model_step(
            inp, dhs, dcs, demb_ref, dlayer_refs, dhead_ref, dhb_ref,
            vocab=vocab, ldtype=dldtype)
        t = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        emit = alive
        out_tok = jnp.where(emit, t, PAD_TOKEN).astype(jnp.int32)
        new_rem = rem - emit.astype(rem.dtype)
        hit_eos = emit & (eos >= 0) & (t == eos)
        live_on = ~hit_eos & (new_rem > 0)
        sess_alive = jnp.where(emit, live_on, sess_alive)
        if i < k_draft:
            agree = props[i] == t
            alive = emit & live_on & agree
        else:
            # past the last proposal nothing can agree — the window
            # always closes here (the scan fn's -2 sentinel)
            alive = jnp.zeros_like(emit)
        hs = [jnp.where(emit[:, None], hn, ho)
              for ho, hn in zip(hs, new_hs)]
        cs = [jnp.where(emit[:, None], cn, co)
              for co, cn in zip(cs, new_cs)]
        dhs = [jnp.where(emit[:, None], hn, ho)
               for ho, hn in zip(dhs, new_dhs)]
        dcs = [jnp.where(emit[:, None], cn, co)
               for co, cn in zip(dcs, new_dcs)]
        final_tok = jnp.where(emit, t, final_tok).astype(jnp.int32)
        rem = new_rem
        toks_ref[i] = out_tok

    next_ref[0] = jnp.where(sess_alive, final_tok, 0).astype(jnp.int32)
    alive_out_ref[0] = sess_alive.astype(jnp.int32)
    rem_out_ref[0] = rem
    for l in range(L):
        h_out_ref[l] = hs[l].astype(jnp.float32)
        c_out_ref[l] = cs[l].astype(jnp.float32)
    for l in range(Ld):
        dh_out_ref[l] = dhs[l].astype(jnp.float32)
        dc_out_ref[l] = dcs[l].astype(jnp.float32)


def spec_window_call(params, fused_layers, cfg, dparams, dfused_layers,
                     dcfg, h_in, c_in, dh_in, dc_in, tokens, alive,
                     remaining, eos_ids, *, k_draft: int, interpret: bool):
    """Trace-level entry for the fused spec window (called inside the
    engine's jitted wrapper). ``h_in``/``c_in`` [L, B, H] f32 target
    carries, ``dh_in``/``dc_in`` [L_d, B, H_d] f32 draft carries; row
    vectors as in `decode_window_call`. Returns ``(h_out, c_out,
    dh_out, dc_out, toks [W, B] int32, next_tok [B] int32, alive_out
    [B] bool, rem_out [B] int32)`` — the scan spec fn's exact shapes,
    so the two programs are interchangeable behind one spec
    `DecodeWindow`."""
    L, B, H = h_in.shape
    Ld, _, Hd = dh_in.shape
    V = cfg.vocab_size
    W = k_draft + 1
    head = params["head"]
    head_kernel = (params["embedding"].T if cfg.tie_embeddings
                   else head["kernel"])
    dhead = dparams["head"]
    dhead_kernel = (dparams["embedding"].T if dcfg.tie_embeddings
                    else dhead["kernel"])

    operands = [params["embedding"]]
    for fused in fused_layers:
        operands += [fused.kernel, fused.recurrent,
                     fused.bias.reshape(1, -1)]
    operands += [head_kernel, head["bias"].reshape(1, -1)]
    operands.append(dparams["embedding"])
    for fused in dfused_layers:
        operands += [fused.kernel, fused.recurrent,
                     fused.bias.reshape(1, -1)]
    operands += [
        dhead_kernel, dhead["bias"].reshape(1, -1),
        h_in, c_in, dh_in, dc_in,
        tokens.reshape(1, -1).astype(jnp.int32),
        alive.reshape(1, -1).astype(jnp.int32),
        remaining.reshape(1, -1).astype(jnp.int32),
        eos_ids.reshape(1, -1).astype(jnp.int32),
    ]
    in_specs = [pl.BlockSpec(memory_space=pltpu.VMEM)] * len(operands)

    out_shape = [
        jax.ShapeDtypeStruct((W, B), jnp.int32),        # token block
        jax.ShapeDtypeStruct((1, B), jnp.int32),        # next token
        jax.ShapeDtypeStruct((1, B), jnp.int32),        # session alive
        jax.ShapeDtypeStruct((1, B), jnp.int32),        # remaining
        jax.ShapeDtypeStruct((L, B, H), jnp.float32),   # target h out
        jax.ShapeDtypeStruct((L, B, H), jnp.float32),   # target c out
        jax.ShapeDtypeStruct((Ld, B, Hd), jnp.float32),  # draft h out
        jax.ShapeDtypeStruct((Ld, B, Hd), jnp.float32),  # draft c out
    ]
    out_specs = [pl.BlockSpec(memory_space=pltpu.VMEM)] * 8

    (toks, next_tok, alive_out, rem_out,
     h_out, c_out, dh_out, dc_out) = pl.pallas_call(
        functools.partial(
            _spec_window_kernel, num_layers=L, hidden=H,
            draft_layers=Ld, draft_hidden=Hd, vocab=V, k_draft=k_draft,
            ldtype=cfg.ldtype, dldtype=dcfg.ldtype,
        ),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(*operands)
    return (h_out, c_out, dh_out, dc_out, toks, next_tok[0],
            alive_out[0].astype(bool), rem_out[0])
