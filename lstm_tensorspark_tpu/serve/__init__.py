"""Serving subsystem: continuous-batching LM inference on the training cell.

An LSTM's per-session decode state is a fixed-size ``(h, c)`` pair per
layer — the portable O(1) autoregressive cache (PAPERS.md, "Compiler-First
State Space Duality and Portable O(1) Autoregressive Caching"). This
package turns the repo's training LM + one-shot sampler (models/generate.py)
into a serving engine:

- ``state_cache``: slot-based device-resident cache of per-session carries
  (LRU eviction, explicit detach/restore), plus ``PrefixCache`` — a
  shared-prompt prefix store (state after ``prompt[:k]`` is ONE (h, c)
  pair: exact prefix reuse is a slot copy) with longest-match lookup,
  refcounted backing slots, and LRU eviction that invalidates dependent
  entries — plus ``SessionTiers``: host-RAM and disk tiers below the
  device slots (async spill of evicted states, inline fill on
  continuation, sha256/fsync-durable session files so a restarted server
  resumes kept sessions token-identically; prefix entries spill/promote
  through the same tiers);
- ``prefix_trie``: the prefix-state FABRIC (``--prefix-fabric on``) — a
  radix trie over token sequences whose nodes own carry snapshots:
  longest-match over ANY shared prefix (tenant preambles, few-shot
  templates), leaf-first eviction with subtree accounting, tiered spill
  under a host-byte bound, and cross-replica propagation of hot nodes
  over the remote transport (idempotent by token-bytes hash);
- ``engine``: bucketed jitted prefill/decode programs over the cache —
  compile count bounded per (phase, bucket[, window], sampling), never
  per batch composition — including ``decode_window``: K tokens per XLA
  program with on-device per-row EOS/budget latching, returned as device
  handles so readback can be pipelined; prefill gathers from per-row src
  slots (resume at any offset from a cached prefix) and ``prefill_chunk``
  consumes a bounded slice of prompt per program;
- ``batcher``: continuous-batching scheduler (admission control, bounded
  queue backpressure, round-robin decode fairness) with an adaptive
  decode-window ladder, dispatch-ahead async readback (window i+1 is
  dispatched before window i's tokens are fetched), prefix-cache
  admission (fresh prompts resume from their longest cached prefix) and
  chunked prefill (<= one bounded prefill program per scheduler
  iteration — a long prompt cannot stall running sessions' decode);
- ``router``: the data-parallel admission front (``--replicas N``) —
  N engine+batcher replicas (thread-per-replica on CPU, device-per-
  replica on TPU, mesh-per-replica with ``--mesh-shards`` — a
  tensor-parallel engine whose params/state shard H across a device
  group), session→replica affinity so recurrent-state slots
  and prefix entries stay replica-local, one global bounded admission
  queue (429), and honest replica-death handling (queued work requeued,
  in-flight failed loudly, idle kept sessions migrated via
  detach/restore);
- ``remote``: the remote-replica RPC transport (``--remote-replica
  URL``) — a peer serve PROCESS satisfying the same router-facing
  surface over the stdlib HTTP endpoint (generate RPCs on
  ``/v1/generate``, liveness on ``/replica/heartbeat``, affinity on
  ``/replica/has_session``), so the admission router becomes a
  front-of-fleet tier and replica death generalises to host death
  (kept sessions fail over through the shared ``--session-dir`` tier);
- ``autotune``: the online serve autotuner (``--autotune on``) — a
  controller thread over windowed telemetry deltas that moves the
  decode-window cap, the prefill-chunk size, the host-tier bound and
  the best-effort admission fraction within pre-warmed bounds (it can
  never trigger a mid-traffic compile), with hysteresis so flat
  workloads never oscillate; decisions exported via ``/stats``
  ``autotune`` + ``serve_autotune_moves_total{knob,direction}``;
- ``registry``: sha256-verified model artifact store (the training→
  serving hand-off: ``supervise --registry-dir`` publishes each new
  best checkpoint; corrupt artifacts are quarantined, never served);
- ``rollout``: the zero-downtime rollout controller (``--registry-dir``
  / ``POST /rollout``) — rolls a registry version across the replicas
  one at a time (drain → swap → off-path warmup → rejoin; kept sessions
  migrate, queued work requeues, capacity stays >= N-1), with optional
  canary shadowing + token-diff before promotion, and the drain/rejoin
  machinery doubles as the device-slot RESIZE move the autotuner's
  capacity leg requests; the engine itself multiplexes N resident
  models (per-model compile-key namespaces and slot accounting,
  requests routed by their ``model`` field);
- ``server``: stdlib ThreadingHTTPServer JSON endpoint + in-process
  client over the replica set, with ``GET /metrics`` Prometheus
  exposition of the stack's telemetry registry (obs/, ``replica``-
  labelled serve families) and histogram summaries inside ``/stats``;
  ``/healthz`` fans per-replica heartbeats into ok/degraded/down;
- ``loadgen``: closed/open-loop load generator (p50/p99 request latency,
  TTFT, inter-token latency, tokens/s), embedding the server-side
  histogram summaries next to its own percentiles.

Telemetry: every layer records into ONE registry (``ServeEngine(
registry=...)``, default ``obs.REGISTRY``; ``obs.NULL_REGISTRY``
disables) — queue depth/wait, scheduler-iteration time, server-side
TTFT/ITL histograms, window-K and prefill-chunk counters, compile and
cache events — and the batcher emits per-request
admit→queue→prefill→decode→readback timelines into the installed
``utils.tracing`` tracer (``--trace``).

CLI: ``python -m lstm_tensorspark_tpu.cli serve --selftest`` (see cli.py).
"""

from .state_cache import CacheFullError, PrefixCache, SessionTiers, StateCache
from .prefix_trie import PrefixPropagator, PrefixTrie
from .autotune import AutoTuneConfig, AutoTuner
from .engine import (
    PAD_TOKEN,
    DecodeWindow,
    SamplingParams,
    ServeEngine,
    UnknownModelError,
)
from .batcher import (
    CLASSES,
    Batcher,
    DeadlineExceededError,
    QueueFullError,
    Request,
)
from .registry import ModelRegistry, RegistryError, config_fingerprint
from .rollout import RolloutController, RolloutError
from .router import Replica, Router
from .remote import RemoteBatcher, RemoteReplica
from .server import InprocessClient, ServeServer
from .loadgen import (
    mesh_sweep,
    replica_sweep,
    run_loadgen,
    run_longtail,
    run_template_mix,
    template_mix_prompts,
)

__all__ = [
    "AutoTuneConfig",
    "AutoTuner",
    "Batcher",
    "CLASSES",
    "CacheFullError",
    "DeadlineExceededError",
    "DecodeWindow",
    "InprocessClient",
    "ModelRegistry",
    "PAD_TOKEN",
    "PrefixCache",
    "PrefixPropagator",
    "PrefixTrie",
    "QueueFullError",
    "RegistryError",
    "RolloutController",
    "RolloutError",
    "RemoteBatcher",
    "RemoteReplica",
    "Replica",
    "Request",
    "Router",
    "SamplingParams",
    "ServeEngine",
    "ServeServer",
    "SessionTiers",
    "StateCache",
    "UnknownModelError",
    "config_fingerprint",
    "mesh_sweep",
    "replica_sweep",
    "run_loadgen",
    "run_longtail",
    "run_template_mix",
    "template_mix_prompts",
]
