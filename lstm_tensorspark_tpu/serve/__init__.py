"""Serving subsystem: continuous-batching LM inference on the training cell.

An LSTM's per-session decode state is a fixed-size ``(h, c)`` pair per
layer — the portable O(1) autoregressive cache (PAPERS.md, "Compiler-First
State Space Duality and Portable O(1) Autoregressive Caching"). This
package turns the repo's training LM + one-shot sampler (models/generate.py)
into a serving engine:

- ``state_cache``: slot-based device-resident cache of per-session carries
  (LRU eviction, explicit detach/restore);
- ``engine``: bucketed jitted prefill/decode programs over the cache —
  compile count bounded per (phase, bucket[, window], sampling), never
  per batch composition — including ``decode_window``: K tokens per XLA
  program with on-device per-row EOS/budget latching, returned as device
  handles so readback can be pipelined;
- ``batcher``: continuous-batching scheduler (admission control, bounded
  queue backpressure, round-robin decode fairness) with an adaptive
  decode-window ladder and dispatch-ahead async readback (window i+1 is
  dispatched before window i's tokens are fetched);
- ``server``: stdlib ThreadingHTTPServer JSON endpoint + in-process client;
- ``loadgen``: closed/open-loop load generator (p50/p99 request latency,
  TTFT, inter-token latency, tokens/s).

CLI: ``python -m lstm_tensorspark_tpu.cli serve --selftest`` (see cli.py).
"""

from .state_cache import CacheFullError, StateCache
from .engine import PAD_TOKEN, DecodeWindow, SamplingParams, ServeEngine
from .batcher import Batcher, QueueFullError, Request
from .server import InprocessClient, ServeServer
from .loadgen import run_loadgen

__all__ = [
    "Batcher",
    "CacheFullError",
    "DecodeWindow",
    "InprocessClient",
    "PAD_TOKEN",
    "QueueFullError",
    "Request",
    "SamplingParams",
    "ServeEngine",
    "ServeServer",
    "StateCache",
    "run_loadgen",
]
