"""Online serve autotuner: closed-loop control of the live serve stack
from WINDOWED telemetry deltas.

PRs 3/4 measured the window-K-vs-ITL and chunk-vs-TTFT tradeoffs offline
and froze the winners into static flags; PR 8 left the host-tier size
static and PR 10 left the best-effort shed bound static. So the serve
plane runs ONE operating point regardless of live traffic shape — a
long-decode chat workload and a short-burst completion workload get the
same window ladder cap, the same prefill chunk, the same tier sizing.
:class:`AutoTuner` closes the loop: a controller thread watches
delta-since-last-window views of the live ``serve_ttft_seconds`` /
``serve_itl_seconds`` / ``serve_queue_wait_seconds`` histograms
(``obs._Family.snapshot_delta`` — the registry is cumulative, and a
controller reacting to lifetime p99s would steer on yesterday's burst)
plus tier occupancy and spill-thrash counters, and periodically moves
four knobs, each within PRE-WARMED bounds:

- **window_k** — the decode-window ceiling (``Batcher.set_window_cap``),
  moved one rung at a time within the existing warmed K ladder: larger
  K when the stack is ITL/throughput-bound and queues are short (the
  window amortizes per-token dispatch), smaller K when the TTFT /
  queue-wait p99 approaches the SLO (an in-flight K-token window is
  exactly what a newly-arrived request waits behind);
- **prefill_chunk** — the chunk size (``Batcher.set_prefill_chunk``),
  moved among the warmed ``prefill_chunk_choices`` set: larger chunks
  under TTFT pressure (a prompt finishes in fewer bounded dispatches),
  smaller chunks in ITL-bound steady decode (each chunk is the stall a
  running session's gap absorbs);
- **host_tier** — the autoscaler leg (``SessionTiers.set_host_entries``):
  the host-tier entry bound grows when PR 8's counters show spill
  thrash (host tier full while disk churn / overflow losses climb) and
  shrinks back toward the configured size when occupancy falls;
- **best_effort** — the admission leg (``Router.set_best_effort_frac``):
  when the state plane thrashes AT its capacity ceiling (host tier
  already at ``host_tier_max``), best-effort traffic is shed earlier;
  relaxed back toward the configured policy when the thrash clears;
- **spec_k** — the speculative-decoding draft depth
  (``Batcher.set_spec_k``), moved one rung at a time within the warmed
  spec ladder from the windowed ``serve_spec_accept_len`` delta: K up
  when the draft keeps earning its depth (mean accepted length near
  the current K), DOWN — ultimately to rung 0, plain decode — when
  acceptance collapses and the draft's propose+verify overhead stops
  paying. Rung 0 casts a re-probe vote whenever live decode traffic is
  flowing (the workload may have shifted back toward draftable text),
  so the fallback is a resting state, not a ratchet. Inert on
  non-speculative stacks.

**The no-compile invariant.** Every decision stays inside compile-key
families ``warmup()`` already covered: ``set_window_cap`` only accepts
warmed ladder rungs, ``set_prefill_chunk`` only accepts members of the
warmed choice set (``Batcher.warmup`` replays the chunk-stop sequence
for EVERY choice), and the capacity/admission knobs touch no compiled
program at all. The controller can therefore NEVER trigger a
mid-traffic XLA compile — asserted via ``serve_compiles_total`` in
tests/test_serve_autotune.py and the bench.

**Hysteresis.** A knob moves only after ``patience_up`` (grow) /
``patience_down`` (shrink) CONSECUTIVE windows agree on the direction,
and then rests for ``cooldown`` windows. Shrinking reacts faster than
growing on purpose: pulling K down protects the SLO (cheap, safe),
pushing it up is an optimization that can afford to wait for sustained
evidence. Windows with fewer than ``min_events`` samples cast no vote,
so a quiet or flat workload never oscillates.

Decisions, knob positions and the last windowed signals are exported in
the ``/stats`` ``autotune`` section and counted in
``serve_autotune_moves_total{knob,direction}``; the controller thread is
stored on the tuner and joined in ``stop()`` (the PR 9 thread-lifecycle
lint contract — ``ServeServer.stop`` drives it).

Remote replicas (serve/remote.py) are out of scope by design: their
knobs belong to their own host's controller — this one only steers the
LOCAL batchers/tiers and the shared router.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import deque

#: the knobs, in evaluation order (also the metric label values)
KNOBS = ("window_k", "prefill_chunk", "host_tier", "best_effort", "spec_k")


@dataclasses.dataclass(frozen=True)
class AutoTuneConfig:
    """Controller policy. Thresholds are fractions of ``slo_s`` so one
    flag (``--slo-ms``) re-anchors the whole policy to an SLO."""

    #: seconds between control windows (each tick reads one delta)
    interval_s: float = 0.25
    #: the TTFT p99 the controller protects (``--slo-ms`` / 1e3)
    slo_s: float = 0.25
    #: a delta histogram with fewer samples than this casts no vote
    min_events: int = 8
    #: consecutive agreeing windows before a GROW move (K up, chunk
    #: down, tier shrink, best-effort relax — the optimization side)
    patience_up: int = 3
    #: consecutive agreeing windows before a SHRINK move (K down, chunk
    #: up, tier grow, best-effort tighten — the SLO-protection side)
    patience_down: int = 1
    #: quiet windows after any move of a knob
    cooldown: int = 2
    #: pressure: ttft p99 above this fraction of the SLO
    ttft_high_frac: float = 0.7
    #: headroom: ttft p99 below this fraction of the SLO
    ttft_low_frac: float = 0.35
    #: pressure: queue-wait p99 above this fraction of the SLO
    queue_high_frac: float = 0.35
    #: headroom: queue-wait p99 below this fraction of the SLO
    queue_low_frac: float = 0.15
    #: pressure: live queue depth above this fraction of queue_size
    depth_high_frac: float = 0.5
    #: host-tier growth ceiling (None = 4x the configured entries)
    host_tier_max: int | None = None
    #: best-effort admission-frac floor the tightening leg stops at
    best_effort_floor: float = 0.1
    #: decision records kept for the /stats autotune section
    history: int = 32
    #: device-slot growth ceiling for the rollout-controller resize leg
    #: (None = 4x the boot slot count); the leg is inert without a
    #: rollout controller on the server
    slots_max: int | None = None
    #: consecutive thrash-at-every-ceiling windows before a slot resize
    #: is requested — a resize drains the whole fleet replica-by-replica
    #: and recompiles, so it demands far more sustained evidence than
    #: the cheap knobs
    slots_patience: int = 8
    #: quiet windows after a resize request (the roll itself takes many
    #: windows; re-requesting mid-roll would just queue churn)
    slots_cooldown: int = 40

    def validate(self) -> "AutoTuneConfig":
        if self.interval_s <= 0:
            raise ValueError(f"interval_s must be > 0, got {self.interval_s}")
        if self.slo_s <= 0:
            raise ValueError(f"slo_s must be > 0, got {self.slo_s}")
        if self.min_events < 1:
            raise ValueError(f"min_events must be >= 1, got {self.min_events}")
        if self.patience_up < 1 or self.patience_down < 1:
            raise ValueError("patience_up/patience_down must be >= 1")
        if self.cooldown < 0:
            raise ValueError(f"cooldown must be >= 0, got {self.cooldown}")
        if not 0.0 < self.best_effort_floor <= 1.0:
            raise ValueError(
                f"best_effort_floor must be in (0, 1], got "
                f"{self.best_effort_floor}")
        if self.host_tier_max is not None and self.host_tier_max < 1:
            raise ValueError(
                f"host_tier_max must be >= 1, got {self.host_tier_max}")
        if self.slots_max is not None and self.slots_max < 1:
            raise ValueError(
                f"slots_max must be >= 1 or None, got {self.slots_max}")
        if self.slots_patience < 1:
            raise ValueError(
                f"slots_patience must be >= 1, got {self.slots_patience}")
        if self.slots_cooldown < 0:
            raise ValueError(
                f"slots_cooldown must be >= 0, got {self.slots_cooldown}")
        return self


class AutoTuner:
    """The controller (module docstring). Build it over a constructed
    :class:`~.server.ServeServer`; ``start()``/``stop()`` manage the
    thread (the server's own lifecycle drives them), ``tick()`` runs one
    control window directly (tests drive it with injected signals)."""

    def __init__(self, server, config: AutoTuneConfig | None = None):
        self.server = server
        self.cfg = (config or AutoTuneConfig()).validate()
        reg = server.engine.metrics
        # the watched families — idempotent re-registration hands back
        # the SAME live families the batchers record into (name + labels
        # + buckets must match; obs enforces that)
        self._f_ttft = reg.histogram(
            "serve_ttft_seconds", "submit → first token (server-side)",
            labelnames=("replica",))
        self._f_itl = reg.histogram(
            "serve_itl_seconds",
            "inter-token gaps, host arrival times (0 within a window burst)",
            labelnames=("replica",))
        self._f_qwait = reg.histogram(
            "serve_queue_wait_seconds", "submit → admission wait",
            labelnames=("replica",))
        self._f_spec_accept = reg.histogram(
            "serve_spec_accept_len",
            "draft proposals accepted per speculative verify window, "
            "per live row",
            labelnames=("replica",),
            buckets=(0.0, 1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0))
        fam = reg.counter(
            "serve_autotune_moves_total",
            "autotuner knob movements, by knob and direction (both "
            "directions climbing together on a flat workload = the "
            "controller is oscillating; pin the knob and diagnose)",
            labelnames=("knob", "direction"))
        self._m_moves = {(k, d): fam.labels(knob=k, direction=d)
                         for k in KNOBS + ("slots",)
                         for d in ("up", "down")}
        # per-consumer delta cursors (only the tick thread touches them)
        self._cur_ttft: dict | None = None
        self._cur_itl: dict | None = None
        self._cur_qwait: dict | None = None
        self._cur_spec: dict | None = None
        self._prev_chunks: float | None = None
        self._prev_tiers: dict | None = None
        # the knobs' CONFIGURED operating points — the relax targets
        b0 = self._local_batchers()[0]
        self._initial_host_entries = self._host_entries()
        self._initial_be_frac = server.router.best_effort_frac
        self._host_max = (self.cfg.host_tier_max
                          if self.cfg.host_tier_max is not None
                          else (None if self._initial_host_entries is None
                                else 4 * self._initial_host_entries))
        if (self._initial_host_entries is not None
                and self._host_max is not None
                and self._host_max < self._initial_host_entries):
            raise ValueError(
                f"host_tier_max {self._host_max} is below the configured "
                f"host tier size {self._initial_host_entries}")
        # the chunk knob needs a warmed choice SET to move within; a
        # single-size (or unchunked) batcher pins the knob
        self._chunk_choices = tuple(b0.prefill_chunk_choices)
        # hysteresis state + history (guarded by _lock: tick() writes,
        # stats() reads from HTTP threads)
        self._lock = threading.Lock()
        self._streak = {k: 0 for k in KNOBS + ("slots",)}
        self._cooldown = {k: 0 for k in KNOBS + ("slots",)}
        self.moves = {k: {"up": 0, "down": 0} for k in KNOBS + ("slots",)}
        # the rollout-controller resize leg (the PR 14 residual: slot
        # count is no longer a frozen boot shape)
        self._initial_slots = server.engine.cache.num_slots
        self._slots_max = (self.cfg.slots_max
                           if self.cfg.slots_max is not None
                           else 4 * self._initial_slots)
        self._history: deque = deque(maxlen=self.cfg.history)
        self._last_window: dict = {}
        self.ticks = 0
        self.errors = 0
        self._last_error: str | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ---- lifecycle -----------------------------------------------------

    def start(self) -> "AutoTuner":
        if self._thread is not None:
            raise RuntimeError("autotuner already started")
        self._stop.clear()
        t = threading.Thread(target=self._run, name="serve-autotuner",
                             daemon=True)
        self._thread = t
        t.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def _run(self) -> None:
        # the wait IS the cadence: stop() parks the loop within one
        # interval of a shutdown
        while not self._stop.wait(self.cfg.interval_s):
            try:
                self.tick()
            except Exception as e:
                # a controller bug must degrade to "knobs stop moving",
                # never to a dead serve plane — recorded, retried next
                # window, surfaced in /stats
                with self._lock:
                    self.errors += 1
                    self._last_error = f"{type(e).__name__}: {e}"

    # ---- plumbing ------------------------------------------------------

    def _local_batchers(self) -> list:
        """The knob surfaces this controller owns: LOCAL replicas only
        (a RemoteReplica's knobs belong to its own host's controller)."""
        return [r.batcher for r in self.server.replicas
                if hasattr(r.batcher, "set_window_cap")]

    def _local_tiers(self) -> list:
        return [r.engine.tiers for r in self.server.replicas
                if getattr(r, "engine", None) is not None
                and getattr(r.engine, "tiers", None) is not None]

    def _host_entries(self) -> int | None:
        tiers = self._local_tiers()
        return tiers[0].host_entries if tiers else None

    # ---- signals -------------------------------------------------------

    def _signals(self) -> dict:
        """One control window's evidence: delta views of the watched
        histograms, the live queue depth, prefill-chunk activity, and
        the tier occupancy/thrash deltas."""
        ttft, self._cur_ttft = self._f_ttft.snapshot_delta(self._cur_ttft)
        itl, self._cur_itl = self._f_itl.snapshot_delta(self._cur_itl)
        qwait, self._cur_qwait = self._f_qwait.snapshot_delta(
            self._cur_qwait)
        spec_accept, self._cur_spec = self._f_spec_accept.snapshot_delta(
            self._cur_spec)
        batchers = self._local_batchers()
        queued = sum(b.queued() for b in batchers)
        chunks_now = float(sum(b.stats()["prefill_chunks_dispatched"]
                               for b in batchers))
        chunk_delta = (0.0 if self._prev_chunks is None
                       else chunks_now - self._prev_chunks)
        self._prev_chunks = chunks_now
        tiers_sig = None
        all_tiers = self._local_tiers()
        if all_tiers:
            snap = {"host": 0, "host_max": 0, "disk_spills": 0.0,
                    "disk_fills": 0.0, "lost": 0.0}
            for t in all_tiers:
                st = t.stats()
                snap["host"] += st["entries"]["host"]
                snap["host_max"] += st["host_entries_max"]
                snap["disk_spills"] += st["spills"]["disk"]
                snap["disk_fills"] += st["fills"]["disk"]
                snap["lost"] += st["lost"]
            prev = self._prev_tiers or snap
            tiers_sig = {
                "host": snap["host"],
                "host_max": snap["host_max"],
                "disk_spills": snap["disk_spills"] - prev["disk_spills"],
                "disk_fills": snap["disk_fills"] - prev["disk_fills"],
                "lost": snap["lost"] - prev["lost"],
            }
            self._prev_tiers = snap
        return {
            "ttft": ttft, "itl": itl, "queue_wait": qwait,
            "queued": queued,
            "queue_size": self.server.router.queue_size,
            "prefill_chunks": chunk_delta,
            "tiers": tiers_sig,
            "spec_accept": spec_accept,
        }

    # ---- verdicts (pure in the signals dict; unit-testable) ------------

    def _pressure(self, sig: dict) -> bool:
        """TTFT / queue-wait approaching the SLO — the shrink signal."""
        cfg = self.cfg
        tt, qw = sig["ttft"], sig["queue_wait"]
        if (tt["count"] >= cfg.min_events
                and tt.get("p99", 0.0) > cfg.slo_s * cfg.ttft_high_frac):
            return True
        if (qw["count"] >= cfg.min_events
                and qw.get("p99", 0.0) > cfg.slo_s * cfg.queue_high_frac):
            return True
        qsize = sig["queue_size"]
        return bool(qsize and sig["queued"] / qsize >= cfg.depth_high_frac)

    def _headroom(self, sig: dict) -> bool:
        """ITL-bound steady decode with short queues — the grow signal.
        Requires POSITIVE evidence of decode traffic (the ITL delta):
        an idle server has headroom by any definition, but moving knobs
        for traffic that does not exist is how controllers oscillate."""
        cfg = self.cfg
        if sig["itl"]["count"] < cfg.min_events:
            return False
        if sig["queued"]:
            return False
        qw, tt = sig["queue_wait"], sig["ttft"]
        if (qw["count"]
                and qw.get("p99", 0.0) > cfg.slo_s * cfg.queue_low_frac):
            return False
        if (tt["count"]
                and tt.get("p99", 0.0) > cfg.slo_s * cfg.ttft_low_frac):
            return False
        return True

    def _spec_batchers(self) -> list:
        return [b for b in self._local_batchers()
                if getattr(b, "speculative", False)]

    def _spec_desire(self, sig: dict) -> int:
        """K_draft vote from the windowed acceptance delta. The draft's
        cost model is simple: one spec window does K_draft cheap draft
        steps + ONE target pass of W=K+1 verify steps, and emits
        accepted+1 tokens. Mean accepted length near the current K
        means the draft is saturating its depth — try the next rung up
        (patience_up: an optimization). Mean below half the depth means
        most verify positions are wasted work — step down (fast,
        patience_down), bottoming out at rung 0 = plain decode. At rung
        0 no spec windows run, so no acceptance evidence can ever
        accumulate; live decode traffic (the ITL delta) is the re-probe
        vote instead — the workload may have shifted back."""
        spec = self._spec_batchers()
        if not spec:
            return 0
        cur = spec[0].spec_k
        cfg = self.cfg
        if cur == 0:
            up = sig["itl"]["count"] >= cfg.min_events
            return 1 if up else 0
        acc = sig.get("spec_accept") or {}
        if acc.get("count", 0) < cfg.min_events:
            return 0
        mean = acc["sum"] / acc["count"]
        if mean >= 0.8 * cur:
            return 1
        if mean < 0.5 * cur:
            return -1
        return 0

    def _thrash(self, sig: dict) -> bool:
        """Spill thrash: the host tier is (near) full while states churn
        through the disk tier or drop as overflow — PR 8's counters as
        the autoscaler's evidence."""
        t = sig.get("tiers")
        if not t or not t["host_max"]:
            return False
        full = t["host"] >= 0.9 * t["host_max"]
        churn = (t["disk_spills"] > 0 or t["disk_fills"] > 0
                 or t["lost"] > 0)
        return bool(full and churn)

    # ---- the control law ----------------------------------------------

    def tick(self, signals: dict | None = None) -> list[dict]:
        """One control window: read the deltas (or use injected
        ``signals`` — tests), update each knob's hysteresis streak, and
        apply at most one move per knob. Returns the applied moves."""
        sig = self._signals() if signals is None else signals
        pressure = self._pressure(sig)
        headroom = self._headroom(sig)
        thrash = self._thrash(sig)
        # desires are URGENCY-signed: -1 = the SLO-PROTECTION side
        # (reacts after patience_down windows — fast), +1 = the
        # optimization side (patience_up — slow). _apply maps the sign
        # to each knob's concrete movement: protecting the SLO means K
        # DOWN but chunk UP (fewer prefill dispatches per prompt), tier
        # GROW, admission TIGHTEN.
        desires = {
            "window_k": -1 if pressure else (1 if headroom else 0),
            # the chunk knob only moves while prefill chunks are
            # actually dispatching — a decode-only window carries no
            # evidence about chunk sizing
            "prefill_chunk": 0 if (not self._chunk_choices
                                   or sig["prefill_chunks"] <= 0)
            else (-1 if pressure else (1 if headroom else 0)),
            "host_tier": -1 if thrash else (
                1 if self._tier_shrinkable(sig) else 0),
            "best_effort": -1 if (thrash and self._tier_at_max()) else (
                1 if (not thrash and self._be_relaxable()) else 0),
            "spec_k": self._spec_desire(sig),
        }
        applied: list[dict] = []
        for knob in KNOBS:
            move = self._consider(knob, desires[knob])
            if move is not None:
                applied.append(move)
        # the capacity leg: only when EVERY cheap knob is exhausted —
        # host tier at ceiling, admission at its shed floor, and the
        # state plane still thrashing
        move = self._consider_slots(
            thrash and self._tier_at_max() and self._be_at_floor())
        if move is not None:
            applied.append(move)
        with self._lock:
            self.ticks += 1
            self._last_window = {
                "ttft": sig["ttft"], "itl": sig["itl"],
                "queue_wait": sig["queue_wait"], "queued": sig["queued"],
                "pressure": pressure, "headroom": headroom,
                "thrash": thrash,
                "spec_accept": sig.get("spec_accept"),
            }
            for move in applied:
                move["tick"] = self.ticks  # when, in control windows
                self.moves[move["knob"]][move["direction"]] += 1
                self._history.append(move)
        for move in applied:
            self._m_moves[(move["knob"], move["direction"])].inc()
        return applied

    def _tier_shrinkable(self, sig: dict) -> bool:
        t = sig.get("tiers")
        if not t:
            return False
        cur = self._host_entries()
        return (cur is not None
                and self._initial_host_entries is not None
                and cur > self._initial_host_entries
                and t["host"] < 0.25 * t["host_max"])

    def _tier_at_max(self) -> bool:
        cur = self._host_entries()
        return (cur is not None and self._host_max is not None
                and cur >= self._host_max)

    def _be_relaxable(self) -> bool:
        return (self.server.router.best_effort_frac
                < self._initial_be_frac - 1e-9)

    def _be_at_floor(self) -> bool:
        return (self.server.router.best_effort_frac
                <= self.cfg.best_effort_floor + 1e-9)

    def _consider_slots(self, desired: bool) -> dict | None:
        """The device-capacity leg (PR 14 residual closed): when the
        state plane still thrashes AFTER the host tier hit its ceiling
        and best-effort shedding hit its floor, every cheap knob is
        exhausted — ask the rollout controller for more device slots
        (a drain-and-rejoin resize move; serve/rollout.py). GROW-ONLY:
        shrinking slots forcibly migrates kept sessions off every
        replica, which is an operator decision (``POST /rollout``), not
        a control loop's. Inert without a controller on the server —
        the pre-registry fleet keeps its frozen boot shape."""
        ctl = getattr(self.server, "rollout", None)
        if ctl is None:
            return None
        with self._lock:
            if self._cooldown["slots"] > 0:
                self._cooldown["slots"] -= 1
                self._streak["slots"] = 0
                return None
            if not desired:
                self._streak["slots"] = 0
                return None
            self._streak["slots"] -= 1
            if -self._streak["slots"] < self.cfg.slots_patience:
                return None
            self._streak["slots"] = 0
        cur = self.server.engine.cache.num_slots
        new = min(self._slots_max, cur * 2)
        if new <= cur:
            return None
        ctl.request_resize(new)  # async: the controller thread rolls it
        with self._lock:
            self._cooldown["slots"] = self.cfg.slots_cooldown
        return {"knob": "slots", "direction": "up",
                "from": cur, "to": new, "via": "rollout"}

    def _consider(self, knob: str, desired: int) -> dict | None:
        """Hysteresis gate: ``desired`` (+1 grow / -1 shrink / 0 hold)
        must repeat for the direction's patience before the move
        applies; a move starts the knob's cooldown; a disagreeing
        window resets the streak."""
        with self._lock:
            if self._cooldown[knob] > 0:
                self._cooldown[knob] -= 1
                self._streak[knob] = 0
                return None
            if desired == 0:
                self._streak[knob] = 0
                return None
            s = self._streak[knob]
            s = s + desired if (s == 0 or (s > 0) == (desired > 0)) \
                else desired
            self._streak[knob] = s
            need = (self.cfg.patience_up if desired > 0
                    else self.cfg.patience_down)
            if abs(s) < need:
                return None
            self._streak[knob] = 0
        move = self._apply(knob, desired)
        if move is not None:
            with self._lock:
                self._cooldown[knob] = self.cfg.cooldown
        return move

    def _apply(self, knob: str, desired: int) -> dict | None:
        """Apply one bounded step; None when already at the bound.
        ``desired`` is the urgency sign (-1 protect / +1 optimize);
        the reported ``direction`` is the knob VALUE's movement. Every
        target value is inside a warmed family (the setters
        re-validate), so no branch here can cause a compile."""
        if knob == "window_k":
            # protect = cap down (an in-flight K-window is what a new
            # arrival waits behind), optimize = cap up
            batchers = self._local_batchers()
            ladder = batchers[0].window_ladder
            cur = batchers[0].window_cap
            i = ladder.index(cur) + desired
            if not 0 <= i < len(ladder):
                return None
            for b in batchers:
                b.set_window_cap(ladder[i])
            return {"knob": knob,
                    "direction": "up" if desired > 0 else "down",
                    "from": cur, "to": ladder[i]}
        if knob == "prefill_chunk":
            # protect = chunk UP (a prompt finishes in fewer bounded
            # dispatches — the TTFT side), optimize = chunk down (bound
            # the stall running sessions' gaps absorb — the ITL side)
            batchers = self._local_batchers()
            choices = self._chunk_choices
            cur = batchers[0].prefill_chunk
            i = choices.index(cur) - desired
            if not 0 <= i < len(choices):
                return None
            for b in batchers:
                b.set_prefill_chunk(choices[i])
            return {"knob": knob,
                    "direction": "up" if desired < 0 else "down",
                    "from": cur, "to": choices[i]}
        if knob == "host_tier":
            # protect = grow under spill thrash, optimize = shrink back
            # toward the configured size when occupancy collapses
            cur = self._host_entries()
            if cur is None:
                return None
            if desired < 0:
                new = cur * 2 if self._host_max is None \
                    else min(self._host_max, cur * 2)
            else:
                new = max(self._initial_host_entries, cur // 2)
            if new == cur:
                return None
            for t in self._local_tiers():
                t.set_host_entries(new)
            return {"knob": knob,
                    "direction": "up" if new > cur else "down",
                    "from": cur, "to": new}
        if knob == "spec_k":
            # the draft-depth leg: one rung at a time within the warmed
            # spec ladder (rung 0 = plain decode — the cost fallback);
            # set_spec_k re-validates membership, so no compile here
            spec = self._spec_batchers()
            if not spec:
                return None
            ladder = spec[0].spec_ladder
            cur = spec[0].spec_k
            i = ladder.index(cur) + desired
            if not 0 <= i < len(ladder):
                return None
            for b in spec:
                b.set_spec_k(ladder[i])
            return {"knob": knob,
                    "direction": "up" if desired > 0 else "down",
                    "from": cur, "to": ladder[i]}
        # best_effort: protect = tighten (shed earlier), optimize = relax
        router = self.server.router
        cur = router.best_effort_frac
        new = (min(self._initial_be_frac, cur * 2) if desired > 0
               else max(self.cfg.best_effort_floor, cur / 2))
        if abs(new - cur) < 1e-9:
            return None
        router.set_best_effort_frac(new)
        return {"knob": knob, "direction": "up" if new > cur else "down",
                "from": round(cur, 4), "to": round(new, 4)}

    # ---- views ---------------------------------------------------------

    def stats(self) -> dict:
        """The ``/stats`` ``autotune`` section: knob positions + bounds,
        the LAST control window's delta signals (the recent-biased p99s
        the lifetime ``metrics`` summaries cannot show), move counts,
        and the bounded decision history."""
        batchers = self._local_batchers()
        b0 = batchers[0]
        knobs = {
            "window_k": {"value": b0.window_cap,
                         "ladder": list(b0.window_ladder)},
            "prefill_chunk": {"value": b0.prefill_chunk,
                              "choices": list(self._chunk_choices)},
            "host_tier": {"value": self._host_entries(),
                          "initial": self._initial_host_entries,
                          "max": self._host_max},
            "best_effort": {
                "value": round(self.server.router.best_effort_frac, 4),
                "initial": round(self._initial_be_frac, 4),
                "floor": self.cfg.best_effort_floor},
            "slots": {"value": self.server.engine.cache.num_slots,
                      "initial": self._initial_slots,
                      "max": self._slots_max,
                      "via": "rollout"},
        }
        spec = self._spec_batchers()
        knobs["spec_k"] = (
            {"value": spec[0].spec_k, "ladder": list(spec[0].spec_ladder)}
            if spec else {"value": None, "ladder": []})
        with self._lock:
            return {
                "interval_s": self.cfg.interval_s,
                "slo_ms": round(self.cfg.slo_s * 1e3, 3),
                "running": self._thread is not None,
                "ticks": self.ticks,
                "errors": self.errors,
                "last_error": self._last_error,
                "knobs": knobs,
                "window": dict(self._last_window),
                "moves": {k: dict(v) for k, v in self.moves.items()},
                "streaks": dict(self._streak),
                "history": list(self._history),
            }
