"""Offline load generator for the serving engine (CPU-runnable).

Two standard modes:

- **closed-loop**: N session threads, each issuing its requests
  back-to-back through the in-process client — models N always-busy
  clients; throughput scales with continuous batching until the decode
  bucket saturates;
- **open-loop**: requests arrive at a fixed rate regardless of completion
  (the arrival process does not slow down when the server does), exposing
  queueing delay and backpressure (429s are counted, not retried).

The report carries request latency p50/p99/mean, time-to-first-token
p50/p99, **inter-token latency** p50/p99/max (pooled over every token
gap of every completed request — the decode-window tradeoff made
visible: larger K raises tokens/sec AND raises tail ITL, because a
window's K tokens arrive in one burst after a K-step device program),
aggregate tokens/sec and requests/sec. Phases are wrapped in
`utils.tracing` spans, so ``--trace`` on the CLI captures the run.

`concurrency_sweep` runs the same closed-loop workload at increasing
session counts on one warm server — the headline check that batched
decode beats sequential serving (ISSUE acceptance: >= 8 concurrent
sessions must out-throughput 1 session). `replica_sweep` runs it at
increasing REPLICA counts (a fresh server per level) — the data-parallel
scaling gate (aggregate tokens/s across N schedulers, greedy parity
token-identical to one replica); every report carries per-replica
routed/served counts plus the router's requeue/rejection deltas.

Prefix-cache / chunked-prefill probes: ``shared_prefix_len`` makes every
prompt share its first N tokens (the shared-system-prompt workload —
TTFT with the cache on should beat cache-off once the prefix is hot, and
the report carries the cache's hit/miss/insert deltas);
``inject_prompt_len`` submits one cold long-prompt request mid-run and
reports it separately — the head-of-line-blocking probe (without chunked
prefill, its monolithic prefill program shows up in every running
session's p99 ITL; with ``prefill_chunk`` the stall is bounded by one
chunk). Reports are JSON-ready dicts: ``cli serve --loadgen --json PATH``
persists them (BENCH_serve_r01.json is the checked-in baseline).
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..resilience.backoff import backoff_delay
from ..utils import span
from .batcher import CLASSES, DeadlineExceededError, QueueFullError
from .engine import GREEDY, SamplingParams
from .server import InprocessClient, ServeServer


def _percentile(sorted_vals: list[float], pct: float) -> float:
    if not sorted_vals:
        return float("nan")
    idx = int(round(pct / 100.0 * (len(sorted_vals) - 1)))
    return sorted_vals[min(max(idx, 0), len(sorted_vals) - 1)]


def _random_prompts(n: int, prompt_len: int, vocab_size: int, seed: int,
                    shared_prefix_len: int = 0):
    """``shared_prefix_len > 0`` models the shared-system-prompt workload:
    every prompt starts with the SAME random prefix of that length and
    differs only in its suffix — the prefix cache's target case."""
    if shared_prefix_len >= prompt_len:
        raise ValueError(
            f"shared_prefix_len {shared_prefix_len} must be < prompt_len "
            f"{prompt_len} (each prompt needs a unique suffix)")
    rng = np.random.RandomState(seed)
    shared = rng.randint(0, vocab_size, size=shared_prefix_len)
    return [
        np.concatenate([
            shared,
            rng.randint(0, vocab_size, size=prompt_len - shared_prefix_len),
        ]).astype(np.int32)
        for _ in range(n)
    ]


def _per_replica(results: list[dict]) -> dict:
    """Completed-request / token counts by the replica that served them
    (``Request.replica``, stamped by the router) — the scaling gate's
    routed-request evidence. Single-replica runs report one bucket."""
    out: dict[str, dict] = {}
    for r in results:
        if r.get("replica") is None:
            continue
        d = out.setdefault(str(r["replica"]), {"completed": 0, "tokens": 0})
        d["completed"] += 1
        d["tokens"] += r["tokens"]
    return out


def _class_report(recs: list[dict], shed: int, retried: int,
                  timeouts: int) -> dict:
    """Per-admission-class slice of a run: completion/shed/retry/timeout
    counts plus TTFT and latency percentiles — the evidence the
    burst-shedding gate compares (priority p99 TTFT holds its SLO while
    best_effort sheds; BENCH_serve_r04.json)."""
    ttft = sorted(r["ttft_s"] for r in recs if r["ttft_s"] is not None)
    lat = sorted(r["latency_s"] for r in recs)

    def pct(vals, p):
        # None (JSON null), never NaN: the classes section is ALWAYS
        # present, so a zero-traffic class in a default single-class run
        # must not make every --json report unparseable to strict
        # RFC-8259 consumers (json.dump writes bare NaN)
        if not vals:
            return None
        return round(_percentile(vals, p) * 1e3, 3)

    return {
        "completed": len(recs),
        "shed": shed,
        "retried": retried,
        "timeouts": timeouts,
        "tokens": sum(r["tokens"] for r in recs),
        "p50_ttft_ms": pct(ttft, 50),
        "p99_ttft_ms": pct(ttft, 99),
        "p50_latency_ms": pct(lat, 50),
        "p99_latency_ms": pct(lat, 99),
    }


#: prefix-cache stats() keys that are per-replica CONFIG, not counters —
#: aggregation keeps the first replica's value instead of summing
_PREFIX_CONFIG_KEYS = ("stride", "max_entries")


def prefix_totals(server: ServeServer) -> dict | None:
    """Prefix-cache stats summed across every replica's cache (entries
    are replica-local; the workload-level hit rate is the sum's). Config
    keys keep replica 0's value; the ONE aggregation used by loadgen
    reports and the CLI's engine section, so the two can never drift."""
    totals = None
    for rep in server.replicas:
        if rep.engine.prefix is None:
            continue
        st = rep.engine.prefix.stats()
        if totals is None:
            totals = dict(st)
            continue
        for k, v in st.items():
            if k not in _PREFIX_CONFIG_KEYS:
                totals[k] += v
    return totals


def _report(results: list[dict], rejected: int, failed: int, wall_s: float,
            mode: str, sessions: int) -> dict:
    lat = sorted(r["latency_s"] for r in results)
    ttft = sorted(r["ttft_s"] for r in results if r["ttft_s"] is not None)
    # inter-token latency: pooled token-arrival gaps across all requests
    # (a request with T tokens contributes T-1 gaps; TTFT is reported
    # separately and is NOT a gap here)
    itl = sorted(g for r in results for g in r.get("itl_s", ()))
    tokens = sum(r["tokens"] for r in results)
    return {
        "mode": mode,
        "sessions": sessions,
        "requests": len(results) + rejected + failed,
        "completed": len(results),
        "rejected": rejected,
        "failed": failed,
        "wall_s": round(wall_s, 4),
        "p50_latency_ms": round(_percentile(lat, 50) * 1e3, 3),
        "p99_latency_ms": round(_percentile(lat, 99) * 1e3, 3),
        "mean_latency_ms": round(
            (sum(lat) / len(lat) if lat else float("nan")) * 1e3, 3),
        "p50_ttft_ms": round(_percentile(ttft, 50) * 1e3, 3),
        "p99_ttft_ms": round(_percentile(ttft, 99) * 1e3, 3),
        "p50_itl_ms": round(_percentile(itl, 50) * 1e3, 3),
        "p99_itl_ms": round(_percentile(itl, 99) * 1e3, 3),
        "max_itl_ms": round(max(itl) * 1e3, 3) if itl else float("nan"),
        "tokens_generated": tokens,
        "tokens_per_sec": round(tokens / wall_s, 2) if wall_s > 0 else 0.0,
        "requests_per_sec": round(len(results) / wall_s, 2)
        if wall_s > 0 else 0.0,
    }


def run_loadgen(
    server: ServeServer,
    *,
    vocab_size: int,
    sessions: int = 8,
    requests_per_session: int = 4,
    prompt_len: int = 8,
    max_new_tokens: int = 16,
    sampling: SamplingParams = GREEDY,
    mode: str = "closed",
    rate: float | None = None,
    seed: int = 0,
    timeout: float = 300.0,
    shared_prefix_len: int = 0,
    inject_prompt_len: int = 0,
    inject_delay_s: float = 0.25,
    priority_frac: float = 1.0,
    deadline_s: float | None = None,
    retry_max: int = 0,
    retry_base_s: float = 0.05,
    retry_cap_s: float = 2.0,
) -> dict:
    """Drive a started :class:`ServeServer`; returns the report dict.

    ``shared_prefix_len``: prompts share their first N tokens (the
    prefix-cache workload). ``inject_prompt_len > 0``: one extra request
    with a prompt of that length is submitted ``inject_delay_s`` seconds
    into the run — the head-of-line-blocking probe (does a max-bucket
    prefill mid-run stall everyone else's ITL?); it is reported under
    ``"injected"`` and EXCLUDED from the pooled latency stats.

    ``priority_frac``: share of traffic submitted as the "priority"
    admission class; the rest goes "best_effort" (interleaved, so a
    burst mixes both). ``deadline_s`` rides on every request.
    ``retry_max > 0``: a 429 shed is retried up to that many times,
    sleeping the server's ``Retry-After`` hint floored by the SHARED
    capped exponential backoff + jitter (resilience/backoff.py — the
    supervisor's curve, one implementation), both capped at
    ``retry_cap_s``; per-class shed/retried/timeout counts land in the
    report's ``classes`` section."""
    if mode not in ("closed", "open"):
        raise ValueError(f"mode must be 'closed' or 'open', got {mode!r}")
    if not 0.0 <= priority_frac <= 1.0:
        raise ValueError(
            f"priority_frac must be in [0, 1], got {priority_frac}")
    client = InprocessClient(server)
    total = sessions * requests_per_session
    prompts = _random_prompts(total, prompt_len, vocab_size, seed,
                              shared_prefix_len)
    n_priority = int(round(sessions * priority_frac))
    results: list[dict] = []
    rejected = [0]
    failed = [0]
    shed = {c: 0 for c in CLASSES}
    retried = {c: 0 for c in CLASSES}
    timeouts = {c: 0 for c in CLASSES}
    lock = threading.Lock()
    prefix_before = prefix_totals(server)
    router_before = server.router.stats()

    def one_request(prompt, klass: str = "priority") -> None:
        t0 = time.perf_counter()
        attempt = 0
        while True:
            try:
                req = server.generate(
                    prompt, max_new_tokens=max_new_tokens,
                    sampling=sampling, timeout=timeout, klass=klass,
                    deadline_s=deadline_s,
                )
                break
            except QueueFullError as e:
                if attempt >= retry_max:
                    # shed for good: an honest 429 the client accepted
                    with lock:
                        rejected[0] += 1
                        shed[klass] += 1
                    return
                attempt += 1
                with lock:
                    retried[klass] += 1
                # honor Retry-After, floored by the shared backoff curve
                # (jittered so a shed burst doesn't re-arrive in
                # lockstep), both capped at retry_cap_s
                hint = getattr(e, "retry_after_s", None) or 0.0
                time.sleep(min(
                    max(hint, backoff_delay(retry_base_s, attempt,
                                            cap=retry_cap_s)),
                    retry_cap_s))
            except DeadlineExceededError:
                # server-side expiry: honest partial output, counted as
                # a timeout for this class — not a failure
                with lock:
                    timeouts[klass] += 1
                return
            except Exception:
                # a timeout or scheduler-side failure must not kill the
                # worker thread (its remaining requests would silently
                # vanish from the report) — count it and keep going
                with lock:
                    failed[0] += 1
                return
        rec = {
            "latency_s": time.perf_counter() - t0,
            "ttft_s": (req.t_first_token - req.t_submit)
            if req.t_first_token and req.t_submit else None,
            "tokens": len(req.tokens),
            "itl_s": req.itl_gaps(),
            "replica": req.replica,
            "klass": klass,
        }
        with lock:
            results.append(rec)

    injected: dict = {}

    def inject() -> None:
        time.sleep(inject_delay_s)
        # a fresh random prompt (distinct seed → shares nothing): a cold
        # max-bucket prefill landing in the middle of steady-state decode
        prompt = _random_prompts(1, inject_prompt_len, vocab_size,
                                 seed + 7919)[0]
        t0 = time.perf_counter()
        try:
            # use_prefix=False: the probe must neither perturb the shared
            # cache (stride-stop inserts would evict real entries) nor
            # skew the report's prefix_cache deltas with its cold miss
            req = server.generate(prompt, max_new_tokens=max_new_tokens,
                                  sampling=sampling, use_prefix=False,
                                  timeout=timeout)
        except Exception as e:
            injected["error"] = f"{type(e).__name__}: {e}"
            return
        injected.update({
            "prompt_len": inject_prompt_len,
            "latency_ms": round((time.perf_counter() - t0) * 1e3, 3),
            "ttft_ms": round((req.t_first_token - req.t_submit) * 1e3, 3)
            if req.t_first_token and req.t_submit else None,
            "tokens": len(req.tokens),
        })

    with span("loadgen", mode=mode, sessions=sessions, total=total):
        t_start = time.perf_counter()
        inject_thread = None
        if inject_prompt_len > 0:
            inject_thread = threading.Thread(target=inject, daemon=True)
            inject_thread.start()
        if mode == "closed":
            def worker(wid: int) -> None:
                # per-session class: the first n_priority sessions are
                # priority, the rest best-effort
                klass = "priority" if wid < n_priority else "best_effort"
                for r in range(requests_per_session):
                    one_request(prompts[wid * requests_per_session + r],
                                klass)

            threads = [
                threading.Thread(target=worker, args=(i,), daemon=True)
                for i in range(sessions)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        else:  # open loop: fixed arrival rate, completion measured async
            if not rate or rate <= 0:
                raise ValueError("open-loop mode needs rate > 0 (req/s)")
            threads = []
            for i, prompt in enumerate(prompts):
                # interleaved class pattern (period = sessions): a burst
                # carries both classes throughout, not one then the other
                klass = ("priority"
                         if (i % max(sessions, 1)) < n_priority
                         else "best_effort")
                target = t_start + i / rate
                delay = target - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                t = threading.Thread(
                    target=one_request, args=(prompt, klass), daemon=True
                )
                t.start()
                threads.append(t)
            for t in threads:
                t.join()
        # wall covers the POOLED workload only — joining the probe after
        # would charge its sleep+request tail to tokens_per_sec while its
        # tokens are excluded from results
        wall = time.perf_counter() - t_start
        if inject_thread is not None:
            inject_thread.join()
    report = _report(results, rejected[0], failed[0], wall, mode, sessions)
    # per-class accounting (shed/retried/timeout + TTFT percentiles):
    # always present so report consumers have a stable shape; a
    # single-class run simply shows zeros for the other class
    report["timeouts"] = sum(timeouts.values())
    report["requests"] += report["timeouts"]
    report["priority_frac"] = priority_frac
    if deadline_s is not None:
        report["deadline_s"] = deadline_s
    if retry_max:
        report["retry_max"] = retry_max
    report["classes"] = {
        c: _class_report([r for r in results if r.get("klass") == c],
                         shed[c], retried[c], timeouts[c])
        for c in CLASSES
    }
    if rate:
        report["offered_rate_rps"] = rate
    report["prompt_len"] = prompt_len
    report["shared_prefix_len"] = shared_prefix_len
    if inject_prompt_len > 0:
        report["injected"] = injected
    # per-replica routing evidence: completed/token counts by serving
    # replica (from the requests) + the router's routed/requeue deltas
    report["replicas"] = _per_replica(results)
    ra, rb = server.router.stats(), router_before
    report["router"] = {
        "replicas": ra["replicas"],
        "live": ra["live"],
        "routed": {k: ra["routed"][k] - rb["routed"].get(k, 0)
                   for k in ra["routed"]},
        "rejected": ra["rejected"] - rb["rejected"],
        "shed_by_class": {
            c: ra["shed_by_class"][c] - rb.get("shed_by_class", {}).get(c, 0)
            for c in ra.get("shed_by_class", {})
        },
        "requeued": ra["requeued"] - rb["requeued"],
        "failed_on_death": ra["failed_on_death"] - rb["failed_on_death"],
        "migrated_sessions":
            ra["migrated_sessions"] - rb["migrated_sessions"],
    }
    if prefix_before is not None:
        after = prefix_totals(server)
        hits = after["hits"] - prefix_before["hits"]
        misses = after["misses"] - prefix_before["misses"]
        report["prefix_cache"] = {
            "hits": hits,
            "misses": misses,
            "hit_rate": round(hits / (hits + misses), 4)
            if hits + misses else 0.0,
            "inserts": after["inserts"] - prefix_before["inserts"],
            "entries": after["entries"],
            "invalidated": after["invalidated"] - prefix_before["invalidated"],
        }
    # server-side latency distributions (obs/ registry histograms) next to
    # the loadgen-side percentiles above, so the two views are directly
    # diffable. NOTE: the registry is CUMULATIVE over the server's life —
    # a sweep's later levels include earlier levels' samples.
    summary = server.metrics_summary()
    hists = {k: summary[k] for k in ("serve_ttft_seconds",
                                     "serve_itl_seconds",
                                     "serve_queue_wait_seconds")
             if isinstance(summary.get(k), dict)}
    if hists:
        report["server_histograms"] = hists
    return report


def tier_totals(server: ServeServer) -> dict | None:
    """SessionTiers stats summed across replicas (same aggregation policy
    as :func:`prefix_totals`): counters summed, per-tier entry counts
    summed, config keys keep replica 0's value."""
    totals = None
    for rep in server.replicas:
        if rep.engine.tiers is None:
            continue
        st = rep.engine.tiers.stats()
        if totals is None:
            totals = {
                "host_entries_max": st["host_entries_max"],
                "entries": dict(st["entries"]),
                "spills": dict(st["spills"]),
                "fills": dict(st["fills"]),
                "misses": st["misses"],
                "corrupt": st["corrupt"],
                "lost": st["lost"],
                "disk_errors": st["disk_errors"],
            }
            continue
        for k in ("entries", "spills", "fills"):
            for t, v in st[k].items():
                totals[k][t] = totals[k].get(t, 0) + v
        for k in ("misses", "corrupt", "lost", "disk_errors"):
            totals[k] += st[k]
    return totals


def run_longtail(
    server: ServeServer,
    *,
    vocab_size: int,
    sessions: int,
    requests_per_session: int = 3,
    prompt_len: int = 8,
    max_new_tokens: int = 8,
    sampling: SamplingParams = GREEDY,
    zipf_s: float = 1.1,
    concurrency: int = 8,
    seed: int = 0,
    timeout: float = 300.0,
) -> dict:
    """Long-tail multi-tenant workload (``cli serve --loadgen
    --idle-churn``): ``sessions`` live kept sessions — size it to ~10x
    the device slots — each created once and then continued by draws
    from a Zipf(``zipf_s``) popularity distribution, so a small hot set
    sees most of the traffic while the long tail sits idle and gets
    LRU-evicted. Exactly the workload the tiered cache is gated on
    (ROADMAP item 2): without tiers, every evicted session's
    continuation fails "expired" and the client re-prefills its FULL
    accumulated history (counted as ``re_prefills`` /
    ``re_prefill_tokens``); with tiers, continuations fill from host or
    disk for one tiny state copy.

    The report extends :func:`_report` with per-tier hit counts and
    rates for the continuations (``tiers``: device/host/disk/lost),
    the re-prefill cost, and the HOT-SET throughput
    (``hot_set.tokens_per_sec`` over the top-10% sessions by rank) —
    the number the tiered-vs-all-on-device gate compares
    (tools/bench_serve.py --tiered-cache → BENCH_serve_r03.json).

    Each logical session's full token history is tracked so a
    re-prefilled session resumes token-identically — re-prefill changes
    the COST, never the output."""
    if sessions < 1:
        raise ValueError(f"sessions must be >= 1, got {sessions}")
    rng = np.random.RandomState(seed)
    prompts = _random_prompts(sessions, prompt_len, vocab_size, seed)
    # Zipf-ish popularity: session rank r drawn with weight (r+1)^-s
    weights = (np.arange(sessions) + 1.0) ** -float(zipf_s)
    weights /= weights.sum()
    schedule = list(rng.choice(sessions, size=sessions * requests_per_session,
                               p=weights))
    hot_k = max(1, sessions // 10)

    lock = threading.Lock()
    cond = threading.Condition(lock)
    # per logical session: server-side sid (None until created), full
    # history (prompt + every generated token), token count, in-flight
    # flag (two concurrent requests on one session would be rejected
    # "busy" — the driver serialises per session, like a real client)
    sids: list[str | None] = [None] * sessions
    history: list[list[int]] = [list(map(int, p)) for p in prompts]
    tokens_by_session = [0] * sessions
    busy: set[int] = set()
    rejected = [0]
    failed = [0]
    re_prefills = [0]
    re_prefill_tokens = [0]
    continuations = [0]  # session_id continuations that COMPLETED
    results: list[dict] = []

    tiers_before = tier_totals(server)
    prefix_before = prefix_totals(server)

    def _generate(logical: int, prompt, *, session_id):
        t0 = time.perf_counter()
        req = server.generate(
            prompt, max_new_tokens=max_new_tokens, sampling=sampling,
            session_id=session_id, keep_session=True, timeout=timeout,
        )
        rec = {
            "latency_s": time.perf_counter() - t0,
            "ttft_s": (req.t_first_token - req.t_submit)
            if req.t_first_token and req.t_submit else None,
            "tokens": len(req.tokens),
            "itl_s": req.itl_gaps(),
            "replica": req.replica,
            "session": logical,
        }
        with lock:
            sids[logical] = req.session_id
            history[logical].extend(int(t) for t in req.tokens)
            tokens_by_session[logical] += len(req.tokens)
            results.append(rec)

    def one_turn(logical: int) -> None:
        with lock:
            sid = sids[logical]
        try:
            if sid is None:
                _generate(logical, prompts[logical], session_id=None)
                return
            with lock:
                cont = [history[logical][-1]]
            try:
                _generate(logical, np.asarray(cont, np.int32),
                          session_id=sid)
                with lock:
                    continuations[0] += 1
                return
            except RuntimeError as e:
                if "unknown session" not in str(e):
                    raise
            # evicted with no restorable tier state: the honest client
            # re-sends its FULL history — the cost the tiers exist to kill
            with lock:
                full = list(history[logical])
                re_prefills[0] += 1
                re_prefill_tokens[0] += len(full)
                sids[logical] = None
            _generate(logical, np.asarray(full, np.int32), session_id=None)
        except QueueFullError:
            with lock:
                rejected[0] += 1
        except Exception:
            with lock:
                failed[0] += 1

    def worker() -> None:
        while True:
            with cond:
                idx = next((i for i, s in enumerate(schedule)
                            if s not in busy), None)
                if idx is None:
                    if not schedule:
                        return
                    # every remaining turn targets an in-flight session:
                    # wait for one to free up
                    cond.wait(timeout=0.05)
                    continue
                logical = schedule.pop(idx)
                busy.add(logical)
            try:
                one_turn(logical)
            finally:
                with cond:
                    busy.discard(logical)
                    cond.notify_all()

    with span("loadgen_longtail", sessions=sessions,
              turns=len(schedule)):
        t_start = time.perf_counter()
        threads = [threading.Thread(target=worker, daemon=True)
                   for _ in range(max(1, concurrency))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t_start

    report = _report(results, rejected[0], failed[0], wall, "longtail",
                     sessions)
    report["prompt_len"] = prompt_len
    report["zipf_s"] = zipf_s
    report["requests_per_session"] = requests_per_session
    report["re_prefills"] = re_prefills[0]
    report["re_prefill_tokens"] = re_prefill_tokens[0]
    hot_tokens = sum(tokens_by_session[:hot_k])
    report["hot_set"] = {
        "sessions": hot_k,
        "tokens_generated": hot_tokens,
        "tokens_per_sec": round(hot_tokens / wall, 2) if wall > 0 else 0.0,
    }
    # per-tier continuation accounting: fills by tier from the tiers'
    # own counters; device hits are the continuations that needed none.
    # Re-prefills count the evicted-and-unrestorable tail ("lost" from
    # the client's point of view), whatever the tiers' miss counter saw.
    conts = continuations[0] + re_prefills[0]
    ta, tb = tier_totals(server), tiers_before
    if ta is not None:
        host = ta["fills"]["host"] - (tb["fills"]["host"] if tb else 0)
        disk = ta["fills"]["disk"] - (tb["fills"]["disk"] if tb else 0)
        lost = re_prefills[0]
        spills = {t: ta["spills"][t] - (tb["spills"][t] if tb else 0)
                  for t in ta["spills"]}
        device = max(continuations[0] - host - disk, 0)
        total = max(conts, 1)
        report["tiers"] = {
            "continuations": conts,
            "hits": {"device": device, "host": host, "disk": disk},
            "lost": lost,
            "spills": spills,
            "hit_rates": {
                "device": round(device / total, 4),
                "host": round(host / total, 4),
                "disk": round(disk / total, 4),
            },
            "entries": dict(ta["entries"]),
        }
    else:
        report["tiers"] = {
            "continuations": conts,
            "hits": {"device": continuations[0], "host": 0, "disk": 0},
            "lost": re_prefills[0],
            "spills": {},
            "hit_rates": {
                "device": round(continuations[0] / max(conts, 1), 4),
                "host": 0.0, "disk": 0.0,
            },
            "entries": {},
        }
    report["replicas"] = _per_replica(results)
    if prefix_before is not None:
        after = prefix_totals(server)
        report["prefix_cache"] = {
            k: after[k] - prefix_before[k]
            for k in ("hits", "misses", "inserts", "invalidated")
        }
    return report


def concurrency_sweep(
    server: ServeServer,
    *,
    vocab_size: int,
    levels: tuple[int, ...] = (1, 8),
    requests_per_session: int = 4,
    prompt_len: int = 8,
    max_new_tokens: int = 16,
    sampling: SamplingParams = GREEDY,
    seed: int = 0,
) -> dict:
    """Closed-loop throughput at each concurrency level on ONE warm server
    (the engine pre-compiles the full bucket lattice before timing, so no
    level is charged XLA compiles mid-run). Returns
    ``{"levels": {n: report}, "speedup_max_vs_1": x}``."""
    with span("loadgen_warmup"):
        # the batcher derives its own window-ladder / chunk / prefix-split
        # programs, so no level is charged a compile mid-run
        server.warmup(sampling, prompt_lens=(prompt_len,))
    reports = {}
    for n in levels:
        reports[n] = run_loadgen(
            server, vocab_size=vocab_size, sessions=n,
            requests_per_session=requests_per_session,
            prompt_len=prompt_len, max_new_tokens=max_new_tokens,
            sampling=sampling, seed=seed + n,
        )
    out = {"levels": reports}
    if 1 in reports:
        base = reports[1]["tokens_per_sec"] or 1e-9
        out["speedup_max_vs_1"] = round(
            max(r["tokens_per_sec"] for r in reports.values()) / base, 3
        )
    return out


def replica_sweep(
    make_server,
    *,
    vocab_size: int,
    levels: tuple[int, ...] = (1, 2),
    sessions: int = 8,
    requests_per_session: int = 4,
    prompt_len: int = 8,
    max_new_tokens: int = 16,
    sampling: SamplingParams = GREEDY,
    seed: int = 0,
    parity_prompts: int = 4,
) -> dict:
    """Replica-count comparison: run the SAME closed-loop workload on a
    fresh ``make_server(n)`` stack per level — the machine-checkable
    scaling gate for data-parallel serving (``cli serve --loadgen
    --replicas 1,2``; BENCH_serve_r02.json).

    ``make_server(n)`` must return an UNSTARTED :class:`ServeServer`
    with ``n`` replicas; each level is warmed before timing (every
    replica compiles its own program lattice) and stopped after.
    ``parity_prompts`` > 0 with greedy sampling additionally decodes a
    fixed prompt set through every level and reports ``parity_ok`` —
    multi-replica greedy output must be token-identical to
    ``--replicas 1`` (each replica runs the same params through the
    same programs; routing must not change a single token).

    Returns ``{"levels": {n: report}, "scaling": {...}, "parity_ok"}``;
    each level's report carries the per-replica routed/served counts
    (``report["replicas"]``/``report["router"]``)."""
    levels = tuple(sorted({int(n) for n in levels}))
    if not levels or levels[0] < 1:
        raise ValueError(f"levels must be positive replica counts, "
                         f"got {levels!r}")
    check_parity = parity_prompts > 0 and sampling.greedy
    probes = (_random_prompts(parity_prompts, prompt_len, vocab_size,
                              seed + 4242) if check_parity else [])
    out: dict = {"levels": {}}
    parity: dict[int, list[list[int]]] = {}
    for n in levels:
        server = make_server(n)
        if len(server.replicas) != n:
            raise ValueError(
                f"make_server({n}) built {len(server.replicas)} replicas")
        with server:
            with span("replica_sweep_warmup", replicas=n):
                server.warmup(sampling, prompt_lens=(prompt_len,))
            out["levels"][n] = run_loadgen(
                server, vocab_size=vocab_size, sessions=sessions,
                requests_per_session=requests_per_session,
                prompt_len=prompt_len, max_new_tokens=max_new_tokens,
                sampling=sampling, seed=seed,
            )
            if probes:
                parity[n] = [
                    list(server.generate(p, max_new_tokens=max_new_tokens,
                                         sampling=sampling).tokens)
                    for p in probes
                ]
    base, top = levels[0], levels[-1]
    tps = {n: out["levels"][n]["tokens_per_sec"] for n in levels}
    out["scaling"] = {
        "tokens_per_sec": tps,
        "base_level": base,
        "top_level": top,
        "speedup_top_vs_base": round(tps[top] / (tps[base] or 1e-9), 3),
    }
    if parity:
        out["parity_ok"] = all(parity[n] == parity[base] for n in levels)
    return out


def mesh_sweep(
    make_server,
    *,
    vocab_size: int,
    levels: tuple[int, ...] = (1, 2),
    sessions: int = 8,
    requests_per_session: int = 4,
    prompt_len: int = 8,
    max_new_tokens: int = 16,
    sampling: SamplingParams = GREEDY,
    seed: int = 0,
    parity_prompts: int = 4,
) -> dict:
    """Tensor-parallel shard-count comparison (``tools/bench_serve.py
    --mesh-shards 1,2``; BENCH_serve_r06.json): the SAME closed-loop
    workload on a fresh ``make_server(shards)`` stack per level —
    aggregate tokens/s + TTFT/ITL percentiles per shard count, the
    sharded/single-device ratio, greedy cross-config token parity, and
    a warmup-asserted zero-mid-traffic-compile check (the measured run
    must never be charged an XLA compile: the warmed lattice IS the
    claim that sharding adds no compile-key gaps).

    On CPU virtual devices the "shards" are threads of one host, so the
    ratio prices GSPMD partition overhead WITHOUT the memory-capacity
    win sharding exists for — it is recorded honestly and is expected
    to be <= 1.0; the capacity claim belongs to real multi-chip hosts
    (the plumbing + parity are what this sweep gates)."""
    levels = tuple(sorted({int(n) for n in levels}))
    if not levels or levels[0] < 1:
        raise ValueError(f"levels must be positive shard counts, "
                         f"got {levels!r}")
    check_parity = parity_prompts > 0 and sampling.greedy
    probes = (_random_prompts(parity_prompts, prompt_len, vocab_size,
                              seed + 4242) if check_parity else [])
    out: dict = {"levels": {}}
    parity: dict[int, list[list[int]]] = {}
    mid_traffic_compiles: dict[int, int] = {}
    for n in levels:
        server = make_server(n)
        with server:
            with span("mesh_sweep_warmup", shards=n):
                server.warmup(sampling, prompt_lens=(prompt_len,))
            warm = sum(r.engine.num_compiles() for r in server.replicas)
            out["levels"][n] = run_loadgen(
                server, vocab_size=vocab_size, sessions=sessions,
                requests_per_session=requests_per_session,
                prompt_len=prompt_len, max_new_tokens=max_new_tokens,
                sampling=sampling, seed=seed,
            )
            if probes:
                parity[n] = [
                    list(server.generate(p, max_new_tokens=max_new_tokens,
                                         sampling=sampling).tokens)
                    for p in probes
                ]
            # zero mid-traffic compiles, warmup-asserted: every program
            # the workload touched was already in the warmed lattice
            mid_traffic_compiles[n] = (
                sum(r.engine.num_compiles() for r in server.replicas)
                - warm)
            es = server.engine.stats()
            out["levels"][n]["mesh_shards"] = es["mesh_shards"]
            out["levels"][n]["decode_window_scan_fallbacks"] = (
                es["decode_window_scan_fallbacks"])
    base, top = levels[0], levels[-1]
    tps = {n: out["levels"][n]["tokens_per_sec"] for n in levels}
    out["scaling"] = {
        "tokens_per_sec": tps,
        "base_shards": base,
        "top_shards": top,
        "shard_ratio_top_vs_base": round(tps[top] / (tps[base] or 1e-9), 3),
        "p50_ttft_ms": {n: out["levels"][n]["p50_ttft_ms"]
                        for n in levels},
        "p99_itl_ms": {n: out["levels"][n]["p99_itl_ms"]
                       for n in levels},
    }
    out["mid_traffic_compiles"] = mid_traffic_compiles
    out["warmup_covered"] = all(v == 0
                                for v in mid_traffic_compiles.values())
    if parity:
        out["parity_ok"] = all(parity[n] == parity[base] for n in levels)
    return out


def kernel_sweep(
    make_server,
    *,
    vocab_size: int,
    kernels: tuple[str, ...] = ("scan", "pallas"),
    sessions: int = 8,
    requests_per_session: int = 4,
    prompt_len: int = 8,
    max_new_tokens: int = 16,
    sampling: SamplingParams = GREEDY,
    seed: int = 0,
    parity_prompts: int = 4,
) -> dict:
    """Decode-kernel comparison (``cli serve --loadgen --decode-kernel
    pallas,scan``; the BENCH_serve_r05.json probe): the SAME closed-loop
    workload on a fresh ``make_server(kernel)`` stack per kernel, with
    tokens/s + TTFT/ITL percentiles per kernel, the pallas-vs-scan
    deltas, and greedy token parity across kernels — the decode window
    must produce the SAME stream whichever kernel computes it.

    Off-TPU the pallas kernel runs in interpreter mode, which is slower
    than the scan window by construction — the report records the honest
    ratio either way (the speed claim belongs to real hardware,
    tests_tpu/)."""
    kernels = tuple(dict.fromkeys(kernels))  # dedupe, keep order
    if not kernels:
        raise ValueError("kernels must name at least one decode kernel")
    check_parity = parity_prompts > 0 and sampling.greedy
    probes = (_random_prompts(parity_prompts, prompt_len, vocab_size,
                              seed + 4242) if check_parity else [])
    out: dict = {"kernels": {}}
    parity: dict[str, list[list[int]]] = {}
    fallbacks: dict[str, int] = {}
    for kern in kernels:
        server = make_server(kern)
        with server:
            with span("kernel_sweep_warmup", kernel=kern):
                server.warmup(sampling, prompt_lens=(prompt_len,))
            out["kernels"][kern] = run_loadgen(
                server, vocab_size=vocab_size, sessions=sessions,
                requests_per_session=requests_per_session,
                prompt_len=prompt_len, max_new_tokens=max_new_tokens,
                sampling=sampling, seed=seed,
            )
            if probes:
                parity[kern] = [
                    list(server.generate(p, max_new_tokens=max_new_tokens,
                                         sampling=sampling).tokens)
                    for p in probes
                ]
            es = server.engine.stats()
            out["kernels"][kern]["decode_kernel"] = es["decode_kernel"]
            fallbacks[kern] = es["decode_window_scan_fallbacks"]
    out["scan_fallbacks"] = fallbacks
    if "scan" in out["kernels"] and "pallas" in out["kernels"]:
        s, p = out["kernels"]["scan"], out["kernels"]["pallas"]
        out["pallas_vs_scan"] = {
            "tokens_per_sec_ratio": round(
                p["tokens_per_sec"] / (s["tokens_per_sec"] or 1e-9), 3),
            "p50_itl_delta_ms": round(
                p["p50_itl_ms"] - s["p50_itl_ms"], 3),
            "p99_itl_delta_ms": round(
                p["p99_itl_ms"] - s["p99_itl_ms"], 3),
            "p50_ttft_delta_ms": round(
                p["p50_ttft_ms"] - s["p50_ttft_ms"], 3),
        }
    if parity:
        base = kernels[0]
        out["parity_ok"] = all(parity[k] == parity[base] for k in kernels)
    return out
