"""Bucketed jitted prefill/decode programs over the recurrent-state cache.

The decode step for a packed batch is ONE compiled program: gather each
row's ``(h, c)`` from the cache by slot index, run the shared training cell
(`models.generate.decode_one` → `ops.lstm_cell.lstm_step` on pre-fused
kernels), sample with `models.generate.sample_logits`, scatter the new
carries back. Prefill is the same shape of program around the masked
`lm_backbone` scan (carry-freeze at padded steps), so a right-padded prompt
ends with exactly the state an unpadded run would produce and the first
sampled token is token-identical to `models/generate.py`.

Recompile discipline (the XLA-on-TPU cost that kills naive serving): every
host-visible batch is padded to a **bucket** —

- prompts pad to the smallest length bucket that fits (``prefill_buckets``);
- batches pad to the smallest batch bucket (``batch_buckets``), dead rows
  pointing at the cache's scratch slot;

so XLA compiles at most once per (phase, batch-bucket[, length-bucket],
sampling-config), never per batch composition. `compile_counts` records
actual traces (incremented at trace time) and is asserted in
tests/test_serve_batcher.py.

Sampling parameters are compile-time constants (they specialize the sampled
program, exactly as in `make_generate_fn`); the batcher groups requests by
`SamplingParams.key()` so one batch is one sampling config. Non-greedy
sampling draws from an engine-global rng chain — reproducible for a fixed
submission order, but not per-session; greedy decode is deterministic and
is the parity-tested mode.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import defaultdict

import jax
import jax.numpy as jnp
import numpy as np

from ..models.generate import decode_one, fuse_layers, sample_logits
from ..models.lstm_lm import LMConfig, _head_kernel, lm_backbone
from ..resilience import faults as _faults
from .state_cache import DetachedState, StateCache


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling config — static at trace time (one compiled
    program per distinct config, same contract as `make_generate_fn`)."""

    temperature: float = 1.0
    top_k: int | None = None
    top_p: float | None = None
    greedy: bool = False

    def key(self) -> tuple:
        return (self.temperature, self.top_k, self.top_p, self.greedy)


GREEDY = SamplingParams(greedy=True)


def _bucket_for(value: int, buckets: tuple[int, ...], what: str) -> int:
    for b in buckets:
        if value <= b:
            return b
    raise ValueError(f"{what} {value} exceeds the largest bucket {buckets[-1]}")


class ServeEngine:
    """Owns params, the fused kernels, the state cache, and the per-bucket
    compiled programs. Thread-safe: one lock serialises device dispatch
    (the cache arrays are threaded through jit functionally — concurrent
    steps would race on `cache.swap`)."""

    def __init__(
        self,
        params,
        cfg: LMConfig,
        *,
        num_slots: int = 64,
        prefill_buckets: tuple[int, ...] = (8, 16, 32, 64, 128),
        batch_buckets: tuple[int, ...] = (1, 2, 4, 8, 16),
        max_sampling_configs: int = 16,
        rng_seed: int = 0,
    ):
        # serving never rematerialises (same override as generate())
        if cfg.remat_chunk is not None:
            cfg = dataclasses.replace(cfg, remat_chunk=None)
        self.cfg = cfg
        self.params = params
        self.fused_layers = fuse_layers(params, cfg)  # once, at init
        self.prefill_buckets = tuple(sorted(prefill_buckets))
        self.batch_buckets = tuple(sorted(batch_buckets))
        self.cache = StateCache(cfg.num_layers, num_slots, cfg.hidden_size)
        # sampling params are compile keys and client-controlled at the
        # HTTP boundary: bound how many distinct configs this engine will
        # ever compile, or a client sweeping temperatures could thrash
        # XLA (~20-40 s per TPU compile) and grow the program cache
        # without limit
        self.max_sampling_configs = max_sampling_configs
        self._sampling_keys: set[tuple] = set()
        self.compile_counts: dict[tuple, int] = defaultdict(int)
        self._prefill_fns: dict[tuple, callable] = {}
        self._decode_fns: dict[tuple, callable] = {}
        self._rng = jax.random.PRNGKey(rng_seed)
        self._dummy_rng = jax.random.PRNGKey(0)
        self._lock = threading.RLock()
        # compile_counts gets its own tiny mutex: _lock is held across
        # entire device calls (dispatch serialization), and stats/health
        # readers must never block behind an in-flight — possibly
        # wedged — dispatch just to copy a counter dict
        self._counts_lock = threading.Lock()
        self._warming = False  # warmup decodes bypass the fault hook

    # ---- limits --------------------------------------------------------

    @property
    def max_prompt_len(self) -> int:
        return self.prefill_buckets[-1]

    @property
    def max_batch(self) -> int:
        return self.batch_buckets[-1]

    # ---- compiled programs --------------------------------------------

    def _admit_sampling(self, sampling: SamplingParams) -> None:
        key = sampling.key()
        if key in self._sampling_keys:
            return
        if len(self._sampling_keys) >= self.max_sampling_configs:
            raise ValueError(
                f"engine already compiled {self.max_sampling_configs} "
                "distinct sampling configs; rejecting a new one (raise "
                "max_sampling_configs if this workload is legitimate)"
            )
        self._sampling_keys.add(key)

    def _next_rng(self, sampling: SamplingParams):
        if sampling.greedy:
            return self._dummy_rng  # greedy ignores the key: skip the split
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _get_prefill_fn(self, batch_b: int, len_b: int, sampling: SamplingParams):
        key = (batch_b, len_b, sampling.key())
        fn = self._prefill_fns.get(key)
        if fn is not None:
            return fn
        cfg = self.cfg
        count_key = ("prefill", batch_b, len_b, sampling.key())

        def prefill_fn(params, h_cache, c_cache, slots, fresh, prompts,
                       lengths, rng):
            # trace-time side effect: one bump per XLA compile of this shape
            with self._counts_lock:
                self.compile_counts[count_key] += 1
            h_in = h_cache[:, slots, :]  # [L, B, H]
            c_in = c_cache[:, slots, :]
            # fresh rows start from zero state — no device-side slot
            # zeroing on acquire, the zero ride along in this program
            live = ~fresh[None, :, None]
            h_in = jnp.where(live, h_in, 0.0)
            c_in = jnp.where(live, c_in, 0.0)
            carries = [(h_in[l], c_in[l]) for l in range(cfg.num_layers)]
            mask = jnp.arange(len_b)[None, :] < lengths[:, None]  # [B, T]
            finals, ys = lm_backbone(params, prompts, cfg, carries=carries,
                                     mask=mask)
            # logits at each row's true last position (same head math, same
            # ldtype as lm_forward — near-tied logits must argmax alike)
            last = jnp.take_along_axis(
                ys, (lengths - 1)[:, None, None], axis=1
            )[:, 0, :]  # [B, H]
            kernel, bias = _head_kernel(params, cfg)
            logits = (
                jnp.dot(last.astype(kernel.dtype), kernel,
                        preferred_element_type=cfg.ldtype)
                + bias.astype(cfg.ldtype)
            )
            token = sample_logits(
                rng, logits, temperature=sampling.temperature,
                top_k=sampling.top_k, top_p=sampling.top_p,
                greedy=sampling.greedy,
            )
            new_h = jnp.stack([f[0] for f in finals])  # [L, B, H]
            new_c = jnp.stack([f[1] for f in finals])
            h_cache = h_cache.at[:, slots, :].set(new_h.astype(jnp.float32))
            c_cache = c_cache.at[:, slots, :].set(new_c.astype(jnp.float32))
            return h_cache, c_cache, token

        fn = jax.jit(prefill_fn)
        self._prefill_fns[key] = fn
        return fn

    def _get_decode_fn(self, batch_b: int, sampling: SamplingParams):
        key = (batch_b, sampling.key())
        fn = self._decode_fns.get(key)
        if fn is not None:
            return fn
        cfg = self.cfg
        count_key = ("decode", batch_b, sampling.key())

        def decode_fn(params, fused, h_cache, c_cache, slots, tokens, rng):
            with self._counts_lock:
                self.compile_counts[count_key] += 1
            h_in = h_cache[:, slots, :]
            c_in = c_cache[:, slots, :]
            carries = [(h_in[l], c_in[l]) for l in range(cfg.num_layers)]
            logits, new_carries = decode_one(params, fused, cfg, carries,
                                             tokens)
            nxt = sample_logits(
                rng, logits, temperature=sampling.temperature,
                top_k=sampling.top_k, top_p=sampling.top_p,
                greedy=sampling.greedy,
            )
            new_h = jnp.stack([nc[0] for nc in new_carries])
            new_c = jnp.stack([nc[1] for nc in new_carries])
            h_cache = h_cache.at[:, slots, :].set(new_h.astype(jnp.float32))
            c_cache = c_cache.at[:, slots, :].set(new_c.astype(jnp.float32))
            return h_cache, c_cache, nxt

        fn = jax.jit(decode_fn)
        self._decode_fns[key] = fn
        return fn

    # ---- host-facing steps --------------------------------------------

    def prefill(self, items, sampling: SamplingParams = GREEDY) -> np.ndarray:
        """Run one bucketed prefill batch.

        ``items``: list of ``(slot, fresh, prompt)`` with ``prompt`` a 1-D
        int array (1 <= len <= max_prompt_len). Rows are padded up to the
        batch bucket (dead rows target the scratch slot) and prompts are
        right-padded to the length bucket (carry-freeze mask). Returns the
        first sampled token per item, ``[len(items)]`` int32.
        """
        n = len(items)
        if n == 0:
            return np.zeros((0,), np.int32)
        lengths = [int(np.asarray(p).size) for _, _, p in items]
        for t in lengths:
            if t < 1:
                raise ValueError("empty prompt")
        self._admit_sampling(sampling)
        batch_b = _bucket_for(n, self.batch_buckets, "prefill batch")
        len_b = _bucket_for(max(lengths), self.prefill_buckets, "prompt length")

        slots = np.full((batch_b,), self.cache.scratch_slot, np.int32)
        fresh = np.ones((batch_b,), bool)
        prompts = np.zeros((batch_b, len_b), np.int32)
        lens = np.ones((batch_b,), np.int32)
        for i, (slot, is_fresh, prompt) in enumerate(items):
            p = np.asarray(prompt, np.int32).reshape(-1)
            slots[i] = slot
            fresh[i] = bool(is_fresh)
            prompts[i, : p.size] = p
            lens[i] = p.size

        with self._lock:
            fn = self._get_prefill_fn(batch_b, len_b, sampling)
            rng = self._next_rng(sampling)
            h, c, tok = fn(self.params, self.cache.h, self.cache.c,
                           jnp.asarray(slots), jnp.asarray(fresh),
                           jnp.asarray(prompts), jnp.asarray(lens), rng)
            self.cache.swap(h, c)
        return np.asarray(tok)[:n]

    def decode(self, slots, tokens, sampling: SamplingParams = GREEDY) -> np.ndarray:
        """Advance each session one token: gather carries by ``slots`` [B],
        feed ``tokens`` [B], return the next token per row ``[B]`` int32.
        Pads to the batch bucket (dead rows at the scratch slot)."""
        n = len(slots)
        if n == 0:
            return np.zeros((0,), np.int32)
        # chaos drills: an armed serve_error fault raises InjectedFault out
        # of the Nth decode call — the batcher must fail ONLY that chunk's
        # requests and keep serving (tests/test_serve_health.py). Warmup's
        # dummy decodes neither count nor fire: the drill targets traffic,
        # and an InjectedFault inside warmup() would kill the whole server
        # at startup instead of one mid-traffic chunk.
        if not self._warming:
            _faults.serve_decode_hook()
        self._admit_sampling(sampling)
        batch_b = _bucket_for(n, self.batch_buckets, "decode batch")
        slots_p = np.full((batch_b,), self.cache.scratch_slot, np.int32)
        slots_p[:n] = np.asarray(slots, np.int32)
        tokens_p = np.zeros((batch_b,), np.int32)
        tokens_p[:n] = np.asarray(tokens, np.int32)

        with self._lock:
            fn = self._get_decode_fn(batch_b, sampling)
            rng = self._next_rng(sampling)
            h, c, tok = fn(self.params, self.fused_layers, self.cache.h,
                           self.cache.c, jnp.asarray(slots_p),
                           jnp.asarray(tokens_p), rng)
            self.cache.swap(h, c)
        return np.asarray(tok)[:n]

    def warmup(self, sampling: SamplingParams = GREEDY,
               prompt_lens: tuple[int, ...] = (1,),
               batch_sizes: tuple[int, ...] | None = None) -> int:
        """Pre-compile the bucket lattice a workload will touch (every
        batch bucket x the length buckets covering ``prompt_lens``, both
        phases) by running dummy steps against the scratch slot — so the
        first real traffic burst is never charged the compiles. Returns
        the number of (phase, bucket) programs now cached."""
        batch_sizes = tuple(batch_sizes or self.batch_buckets)
        len_buckets = sorted({
            _bucket_for(t, self.prefill_buckets, "prompt length")
            for t in prompt_lens
        })
        scratch = self.cache.scratch_slot
        self._warming = True
        try:
            for b in batch_sizes:
                bb = _bucket_for(b, self.batch_buckets, "batch")
                for t in len_buckets:
                    items = [(scratch, True, np.zeros((t,), np.int32))] * bb
                    self.prefill(items, sampling)
                self.decode([scratch] * bb, [0] * bb, sampling)
        finally:
            self._warming = False
        return len(self._prefill_fns) + len(self._decode_fns)

    # ---- session lifecycle (thin wrappers over the cache) -------------

    def detach_session(self, session_id: str) -> DetachedState:
        with self._lock:
            return self.cache.detach(session_id)

    def restore_session(self, session_id: str, state: DetachedState) -> int:
        with self._lock:
            return self.cache.restore(session_id, state)

    def num_compiles(self, phase: str | None = None) -> int:
        # snapshot under the COUNTS lock (not _lock, which is held across
        # whole device calls): a first-time compile inserts into
        # compile_counts at trace time, and iterating concurrently from a
        # stats/health handler would raise "dictionary changed size
        # during iteration" — while blocking on _lock would park the
        # handler behind an in-flight (possibly wedged) dispatch
        with self._counts_lock:
            items = list(self.compile_counts.items())
        return sum(v for k, v in items if phase is None or k[0] == phase)

    def stats(self) -> dict:
        with self._counts_lock:
            compiles = dict(self.compile_counts)
        return {
            "cache": self.cache.stats(),
            "compiles": {repr(k): v for k, v in compiles.items()},
            "prefill_buckets": self.prefill_buckets,
            "batch_buckets": self.batch_buckets,
        }
