"""Bucketed jitted prefill/decode programs over the recurrent-state cache.

The decode step for a packed batch is ONE compiled program: gather each
row's ``(h, c)`` from the cache by slot index, run the shared training cell
(`models.generate.decode_one` → `ops.lstm_cell.lstm_step` on pre-fused
kernels), sample with `models.generate.sample_logits`, scatter the new
carries back. Prefill is the same shape of program around the masked
`lm_backbone` scan (carry-freeze at padded steps), so a right-padded prompt
ends with exactly the state an unpadded run would produce and the first
sampled token is token-identical to `models/generate.py`.

**Windowed decode** (`decode_window`): the same fused step wrapped in a
`lax.scan` that advances the packed batch K tokens in ONE XLA program —
carries are gathered from the cache once at window entry and scattered
once at exit, so K-fold fewer dispatches, gathers, scatters and host
round-trips per generated token. Per-row liveness is latched **on
device**: a row that emits its ``eos_id`` or exhausts its token budget
freezes its carries for the rest of the window and emits ``PAD_TOKEN``
(-1), so a window is always safe to run even when rows finish mid-window
— frozen rows scatter their unchanged carries back. The window program
returns device HANDLES (:class:`DecodeWindow`), not host arrays: the
batcher can dispatch window i+1 from window i's ``next_tokens``/
``alive``/``remaining`` handles *before* fetching window i's tokens
(`fetch_window`), overlapping host readback and Python token
distribution with device compute (JAX async dispatch; program order is
enforced by the cache arrays threading functionally through every
dispatch).

**Resumable / chunked prefill**: every prefill program gathers carries
from per-row ``src`` slots and scatters to ``dst`` slots. With src == dst
that is the classic in-place prefill; with src pointing at a
prefix-cache slot (state_cache.PrefixCache) the program resumes prefill
at an arbitrary prompt offset from a cached carry — the src slot is
READ-ONLY in the program, so a shared prefix is never aliased by a
session's writes. ``prefill_chunk`` is the head-less variant
(consume up to C tokens, scatter state, sample nothing): the batcher
chains chunk programs — one bounded dispatch per scheduler iteration —
so a bucket-128 prompt no longer stalls every running session's decode
behind one monolithic prefill program.

Recompile discipline (the XLA-on-TPU cost that kills naive serving): every
host-visible batch is padded to a **bucket** —

- prompts pad to the smallest length bucket that fits (``prefill_buckets``);
- batches pad to the smallest batch bucket (``batch_buckets``), dead rows
  pointing at the cache's scratch slot;
- window sizes come from a small fixed ladder chosen by the batcher
  (e.g. 1/4/8), each a compile key: at most one compile per
  ``("decode_window", batch-bucket, K, sampling-config)``;
- intermediate prefill chunks are sampling-free: one compile per
  ``("prefill_chunk", batch-bucket, length-bucket)`` across ALL sampling
  configs;

so XLA compiles at most once per (phase, batch-bucket[, length-bucket]
[, window], sampling-config), never per batch composition.
`compile_counts` records actual traces (incremented at trace time) and is
asserted in tests/test_serve_batcher.py + tests/test_serve_window.py.

Sampling parameters are compile-time constants (they specialize the sampled
program, exactly as in `make_generate_fn`); the batcher groups requests by
`SamplingParams.key()` so one batch is one sampling config. Non-greedy
sampling draws from an engine-global rng chain — reproducible for a fixed
submission order, but not per-session; greedy decode is deterministic and
is the parity-tested mode.

**Mesh (tensor-parallel) engine** (``mesh_shards > 1``): the replica's
params and cache slots shard their hidden/gate dimension over a one-axis
``("model",)`` device mesh using the training-side GSPMD specs
(parallel/tensor_parallel.py) — the same jit programs then run sharded
with XLA deriving the per-step h all-gather and logits psum from the
placements, so a model too large for one chip serves behind the router
as just another replica. Compile-key families grow a trailing shard
axis (``("decode_window", bucket, K, sampling, shards)``); the Pallas
window kernel is single-device and falls back to the scan program,
loudly and counted (tests/test_serve_mesh.py pins token-identical
greedy AND sampled parity vs the single-device engine).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import defaultdict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import obs
from ..models.generate import decode_one, fuse_layers, sample_logits
from ..models.lstm_lm import LMConfig, _head_kernel, lm_backbone
from ..ops import pallas_decode
from ..resilience import faults as _faults
from .state_cache import DetachedState, PrefixCache, SessionTiers, StateCache

# Emitted by decode_window for a row that is no longer live (post-EOS /
# budget-exhausted / batch padding): the host stops distributing a row's
# tokens at the first PAD_TOKEN. -1 cannot collide with a vocab id.
PAD_TOKEN = -1
assert pallas_decode.PAD_TOKEN == PAD_TOKEN  # one wire contract, two files

#: decode_kernel choices: "scan" = the lax.scan window; "pallas" = the
#: fused VMEM-resident window kernel (ops/pallas_decode.py; interpreter
#: mode off-TPU so CPU tier-1 proves parity); "auto" = pallas on TPU
#: when the VMEM plan fits, scan otherwise (interpreted pallas is a
#: correctness path, not a fast one).
DECODE_KERNELS = ("auto", "pallas", "scan")


class UnknownModelError(Exception):
    """A request named a model that is not resident on this engine (or,
    at the router, on any live replica). Maps to HTTP 404 — the client
    asked for something the fleet does not currently serve, which is
    neither a bad request shape nor a capacity problem."""


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling config — static at trace time (one compiled
    program per distinct config, same contract as `make_generate_fn`)."""

    temperature: float = 1.0
    top_k: int | None = None
    top_p: float | None = None
    greedy: bool = False

    def key(self) -> tuple:
        return (self.temperature, self.top_k, self.top_p, self.greedy)


GREEDY = SamplingParams(greedy=True)


@dataclasses.dataclass(frozen=True)
class DecodeWindow:
    """A dispatched (possibly still in-flight) decode window.

    All array fields are DEVICE handles — nothing here forces a sync.
    ``tokens`` is the window's output ``[batch_b, window]`` (``PAD_TOKEN``
    for non-live rows); ``next_tokens``/``alive``/``remaining`` are the
    row states a follow-up window needs, so :meth:`ServeEngine.
    decode_window_next` can dispatch window i+1 from window i's handles
    before the host ever reads window i (`fetch_window`)."""

    tokens: jax.Array       # [batch_b, window] int32, PAD_TOKEN when dead
    next_tokens: jax.Array  # [batch_b] int32 — input for the next window
    alive: jax.Array        # [batch_b] bool — rows still decoding
    remaining: jax.Array    # [batch_b] int32 — per-row budget left
    slots: jax.Array        # [batch_b] int32 cache slots (reused as-is)
    eos_ids: jax.Array      # [batch_b] int32, -1 = no eos for that row
    batch_b: int
    window: int
    n: int                  # live (non-padding) rows; fetch strips the rest
    sampling: SamplingParams
    # host perf_counter stamp taken right after dispatch: the batcher
    # derives dispatch→fetch readback latency and the request timeline's
    # decode_window span from it (telemetry only — never device-ordered)
    t_dispatch: float = 0.0
    # which resident model produced this window — decode_window_next
    # dispatches the follow-up against the same model's params
    model: str | None = None
    # speculative verify window (spec_window): ``window`` is the verify
    # length W = K_draft + 1 (max tokens one spec step can emit), and the
    # follow-up dispatch goes through spec_window_next, never
    # decode_window_next — the two programs carry different device state
    # (the spec one also threads the draft model's carries)
    spec: bool = False


def _bucket_for(value: int, buckets: tuple[int, ...], what: str) -> int:
    for b in buckets:
        if value <= b:
            return b
    raise ValueError(f"{what} {value} exceeds the largest bucket {buckets[-1]}")


class ServeEngine:
    """Owns params, the fused kernels, the state cache, and the per-bucket
    compiled programs. Thread-safe: one lock serialises device dispatch
    (the cache arrays are threaded through jit functionally — concurrent
    steps would race on `cache.swap`)."""

    def __init__(
        self,
        params,
        cfg: LMConfig,
        *,
        num_slots: int = 64,
        prefill_buckets: tuple[int, ...] = (8, 16, 32, 64, 128),
        batch_buckets: tuple[int, ...] = (1, 2, 4, 8, 16),
        max_sampling_configs: int = 16,
        rng_seed: int = 0,
        prefix_cache: bool = False,
        prefix_stride: int = 8,
        prefix_entries: int = 16,
        prefix_fabric: bool = False,
        prefix_nodes: int = 64,
        prefix_host_mb: float = 64.0,
        tiered_cache: bool = False,
        host_tier_entries: int = 256,
        session_dir: str | None = None,
        replica: int = 0,
        registry=None,
        device=None,
        decode_kernel: str = "auto",
        mesh_shards: int = 1,
        mesh_devices=None,
        model_id: str = "default",
        model_version: int = 0,
    ):
        # serving never rematerialises (same override as generate())
        if cfg.remat_chunk is not None:
            cfg = dataclasses.replace(cfg, remat_chunk=None)
        self.cfg = cfg
        # device-per-replica serving (serve/router.py): committing params
        # + cache arrays pins every program of this engine to one device,
        # so N replicas spread across jax.devices() compute concurrently
        # (uncommitted host inputs follow the committed operands)
        self.device = device
        # ---- mesh-per-replica: tensor-parallel engine ----------------
        # mesh_shards > 1 shards THIS replica's params and cache slots
        # over a one-axis ("model",) mesh (parallel/mesh.make_serve_mesh)
        # using the exact GSPMD specs training uses
        # (parallel/tensor_parallel.lm_param_specs: gate kernels
        # column-sharded [D, H/P], recurrent [H, H/P], head row-sharded
        # [H/P, V], embedding replicated) — XLA derives the per-step h
        # all-gather and the logits psum from the placements, so every
        # existing jit program (prefill/decode/decode_window) runs
        # sharded UNCHANGED and the batcher/router never know. The model
        # no longer has to fit one chip; behind the router a mesh
        # replica is just another replica.
        self.mesh_shards = int(mesh_shards)
        self.mesh = None
        cache_sharding = None
        if self.mesh_shards > 1:
            if device is not None:
                raise ValueError(
                    "mesh_shards > 1 owns its own device group — do not "
                    "also pass device= (device-per-replica placement)")
            if cfg.hidden_size % self.mesh_shards != 0:
                raise ValueError(
                    f"hidden_size {cfg.hidden_size} is not divisible by "
                    f"mesh_shards {self.mesh_shards} — the gate/hidden "
                    "dimension shards evenly or not at all")
            from ..parallel.mesh import make_serve_mesh
            from ..parallel.tensor_parallel import place_lm_params
            from jax.sharding import NamedSharding, PartitionSpec as P

            self.mesh = make_serve_mesh(self.mesh_shards,
                                        devices=mesh_devices)
            params = place_lm_params(params, self.mesh)
            # cache slots shard over the hidden axis exactly like h: the
            # gather-by-slot, the step, and the scatter-back all stay on
            # the shard-local rows, with no resharding at window entry
            cache_sharding = NamedSharding(self.mesh, P(None, None, "model"))
        elif mesh_devices is not None:
            raise ValueError("mesh_devices needs mesh_shards > 1")
        self.params = params
        self.fused_layers = fuse_layers(params, cfg)  # once, at init
        # ---- resident models -----------------------------------------
        # N models (same LMConfig — the cache slots and bucket programs
        # are shape-compatible across residents) live side by side; each
        # dispatch resolves its (params, fused) pair by model id, and the
        # batcher groups batches so one dispatch is one model. The
        # DEFAULT model (``model_id``) keeps the legacy compile-key arity
        # — a single-model fleet's keys, stats, and tests are unchanged;
        # extra residents append their id to program/count keys (family
        # string stays FIRST: graftlint warmup-coverage reads elts[0]).
        self.model_id = str(model_id)
        self._residents: dict[str, dict] = {
            self.model_id: {"params": self.params,
                            "fused": self.fused_layers,
                            "version": model_version},
        }
        self.prefill_buckets = tuple(sorted(prefill_buckets))
        self.batch_buckets = tuple(sorted(batch_buckets))
        # the telemetry registry every serve-side component records into
        # (obs.REGISTRY process-wide default; obs.NULL_REGISTRY disables);
        # the batcher and server read engine.metrics so one constructor
        # argument scopes the whole stack
        self.metrics = obs.REGISTRY if registry is None else registry
        self.cache = StateCache(cfg.num_layers, num_slots, cfg.hidden_size,
                                registry=self.metrics, device=device,
                                sharding=cache_sharding)
        # tiered session-state cache (state_cache.SessionTiers): device
        # slots stay tier 0; LRU-evicted sessions spill async to host RAM
        # with a durable disk tier below (``session_dir`` — also what a
        # restarted server restores sessions from). A session_dir alone
        # implies the tiers: durability needs the spill plane.
        self.tiers = (
            SessionTiers(self.cache, host_entries=host_tier_entries,
                         directory=session_dir, registry=self.metrics,
                         replica=replica)
            if (tiered_cache or session_dir is not None) else None
        )
        # shared-prompt prefix reuse: opt-in at engine construction; the
        # batcher consults engine.prefix on every fresh admission when
        # present. ``prefix_fabric`` selects the radix PrefixTrie
        # (longest-match over ANY shared prefix, host-byte-bounded
        # spill, cross-replica propagation hooks) over the exact-match
        # PrefixCache — both duck-type the same store contract, so
        # everything downstream of engine.prefix is agnostic. With
        # tiers attached, an evicted backing slot SPILLS the entry
        # instead of invalidating it (either store).
        if prefix_fabric:
            from .prefix_trie import PrefixTrie
            self.prefix = PrefixTrie(
                self.cache, stride=prefix_stride, max_nodes=prefix_nodes,
                host_bytes=int(prefix_host_mb * 2 ** 20),
                registry=self.metrics, tiers=self.tiers)
        elif prefix_cache:
            self.prefix = PrefixCache(
                self.cache, stride=prefix_stride,
                max_entries=prefix_entries, registry=self.metrics,
                tiers=self.tiers)
        else:
            self.prefix = None
        # sampling params are compile keys and client-controlled at the
        # HTTP boundary: bound how many distinct configs this engine will
        # ever compile, or a client sweeping temperatures could thrash
        # XLA (~20-40 s per TPU compile) and grow the program cache
        # without limit
        self.max_sampling_configs = max_sampling_configs
        self._sampling_keys: set[tuple] = set()
        # ---- decode-kernel selection (ops/pallas_decode.py) ----------
        # resolved ONCE here to "pallas" or "scan"; per-dispatch the
        # pallas path still falls back to the scan window for sampling
        # configs / shapes the kernel does not cover (counted honestly
        # in decode_window_scan_fallbacks — a silent switch would make
        # the measured speedup a lie).
        if decode_kernel not in DECODE_KERNELS:
            raise ValueError(
                f"decode_kernel must be one of {DECODE_KERNELS}, got "
                f"{decode_kernel!r}")
        if self.mesh is not None:
            platform = self.mesh.devices.flat[0].platform
        else:
            platform = (device.platform if device is not None
                        else jax.default_backend())
        if decode_kernel == "auto":
            # off-TPU the interpreted kernel is a correctness path, not
            # a fast one — auto stays on the scan window there; a SHARDED
            # engine resolves to scan too (the fused kernel is a
            # single-device program — it cannot read sharded carries)
            use_pallas = (platform == "tpu" and self.mesh_shards == 1
                          and pallas_decode.plan_fits(
                self.batch_buckets[-1], 8, cfg.num_layers,
                cfg.hidden_size, cfg.embed, cfg.vocab_size, sampled=True))
            self.decode_kernel = "pallas" if use_pallas else "scan"
        else:
            self.decode_kernel = decode_kernel
        if self.decode_kernel == "pallas" and self.mesh_shards > 1:
            # the EXPLICIT pallas pick on a mesh engine: honored as a
            # request, unsatisfiable as a program — every window falls
            # back to the scan program (counted per dispatch in
            # decode_window_scan_fallbacks via _pallas_window_ok), and
            # this boot-time line says so before the first request pays
            # attention to the counter. Loud fallback, never a crash or
            # a silent resolve.
            print(
                f"serve: --decode-kernel pallas is not supported on a "
                f"{self.mesh_shards}-shard mesh engine (the fused window "
                "kernel is single-device) — every decode window falls "
                "back to the scan program, counted in "
                "decode_window_scan_fallbacks", flush=True)
        self._pallas_interpret = platform != "tpu"
        self.decode_window_scan_fallbacks = 0  # pallas→scan dispatches
        # sharded engines grow a trailing shard axis on every compile-key
        # family — ("decode_window", bucket, K, sampling, shards) — so a
        # mixed fleet's aggregated /stats can never conflate a sharded
        # program with a single-device one; single-device engines keep
        # the legacy arity (shards == 1 adds nothing to the key)
        self._shard_suffix: tuple = (
            (self.mesh_shards,) if self.mesh_shards > 1 else ())
        self.compile_counts: dict[tuple, int] = defaultdict(int)
        self._prefill_fns: dict[tuple, callable] = {}
        self._prefill_chunk_fns: dict[tuple, callable] = {}
        self._decode_fns: dict[tuple, callable] = {}
        self._decode_window_fns: dict[tuple, callable] = {}
        self._decode_window_pallas_fns: dict[tuple, callable] = {}
        # ---- speculative decoding (draft model) ----------------------
        # attach_draft installs a small distilled draft LM paired with
        # the DEFAULT model; spec_window then verifies K_draft proposed
        # tokens in one teacher-forced target pass. The draft's h/c live
        # in their own arrays indexed by the SAME slot numbers as the
        # state cache (never spilled through SessionTiers — draft state
        # is acceptance-only, rebuilt from zero on restore).
        self.draft: dict | None = None
        self._draft_h = None
        self._draft_c = None
        self._draft_prefill_fns: dict[tuple, callable] = {}
        self._spec_window_fns: dict[tuple, callable] = {}
        self._spec_window_pallas_fns: dict[tuple, callable] = {}
        self._rng = jax.random.PRNGKey(rng_seed)
        self._dummy_rng = jax.random.PRNGKey(0)
        self._lock = threading.RLock()
        # compile_counts gets its own tiny mutex: _lock is held across
        # entire device calls (dispatch serialization), and stats/health
        # readers must never block behind an in-flight — possibly
        # wedged — dispatch just to copy a counter dict
        self._counts_lock = threading.Lock()
        self._warming = False  # warmup decodes bypass the fault hook
        # per-phase compile counter for /metrics, bumped at trace time
        # alongside compile_counts (which keeps the full per-key detail
        # for /stats — bucket/window/sampling tuples are too wide for
        # Prometheus label cardinality)
        fam = self.metrics.counter(
            "serve_compiles_total", "XLA traces by program phase",
            labelnames=("phase",))
        self._m_compiles = {
            phase: fam.labels(phase=phase)
            for phase in ("prefill", "prefill_chunk", "decode",
                          "decode_window", "decode_window_pallas",
                          "spec_window", "spec_window_pallas",
                          "draft_prefill")
        }

    # ---- limits --------------------------------------------------------

    @property
    def max_prompt_len(self) -> int:
        return self.prefill_buckets[-1]

    @property
    def max_batch(self) -> int:
        return self.batch_buckets[-1]

    # ---- resident models ----------------------------------------------

    # The ``self._residents`` reads below are DELIBERATELY lock-free:
    # updates REPLACE the dict wholesale (add/remove/swap never mutate it
    # in place), so a reader sees either the whole old table or the whole
    # new one — and stats/health/routing probes can never block behind an
    # in-flight (possibly wedged) dispatch holding _lock.

    def _resolve_model(self, model: str | None):
        """``(model_id, params, fused, key_suffix)`` for one dispatch.
        ``None`` means the default model; the default's suffix is empty so
        its compile keys keep the legacy arity."""
        mid = self.model_id if model is None else model
        res = self._residents.get(mid)  # graftlint: disable=cross-thread-state
        if res is None:
            raise UnknownModelError(
                f"model {mid!r} is not resident on this engine "
                f"(resident: {sorted(self._residents)})")  # graftlint: disable=cross-thread-state
        suffix = () if mid == self.model_id else (mid,)
        return mid, res["params"], res["fused"], suffix

    def has_model(self, model_id: str | None) -> bool:
        return (model_id is None
                or model_id in self._residents)  # graftlint: disable=cross-thread-state

    @property
    def model_version(self) -> int | str:
        """The DEFAULT model's resident version (what a versionless
        request is served by) — rollout observability's convergence
        check."""
        return self._residents[self.model_id]["version"]  # graftlint: disable=cross-thread-state

    def resident_models(self) -> dict[str, int | str]:
        """{model_id: version} of every resident (default included),
        read via the wholesale-replace protocol above."""
        residents = self._residents  # graftlint: disable=cross-thread-state
        return {mid: res["version"] for mid, res in residents.items()}

    def add_model(self, model_id: str, params, *, version: int | str = 0):
        """Make a model resident (or replace one): mesh-place its params
        like __init__ did for the boot model, fuse once, and install
        under the dispatch lock — in-flight dispatches finish on the old
        pair, the next dispatch reads the new one. Same-shape params
        reuse the already-compiled programs (params are traced arguments,
        not constants), so a same-model weight swap costs ZERO compiles;
        a NEW model id gets its own compile-key namespace and must be
        warmed before taking traffic (rollout controller's warmup
        phase)."""
        model_id = str(model_id)
        if self.mesh is not None:
            from ..parallel.tensor_parallel import place_lm_params
            params = place_lm_params(params, self.mesh)
        fused = fuse_layers(params, self.cfg)
        with self._lock:
            residents = dict(self._residents)
            residents[model_id] = {
                "params": params, "fused": fused, "version": version}
            # REPLACE the table (resident_models reads it lock-free)
            self._residents = residents
            if model_id == self.model_id:
                self.params = params
                self.fused_layers = fused

    def swap_model(self, params, *, model_id: str | None = None,
                   version: int | str | None = None) -> None:
        """Replace an ALREADY-resident model's params (the rolling-reload
        swap step). Unlike :meth:`add_model` this refuses unknown ids —
        a typoed rollout must fail loudly, not silently grow a second
        resident nobody routes to."""
        mid = self.model_id if model_id is None else str(model_id)
        with self._lock:
            if mid not in self._residents:
                raise UnknownModelError(
                    f"cannot swap model {mid!r}: not resident "
                    f"(resident: {sorted(self._residents)})")
            if version is None:
                version = self._residents[mid]["version"]
            self.add_model(mid, params, version=version)

    def remove_model(self, model_id: str) -> None:
        """Evict a non-default resident and its compiled programs. The
        caller (rollout controller / server) is responsible for having
        drained the model's sessions first — the engine only owns
        params and programs."""
        with self._lock:
            if model_id == self.model_id:
                raise ValueError(
                    f"cannot remove the default model {model_id!r}")
            if model_id not in self._residents:
                raise UnknownModelError(
                    f"model {model_id!r} is not resident")
            residents = dict(self._residents)
            residents.pop(model_id)
            self._residents = residents
            for cache in (self._prefill_fns, self._prefill_chunk_fns,
                          self._decode_fns, self._decode_window_fns,
                          self._decode_window_pallas_fns):
                for key in [k for k in cache if k and k[-1] == model_id]:
                    cache.pop(key)

    def resize_slots(self, num_slots: int) -> None:
        """Reallocate the state cache at a new device-slot count — the
        rollout controller's drain-and-rejoin resize move (the PR 14
        autotuner residual: slot count is no longer a frozen boot
        shape). Only legal with no resident sessions; prefix entries are
        dropped first (they are derived state, re-insertable)."""
        prefix = self.prefix  # outside _lock: stats() reads it lock-free
        if prefix is not None:
            prefix.clear()  # takes the prefix cache's own lock
        with self._lock:
            self.cache.resize(num_slots)
            if self.draft is not None:
                # draft state is slot-indexed alongside the cache: resize
                # reallocates it to the new slot count (zeros — legal,
                # resize requires no resident sessions)
                self._alloc_draft_state_locked()

    # ---- speculative decoding: draft model ----------------------------

    # ``self.draft`` follows the ``_residents`` wholesale-replace
    # protocol above: attach_draft REPLACES the dict under _lock (never
    # mutates it in place), so the lock-free probes below see either no
    # draft or a whole one — and never block behind an in-flight
    # (possibly wedged) dispatch holding _lock. The draft h/c arrays are
    # NOT covered by this: they are swapped on every spec dispatch, so
    # every ``_draft_h``/``_draft_c`` touch stays under _lock.

    @property
    def has_draft(self) -> bool:
        return self.draft is not None  # graftlint: disable=cross-thread-state

    def attach_draft(self, draft_params, draft_cfg: LMConfig, *,
                     version: int | str = 0) -> None:
        """Install the distilled draft LM paired with the DEFAULT model.
        The draft proposes K_draft greedy tokens per :meth:`spec_window`
        dispatch; the target verifies them all in one teacher-forced
        pass, so greedy output stays token-identical by construction no
        matter how bad the draft is — draft quality only moves the
        acceptance rate. Single-device engines only: the draft cache and
        the fused spec kernel are unsharded programs."""
        if self.mesh_shards > 1:
            raise ValueError(
                "speculative decoding is not supported on a mesh "
                f"({self.mesh_shards}-shard) engine — the draft cache and "
                "the spec verify programs are single-device")
        if draft_cfg.vocab_size != self.cfg.vocab_size:
            raise ValueError(
                f"draft vocab {draft_cfg.vocab_size} != target vocab "
                f"{self.cfg.vocab_size} — proposals must share the "
                "token space they are verified in")
        if draft_cfg.remat_chunk is not None:
            draft_cfg = dataclasses.replace(draft_cfg, remat_chunk=None)
        with self._lock:
            self.draft = {
                "params": draft_params,
                "fused": fuse_layers(draft_params, draft_cfg),
                "cfg": draft_cfg,
                "version": version,
            }
            self._alloc_draft_state_locked()

    def _alloc_draft_state_locked(self) -> None:
        """(Re)allocate the draft h/c arrays: ``[L_draft, num_slots + 1,
        H_draft]`` f32, same slot indexing (scratch row included) as the
        state cache. Zero state is always SAFE here — the draft never
        affects emitted tokens, only how many of its proposals the
        target accepts."""
        dcfg = self.draft["cfg"]
        total = int(self.cache.h.shape[1])
        zeros = jnp.zeros((dcfg.num_layers, total, dcfg.hidden_size),
                          jnp.float32)
        if self.device is not None:
            zeros = jax.device_put(zeros, self.device)
        self._draft_h = zeros
        self._draft_c = zeros

    # ---- compiled programs --------------------------------------------

    def _admit_sampling(self, sampling: SamplingParams) -> None:
        key = sampling.key()
        if key in self._sampling_keys:
            return
        if len(self._sampling_keys) >= self.max_sampling_configs:
            raise ValueError(
                f"engine already compiled {self.max_sampling_configs} "
                "distinct sampling configs; rejecting a new one (raise "
                "max_sampling_configs if this workload is legitimate)"
            )
        self._sampling_keys.add(key)

    def _next_rng(self, sampling: SamplingParams):
        if sampling.greedy:
            return self._dummy_rng  # greedy ignores the key: skip the split
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _consume_prompt(self, h_cache, c_cache, params, src_slots, dst_slots,
                        fresh, prompts, lengths, len_b, cfg=None):
        """Shared traced body of BOTH prefill programs: gather carries
        FROM src (a prefix-cache slot for resumed prefill, the session's
        own slot otherwise), consume the masked prompt tokens, and scatter
        the advanced state TO dst. The prefix slot is read-only in the
        program, so a refcounted prefix entry is never aliased by a
        session's writes. Returns the updated cache arrays plus the
        per-position backbone outputs ``ys`` — the final program's head
        reads them; the chunk program drops them (XLA dead-code-eliminates
        the head-side compute). ``cfg`` overrides the target config — the
        draft-prefill program runs this same body over the DRAFT model's
        arrays."""
        if cfg is None:
            cfg = self.cfg
        h_in = h_cache[:, src_slots, :]  # [L, B, H]
        c_in = c_cache[:, src_slots, :]
        # fresh rows start from zero state — no device-side slot
        # zeroing on acquire, the zero rides along in this program
        live = ~fresh[None, :, None]
        h_in = jnp.where(live, h_in, 0.0)
        c_in = jnp.where(live, c_in, 0.0)
        carries = [(h_in[l], c_in[l]) for l in range(cfg.num_layers)]
        mask = jnp.arange(len_b)[None, :] < lengths[:, None]  # [B, T]
        finals, ys = lm_backbone(params, prompts, cfg, carries=carries,
                                 mask=mask)
        new_h = jnp.stack([f[0] for f in finals])  # [L, B, H]
        new_c = jnp.stack([f[1] for f in finals])
        h_cache = h_cache.at[:, dst_slots, :].set(new_h.astype(jnp.float32))
        c_cache = c_cache.at[:, dst_slots, :].set(new_c.astype(jnp.float32))
        return h_cache, c_cache, ys

    def _get_prefill_fn(self, batch_b: int, len_b: int,
                        sampling: SamplingParams, mkey: tuple = ()):
        key = (batch_b, len_b, sampling.key(), *mkey)
        fn = self._prefill_fns.get(key)
        if fn is not None:
            return fn
        cfg = self.cfg
        count_key = ("prefill", batch_b, len_b, sampling.key(),
                     *self._shard_suffix, *mkey)

        def prefill_fn(params, h_cache, c_cache, src_slots, dst_slots,
                       fresh, prompts, lengths, rng):
            # trace-time side effect: one bump per XLA compile of this shape
            with self._counts_lock:
                self.compile_counts[count_key] += 1
            self._m_compiles["prefill"].inc()
            h_cache, c_cache, ys = self._consume_prompt(
                h_cache, c_cache, params, src_slots, dst_slots, fresh,
                prompts, lengths, len_b)
            # logits at each row's true last position (same head math, same
            # ldtype as lm_forward — near-tied logits must argmax alike)
            last = jnp.take_along_axis(
                ys, (lengths - 1)[:, None, None], axis=1
            )[:, 0, :]  # [B, H]
            kernel, bias = _head_kernel(params, cfg)
            logits = (
                jnp.dot(last.astype(kernel.dtype), kernel,
                        preferred_element_type=cfg.ldtype)
                + bias.astype(cfg.ldtype)
            )
            token = sample_logits(
                rng, logits, temperature=sampling.temperature,
                top_k=sampling.top_k, top_p=sampling.top_p,
                greedy=sampling.greedy,
            )
            return h_cache, c_cache, token

        fn = jax.jit(prefill_fn)
        self._prefill_fns[key] = fn
        return fn

    def _get_prefill_chunk_fn(self, batch_b: int, len_b: int,
                              mkey: tuple = ()):
        """An intermediate prefill chunk: consume up to ``len_b`` prompt
        tokens from a gathered state and scatter the advanced state — no
        head, no sampling (the final chunk's program does those), so one
        compile per ("prefill_chunk", batch-bucket, length-bucket) covers
        EVERY sampling config."""
        key = (batch_b, len_b, *mkey)
        fn = self._prefill_chunk_fns.get(key)
        if fn is not None:
            return fn
        count_key = ("prefill_chunk", batch_b, len_b, *self._shard_suffix,
                     *mkey)

        def chunk_fn(params, h_cache, c_cache, src_slots, dst_slots, fresh,
                     prompts, lengths):
            with self._counts_lock:
                self.compile_counts[count_key] += 1
            self._m_compiles["prefill_chunk"].inc()
            h_cache, c_cache, _ = self._consume_prompt(
                h_cache, c_cache, params, src_slots, dst_slots, fresh,
                prompts, lengths, len_b)
            return h_cache, c_cache

        fn = jax.jit(chunk_fn)
        self._prefill_chunk_fns[key] = fn
        return fn

    def _get_decode_fn(self, batch_b: int, sampling: SamplingParams,
                       mkey: tuple = ()):
        key = (batch_b, sampling.key(), *mkey)
        fn = self._decode_fns.get(key)
        if fn is not None:
            return fn
        cfg = self.cfg
        count_key = ("decode", batch_b, sampling.key(), *self._shard_suffix,
                     *mkey)

        def decode_fn(params, fused, h_cache, c_cache, slots, tokens, rng):
            with self._counts_lock:
                self.compile_counts[count_key] += 1
            self._m_compiles["decode"].inc()
            h_in = h_cache[:, slots, :]
            c_in = c_cache[:, slots, :]
            carries = [(h_in[l], c_in[l]) for l in range(cfg.num_layers)]
            logits, new_carries = decode_one(params, fused, cfg, carries,
                                             tokens)
            nxt = sample_logits(
                rng, logits, temperature=sampling.temperature,
                top_k=sampling.top_k, top_p=sampling.top_p,
                greedy=sampling.greedy,
            )
            new_h = jnp.stack([nc[0] for nc in new_carries])
            new_c = jnp.stack([nc[1] for nc in new_carries])
            h_cache = h_cache.at[:, slots, :].set(new_h.astype(jnp.float32))
            c_cache = c_cache.at[:, slots, :].set(new_c.astype(jnp.float32))
            return h_cache, c_cache, nxt

        fn = jax.jit(decode_fn)
        self._decode_fns[key] = fn
        return fn

    def _get_decode_window_fn(self, batch_b: int, window: int,
                              sampling: SamplingParams, mkey: tuple = ()):
        key = (batch_b, window, sampling.key(), *mkey)
        fn = self._decode_window_fns.get(key)
        if fn is not None:
            return fn
        cfg = self.cfg
        count_key = ("decode_window", batch_b, window, sampling.key(),
                     *self._shard_suffix, *mkey)

        def window_fn(params, fused, h_cache, c_cache, slots, tokens,
                      alive, remaining, eos_ids, rng):
            with self._counts_lock:
                self.compile_counts[count_key] += 1
            self._m_compiles["decode_window"].inc()
            h_in = h_cache[:, slots, :]
            c_in = c_cache[:, slots, :]
            carries = [(h_in[l], c_in[l]) for l in range(cfg.num_layers)]

            def step(carry, rng_step):
                carries, token, alive, remaining = carry
                logits, new_carries = decode_one(params, fused, cfg,
                                                 carries, token)
                nxt = sample_logits(
                    rng_step, logits, temperature=sampling.temperature,
                    top_k=sampling.top_k, top_p=sampling.top_p,
                    greedy=sampling.greedy,
                )
                # rows alive at step entry emit this step's token and
                # commit its carry update (exactly the K=1 semantics:
                # the EOS-emitting step still writes its carries, the
                # steps after it never run)
                emit = alive
                out_tok = jnp.where(emit, nxt, PAD_TOKEN).astype(jnp.int32)
                new_remaining = remaining - emit.astype(remaining.dtype)
                hit_eos = emit & (eos_ids >= 0) & (nxt == eos_ids)
                new_alive = emit & ~hit_eos & (new_remaining > 0)
                frozen = [
                    (jnp.where(emit[:, None], hn, ho),
                     jnp.where(emit[:, None], cn, co))
                    for (ho, co), (hn, cn) in zip(carries, new_carries)
                ]
                # dead rows feed token 0 onward — their carries are frozen
                # and their outputs PAD, so the value never matters, but a
                # PAD_TOKEN (-1) embedding lookup must not happen
                next_tok = jnp.where(new_alive, nxt, 0).astype(jnp.int32)
                return (frozen, next_tok, new_alive, new_remaining), out_tok

            rngs = jax.random.split(rng, window)
            (carries, next_tok, alive_out, rem_out), toks = lax.scan(
                step, (carries, tokens, alive, remaining), rngs
            )
            new_h = jnp.stack([nc[0] for nc in carries])
            new_c = jnp.stack([nc[1] for nc in carries])
            h_cache = h_cache.at[:, slots, :].set(new_h.astype(jnp.float32))
            c_cache = c_cache.at[:, slots, :].set(new_c.astype(jnp.float32))
            toks = jnp.moveaxis(toks, 0, 1)  # [K, B] → [B, K]
            return h_cache, c_cache, toks, next_tok, alive_out, rem_out

        fn = jax.jit(window_fn)
        self._decode_window_fns[key] = fn
        return fn

    def _get_decode_window_pallas_fn(self, batch_b: int, window: int,
                                     sampling: SamplingParams,
                                     mkey: tuple = ()):
        """The fused Pallas decode window (ops/pallas_decode.py): same
        host-facing signature and handle shapes as the scan window fn,
        so `decode_window`/`decode_window_next` can dispatch either per
        compile key and the batcher's pipeline never knows which kernel
        produced a `DecodeWindow`. Compile-key family
        ``("decode_window_pallas", bucket, K, sampling)`` — covered by
        `warmup` through the same `decode_window` calls."""
        key = (batch_b, window, sampling.key(), *mkey)
        fn = self._decode_window_pallas_fns.get(key)
        if fn is not None:
            return fn
        cfg = self.cfg
        count_key = ("decode_window_pallas", batch_b, window,
                     sampling.key(), *self._shard_suffix, *mkey)
        interpret = self._pallas_interpret

        def window_fn(params, fused, h_cache, c_cache, slots, tokens,
                      alive, remaining, eos_ids, rng):
            with self._counts_lock:
                self.compile_counts[count_key] += 1
            self._m_compiles["decode_window_pallas"].inc()
            h_in = h_cache[:, slots, :]
            c_in = c_cache[:, slots, :]
            noise = None
            if not sampling.greedy:
                # the scan window's EXACT rng discipline: one split per
                # step, categorical == Gumbel-argmax — drawing the
                # noise here (traced, outside the kernel) keeps the
                # sampled tokens bit-identical to sample_logits
                rngs = jax.random.split(rng, window)
                noise = jnp.stack([
                    jax.random.gumbel(r, (batch_b, cfg.vocab_size),
                                      jnp.float32)
                    for r in rngs
                ])
            h_out, c_out, toks, next_tok, alive_out, rem_out = (
                pallas_decode.decode_window_call(
                    params, fused, cfg, h_in, c_in, tokens, alive,
                    remaining, eos_ids, noise, window=window,
                    temperature=sampling.temperature,
                    greedy=sampling.greedy, interpret=interpret))
            h_cache = h_cache.at[:, slots, :].set(h_out)
            c_cache = c_cache.at[:, slots, :].set(c_out)
            toks = jnp.moveaxis(toks, 0, 1)  # [K, B] → [B, K]
            return h_cache, c_cache, toks, next_tok, alive_out, rem_out

        fn = jax.jit(window_fn)
        self._decode_window_pallas_fns[key] = fn
        return fn

    def _get_draft_prefill_fn(self, batch_b: int, len_b: int):
        """The draft model's prompt-consumption program: same masked
        backbone body as ``prefill_chunk`` but over the DRAFT params and
        the draft h/c arrays — no head, no sampling (the draft only
        proposes during decode). One compile per ``("draft_prefill",
        batch-bucket, length-bucket)``; the batcher mirrors every target
        prefill dispatch (chunk and final alike) with one of these, so
        the length lattice is exactly the target's."""
        key = (batch_b, len_b)
        fn = self._draft_prefill_fns.get(key)
        if fn is not None:
            return fn
        with self._lock:  # reentrant: the dispatch path already holds it
            dcfg = self.draft["cfg"]
        count_key = ("draft_prefill", batch_b, len_b)

        def draft_fn(dparams, dh, dc, src_slots, dst_slots, fresh,
                     prompts, lengths):
            with self._counts_lock:
                self.compile_counts[count_key] += 1
            self._m_compiles["draft_prefill"].inc()
            dh, dc, _ = self._consume_prompt(
                dh, dc, dparams, src_slots, dst_slots, fresh,
                prompts, lengths, len_b, cfg=dcfg)
            return dh, dc

        fn = jax.jit(draft_fn)
        self._draft_prefill_fns[key] = fn
        return fn

    def _get_spec_window_fn(self, batch_b: int, k_draft: int):
        """The speculative verify window (scan form), greedy-only. ONE
        program does both phases:

        1. **Propose** — the draft decodes ``k_draft`` greedy tokens from
           its slot state (a plain K-step scan; its propose-time carries
           are DISCARDED).
        2. **Verify** — ``W = k_draft + 1`` joint steps. Step ``i`` feeds
           the target the (i-1)-th proposal (step 0 feeds the last
           committed token) and takes the target's argmax ``t`` as the
           emitted token; the row keeps emitting only while the NEXT
           proposal agrees with ``t`` (sentinel -2 at the last step never
           agrees). The step that detects the disagreement still emits
           its own ``t`` — that is the correction token — so every spec
           step with a live row emits >= 1 token and the emitted
           sequence is EXACTLY the plain greedy sequence (the target
           carries latch on the same ``emit`` mask as the plain window,
           so after m emissions the committed state consumed exactly the
           plain window's inputs). The draft runs alongside
           teacher-forced on the same inputs with the same latch, which
           IS its state commit — rejected proposals beyond the accepted
           prefix roll back for free because neither model's carry ever
           latched past the last emission (the O(1)-rollback property).

        A draft disagreement ends the WINDOW, not the session: the
        returned ``alive`` handle is the session latch (EOS/budget only),
        so the batcher's liveness authority keeps its plain-window
        meaning."""
        key = (batch_b, k_draft)
        fn = self._spec_window_fns.get(key)
        if fn is not None:
            return fn
        cfg = self.cfg
        with self._lock:  # reentrant: the dispatch path already holds it
            dcfg = self.draft["cfg"]
        count_key = ("spec_window", batch_b, k_draft)

        def spec_fn(params, fused, dparams, dfused, h_cache, c_cache,
                    dh_cache, dc_cache, slots, tokens, alive, remaining,
                    eos_ids):
            with self._counts_lock:
                self.compile_counts[count_key] += 1
            self._m_compiles["spec_window"].inc()
            h_in = h_cache[:, slots, :]
            c_in = c_cache[:, slots, :]
            carries = [(h_in[l], c_in[l]) for l in range(cfg.num_layers)]
            dh_in = dh_cache[:, slots, :]
            dc_in = dc_cache[:, slots, :]
            dcarries = [(dh_in[l], dc_in[l])
                        for l in range(dcfg.num_layers)]

            def propose(carry, _):
                dcar, tok = carry
                logits, ndc = decode_one(dparams, dfused, dcfg, dcar, tok)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return (ndc, nxt), nxt

            (_, _), props = lax.scan(propose, (dcarries, tokens), None,
                                     length=k_draft)  # [K, B]
            # verify inputs: step 0 re-feeds the last committed token,
            # steps 1..K feed the proposals; the "next proposal" stream
            # ends in a sentinel no argmax can equal, so the last step
            # always closes the window
            inputs = jnp.concatenate([tokens[None, :], props], axis=0)
            next_prop = jnp.concatenate(
                [props, jnp.full((1, batch_b), -2, jnp.int32)], axis=0)

            def verify(carry, xs):
                (tcar, dcar, alive_w, sess_alive, rem, final_tok) = carry
                inp, nprop = xs
                logits, ntc = decode_one(params, fused, cfg, tcar, inp)
                _, ndc = decode_one(dparams, dfused, dcfg, dcar, inp)
                t = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                emit = alive_w
                out_tok = jnp.where(emit, t, PAD_TOKEN).astype(jnp.int32)
                new_rem = rem - emit.astype(rem.dtype)
                hit_eos = emit & (eos_ids >= 0) & (t == eos_ids)
                live_on = ~hit_eos & (new_rem > 0)
                # the session latch (plain-window rule) and the window
                # latch (additionally needs the next proposal to agree)
                # MUST be separate: a mismatch stops emission, not the
                # conversation
                new_sess = jnp.where(emit, live_on, sess_alive)
                new_alive_w = emit & live_on & (nprop == t)
                t_frozen = [
                    (jnp.where(emit[:, None], hn, ho),
                     jnp.where(emit[:, None], cn, co))
                    for (ho, co), (hn, cn) in zip(tcar, ntc)
                ]
                d_frozen = [
                    (jnp.where(emit[:, None], hn, ho),
                     jnp.where(emit[:, None], cn, co))
                    for (ho, co), (hn, cn) in zip(dcar, ndc)
                ]
                new_final = jnp.where(emit, t, final_tok).astype(jnp.int32)
                return (t_frozen, d_frozen, new_alive_w, new_sess,
                        new_rem, new_final), out_tok

            init = (carries, dcarries, alive, alive, remaining, tokens)
            (tcar, dcar, _aw, sess_alive, rem_out, final_tok), toks = (
                lax.scan(verify, init, (inputs, next_prop)))
            # next window's input is the LAST EMITTED token (dead rows
            # feed 0 — value never used, but PAD must not hit the
            # embedding)
            next_tok = jnp.where(sess_alive, final_tok, 0).astype(jnp.int32)
            new_h = jnp.stack([nc[0] for nc in tcar])
            new_c = jnp.stack([nc[1] for nc in tcar])
            h_cache = h_cache.at[:, slots, :].set(new_h.astype(jnp.float32))
            c_cache = c_cache.at[:, slots, :].set(new_c.astype(jnp.float32))
            dnew_h = jnp.stack([nc[0] for nc in dcar])
            dnew_c = jnp.stack([nc[1] for nc in dcar])
            dh_cache = dh_cache.at[:, slots, :].set(
                dnew_h.astype(jnp.float32))
            dc_cache = dc_cache.at[:, slots, :].set(
                dnew_c.astype(jnp.float32))
            toks = jnp.moveaxis(toks, 0, 1)  # [W, B] → [B, W]
            return (h_cache, c_cache, dh_cache, dc_cache, toks, next_tok,
                    sess_alive, rem_out)

        fn = jax.jit(spec_fn)
        self._spec_window_fns[key] = fn
        return fn

    def _get_spec_window_pallas_fn(self, batch_b: int, k_draft: int):
        """The fused Pallas spec window (ops/pallas_decode.py): identical
        host-facing contract to the scan spec fn — same handles, same
        latch algebra — with both models' weights and carries VMEM-
        resident for the whole propose+verify pass. Compile-key family
        ``("spec_window_pallas", bucket, K_draft)``."""
        key = (batch_b, k_draft)
        fn = self._spec_window_pallas_fns.get(key)
        if fn is not None:
            return fn
        cfg = self.cfg
        with self._lock:  # reentrant: the dispatch path already holds it
            dcfg = self.draft["cfg"]
        count_key = ("spec_window_pallas", batch_b, k_draft)
        interpret = self._pallas_interpret

        def spec_fn(params, fused, dparams, dfused, h_cache, c_cache,
                    dh_cache, dc_cache, slots, tokens, alive, remaining,
                    eos_ids):
            with self._counts_lock:
                self.compile_counts[count_key] += 1
            self._m_compiles["spec_window_pallas"].inc()
            h_in = h_cache[:, slots, :]
            c_in = c_cache[:, slots, :]
            dh_in = dh_cache[:, slots, :]
            dc_in = dc_cache[:, slots, :]
            (h_out, c_out, dh_out, dc_out, toks, next_tok, sess_alive,
             rem_out) = pallas_decode.spec_window_call(
                params, fused, cfg, dparams, dfused, dcfg,
                h_in, c_in, dh_in, dc_in, tokens, alive, remaining,
                eos_ids, k_draft=k_draft, interpret=interpret)
            h_cache = h_cache.at[:, slots, :].set(h_out)
            c_cache = c_cache.at[:, slots, :].set(c_out)
            dh_cache = dh_cache.at[:, slots, :].set(dh_out)
            dc_cache = dc_cache.at[:, slots, :].set(dc_out)
            toks = jnp.moveaxis(toks, 0, 1)  # [W, B] → [B, W]
            return (h_cache, c_cache, dh_cache, dc_cache, toks, next_tok,
                    sess_alive, rem_out)

        fn = jax.jit(spec_fn)
        self._spec_window_pallas_fns[key] = fn
        return fn

    def _spec_pallas_ok(self, batch_b: int, k_draft: int) -> bool:
        cfg = self.cfg
        with self._lock:  # reentrant: the dispatch path already holds it
            dcfg = self.draft["cfg"]
        return pallas_decode.spec_plan_fits(
            batch_b, k_draft, cfg.num_layers, cfg.hidden_size, cfg.embed,
            cfg.vocab_size, dcfg.num_layers, dcfg.hidden_size, dcfg.embed)

    def _spec_window_fn_for(self, batch_b: int, k_draft: int):
        """Spec-window program pick, same policy as ``_window_fn_for``:
        fused Pallas when selected AND the joint (target + draft) VMEM
        plan fits, scan otherwise — fallbacks counted in the same
        ``decode_window_scan_fallbacks`` (a silently-switched kernel
        would fake the measured speedup)."""
        if self.decode_kernel == "pallas":
            if self._spec_pallas_ok(batch_b, k_draft):
                return self._get_spec_window_pallas_fn(batch_b, k_draft)
            with self._counts_lock:
                self.decode_window_scan_fallbacks += 1
        return self._get_spec_window_fn(batch_b, k_draft)

    def _pallas_window_ok(self, batch_b: int, window: int,
                          sampling: SamplingParams) -> bool:
        cfg = self.cfg
        if self.mesh_shards > 1:
            # the fused kernel is a single-device program: on a sharded
            # engine every pallas pick falls back to the scan window —
            # counted per dispatch (in _window_fn_for), announced once
            # at boot (__init__'s log line)
            return False
        return (pallas_decode.sampling_supported(
                    sampling.temperature, sampling.top_k, sampling.top_p,
                    sampling.greedy)
                and pallas_decode.plan_fits(
                    batch_b, window, cfg.num_layers, cfg.hidden_size,
                    cfg.embed, cfg.vocab_size,
                    sampled=not sampling.greedy))

    def _window_fn_for(self, batch_b: int, window: int,
                       sampling: SamplingParams, mkey: tuple = ()):
        """Pick the window program for this compile key: the fused
        Pallas kernel when selected AND it covers this (shape, sampling)
        — otherwise the scan window, with the fallback counted (a
        silently-switched kernel would fake the measured speedup)."""
        if self.decode_kernel == "pallas":
            if self._pallas_window_ok(batch_b, window, sampling):
                return self._get_decode_window_pallas_fn(
                    batch_b, window, sampling, mkey)
            with self._counts_lock:
                self.decode_window_scan_fallbacks += 1
        return self._get_decode_window_fn(batch_b, window, sampling, mkey)

    # ---- host-facing steps --------------------------------------------

    @staticmethod
    def _norm_prefill_items(items):
        """Normalise prefill items to ``(dst_slot, src_slot, fresh,
        prompt)`` quads. The legacy triple ``(slot, fresh, prompt)`` means
        src == dst (prefill in place); a quad names a separate gather
        source — a prefix-cache slot for resumed prefill."""
        out = []
        for it in items:
            if len(it) == 3:
                slot, fresh, prompt = it
                out.append((slot, slot, fresh, prompt))
            else:
                out.append(tuple(it))
        return out

    def _pack_prefill(self, items):
        """Pad normalised items to (batch, length) buckets; returns the
        padded host arrays + (n, batch_b, len_b). Final and intermediate
        chunk programs share ONE length-bucket lattice (prefill_buckets) —
        Batcher.warmup's replay assumes this."""
        n = len(items)
        lengths = [int(np.asarray(p).size) for _, _, _, p in items]
        for t in lengths:
            if t < 1:
                raise ValueError("empty prompt")
        batch_b = _bucket_for(n, self.batch_buckets, "prefill batch")
        len_b = _bucket_for(max(lengths), self.prefill_buckets,
                            "prompt length")
        scratch = self.cache.scratch_slot
        src = np.full((batch_b,), scratch, np.int32)
        dst = np.full((batch_b,), scratch, np.int32)
        fresh = np.ones((batch_b,), bool)
        prompts = np.zeros((batch_b, len_b), np.int32)
        lens = np.ones((batch_b,), np.int32)
        for i, (d, s, is_fresh, prompt) in enumerate(items):
            p = np.asarray(prompt, np.int32).reshape(-1)
            dst[i] = d
            src[i] = s
            fresh[i] = bool(is_fresh)
            prompts[i, : p.size] = p
            lens[i] = p.size
        return src, dst, fresh, prompts, lens, n, batch_b, len_b

    def prefill(self, items, sampling: SamplingParams = GREEDY, *,
                model: str | None = None) -> np.ndarray:
        """Run one bucketed prefill batch (the FINAL — or only — chunk of
        each row's prompt: ends with the head + sampler).

        ``items``: ``(slot, fresh, prompt)`` triples or ``(dst_slot,
        src_slot, fresh, prompt)`` quads (see ``_norm_prefill_items``) with
        ``prompt`` a 1-D int array (1 <= len <= max_prompt_len). Rows are
        padded up to the batch bucket (dead rows target the scratch slot)
        and prompts are right-padded to the length bucket (carry-freeze
        mask). Returns the first sampled token per item, ``[len(items)]``
        int32.
        """
        if len(items) == 0:
            return np.zeros((0,), np.int32)
        self._admit_sampling(sampling)
        src, dst, fresh, prompts, lens, n, batch_b, len_b = (
            self._pack_prefill(self._norm_prefill_items(items)))
        with self._lock:
            _, params, _, mkey = self._resolve_model(model)
            fn = self._get_prefill_fn(batch_b, len_b, sampling, mkey)
            rng = self._next_rng(sampling)
            h, c, tok = fn(params, self.cache.h, self.cache.c,
                           jnp.asarray(src), jnp.asarray(dst),
                           jnp.asarray(fresh), jnp.asarray(prompts),
                           jnp.asarray(lens), rng)
            self.cache.swap(h, c)
        return np.asarray(tok)[:n]

    def prefill_chunk(self, items, *, model: str | None = None) -> None:
        """Dispatch one INTERMEDIATE prefill chunk batch: advance each
        row's state over its chunk tokens and scatter it — no head, no
        sampling, nothing returned (async dispatch; the final chunk via
        :meth:`prefill` emits the first token). ``items`` as in
        :meth:`prefill`."""
        if len(items) == 0:
            return
        src, dst, fresh, prompts, lens, _, batch_b, len_b = (
            self._pack_prefill(self._norm_prefill_items(items)))
        with self._lock:
            _, params, _, mkey = self._resolve_model(model)
            fn = self._get_prefill_chunk_fn(batch_b, len_b, mkey)
            h, c = fn(params, self.cache.h, self.cache.c,
                      jnp.asarray(src), jnp.asarray(dst), jnp.asarray(fresh),
                      jnp.asarray(prompts), jnp.asarray(lens))
            self.cache.swap(h, c)

    def draft_prefill(self, items) -> None:
        """Advance the DRAFT model's slot state over prompt fragments —
        the batcher mirrors every target prefill dispatch (chunk and
        final) with one of these so the draft's h/c track the session's
        consumed context. ``items`` are ``(slot, fresh, fragment)``
        triples; ``fresh`` starts the draft from zero (a session's first
        fragment — including prefix-resumed rows, which the draft cannot
        resume: it has no prefix entries, so it rebuilds from zero at
        the offset, losslessly trading acceptance rate). Async dispatch,
        nothing returned."""
        if self.draft is None:  # graftlint: disable=cross-thread-state
            raise ValueError("draft_prefill needs an attached draft "
                             "(attach_draft)")
        if len(items) == 0:
            return
        src, dst, fresh, prompts, lens, _, batch_b, len_b = (
            self._pack_prefill(self._norm_prefill_items(items)))
        with self._lock:
            fn = self._get_draft_prefill_fn(batch_b, len_b)
            dh, dc = fn(self.draft["params"], self._draft_h, self._draft_c,
                        jnp.asarray(src), jnp.asarray(dst),
                        jnp.asarray(fresh), jnp.asarray(prompts),
                        jnp.asarray(lens))
            self._draft_h, self._draft_c = dh, dc

    def decode(self, slots, tokens, sampling: SamplingParams = GREEDY, *,
               model: str | None = None) -> np.ndarray:
        """Advance each session one token: gather carries by ``slots`` [B],
        feed ``tokens`` [B], return the next token per row ``[B]`` int32.
        Pads to the batch bucket (dead rows at the scratch slot)."""
        n = len(slots)
        if n == 0:
            return np.zeros((0,), np.int32)
        # chaos drills: an armed serve_error fault raises InjectedFault out
        # of the Nth decode call — the batcher must fail ONLY that chunk's
        # requests and keep serving (tests/test_serve_health.py). Warmup's
        # dummy decodes neither count nor fire: the drill targets traffic,
        # and an InjectedFault inside warmup() would kill the whole server
        # at startup instead of one mid-traffic chunk.
        if not self._warming:
            _faults.serve_decode_hook()
        self._admit_sampling(sampling)
        batch_b = _bucket_for(n, self.batch_buckets, "decode batch")
        slots_p = np.full((batch_b,), self.cache.scratch_slot, np.int32)
        slots_p[:n] = np.asarray(slots, np.int32)
        tokens_p = np.zeros((batch_b,), np.int32)
        tokens_p[:n] = np.asarray(tokens, np.int32)

        with self._lock:
            _, params, fused, mkey = self._resolve_model(model)
            fn = self._get_decode_fn(batch_b, sampling, mkey)
            rng = self._next_rng(sampling)
            h, c, tok = fn(params, fused, self.cache.h,
                           self.cache.c, jnp.asarray(slots_p),
                           jnp.asarray(tokens_p), rng)
            self.cache.swap(h, c)
        return np.asarray(tok)[:n]

    def decode_window(self, slots, tokens, remaining, eos_ids=None,
                      sampling: SamplingParams = GREEDY, *,
                      window: int, model: str | None = None) -> DecodeWindow:
        """Dispatch one K-token decode window and return device HANDLES
        (no sync — pair with :meth:`fetch_window`).

        ``slots``/``tokens``/``remaining`` are per-row [B] host values
        (current slot, last emitted token, tokens-of-budget left);
        ``eos_ids`` [B] uses -1 for "no eos". Rows are padded to the batch
        bucket (dead rows: scratch slot, alive=False → all-PAD output,
        frozen carries). Rows latch dead on device when they emit their
        eos or exhaust ``remaining``, so ``window`` may exceed a row's
        budget safely."""
        n = len(slots)
        if n == 0 or window < 1:
            raise ValueError(f"decode_window needs rows and window >= 1, "
                             f"got {n} rows, window {window}")
        if not self._warming:
            _faults.serve_decode_hook()
        self._admit_sampling(sampling)
        batch_b = _bucket_for(n, self.batch_buckets, "decode batch")
        slots_p = np.full((batch_b,), self.cache.scratch_slot, np.int32)
        slots_p[:n] = np.asarray(slots, np.int32)
        tokens_p = np.zeros((batch_b,), np.int32)
        tokens_p[:n] = np.asarray(tokens, np.int32)
        rem_p = np.zeros((batch_b,), np.int32)
        rem_p[:n] = np.asarray(remaining, np.int32)
        eos_p = np.full((batch_b,), -1, np.int32)
        if eos_ids is not None:
            eos_p[:n] = np.asarray(eos_ids, np.int32)
        alive_p = np.zeros((batch_b,), bool)
        alive_p[:n] = rem_p[:n] > 0

        with self._lock:
            mid, params, fused, mkey = self._resolve_model(model)
            fn = self._window_fn_for(batch_b, window, sampling, mkey)
            rng = self._next_rng(sampling)
            slots_d = jnp.asarray(slots_p)
            eos_d = jnp.asarray(eos_p)
            h, c, toks, next_tok, alive, rem = fn(
                params, fused, self.cache.h, self.cache.c,
                slots_d, jnp.asarray(tokens_p), jnp.asarray(alive_p),
                jnp.asarray(rem_p), eos_d, rng,
            )
            self.cache.swap(h, c)
        return DecodeWindow(
            tokens=toks, next_tokens=next_tok, alive=alive, remaining=rem,
            slots=slots_d, eos_ids=eos_d, batch_b=batch_b, window=window,
            n=n, sampling=sampling, t_dispatch=time.perf_counter(),
            model=mid,
        )

    def decode_window_next(self, prev: DecodeWindow, *,
                           window: int | None = None) -> DecodeWindow:
        """Dispatch the follow-up window for the SAME packed rows entirely
        from ``prev``'s device handles — callable before ``prev`` has been
        fetched (or even finished computing): this is the dispatch-ahead
        half of the async-readback pipeline. Rows ``prev`` latched dead
        stay frozen, so running ahead never corrupts a finished session's
        cached state."""
        window = prev.window if window is None else window
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        if not self._warming:
            _faults.serve_decode_hook()
        with self._lock:
            _, params, fused, mkey = self._resolve_model(prev.model)
            fn = self._window_fn_for(prev.batch_b, window, prev.sampling,
                                     mkey)
            rng = self._next_rng(prev.sampling)
            h, c, toks, next_tok, alive, rem = fn(
                params, fused, self.cache.h, self.cache.c,
                prev.slots, prev.next_tokens, prev.alive, prev.remaining,
                prev.eos_ids, rng,
            )
            self.cache.swap(h, c)
        return dataclasses.replace(
            prev, tokens=toks, next_tokens=next_tok, alive=alive,
            remaining=rem, window=window, t_dispatch=time.perf_counter(),
        )

    def spec_window(self, slots, tokens, remaining, eos_ids=None, *,
                    k_draft: int, model: str | None = None) -> DecodeWindow:
        """Dispatch one speculative step: the draft proposes ``k_draft``
        tokens, the target verifies them all in ONE teacher-forced pass
        of ``W = k_draft + 1`` joint steps, and the longest agreeing
        prefix plus the target's own correction token emit (1..W tokens
        per live row — see ``_get_spec_window_fn`` for the latch
        algebra). Greedy-only (speculation never changes the sampled
        distribution here because only greedy verification is
        implemented); the emitted tokens are token-identical to plain
        greedy decode by construction. Returns a :class:`DecodeWindow`
        with ``spec=True`` and ``window = k_draft + 1`` — fetch with the
        same ``fetch_window_summary``; chain with
        :meth:`spec_window_next`."""
        n = len(slots)
        if self.draft is None:  # graftlint: disable=cross-thread-state
            raise ValueError("spec_window needs an attached draft "
                             "(attach_draft)")
        if n == 0 or k_draft < 1:
            raise ValueError(f"spec_window needs rows and k_draft >= 1, "
                             f"got {n} rows, k_draft {k_draft}")
        if not self._warming:
            _faults.serve_decode_hook()
        batch_b = _bucket_for(n, self.batch_buckets, "decode batch")
        slots_p = np.full((batch_b,), self.cache.scratch_slot, np.int32)
        slots_p[:n] = np.asarray(slots, np.int32)
        tokens_p = np.zeros((batch_b,), np.int32)
        tokens_p[:n] = np.asarray(tokens, np.int32)
        rem_p = np.zeros((batch_b,), np.int32)
        rem_p[:n] = np.asarray(remaining, np.int32)
        eos_p = np.full((batch_b,), -1, np.int32)
        if eos_ids is not None:
            eos_p[:n] = np.asarray(eos_ids, np.int32)
        alive_p = np.zeros((batch_b,), bool)
        alive_p[:n] = rem_p[:n] > 0

        with self._lock:
            mid, params, fused, _ = self._resolve_model(model)
            if mid != self.model_id:
                raise ValueError(
                    f"spec_window serves the DEFAULT model only (the "
                    f"draft is distilled against it); got model {mid!r}")
            fn = self._spec_window_fn_for(batch_b, k_draft)
            slots_d = jnp.asarray(slots_p)
            eos_d = jnp.asarray(eos_p)
            h, c, dh, dc, toks, next_tok, alive, rem = fn(
                params, fused, self.draft["params"], self.draft["fused"],
                self.cache.h, self.cache.c, self._draft_h, self._draft_c,
                slots_d, jnp.asarray(tokens_p), jnp.asarray(alive_p),
                jnp.asarray(rem_p), eos_d,
            )
            self.cache.swap(h, c)
            self._draft_h, self._draft_c = dh, dc
        return DecodeWindow(
            tokens=toks, next_tokens=next_tok, alive=alive, remaining=rem,
            slots=slots_d, eos_ids=eos_d, batch_b=batch_b,
            window=k_draft + 1, n=n, sampling=GREEDY,
            t_dispatch=time.perf_counter(), model=mid, spec=True,
        )

    def spec_window_next(self, prev: DecodeWindow, *,
                         k_draft: int | None = None) -> DecodeWindow:
        """Dispatch the follow-up speculative step for the SAME packed
        rows from ``prev``'s device handles — the spec half of the
        dispatch-ahead pipeline (``prev.next_tokens`` is the last
        EMITTED token per row, so the successor's step 0 re-verifies
        from exactly the committed state). ``k_draft`` may differ from
        ``prev``'s (the autotuner's knob moves between windows)."""
        if not prev.spec:
            raise ValueError("spec_window_next needs a spec DecodeWindow")
        if self.draft is None:  # graftlint: disable=cross-thread-state
            raise ValueError("spec_window_next needs an attached draft")
        k = (prev.window - 1) if k_draft is None else k_draft
        if k < 1:
            raise ValueError(f"k_draft must be >= 1, got {k}")
        if not self._warming:
            _faults.serve_decode_hook()
        with self._lock:
            _, params, fused, _ = self._resolve_model(prev.model)
            fn = self._spec_window_fn_for(prev.batch_b, k)
            h, c, dh, dc, toks, next_tok, alive, rem = fn(
                params, fused, self.draft["params"], self.draft["fused"],
                self.cache.h, self.cache.c, self._draft_h, self._draft_c,
                prev.slots, prev.next_tokens, prev.alive, prev.remaining,
                prev.eos_ids,
            )
            self.cache.swap(h, c)
            self._draft_h, self._draft_c = dh, dc
        return dataclasses.replace(
            prev, tokens=toks, next_tokens=next_tok, alive=alive,
            remaining=rem, window=k + 1, t_dispatch=time.perf_counter(),
        )

    @staticmethod
    def fetch_window(win: DecodeWindow) -> np.ndarray:
        """Block until the window's tokens are on host; returns ``[n, K]``
        int32 (padding rows stripped; ``PAD_TOKEN`` after a row's EOS or
        budget end). The ONLY sync point of the windowed decode path."""
        return np.asarray(jax.device_get(win.tokens))[: win.n]

    @staticmethod
    def fetch_window_summary(
            win: DecodeWindow) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Fetch the token block AND the per-row on-device scheduler
        summary in ONE transfer: ``(tokens [n, K], remaining [n],
        alive [n])``. The window program already latched EOS/budget per
        row on device, so the scheduler tick reads this summary instead
        of re-deriving liveness host-side per token — same single sync
        point as :meth:`fetch_window` (graftlint host-sync allow-list),
        one ``device_get`` for all three arrays."""
        toks, rem, alive = jax.device_get(
            (win.tokens, win.remaining, win.alive))
        n = win.n
        return (np.asarray(toks)[:n], np.asarray(rem)[:n],
                np.asarray(alive)[:n])

    def warmup(self, sampling: SamplingParams = GREEDY,
               prompt_lens: tuple[int, ...] = (1,),
               batch_sizes: tuple[int, ...] | None = None,
               windows: tuple[int, ...] = (),
               chunk_lens: tuple[int, ...] = (),
               models: tuple[str, ...] | None = None,
               spec_windows: tuple[int, ...] = ()) -> int:
        """Pre-compile the bucket lattice a workload will touch (every
        batch bucket x the length buckets covering ``prompt_lens``, both
        phases, plus a ``decode_window`` program per batch bucket x each
        K > 1 in ``windows``, plus a ``prefill_chunk`` program per batch
        bucket x the length buckets covering ``chunk_lens`` — chunked
        prefill / prefix-insert splits dispatch those mid-traffic) by
        running dummy steps against the scratch slot — so the first real
        traffic burst is never charged the compiles. Front-ends should
        call ``Batcher.warmup`` / ``ServeServer.warmup`` instead: the
        split and window lengths are scheduler policy, and only the
        batcher can derive them. Returns the number of (phase, bucket)
        programs now cached."""
        batch_sizes = tuple(batch_sizes or self.batch_buckets)
        len_buckets = sorted({
            _bucket_for(t, self.prefill_buckets, "prompt length")
            for t in prompt_lens
        })
        chunk_buckets = sorted({
            _bucket_for(t, self.prefill_buckets, "chunk length")
            for t in chunk_lens
        })
        # every RESIDENT model warms its own program namespace (extra
        # residents are separate traces — the rollout/canary path must
        # never charge the first routed request a compile)
        model_ids = (tuple(models) if models is not None
                     else tuple(self._residents))  # graftlint: disable=cross-thread-state
        scratch = self.cache.scratch_slot
        self._warming = True
        try:
            for mid in model_ids:
                for b in batch_sizes:
                    bb = _bucket_for(b, self.batch_buckets, "batch")
                    for t in len_buckets:
                        items = [(scratch, True,
                                  np.zeros((t,), np.int32))] * bb
                        self.prefill(items, sampling, model=mid)
                    for t in chunk_buckets:
                        items = [(scratch, True,
                                  np.zeros((t,), np.int32))] * bb
                        self.prefill_chunk(items, model=mid)
                    self.decode([scratch] * bb, [0] * bb, sampling,
                                model=mid)
                    # every rung compiles as a window program — INCLUDING
                    # k=1: the batcher's sync path uses the fused decode
                    # fn for K=1, but the pipelined window tail dispatches
                    # K=1 as a decode_window, and an unwarmed one would
                    # compile in the middle of serving traffic
                    for k in sorted(set(windows)):
                        win = self.decode_window(
                            [scratch] * bb, [0] * bb, [k] * bb,
                            sampling=sampling, window=k, model=mid,
                        )
                        self.fetch_window(win)
                    if (self.draft is not None  # graftlint: disable=cross-thread-state
                            and mid == self.model_id):
                        # the speculative plane's whole program lattice:
                        # a draft_prefill per length bucket the batcher
                        # can mirror (finals AND chunks — it mirrors
                        # both), and a spec_window per warmed K_draft
                        # rung, so the autotuner moving spec_k among
                        # warmed rungs never costs a mid-traffic compile
                        for t in sorted({*len_buckets, *chunk_buckets}):
                            items = [(scratch, True,
                                      np.zeros((t,), np.int32))] * bb
                            self.draft_prefill(items)
                        for k in sorted(set(spec_windows)):
                            if k < 1:
                                continue  # rung 0 = plain decode
                            win = self.spec_window(
                                [scratch] * bb, [0] * bb, [k + 1] * bb,
                                k_draft=k,
                            )
                            self.fetch_window(win)
            if self.tiers is not None:
                # the tier-fill scatter lattice is warmup-covered like
                # every other program family: a continuation burst must
                # never pay a mid-traffic compile for its batched fill
                self.tiers.warmup_fills(self.batch_buckets[-1])
            if self.prefix is not None and hasattr(self.prefix,
                                                   "adopt_remote"):
                # the fabric's remote-adopt path lands a propagated node
                # via a batch-1 write_slots scatter; warm it against the
                # scratch slot so the first mid-traffic adoption does
                # not compile (slot S is scratch — nothing reads it back)
                scratch = self.cache.scratch_slot
                zeros = np.zeros((self.cfg.num_layers, 1,
                                  self.cfg.hidden_size), np.float32)
                self.cache.write_slots(np.asarray([scratch]), zeros,
                                       zeros)
        finally:
            self._warming = False
        return (len(self._prefill_fns) + len(self._prefill_chunk_fns)
                + len(self._decode_fns) + len(self._decode_window_fns)
                + len(self._decode_window_pallas_fns)
                + len(self._draft_prefill_fns) + len(self._spec_window_fns)
                + len(self._spec_window_pallas_fns))

    # ---- session lifecycle (thin wrappers over the cache) -------------

    def detach_session(self, session_id: str) -> DetachedState:
        with self._lock:
            return self.cache.detach(session_id)

    def restore_session(self, session_id: str, state: DetachedState) -> int:
        with self._lock:
            return self.cache.restore(session_id, state)

    def has_session(self, session_id: str) -> bool:
        """Affinity probe (serve/router.py): True when the session is
        device-resident OR restorable from a tier (host RAM / disk)."""
        if session_id in self.cache:
            return True
        return self.tiers is not None and self.tiers.has(session_id)

    def num_compiles(self, phase: str | None = None) -> int:
        # snapshot under the COUNTS lock (not _lock, which is held across
        # whole device calls): a first-time compile inserts into
        # compile_counts at trace time, and iterating concurrently from a
        # stats/health handler would raise "dictionary changed size
        # during iteration" — while blocking on _lock would park the
        # handler behind an in-flight (possibly wedged) dispatch
        with self._counts_lock:
            items = list(self.compile_counts.items())
        return sum(v for k, v in items if phase is None or k[0] == phase)

    def stats(self) -> dict:
        with self._counts_lock:
            compiles = dict(self.compile_counts)
            fallbacks = self.decode_window_scan_fallbacks
        draft = self.draft  # graftlint: disable=cross-thread-state
        return {
            "decode_kernel": self.decode_kernel,
            "mesh_shards": self.mesh_shards,
            "model_id": self.model_id,
            "models": self.resident_models(),
            "draft": None if draft is None else {
                "hidden_size": draft["cfg"].hidden_size,
                "num_layers": draft["cfg"].num_layers,
                "version": draft["version"],
            },
            "decode_window_scan_fallbacks": fallbacks,
            "cache": self.cache.stats(),
            "prefix_cache": None if self.prefix is None else self.prefix.stats(),
            "tiers": None if self.tiers is None else self.tiers.stats(),
            "compiles": {repr(k): v for k, v in compiles.items()},
            "prefill_buckets": self.prefill_buckets,
            "batch_buckets": self.batch_buckets,
        }
