"""Network resilience layer for the remote-replica plane (ISSUE 17).

PR 13 put the router across hosts over single-shot ``urllib`` calls:
every RPC opened a fresh TCP connection, a slow peer burned the full
``rpc_timeout`` per call with no retry, and one blackhole longer than
``DEAD_AFTER * poll_interval`` permanently retired a healthy peer.
This module is the shared transport that fixes the plane:

``PeerTransport``
    Connection-reusing HTTP client with split connect/read timeouts,
    bounded retries driven by the shared ``resilience.backoff
    .backoff_delay`` ladder, and per-peer failure classification.  Every
    failure is tagged with ``executed``: ``False`` means the request
    provably never reached the peer (connect-phase failure — safe to
    re-route anywhere, even a kept-session continuation), ``None`` means
    indeterminate (the request may have executed — only safe to retry
    against the *same* peer under a ``request_id`` replay).

``CircuitBreaker``
    Per-peer state machine: N consecutive transport failures open the
    circuit so fresh requests route away instantly instead of each
    waiting out ``rpc_timeout``; the heartbeat poller doubles as the
    half-open prober; H consecutive probe successes close it again
    (hysteresis — one lucky packet does not rejoin a flapping peer).
    Circuit-open is deliberately distinct from dead: a refused
    connection (no listener) still retires, a partition never does.

``SettledCache``
    Peer-side idempotent-replay cache for the non-idempotent generate
    POST: the client mints a ``request_id``, the peer remembers the
    settled reply, and a retried POST whose first attempt actually
    executed returns the cached settle instead of double-decoding
    (exactly-once effect over at-least-once delivery).

Fault injection (``resilience.faults`` ``net_latency`` / ``net_drop`` /
``net_blackhole`` / ``net_flap``) hooks in at ``PeerTransport._attempt``
so heartbeat, residency, and generate paths all see the same wire.
"""

from __future__ import annotations

import http.client
import json
import socket
import threading
import time
from collections import OrderedDict
from urllib.parse import urlsplit

from ..resilience import faults
from ..resilience.backoff import backoff_delay

__all__ = [
    "CircuitBreaker",
    "PeerHTTPError",
    "PeerTransport",
    "SettledCache",
    "TransportError",
]

# Circuit gauge values (docs/OPERATIONS.md "Circuit open" runbook row).
CIRCUIT_CLOSED = 0
CIRCUIT_OPEN = 1
CIRCUIT_HALF_OPEN = 2


class TransportError(OSError):
    """A transport-level RPC failure with delivery provenance.

    ``kind``
        ``refused`` | ``connect_timeout`` | ``timeout`` | ``reset`` |
        ``circuit_open`` | ``response_dropped`` | ``protocol``.
    ``executed``
        ``False`` — provably never delivered (failed before the request
        bytes could reach a listener); re-routing is always safe.
        ``None`` — indeterminate: the peer may have executed the call
        (e.g. read timeout after the POST was sent, response dropped);
        only a same-peer ``request_id`` replay is safe.
    ``attempts``
        How many wire attempts the failing call made (set by the retry
        loop on the finally-raised error).
    """

    def __init__(self, kind: str, message: str, *, executed=False,
                 attempts: int = 1):
        super().__init__(message)
        self.kind = kind
        self.executed = executed
        self.attempts = attempts


class PeerHTTPError(Exception):
    """The peer answered with an HTTP error status.

    Reaching this far means the peer process is alive and talking — it
    counts as a circuit *success* even though the call failed.  ``body``
    is the peer's decoded JSON error payload (the uniform
    ``{"error", "code", "retryable", ...}`` shape from serve/server.py)
    when one was parseable, else ``{}``.
    """

    def __init__(self, status: int, body: dict | None = None):
        super().__init__(f"peer returned HTTP {status}")
        self.status = int(status)
        self.body = body if isinstance(body, dict) else {}


class CircuitBreaker:
    """Per-peer circuit breaker with flap damping and rejoin hysteresis.

    Closed regime: any success fully resets the failure streak, so an
    alternating ok/fail link (flap) below ``open_after`` never opens the
    circuit — it degrades via per-call retries instead of oscillating.
    ``open_after`` consecutive failures open it.  Open regime: probes
    (the heartbeat poller) keep flowing; ``rejoin_after`` *consecutive*
    successes close it — a single lucky probe only moves it to
    half-open.  ``suspect(after)`` exposes the milder damping threshold
    the residency cache uses: ``after <= open_after`` consecutive
    failures mark the peer's cached state untrusted before the circuit
    fully opens.
    """

    def __init__(self, *, open_after: int = 3, rejoin_after: int = 2,
                 gauge=None):
        if open_after < 1 or rejoin_after < 1:
            raise ValueError("circuit thresholds must be >= 1")
        self.open_after = int(open_after)
        self.rejoin_after = int(rejoin_after)
        self._lock = threading.Lock()
        self._open = False
        self._fail_streak = 0
        self._ok_streak = 0
        self.opened_total = 0
        self.closed_total = 0
        self._gauge = gauge          # metric child: .set(state int)
        self._set_gauge(CIRCUIT_CLOSED)

    def _set_gauge(self, value: int) -> None:
        if self._gauge is not None:
            self._gauge.set(float(value))

    def allow(self) -> bool:
        """False while open — callers fail fast instead of waiting out
        a timeout against a partitioned peer.  Probes bypass this."""
        with self._lock:
            return not self._open

    @property
    def is_open(self) -> bool:
        with self._lock:
            return self._open

    def record_failure(self) -> None:
        with self._lock:
            self._ok_streak = 0
            self._fail_streak += 1
            if not self._open and self._fail_streak >= self.open_after:
                self._open = True
                self.opened_total += 1
            value = CIRCUIT_OPEN if self._open else CIRCUIT_CLOSED
        self._set_gauge(value)

    def record_success(self) -> None:
        with self._lock:
            if self._open:
                self._ok_streak += 1
                if self._ok_streak >= self.rejoin_after:
                    self._open = False
                    self._fail_streak = 0
                    self._ok_streak = 0
                    self.closed_total += 1
                    value = CIRCUIT_CLOSED
                else:
                    value = CIRCUIT_HALF_OPEN
            else:
                self._fail_streak = 0
                self._ok_streak += 1
                value = CIRCUIT_CLOSED
        self._set_gauge(value)

    def suspect(self, after: int) -> bool:
        """True when open, or when ``after`` consecutive failures have
        accrued — the damping threshold at which cached residency stops
        being trusted (M in the flap-damping spec, M <= N)."""
        with self._lock:
            return self._open or self._fail_streak >= int(after)

    def state(self) -> str:
        with self._lock:
            if not self._open:
                return "closed"
            return "half_open" if self._ok_streak > 0 else "open"


_RETRYABLE_HTTP = ()            # HTTP statuses are never transport-retried


class PeerTransport:
    """Connection-reusing JSON-over-HTTP client for one remote peer.

    All remote RPCs (heartbeat, has_session, stats, warmup, generate)
    go through ``rpc_get`` / ``rpc_post`` — the names are deliberately
    distinctive so the graftlint io-under-lock rule can flag any call
    made while a hot lock is held.  Retries use the shared
    ``backoff_delay`` ladder and stop early once the circuit opens
    (burning the remaining budget against a dead link helps nobody).
    """

    MAX_POOL = 4

    def __init__(self, url: str, *, peer: int = 0, connect_timeout: float = 1.0,
                 max_retries: int = 2, retry_base_s: float = 0.05,
                 circuit: CircuitBreaker | None = None, registry=None):
        parts = urlsplit(url if "//" in url else "//" + url)
        if parts.scheme not in ("", "http"):
            raise ValueError(f"PeerTransport supports http:// urls, got {url!r}")
        if not parts.hostname:
            raise ValueError(f"peer url has no host: {url!r}")
        self.url = url.rstrip("/")
        self.host = parts.hostname
        self.port = parts.port or 80
        self.peer = int(peer)
        self.connect_timeout = float(connect_timeout)
        self.max_retries = int(max_retries)
        self.retry_base_s = float(retry_base_s)
        self.circuit = circuit if circuit is not None else CircuitBreaker()
        self._pool: list[http.client.HTTPConnection] = []
        self._pool_lock = threading.Lock()
        self.retries_total = 0
        self._m_rpc = None
        self._m_retries = None
        self._m_seconds = None
        if registry is not None:
            self._m_rpc = registry.counter(
                "serve_remote_rpc_total",
                "remote replica RPC attempts by method and outcome",
                labelnames=("method", "outcome", "peer"))
            self._m_retries = registry.counter(
                "serve_remote_retries_total",
                "remote RPC wire retries (attempts beyond the first)",
                labelnames=("peer",))
            self._m_seconds = registry.histogram(
                "serve_remote_rpc_seconds",
                "remote RPC attempt latency (per wire attempt)",
                labelnames=("method", "peer"))

    # ---- metric helpers -------------------------------------------------

    def _count(self, method: str, outcome: str) -> None:
        if self._m_rpc is not None:
            self._m_rpc.labels(method=method, outcome=outcome,
                               peer=str(self.peer)).inc()

    def _observe(self, method: str, seconds: float) -> None:
        if self._m_seconds is not None:
            self._m_seconds.labels(method=method,
                                   peer=str(self.peer)).observe(seconds)

    # ---- connection pool ------------------------------------------------

    def _checkout(self) -> http.client.HTTPConnection:
        with self._pool_lock:
            if self._pool:
                return self._pool.pop()
        return http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.connect_timeout)

    def _checkin(self, conn: http.client.HTTPConnection) -> None:
        with self._pool_lock:
            if len(self._pool) < self.MAX_POOL:
                self._pool.append(conn)
                return
        conn.close()

    def close(self) -> None:
        with self._pool_lock:
            pool, self._pool = self._pool, []
        for conn in pool:
            conn.close()

    # ---- RPC ------------------------------------------------------------

    def rpc_get(self, path: str, *, method: str, timeout: float | None = None,
                retries: int | None = None, probe: bool = False) -> dict:
        """Idempotent GET.  Retried up to ``retries`` times (default
        ``max_retries``) regardless of delivery provenance."""
        return self._rpc("GET", path, None, method=method, timeout=timeout,
                         retries=retries, replay_safe=True, probe=probe)

    def rpc_post(self, path: str, body: dict, *, method: str,
                 timeout: float | None = None, retries: int | None = None,
                 replay_safe: bool = False, probe: bool = False,
                 deadline: float | None = None) -> dict:
        """POST.  Only retried on indeterminate failures when
        ``replay_safe`` (idempotent endpoint, or the body carries a
        ``request_id`` the peer deduplicates on); provably-undelivered
        failures (``executed is False``) are always retry-eligible."""
        return self._rpc("POST", path, body, method=method, timeout=timeout,
                         retries=retries, replay_safe=replay_safe,
                         probe=probe, deadline=deadline)

    def _rpc(self, verb: str, path: str, body: dict | None, *, method: str,
             timeout: float | None, retries: int | None, replay_safe: bool,
             probe: bool = False, deadline: float | None = None) -> dict:
        budget = self.max_retries if retries is None else int(retries)
        attempt = 0
        while True:
            attempt += 1
            if not probe and not self.circuit.allow():
                self._count(method, "circuit_open")
                raise TransportError(
                    "circuit_open",
                    f"peer {self.peer} circuit open — routing away",
                    executed=False, attempts=attempt - 1)
            t0 = time.perf_counter()
            try:
                out = self._attempt(verb, path, body, method, timeout)
            except PeerHTTPError:
                # The peer answered: link is fine, the call is not.
                self.circuit.record_success()
                self._count(method, "error")
                self._observe(method, time.perf_counter() - t0)
                raise
            except TransportError as err:
                self.circuit.record_failure()
                self._count(method, "unreachable")
                self._observe(method, time.perf_counter() - t0)
                err.attempts = attempt
                retryable = err.executed is False or replay_safe
                if (not retryable or attempt > budget
                        or (not probe and self.circuit.is_open)):
                    raise
                delay = backoff_delay(self.retry_base_s, attempt)
                # ``deadline`` shares the request clock (perf_counter —
                # ``Request.deadline`` is stamped from it at submit).
                if deadline is not None and \
                        time.perf_counter() + delay >= deadline:
                    raise
                self.retries_total += 1
                if self._m_retries is not None:
                    self._m_retries.labels(peer=str(self.peer)).inc()
                if delay > 0:
                    time.sleep(delay)
                continue
            else:
                self.circuit.record_success()
                self._count(method, "ok")
                self._observe(method, time.perf_counter() - t0)
                return out

    def _attempt(self, verb: str, path: str, body: dict | None,
                 method: str, timeout: float | None) -> dict:
        action = faults.serve_net_hook(self.peer, method)
        drop_response = False
        if action is not None:
            kind = action[0]
            if kind == "latency":
                time.sleep(action[1] / 1000.0)
            elif kind == "blackhole":
                # SYN-drop semantics: the connect phase times out, the
                # request bytes never reach a listener.
                time.sleep(self.connect_timeout)
                raise TransportError(
                    "connect_timeout",
                    f"peer {self.peer} blackholed (injected)",
                    executed=False)
            elif kind == "fail":
                raise TransportError(
                    "reset", f"peer {self.peer} link flap (injected)",
                    executed=False)
            elif kind == "drop":
                drop_response = True
        conn = self._checkout()
        phase = "connect"
        try:
            if conn.sock is None:
                conn.timeout = self.connect_timeout
                conn.connect()
            conn.sock.settimeout(timeout)
            phase = "exchange"
            payload = None
            headers = {}
            if body is not None:
                payload = json.dumps(body).encode("utf-8")
                headers["Content-Type"] = "application/json"
            conn.request(verb, path, body=payload, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            status = resp.status
            reuse = not resp.will_close
        except ConnectionRefusedError as err:
            conn.close()
            raise TransportError(
                "refused", f"peer {self.peer} refused connection: {err}",
                executed=False) from err
        except (socket.timeout, TimeoutError) as err:
            conn.close()
            if phase == "connect":
                raise TransportError(
                    "connect_timeout",
                    f"peer {self.peer} connect timed out", executed=False,
                ) from err
            # The request may have been sent and executed — only a
            # request_id replay can safely retry this.
            raise TransportError(
                "timeout", f"peer {self.peer} RPC timed out mid-exchange",
                executed=None) from err
        except (OSError, http.client.HTTPException) as err:
            conn.close()
            executed = False if phase == "connect" else None
            raise TransportError(
                "reset", f"peer {self.peer} connection error: {err}",
                executed=executed) from err
        if reuse:
            self._checkin(conn)
        else:
            conn.close()
        if drop_response:
            # net_drop: the call executed on the wire; the client loses
            # the response — indeterminate, exercises the replay path.
            raise TransportError(
                "response_dropped",
                f"peer {self.peer} response dropped (injected)",
                executed=None)
        try:
            decoded = json.loads(data.decode("utf-8")) if data else {}
        except ValueError as err:
            if status < 400:
                raise TransportError(
                    "protocol",
                    f"peer {self.peer} sent unparseable JSON", executed=None,
                ) from err
            decoded = {}
        if status >= 400:
            raise PeerHTTPError(status, decoded)
        return decoded


class SettledCache:
    """Peer-side settled-result cache keyed by client-minted request_id.

    ``begin(rid)`` returns ``("mine", None)`` for the first delivery
    (the caller must later ``settle`` or ``abandon``), ``("hit",
    (status, payload))`` for a replay of an already-settled request, and
    ``("timeout", None)`` if a concurrent first delivery is still
    executing past ``wait_timeout``.  Only terminal outcomes worth
    replaying are settled (HTTP 200 and 504 deadline_exceeded — both
    mean tokens were decoded); transient errors are abandoned so the
    retry re-executes.  Bounded LRU + TTL; in-flight entries are never
    evicted.
    """

    def __init__(self, *, max_entries: int = 1024, ttl_s: float = 600.0,
                 registry=None):
        self.max_entries = int(max_entries)
        self.ttl_s = float(ttl_s)
        self._lock = threading.Lock()
        self._settled: OrderedDict[str, tuple[int, dict, float]] = \
            OrderedDict()
        self._inflight: dict[str, threading.Event] = {}
        self.hits = 0
        self.waits = 0
        self.stores = 0
        self._m_dedup = None
        if registry is not None:
            self._m_dedup = registry.counter(
                "serve_replay_dedup_total",
                "generate replay dedup events by result",
                labelnames=("result",))

    def _count(self, result: str) -> None:
        if self._m_dedup is not None:
            self._m_dedup.labels(result=result).inc()

    def begin(self, rid: str, wait_timeout: float | None = None):
        waited = False
        while True:
            with self._lock:
                entry = self._settled.get(rid)
                if entry is not None:
                    self._settled.move_to_end(rid)
                    self.hits += 1
                    hit = (entry[0], entry[1])
                else:
                    event = self._inflight.get(rid)
                    if event is None:
                        self._inflight[rid] = threading.Event()
                        return ("mine", None)
                    hit = None
            if hit is not None:
                self._count("hit")
                return ("hit", hit)
            if not waited:
                waited = True
                self.waits += 1
                self._count("wait")
            if not event.wait(wait_timeout):
                return ("timeout", None)
            # Either settled (replay it) or abandoned (becomes ours).

    def settle(self, rid: str, status: int, payload: dict) -> None:
        now = time.monotonic()
        with self._lock:
            event = self._inflight.pop(rid, None)
            self._settled[rid] = (int(status), payload, now)
            self._settled.move_to_end(rid)
            self.stores += 1
            while len(self._settled) > self.max_entries:
                self._settled.popitem(last=False)
            cutoff = now - self.ttl_s
            stale = [k for k, (_, _, t) in self._settled.items()
                     if t < cutoff]
            for k in stale:
                del self._settled[k]
        self._count("store")
        if event is not None:
            event.set()

    def abandon(self, rid: str) -> None:
        with self._lock:
            event = self._inflight.pop(rid, None)
        if event is not None:
            event.set()

    def stats(self) -> dict:
        with self._lock:
            return {"settled": len(self._settled),
                    "inflight": len(self._inflight),
                    "hits": self.hits, "waits": self.waits,
                    "stores": self.stores}
