"""Admission router over N per-replica schedulers (data-parallel serving).

The serve stack's scaling unit is a **replica**: one :class:`ServeEngine`
(its own state cache, prefix cache and compiled programs — on TPU also
its own device, via ``ServeEngine(device=...)``) plus one
:class:`Batcher` driven by its own scheduler thread. Replicas share
NOTHING on the hot path: recurrent-state slots and ``PrefixCache``
entries are replica-local, so there is no cross-replica cache coherence
to get wrong — the source paper's driver/worker split applied to
inference (the router is the driver, replicas are the map workers; cf.
the DrJAX map/reduce framing in PAPERS.md). Aggregate decode tokens/s
then scales with replicas instead of hard-capping at one scheduler
(BENCH_serve_r02.json is the measured trajectory).

Routing (:meth:`Router.submit`):

- **session → replica affinity**: a request naming a ``session_id`` goes
  to the replica whose state cache holds that session — probed directly
  (``sid in engine.cache``), so the cache IS the affinity table and
  there is no side mapping to go stale. A kept session's continuations
  therefore always land where its carries (and any prefix entries its
  prompts seeded) live.
- **fresh requests** go to the least-loaded live replica
  (queued + active + prefilling), round-robin on ties — so an idle
  fleet splits a burst instead of piling onto replica 0.
- **admission** enforces ONE global bound: total queued across live
  replicas ``>= queue_size`` raises :class:`QueueFullError` (HTTP 429).
  Per-replica queues are sized at the same bound, so the global check
  is the only one that ever fires.

Replica death — a scheduler thread that EXITS outside ``stop()``
(uncaught exception) — is detected by :meth:`Router.sweep` (piggybacked
on every submit and health probe; no monitor thread) and the replica is
retired exactly once:

1. its queued, not-yet-admitted requests are **requeued** onto live
   replicas (bypassing the global bound — they already held queue slots
   before the death);
2. its in-flight (admitted) requests **fail honestly**: under
   dispatch-ahead windowed decode the host cannot know how many tokens
   an un-fetched window already consumed, so resuming mid-decode on
   another replica could silently double-decode — "state lost" is the
   truthful verdict;
3. its idle kept sessions **migrate** to live replicas via the exact
   ``detach``/``restore`` path (state_cache), BEFORE the requeue — so a
   queued continuation follows its migrated state and completes
   token-identically to an uninterrupted run. Sessions that cannot be
   restored are dropped; their next continuation fails loudly as
   "unknown session" (never silently decodes from zero state).

A WEDGED replica (thread alive, heartbeat stale) is only excluded from
fresh routing and health — its thread may still wake and touch its
structures, so retirement (which mutates them from the router's thread)
would race; see docs/OPERATIONS.md "Router runbook". Retirement runs
inline on the detecting probe/submit thread; its cost is bounded by
``num_slots`` × one O(1) LSTM state per kept session (KBs each —
detach/restore of idle state, no pending compute to await), so a sweep
stays well under orchestrator probe timeouts. A continuation submitted
concurrently with its session's in-flight migration can land between
detach and restore and fail "unknown session" once — transient by
construction; an immediate retry follows the restored state.

Lock order: ``Router._lock`` is acquired ABOVE replica locks (the
router reads ``Batcher.queued()``/``load()`` and probes caches while
holding it); nothing in a replica ever calls back up into the router,
so the acquisition graph stays acyclic (graftlint ``lock-order``).
"""

from __future__ import annotations

import itertools
import threading
import time

from .batcher import (
    CLASSES,
    Batcher,
    QueueFullError,
    Request,
    register_shed_instruments,
    retry_after_from_p99,
)
from .engine import ServeEngine, UnknownModelError
from .state_cache import PREFIX_SID_NAMESPACE


class Replica:
    """One engine + scheduler pair. The thread handle lives here so the
    router and server agree on liveness; ``retired`` marks a dead
    replica whose cleanup (requeue/fail/migrate) already ran."""

    __slots__ = ("index", "engine", "batcher", "thread", "retired",
                 "draining")

    def __init__(self, index: int, engine: ServeEngine, batcher: Batcher):
        self.index = index
        self.engine = engine
        self.batcher = batcher
        self.thread: threading.Thread | None = None
        self.retired = False  # claimed under the router lock, exactly once
        # held out of rotation by the rollout controller: fresh routing,
        # the admission bound and the death sweep all skip it (its
        # scheduler thread is about to be stopped DELIBERATELY)
        self.draining = False

    def alive(self) -> bool:
        """Live: never started (requests queue until ``start()``) or the
        thread is running. Started-and-exited is dead — except during a
        drain, when the controller stops the thread on purpose."""
        return not self.retired and (
            self.thread is None or self.thread.is_alive())

    def routable(self) -> bool:
        """Eligible for routing: live AND not mid-drain."""
        return self.alive() and not self.draining

    def stale(self, stale_after: float) -> bool:
        """Running but heartbeat-silent past ``stale_after`` — the wedge
        case (thread stuck inside a dispatch that never returns). An
        unstarted replica has no heartbeat and is NOT stale."""
        hb = self.batcher.last_heartbeat
        return (self.thread is not None and hb is not None
                and time.monotonic() - hb > stale_after)

    def circuit_open(self) -> bool:
        """True while the replica's transport circuit is open or suspect
        (remote replicas only — a partitioned peer must be routed
        around instantly, like a wedge, while its heartbeat prober
        works toward rejoin). Local replicas have no circuit."""
        return False


class Router:
    """Admission front for a set of replicas (module docstring)."""

    #: tenant token-bucket table cap: beyond this, fully-refilled buckets
    #: (indistinguishable from absent ones) are pruned — an adversarial
    #: stream of fresh tenant names cannot grow router memory unboundedly
    MAX_TENANT_BUCKETS = 4096

    def __init__(self, replicas: list[Replica], *, queue_size: int = 64,
                 stale_after: float = 60.0,
                 best_effort_frac: float = 0.5, registry=None,
                 tenant_rate: float | None = None,
                 tenant_burst: float = 5.0):
        if not replicas:
            raise ValueError("router needs at least one replica")
        if queue_size < 1:
            raise ValueError(f"queue_size must be >= 1, got {queue_size}")
        if not 0.0 < best_effort_frac <= 1.0:
            raise ValueError(
                f"best_effort_frac must be in (0, 1], got {best_effort_frac}")
        if tenant_rate is not None and tenant_rate <= 0:
            raise ValueError(
                f"tenant_rate must be > 0 req/s or None, got {tenant_rate}")
        if tenant_burst < 1:
            raise ValueError(
                f"tenant_burst must be >= 1, got {tenant_burst}")
        self.replicas = list(replicas)
        self.queue_size = queue_size
        # per-tenant token buckets (requests/s with a burst allowance) on
        # TOP of the class policy: one tenant flooding the fleet is
        # rate-limited before it can consume the shared queue bound the
        # other tenants' traffic lives under. None = no per-tenant
        # limiting (requests without a tenant field are never limited).
        self.tenant_rate = tenant_rate
        self.tenant_burst = float(tenant_burst)
        self._tenant_buckets: dict[str, list] = {}  # tenant -> [tokens, t]
        # SLO-aware shedding: best-effort requests are 429'd once the
        # live queue reaches this smaller bound, so a best-effort burst
        # sheds while the priority class keeps the remaining headroom —
        # the honest degradation the old single fixed bound couldn't
        # express (it shed both classes indiscriminately, FIFO)
        self.best_effort_frac = float(best_effort_frac)
        self._best_effort_bound = max(
            1, int(round(queue_size * best_effort_frac)))
        # heartbeat-staleness bound for ROUTING (mirrors the server's
        # health_stale_after): a wedged replica must stop receiving fresh
        # sessions — they would hang to client timeout while holding
        # global queue capacity, even with healthy replicas idle
        self.stale_after = stale_after
        self._lock = threading.Lock()
        self._rr = itertools.count()  # round-robin tie-break cursor
        # rollout canary hook: called with every successfully admitted
        # request OUTSIDE the lock (the hook submits shadow work back
        # through replica batchers — calling it under ``_lock`` would
        # deadlock on re-entry through submit's own acquisition)
        self._canary = None
        # the death sweep starts DISARMED: ServeServer.start() arms it
        # (set_stopping(False)) only once every scheduler thread is
        # running — otherwise a submit/probe racing the first start()
        # could see an assigned-but-not-yet-started thread and retire a
        # replica that is about to serve
        self._stopping = True
        self.rejected = 0            # global-bound 429s
        self.shed = {c: 0 for c in CLASSES}  # 429s by admission class
        self.tenant_limited = {c: 0 for c in CLASSES}  # token-bucket 429s
        self.requeued = 0            # dead-replica queue → live replica
        self.rerouted = 0            # undelivered RPCs re-picked elsewhere
        self.failed_on_death = 0     # in-flight requests failed honestly
        self.migrated_sessions = 0   # idle kept sessions detach/restored
        self.lost_sessions = 0       # could not be restored anywhere
        self.routed: dict[int, int] = {r.index: 0 for r in self.replicas}
        reg = registry if registry is not None else replicas[0].engine.metrics
        self._m_rejected = reg.counter(
            "serve_router_rejected_total",
            "requests 429'd at the router's global admission bound")
        # ALSO recorded under the shared outcome family (replica="router"):
        # the global bound fires before any per-replica bound can, and the
        # runbook's queue-saturation signature is
        # serve_requests_total{outcome="rejected"} — it must keep climbing
        # on real 429s, not flatline because rejection moved up a layer
        self._m_rejected_outcome = reg.counter(
            "serve_requests_total",
            labelnames=("outcome", "replica")).labels(
            outcome="rejected", replica="router")
        fam = reg.counter("serve_router_routed_total",
                          "requests routed, by target replica",
                          labelnames=("replica",))
        self._m_routed = {r.index: fam.labels(replica=str(r.index))
                          for r in self.replicas}
        self._m_requeued = reg.counter(
            "serve_router_requeued_total",
            "dead-replica queued requests requeued onto live replicas")
        self._m_failed_death = reg.counter(
            "serve_router_death_failures_total",
            "in-flight requests failed honestly on replica death")
        self._m_migrated = reg.counter(
            "serve_router_migrated_sessions_total",
            "idle kept sessions moved off dead replicas via detach/restore")
        self._m_rerouted = reg.counter(
            "serve_router_rerouted_total",
            "provably-undelivered remote RPCs re-routed to another replica")
        # shared with the batcher's own queue bound: one registration
        # site + one policy function, so the two layers can never hint
        # different Retry-After curves for the same queue state; the
        # tenant_limited="yes" children count this router's per-tenant
        # token-bucket 429s
        (self._m_shed, self._m_tenant_shed,
         self._m_retry_after) = register_shed_instruments(reg)
        # the live queue-wait histogram family (registered by the
        # batchers, same name/labels/buckets — idempotent): its p99 IS
        # the drain-time evidence Retry-After is computed from
        self._qwait = reg.histogram(
            "serve_queue_wait_seconds", "submit → admission wait",
            labelnames=("replica",))

    def _suspect(self, r: Replica) -> bool:
        """Unfit for fresh work: heartbeat-stale (the wedge) OR
        transport circuit open/suspect (the partition). Both are
        route-around states, not deaths — the replica stays in the
        fleet and rejoins when its heartbeat/probes recover."""
        return r.stale(self.stale_after) or r.circuit_open()

    # ---- client side ---------------------------------------------------

    def submit(self, req: Request) -> None:
        """Admit + route one request, or raise :class:`QueueFullError`
        (SLO-aware shed; HTTP 429 with ``retry_after_s``) /
        ``RuntimeError`` when no replica is live. Called from client/HTTP
        threads.

        Shedding is class-aware: ``best_effort`` requests are rejected
        once the live queue reaches ``best_effort_frac * queue_size``,
        ``priority`` only at the full bound — so a burst degrades by
        shedding the cheap class first. Every shed carries a
        ``Retry-After`` computed from the live queue-wait p99 histogram
        (the measured drain time), not a made-up constant."""
        self.sweep()
        with self._lock:
            live = [r for r in self.replicas if r.routable()]
            if not live:
                raise RuntimeError(
                    "no routable replica schedulers (replicas dead or "
                    "draining)")
            # per-tenant token bucket FIRST: a rate-limited tenant is
            # rejected before it can consume the shared queue bound the
            # other tenants' traffic lives under
            if self.tenant_rate is not None and req.tenant is not None:
                retry = self._tenant_take_locked(req.tenant)
                if retry is not None:
                    self.tenant_limited[req.klass] += 1
                    self._m_tenant_shed[req.klass].inc()
                    self._m_retry_after.observe(retry)
                    raise QueueFullError(
                        f"tenant {req.tenant!r} exceeded its "
                        f"{self.tenant_rate:g} req/s rate limit; retry "
                        f"after {retry:.2f}s", retry_after_s=retry)
            # the bound covers NON-SUSPECT queues only: a wedged replica
            # never drains (its admission loop is stuck) and a
            # partitioned one drains only after it heals, so counting
            # their stranded entries would shrink the fleet's effective
            # admission capacity until recovery. If the wedge/partition
            # recovers, a transient overshoot of the bound drains
            # normally.
            queued = sum(r.batcher.queued() for r in live
                         if not self._suspect(r))
            bound = (self._best_effort_bound
                     if req.klass == "best_effort" else self.queue_size)
            if queued >= bound:
                retry = self._retry_after_locked(queued)
                self.rejected += 1
                self.shed[req.klass] += 1
                self._m_rejected.inc()
                self._m_rejected_outcome.inc()
                self._m_shed[req.klass].inc()
                self._m_retry_after.observe(retry)
                raise QueueFullError(
                    f"submit queue full for class {req.klass!r} "
                    f"({queued} pending >= bound {bound}); retry after "
                    f"{retry:.2f}s", retry_after_s=retry)
            self._dispatch_locked(req, live)
        canary = self._canary
        if canary is not None:
            try:
                canary(req)
            except Exception:
                pass  # a shadow must never fail the admitted primary

    def _tenant_take_locked(self, tenant: str) -> float | None:
        """Take one token from ``tenant``'s bucket. Returns None when a
        token was available (request admitted to the normal policy), or
        the honest Retry-After: the time until the bucket accrues a
        token, floored by the shared queue-drain policy
        (:func:`~.batcher.retry_after_from_p99`) so a rate-limited
        client never retries into a congested queue faster than a shed
        one would."""
        now = time.monotonic()
        bucket = self._tenant_buckets.get(tenant)
        if bucket is None:
            if len(self._tenant_buckets) >= self.MAX_TENANT_BUCKETS:
                # prune fully-refilled buckets — indistinguishable from
                # absent ones, so dropping them changes no verdict
                full = [t for t, (tok, ts) in self._tenant_buckets.items()
                        if tok + (now - ts) * self.tenant_rate
                        >= self.tenant_burst]
                for t in full:
                    del self._tenant_buckets[t]
                while len(self._tenant_buckets) >= self.MAX_TENANT_BUCKETS:
                    # nothing prunable (a flood of FRESH tenant names
                    # faster than the refill rate): evict the fullest
                    # bucket — the closest to indistinguishable-from-
                    # absent, so dropping it perturbs verdicts least.
                    # The cap is a hard bound, not a hint: without this
                    # the table grows with attacker send rate.
                    victim = max(
                        self._tenant_buckets,
                        key=lambda t: self._tenant_buckets[t][0]
                        + (now - self._tenant_buckets[t][1])
                        * self.tenant_rate)
                    del self._tenant_buckets[victim]
            bucket = self._tenant_buckets[tenant] = [self.tenant_burst, now]
        tokens = min(self.tenant_burst,
                     bucket[0] + (now - bucket[1]) * self.tenant_rate)
        bucket[1] = now
        if tokens >= 1.0:
            bucket[0] = tokens - 1.0
            return None
        bucket[0] = tokens
        deficit = (1.0 - tokens) / self.tenant_rate
        agg = self._qwait.aggregate_over("replica")
        s = agg.get("") or {}
        return max(deficit, retry_after_from_p99(s.get("p99"), 0.0))

    def set_best_effort_frac(self, frac: float) -> None:
        """Move the best-effort shed bound at runtime — the autotuner's
        admission knob (tightened when the state plane thrashes at its
        capacity ceiling, relaxed back toward the configured policy when
        the pressure clears). Same validation as construction."""
        if not 0.0 < frac <= 1.0:
            raise ValueError(
                f"best_effort_frac must be in (0, 1], got {frac}")
        with self._lock:
            self.best_effort_frac = float(frac)
            self._best_effort_bound = max(
                1, int(round(self.queue_size * frac)))

    def _retry_after_locked(self, queued: int) -> float:
        """Honest Retry-After (seconds) for a shed: the fleet's queue-wait
        p99 — the measured time a queued request recently waited for
        admission — through the shared policy
        (:func:`~.batcher.retry_after_from_p99`) at the current queue
        fullness."""
        agg = self._qwait.aggregate_over("replica")
        s = agg.get("") or {}
        return retry_after_from_p99(
            s.get("p99"), queued / max(self.queue_size, 1))

    def _dispatch_locked(self, req: Request, live: list[Replica]) -> None:
        self._submit_to_locked(req, self._pick_locked(req, live))

    def _submit_to_locked(self, req: Request, target: Replica) -> None:
        req.replica = target.index
        # per-replica queues are sized at the global bound, so this never
        # raises QueueFullError here; a bad prompt still raises ValueError
        # before any accounting (nothing to undo)
        target.batcher.submit(req)
        self.routed[target.index] += 1
        self._m_routed[target.index].inc()

    def _pick_locked(self, req: Request, live: list[Replica]) -> Replica:
        if req.model is not None:
            # multi-model routing: only replicas with the model resident
            # are candidates — a miss everywhere is the client's error
            # (HTTP 404), not a capacity condition
            hosts = [r for r in live if r.engine.has_model(req.model)]
            if not hosts:
                raise UnknownModelError(
                    f"model {req.model!r} is not resident on any "
                    "routable replica")
            live = hosts
        sid = req.session_id
        if sid is not None:
            # affinity: the replica holding the session's carries owns the
            # session — even when heartbeat-stale (a transient stall must
            # not hard-fail a valid session; routing elsewhere would
            # GUARANTEE an "unknown session" error). No match → route by
            # load; the target batcher then fails an expired continuation
            # loudly (never decodes from zero state), exactly as in the
            # single-replica stack.
            for r in live:
                if sid in r.engine.cache:
                    return r
            # drain affinity: a DRAINING replica's kept sessions migrate
            # off as they go idle — a continuation racing that migration
            # is migrated HERE, just in time, so it never fails "unknown
            # session" mid-rollout
            target = self._drain_affinity_locked(sid, live)
            if target is not None:
                return target
            # tier residency (SessionTiers): the session was spilled off
            # its device slot. MEMORY tiers first — a replica holding the
            # session in its pending/host/evacuating tiers is the OWNER
            # with the freshest request boundary, and with a SHARED
            # --session-dir every replica's disk probe matches, possibly
            # against an older not-yet-overwritten file (filling that
            # elsewhere would silently decode stale tokens). Fill-ahead
            # promotes the memory copy so the state is already
            # device-resident when the continuation reaches admission;
            # skipped on a wedged replica (its locks may be held across a
            # dispatch that never returns — admission fills once it
            # wakes).
            for r in live:
                tiers = r.engine.tiers
                if tiers is not None and tiers.has_memory(sid):
                    if not self._suspect(r):
                        tiers.fill_ahead(sid)
                    return r
            # disk tier only: no live replica holds a fresher memory
            # copy, so the (shared) file IS the last flushed boundary —
            # any tiered replica can restore it; pick healthy ones by
            # load (stale replicas only as a last resort). The residency
            # stat is deduped per DISTINCT session directory: this runs
            # under the router's global lock, and an unknown-sid burst
            # must cost at most one stat per directory, not per replica.
            cands = []
            by_dir: dict[str, bool] = {}
            for r in live:
                tiers = r.engine.tiers
                if tiers is None:
                    continue
                d = tiers.disk_dir
                if d is None:
                    continue  # memory tiers already probed above
                hit = by_dir.get(d)
                if hit is None:
                    hit = by_dir[d] = tiers.has(sid)
                if hit:
                    cands.append(r)
            healthy = [r for r in cands if not self._suspect(r)]
            if cands:
                return min(healthy or cands,
                           key=lambda r: r.batcher.load())
        # fresh sessions avoid wedged (stale) and circuit-open
        # (partitioned) replicas while any healthy one exists — work
        # routed there hangs to client timeout (or fails fast against
        # an open circuit) while holding queue capacity
        fresh = [r for r in live if not self._suspect(r)]
        pool = fresh or live
        loads = [(r.batcher.load(), r) for r in pool]
        lo = min(load for load, _ in loads)
        cands = [r for load, r in loads if load == lo]
        return cands[next(self._rr) % len(cands)]

    def _drain_affinity_locked(self, sid: str,
                               live: list[Replica]) -> Replica | None:
        """Resolve a continuation whose session still lives on a
        DRAINING replica. Idle sessions are detach/restored onto a live
        peer right now — O(1) LSTM state, KBs, same cost bound as the
        fill-ahead this lock already tolerates — and the continuation
        follows, token-identical. A pinned (in-flight) session routes to
        the drainee itself while its scheduler still runs: it finishes
        the active work, and the session migrates once idle. Overlapping
        same-session submits can still race the detach window and fail
        once — the documented transient, unchanged by rollouts."""
        for d in self.replicas:
            if not d.draining or d.retired:
                continue
            cache = d.engine.cache
            if sid in cache:
                if cache.is_pinned(sid):
                    if d.alive():
                        return d
                    continue  # stopped mid-flight: unreachable in a
                    # controller-sequenced drain (load hits 0 first)
                try:
                    state = d.engine.detach_session(sid)
                except KeyError:
                    continue  # went idle and migrated under our probe
                healthy = [r for r in live if not self._suspect(r)]
                for target in sorted(healthy or live,
                                     key=lambda r: r.batcher.load()):
                    try:
                        target.engine.restore_session(sid, state)
                    except Exception:
                        continue  # every slot pinned: try the next
                    self.migrated_sessions += 1
                    self._m_migrated.inc()
                    return target
                # nowhere to put it: undo — serve where the state is
                d.engine.restore_session(sid, state)
                return d if d.alive() else None
            tiers = d.engine.tiers
            if (tiers is not None and tiers.has_memory(sid)
                    and d.alive()):
                # the drainee owns the freshest boundary and its
                # scheduler still runs — admission fills from the tier.
                # Once the controller stops the thread it evacuates the
                # tiers immediately, so the post-stop window falls
                # through to the shared-disk probe instead of hanging.
                return d
        return None

    # ---- rollout drain (controller-driven) ------------------------------

    def begin_drain(self, index: int) -> Replica:
        """Take replica ``index`` out of rotation for a rolling swap or
        resize. One replica at a time, and never the last routable one,
        so serving capacity stays >= N-1 for the whole rollout. The
        death sweep skips a draining replica — its scheduler thread is
        stopped deliberately, not dead."""
        with self._lock:
            rep = self._replica_locked(index)
            if rep.retired:
                raise ValueError(f"replica {index} is retired")
            for r in self.replicas:
                if r.draining and r is not rep:
                    raise RuntimeError(
                        f"replica {r.index} is already draining; "
                        "rollouts move one replica at a time")
            if not any(r.routable() and r is not rep
                       for r in self.replicas):
                raise RuntimeError(
                    "cannot drain the last routable replica")
            rep.draining = True
            return rep

    def end_drain(self, index: int) -> None:
        """Return a drained replica to rotation (rollout rejoin)."""
        with self._lock:
            self._replica_locked(index).draining = False

    def _replica_locked(self, index: int) -> Replica:
        for r in self.replicas:
            if r.index == index:
                return r
        raise ValueError(f"no replica with index {index}")

    # ---- canary shadowing ----------------------------------------------

    def set_canary(self, hook) -> None:
        """Install the rollout controller's shadow hook: called with
        every successfully admitted request, OUTSIDE the router lock.
        Exceptions are swallowed at the call site — a shadow must never
        fail the primary it mirrors."""
        self._canary = hook

    def clear_canary(self) -> None:
        self._canary = None

    # ---- replica-death handling ----------------------------------------

    def set_stopping(self, stopping: bool) -> None:
        """A deliberate ``stop()`` joins every scheduler thread — the
        sweep must not mistake that for death and start requeueing."""
        with self._lock:
            self._stopping = bool(stopping)

    def sweep(self) -> None:
        """Detect replicas whose scheduler thread DIED (started, then
        exited outside ``stop()``) and retire each exactly once.
        Piggybacked on submit() and the health probe — O(replicas) when
        nothing died, so no monitor thread is needed."""
        claimed: list[Replica] = []
        with self._lock:
            if self._stopping:
                return
            for r in self.replicas:
                # a draining replica's thread is stopped DELIBERATELY by
                # the rollout controller — not a death
                if (not r.retired and not r.draining
                        and r.thread is not None
                        and not r.thread.is_alive()):
                    r.retired = True  # claim under the lock, clean outside
                    claimed.append(r)
        for r in claimed:
            self._retire(r)

    def _retire(self, dead: Replica) -> None:
        """Runs OUTSIDE the router lock: reaches into the dead replica's
        batcher and cache (their own locks) and resubmits through the
        normal routing path."""
        drained = dead.batcher.drain_queue()
        failed = dead.batcher.fail_inflight(
            f"replica {dead.index} scheduler died mid-request; its decode "
            "position is indeterminate under dispatch-ahead windows "
            "(state lost — resend the request)")
        # migrate idle kept sessions FIRST so a drained continuation is
        # requeued to wherever its state now lives
        self.migrate_from(dead)
        self.requeue(drained, dead)
        with self._lock:
            self.failed_on_death += failed
        if failed:
            self._m_failed_death.inc(failed)

    def migrate_from(self, rep: Replica) -> tuple[int, int]:
        """Move every kept session off ``rep``: device-resident idle
        sessions via detach/restore onto a live healthy peer, tier-held
        sessions via :meth:`SessionTiers.evacuate` (shared disk when one
        exists, else adopted into a peer's host tier). Shared by
        replica-death retirement and the rollout controller's drain —
        which is why targets exclude ``rep`` explicitly and skip
        draining peers rather than relying on ``alive()`` alone.
        Returns ``(migrated, lost)`` and folds both into the router's
        aggregate counters. Runs OUTSIDE the router lock (takes it
        briefly per session)."""
        migrated = lost = 0
        for sid in rep.engine.cache.session_ids():
            if sid.startswith(PREFIX_SID_NAMESPACE):
                continue  # prefix entries are an optimisation — they die
                # with their replica and re-seed from live traffic
            try:
                state = rep.engine.detach_session(sid)
            except KeyError:
                continue  # raced an eviction; nothing to move
            placed = False
            with self._lock:
                targets = [r for r in self.replicas
                           if r.routable() and r is not rep]
            # healthy targets ONLY — no wedged fallback: a wedged
            # replica's engine lock may be held across a dispatch that
            # never returns, so restore_session could block this thread
            # (a health probe!) forever, and even a successful restore
            # parks the session where continuations hang to client
            # timeout. No healthy target → the session is lost, honestly.
            healthy = [r for r in targets if not self._suspect(r)]
            for target in sorted(healthy,
                                 key=lambda r: r.batcher.load()):
                try:
                    target.engine.restore_session(sid, state)
                except Exception:
                    continue  # cache full of pinned slots: try the next
                if not target.alive():
                    # the target died while the restore was in flight
                    # (double death): a session landed in a corpse's cache
                    # is unreachable — pull it back out and keep looking
                    # rather than reporting a migration that never helps
                    try:
                        state = target.engine.detach_session(sid)
                    except Exception:
                        break  # its own retirement already took the sid
                    continue
                placed = True
                break
            if placed:
                migrated += 1
                self._m_migrated.inc()
            else:
                lost += 1
        # tier-held sessions (spilled to host RAM / pending spills) are
        # still reachable — the replica's THREAD died (or was stopped),
        # not the process. Persist them to the shared disk tier when one
        # exists (any live replica then fills from it on demand), else
        # adopt them into a live healthy replica's host tier.
        if rep.engine.tiers is not None:
            persisted, homeless = rep.engine.tiers.evacuate()
            migrated += persisted
            if persisted:
                self._m_migrated.inc(persisted)
            for sid, state in homeless:
                with self._lock:
                    targets = [r for r in self.replicas
                               if r.routable() and r is not rep
                               and r.engine.tiers is not None
                               and not self._suspect(r)]
                target = min(targets, key=lambda r: r.batcher.load(),
                             default=None)
                if target is not None:
                    target.engine.tiers.adopt(sid, state)
                    migrated += 1
                    self._m_migrated.inc()
                else:
                    lost += 1
        with self._lock:
            self.migrated_sessions += migrated
            self.lost_sessions += lost
        return migrated, lost

    def requeue(self, reqs: list[Request], source: Replica) -> int:
        """Resubmit drained, not-yet-admitted requests through the
        normal routing path. Deadlines survive: ``Batcher.submit`` only
        stamps ``t_submit``/``deadline`` when unset, so a requeued
        request keeps its original clock. No global-bound recheck —
        these requests already held queue slots before the drain.
        Concurrent submits can still steal that headroom (the drain
        released it before this loop re-enqueues), so capacity is
        checked under the router lock (every client submit serialises
        through it) and a full affinity pick falls back to any live
        replica with room — no exception-driven retry, so the
        per-replica rejected counters never see these internal probes.
        Returns the number requeued; the rest fail honestly on
        ``source``'s batcher. Shared by replica-death retirement and
        the rollout controller's drain."""
        requeued = 0
        for req in reqs:
            try:
                with self._lock:
                    live = [r for r in self.replicas
                            if r.routable() and r is not source]
                    if not live:
                        raise RuntimeError("no live replica schedulers")
                    target = self._pick_locked(req, live)
                    if target.batcher.queued() >= self.queue_size:
                        if req.session_id is not None:
                            # never override affinity: rerouting a
                            # continuation to a replica without its state
                            # would fail it "unknown session" while the
                            # session is intact — queue-full is the
                            # honest verdict here
                            raise QueueFullError(
                                "the session's replica queue is full")
                        target = next(
                            (r for r in sorted(
                                live, key=lambda x: x.batcher.queued())
                             if r.batcher.queued() < self.queue_size),
                            None)
                    if target is None:
                        raise QueueFullError(
                            "every live replica's queue is full")
                    self._submit_to_locked(req, target)
                requeued += 1
                self._m_requeued.inc()
            except Exception as e:
                source.batcher.fail_request(
                    req, f"replica {source.index} went out of rotation "
                         f"and the request could not be requeued: {e}")
        with self._lock:
            self.requeued += requeued
        return requeued

    def reroute(self, req: Request, source: Replica) -> bool:
        """Re-pick a replica for a request whose remote RPC provably
        NEVER reached ``source`` (``TransportError.executed is False``:
        connect refused/timed out, or circuit fail-fast). Because
        nothing executed, resending — even a kept continuation, which
        the shared disk tier fills on the survivor — cannot double-
        decode. No global-bound recheck (the request already holds its
        admission slot); bounded by the fleet size so a total outage
        settles honestly instead of ping-ponging. Returns True when a
        new replica accepted the request."""
        req.reroutes += 1
        if req.reroutes > max(len(self.replicas) - 1, 1):
            return False
        try:
            with self._lock:
                live = [r for r in self.replicas
                        if r.routable() and r is not source]
                if not live:
                    return False
                self._submit_to_locked(req, self._pick_locked(req, live))
                self.rerouted += 1
        except Exception:
            return False
        self._m_rerouted.inc()
        return True

    # ---- views ---------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "replicas": len(self.replicas),
                "live": sum(1 for r in self.replicas if r.alive()),
                "retired": [r.index for r in self.replicas if r.retired],
                "draining": [r.index for r in self.replicas
                             if r.draining],
                "queue_size": self.queue_size,
                "routed": {str(k): v
                           for k, v in sorted(self.routed.items())},
                "rejected": self.rejected,
                "shed_by_class": dict(self.shed),
                "tenant_limited": dict(self.tenant_limited),
                "tenant_rate": self.tenant_rate,
                "best_effort_bound": self._best_effort_bound,
                "best_effort_frac": self.best_effort_frac,
                "requeued": self.requeued,
                "rerouted": self.rerouted,
                "failed_on_death": self.failed_on_death,
                "migrated_sessions": self.migrated_sessions,
                "lost_sessions": self.lost_sessions,
            }
