"""Global prefix-state fabric: a radix trie of recurrent carries
(ISSUE 19 — ROADMAP open item 4 generalised from the exact-match
``PrefixCache``).

An LSTM's state after ANY prefix is one O(1) ``(h, c)`` pair per layer,
so prefix sharing needs no length-proportional KV plumbing — a shared
prefix is one device slot. The exact-match ``PrefixCache`` only reuses
prefixes that byte-match a previously-inserted stride-aligned key and
caps capacity at ``max_entries`` device-backed entries; at template-mix
scale (tenant preamble x few-shot template x unique suffix) that LRU
thrashes and most admissions recompute a preamble the fleet has run
thousands of times. :class:`PrefixTrie` replaces the flat dict with a
**radix tree over token sequences whose nodes own carry snapshots**:

- :meth:`lookup` walks the trie to the LONGEST stateful node on the
  prompt's path — any shared prefix wins, not just exact re-prompts —
  with the matched length still capped at ``len(prompt) - 1`` so greedy
  output stays token-identical to an uncached run;
- the batcher's stride-aligned insert points (every chunk stop of a
  resumed prefill) become interior nodes, so ONE cold tenant-preamble
  prefill warms every descendant template;
- cold nodes spill through :class:`SessionTiers` (the state cache's
  eviction listener keeps the state host-side, ``slot=None``) under a
  configurable **host-tier byte bound**; a later hit promotes the node
  back for one host->device copy;
- eviction is leaf-first over zero-ref nodes with subtree accounting
  (``stateful_desc``): interior nodes — the high-fanout preambles —
  outlive their leaves;
- hot inserts propagate cross-replica (:class:`PrefixPropagator`) over
  the PR 13/17 remote transport: circuit-breaker aware, idempotent by
  node token-bytes hash, so one replica's prefill warms the fleet.

Every contract the exact-match cache established holds here: backing
slots live in the reserved ``prefix/`` namespace, lookups refcount-pin
their node's backing slot until the resumed prefill is dispatched, and
the trie shares the state cache's reentrant lock — the eviction
listener fires under it, and a private lock would ABBA with the
``acquire``/``pin`` calls made from trie methods (the
``viol_trie_lock`` / ``clean_trie_lock`` graftlint fixture pair keeps
that discipline checked). The propagator's enqueue under the lock is a
deque append only; the device fetch and the network POST happen on its
worker thread outside the lock (graftlint io-under-lock / host-sync).
"""

from __future__ import annotations

import base64
import hashlib
import threading
import time
from collections import OrderedDict, deque

import numpy as np

from .. import obs
from .state_cache import (
    PREFIX_SID_NAMESPACE,
    CacheFullError,
    DetachedState,
    StateCache,
)

__all__ = ["PrefixPropagator", "PrefixTrie", "TrieNode"]


class TrieNode:
    """One radix-trie node: the compressed token ``edge`` from its
    parent, children keyed by their edge's first token, and — when
    stateful (``sid`` is not None) — a carry snapshot in a refcounted
    state-cache slot under the ``prefix/`` namespace. ``slot`` is None
    while the node is SPILLED (state lives in the host tier until a
    lookup promotes it back). ``stateful_desc`` counts stateful nodes
    strictly below — the leaf-first eviction's subtree accounting."""

    __slots__ = ("edge", "children", "parent", "length", "key", "sid",
                 "slot", "refs", "stateful_desc")

    def __init__(self, edge: tuple, parent: "TrieNode | None"):
        self.edge = edge
        self.children: dict[int, TrieNode] = {}
        self.parent = parent
        self.length = (0 if parent is None
                       else parent.length + len(edge))
        self.key: bytes | None = None   # set while stateful
        self.sid: str | None = None
        self.slot: int | None = None
        self.refs = 0
        self.stateful_desc = 0


class PrefixTrie:
    """Radix-trie prefix-state store over the :class:`StateCache` —
    duck-type compatible with ``PrefixCache`` (``lookup`` / ``release``
    / ``insert`` / ``boundary`` / ``clear`` / ``stats`` and entry
    objects exposing ``slot`` / ``length`` / ``sid`` / ``refs``), so the
    batcher's admission and insert paths drive it unchanged.

    ``max_nodes`` bounds STATEFUL nodes (each device-resident one holds
    a state-cache slot); ``host_bytes`` bounds the spilled-node host
    footprint (each spilled state is ``state_bytes`` =
    2 * layers * hidden * 4). Structural split nodes are token tuples
    only — a few dozen bytes each — and are pruned/merged when the
    state they separated is evicted."""

    def __init__(self, cache: StateCache, *, stride: int = 8,
                 max_nodes: int = 64, host_bytes: int = 64 * 2 ** 20,
                 registry=None, tiers=None):
        if stride < 1:
            raise ValueError(f"stride must be >= 1, got {stride}")
        if max_nodes < 1:
            raise ValueError(f"max_nodes must be >= 1, got {max_nodes}")
        if host_bytes < 0:
            raise ValueError(f"host_bytes must be >= 0, got {host_bytes}")
        self.cache = cache
        self.stride = stride
        self.max_nodes = max_nodes
        self.host_bytes = int(host_bytes)
        self.tiers = tiers
        self._lock = cache._lock  # shared on purpose (see module doc)
        self.root = TrieNode((), None)
        # LRU over stateful nodes (key -> node, oldest first): the
        # eviction scan order AND the exact-key dedup index
        self._stateful: OrderedDict[bytes, TrieNode] = OrderedDict()
        self._by_sid: dict[str, TrieNode] = {}
        self._sid_counter = 0
        self._spilled_nodes = 0
        self.state_bytes = 2 * cache.num_layers * cache.hidden_size * 4
        # recently-applied remote insert hashes (idempotent replay
        # dedup for at-least-once propagation delivery), bounded LRU
        self._applied: OrderedDict[str, None] = OrderedDict()
        self._applied_max = 4096
        self._propagator: PrefixPropagator | None = None
        self.hits = 0
        self.misses = 0
        self.inserts = 0
        self.evictions = 0
        self.invalidated = 0
        self.spilled = 0
        self.promoted = 0
        self.propagated_in = 0       # remote inserts applied locally
        self.propagation_dedup = 0   # remote inserts already resident
        reg = obs.REGISTRY if registry is None else registry
        self._m = reg.counter(
            "serve_prefix_trie_events_total",
            "prefix-trie outcomes (hit/miss/insert/evict/invalidate/"
            "spill/promote)",
            labelnames=("event",))
        self._m_hit = self._m.labels(event="hit")
        self._m_miss = self._m.labels(event="miss")
        self._m_insert = self._m.labels(event="insert")
        self._m_evict = self._m.labels(event="evict")
        self._m_invalidate = self._m.labels(event="invalidate")
        self._m_spill = self._m.labels(event="spill")
        self._m_promote = self._m.labels(event="promote")
        self._m_prop = reg.counter(
            "serve_prefix_trie_propagation_total",
            "cross-replica prefix-node propagation events (out = sent "
            "to a peer, in = applied from a peer, dedup = replay or "
            "already-resident, error = transport/peer failure)",
            labelnames=("direction",))
        cache.evict_listeners.append(self._on_slot_evicted_locked)

    # ---- key helpers ---------------------------------------------------

    @staticmethod
    def _key(tokens) -> bytes:
        return np.asarray(tokens, np.int32).tobytes()

    @staticmethod
    def token_hash(tokens) -> str:
        """sha256 of the node's token bytes — the propagation plane's
        idempotency key."""
        return hashlib.sha256(PrefixTrie._key(tokens)).hexdigest()

    def boundary(self, length: int) -> int:
        """Largest cacheable prefix length for a ``length``-token
        prompt (same contract as ``PrefixCache.boundary``)."""
        k = ((length - 1) // self.stride) * self.stride
        return k if k >= self.stride else 0

    def attach_propagator(self, propagator: "PrefixPropagator") -> None:
        self._propagator = propagator

    # ---- lookup / promote ----------------------------------------------

    def lookup(self, prompt) -> tuple[TrieNode | None, int]:
        """Longest-match walk: returns ``(node, matched_len)`` for the
        DEEPEST stateful node on the prompt's path with
        ``length <= len(prompt) - 1`` whose state is available (device
        slot, or promotable from the host tier), ref-held and pinned —
        the caller MUST :meth:`release` after dispatching the resumed
        prefill. Unlike the exact-match cache, any shared prefix
        matches — the prompt need never have been seen before."""
        toks = np.asarray(prompt, np.int32).reshape(-1).tolist()
        limit = len(toks) - 1
        with self._lock:
            candidates = []
            node = self.root
            depth = 0
            while depth < limit:
                child = node.children.get(toks[depth])
                if child is None:
                    break
                n = len(child.edge)
                # stateful nodes live only at node boundaries, so an
                # edge overrunning the match limit (or mismatching
                # partway) ends the walk — nothing deeper can qualify
                if depth + n > limit:
                    break
                if tuple(toks[depth:depth + n]) != child.edge:
                    break
                depth += n
                node = child
                if node.sid is not None:
                    candidates.append(node)
            # deepest-first: a spilled candidate whose tiered state was
            # lost drops out (invalidated) and the next-shallower
            # stateful ancestor still saves most of the prefill
            for cand in reversed(candidates):
                if cand.slot is None and not self._promote_locked(cand):
                    continue
                self._stateful.move_to_end(cand.key)
                # refresh the BACKING slot's state-cache recency too —
                # pin/unpin never reorder the LRU (reentrant RLock)
                self.cache.lookup(cand.sid)
                if cand.refs == 0:
                    self.cache.pin(cand.sid)
                cand.refs += 1
                self.hits += 1
                self._m_hit.inc()
                return cand, cand.length
            self.misses += 1
            self._m_miss.inc()
            return None, 0

    def _promote_locked(self, node: TrieNode) -> bool:
        """Restore a SPILLED node's state from the tiers into a fresh
        slot. Returns False and drops the node when the tiered state is
        gone; False without dropping when no slot can be had right now
        (every slot pinned — transient miss). Memory-only fill: this
        runs with the shared cache lock HELD (graftlint io-under-lock —
        prefix states never reach the disk tier)."""
        try:
            slot, fresh = self.cache.acquire(node.sid)
        except CacheFullError:
            return False
        if fresh and (self.tiers is None
                      or not self.tiers.fill_memory(node.sid, slot)):
            self.cache.release(node.sid)
            self._drop_state_locked(node)
            self.invalidated += 1
            self._m_invalidate.inc()
            return False
        node.slot = slot
        self._spilled_nodes -= 1
        self.promoted += 1
        self._m_promote.inc()
        return True

    def release(self, node: TrieNode) -> None:
        """Drop one ref; the last ref unpins the backing slot. Safe
        after invalidation (the sid index no longer points here)."""
        with self._lock:
            if node.refs > 0:
                node.refs -= 1
            if node.refs == 0 and node.sid is not None \
                    and self._by_sid.get(node.sid) is node:
                self.cache.unpin(node.sid)

    # ---- insert --------------------------------------------------------

    def insert(self, tokens, src_slot: int) -> bool:
        """Snapshot the state in ``src_slot`` (== the state after
        exactly ``tokens``) into the trie node at that token path,
        creating/splitting radix nodes as needed. Returns False — never
        raises — on dedup, all-nodes-ref-held, or slot exhaustion:
        prefix caching degrades, it does not fail requests."""
        toks = tuple(int(t) for t in np.asarray(tokens, np.int32).reshape(-1))
        if not toks:
            return False
        key = self._key(toks)
        with self._lock:
            existing = self._stateful.get(key)
            if existing is not None:
                # dedup-hit is a hotness signal: refresh both LRUs
                self._stateful.move_to_end(key)
                self.cache.lookup(existing.sid)
                return False
            if not self._make_room_locked():
                return False
            self._sid_counter += 1
            sid = f"{PREFIX_SID_NAMESPACE}{self._sid_counter}"
            try:
                slot, _ = self.cache.acquire(sid)
            except CacheFullError:
                return False
            self.cache.copy_slot(src_slot, slot)
            node = self._ensure_path_locked(toks)
            self._set_state_locked(node, key, sid, slot)
            self.inserts += 1
            self._m_insert.inc()
            if self._propagator is not None:
                self._propagator.enqueue_locked(toks, sid)
            return True

    def adopt_remote(self, tokens, state: DetachedState,
                     token_hash: str | None = None) -> str:
        """Apply one propagated node from a peer: idempotent by token
        path (already-stateful node = dedup) and by recently-applied
        hash (at-least-once delivery replay). The state lands in a
        device slot via the warmed batch-1 scatter; a cold adoptee just
        LRU-spills into the host tier like any local node. Returns
        ``"applied"`` | ``"dedup"`` | ``"rejected"``."""
        toks = tuple(int(t) for t in np.asarray(tokens, np.int32).reshape(-1))
        # propagated lengths must stay stride multiples: the batcher's
        # warmup only covers resume starts at stride multiples, and an
        # off-stride node would dispatch an unwarmed remainder program
        if not toks or len(toks) % self.stride != 0:
            return "rejected"
        if state.h.shape != (self.cache.num_layers, self.cache.hidden_size):
            return "rejected"
        h = token_hash or self.token_hash(toks)
        key = self._key(toks)
        with self._lock:
            if h in self._applied or key in self._stateful:
                if key in self._stateful:
                    self._stateful.move_to_end(key)
                self.propagation_dedup += 1
                self._m_prop.labels(direction="dedup").inc()
                return "dedup"
            if not self._make_room_locked():
                return "rejected"
            self._sid_counter += 1
            sid = f"{PREFIX_SID_NAMESPACE}{self._sid_counter}"
            try:
                slot, _ = self.cache.acquire(sid)
            except CacheFullError:
                return "rejected"
            self.cache.write_slots(
                np.asarray([slot]), np.asarray(state.h)[:, None, :],
                np.asarray(state.c)[:, None, :])
            node = self._ensure_path_locked(toks)
            self._set_state_locked(node, key, sid, slot)
            self._applied[h] = None
            self._applied.move_to_end(h)
            while len(self._applied) > self._applied_max:
                self._applied.popitem(last=False)
            self.propagated_in += 1
            self._m_prop.labels(direction="in").inc()
            return "applied"

    # ---- radix structure (all under the shared lock) -------------------

    def _ensure_path_locked(self, toks: tuple) -> TrieNode:
        """Walk/create the radix path for ``toks``, splitting compressed
        edges as needed, and return the node at exactly that depth."""
        node = self.root
        depth = 0
        while depth < len(toks):
            first = toks[depth]
            child = node.children.get(first)
            if child is None:
                leaf = TrieNode(toks[depth:], node)
                node.children[first] = leaf
                return leaf
            edge = child.edge
            # longest common prefix of the remaining tokens and the edge
            m = 0
            remaining = len(toks) - depth
            while m < len(edge) and m < remaining \
                    and edge[m] == toks[depth + m]:
                m += 1
            if m == len(edge):
                node = child
                depth += m
                continue
            # split child's edge at m: mid owns edge[:m], child keeps
            # the tail. mid inherits child's subtree accounting.
            mid = TrieNode(edge[:m], node)
            mid.stateful_desc = child.stateful_desc + (
                1 if child.sid is not None else 0)
            node.children[first] = mid
            child.edge = edge[m:]
            child.parent = mid
            mid.children[edge[m]] = child
            if m == remaining:
                return mid
            node = mid
            depth += m
        return node

    def _set_state_locked(self, node: TrieNode, key: bytes, sid: str,
                          slot: int) -> None:
        node.key, node.sid, node.slot, node.refs = key, sid, slot, 0
        self._stateful[key] = node
        self._stateful.move_to_end(key)
        self._by_sid[sid] = node
        p = node.parent
        while p is not None:
            p.stateful_desc += 1
            p = p.parent

    def _drop_state_locked(self, node: TrieNode) -> None:
        """Remove a node's state (NOT its slot — callers own that) and
        prune/merge the structure it no longer justifies."""
        if node.sid is None:
            return
        if node.slot is None:
            self._spilled_nodes -= 1
        self._stateful.pop(node.key, None)
        self._by_sid.pop(node.sid, None)
        node.key = node.sid = node.slot = None
        node.refs = 0
        p = node.parent
        while p is not None:
            p.stateful_desc -= 1
            p = p.parent
        self._prune_locked(node)

    def _prune_locked(self, node: TrieNode) -> None:
        # delete childless structural nodes upward, then merge a
        # single-child structural survivor with its child (radix
        # compression is an invariant, not a one-time construction)
        while (node.parent is not None and node.sid is None
               and not node.children):
            parent = node.parent
            parent.children.pop(node.edge[0], None)
            node = parent
        if (node.parent is not None and node.sid is None
                and len(node.children) == 1):
            (child,) = node.children.values()
            child.edge = node.edge + child.edge
            child.parent = node.parent
            node.parent.children[node.edge[0]] = child

    # ---- eviction / spill ----------------------------------------------

    def _victim_locked(self) -> TrieNode | None:
        """Leaf-first zero-ref victim in LRU order: prefer nodes with
        no stateful descendants (evicting an interior preamble node
        before its template leaves would re-cost the shared prefill the
        subtree exists to save); fall back to any zero-ref node so the
        cap stays hard."""
        fallback = None
        for node in self._stateful.values():
            if node.refs:
                continue
            if node.stateful_desc == 0:
                return node
            if fallback is None:
                fallback = node
        return fallback

    def _make_room_locked(self) -> bool:
        while len(self._stateful) >= self.max_nodes:
            victim = self._victim_locked()
            if victim is None:
                return False  # every node is mid-use
            self._evict_node_locked(victim)
        return True

    def _evict_node_locked(self, node: TrieNode) -> None:
        sid = node.sid
        self._drop_state_locked(node)
        self.cache.release(sid)
        if self.tiers is not None:
            # memory tiers only: this fires under the shared cache lock
            # and prefix states never reach the disk tier
            self.tiers.discard_memory(sid)
        self.evictions += 1
        self._m_evict.inc()

    def _on_slot_evicted_locked(self, sid: str, slot: int) -> None:
        # state-cache LRU took a backing slot. Tiered: the SessionTiers
        # listener captured the state, so the node survives SPILLED and
        # a later hit promotes it back. Untiered: the node is garbage.
        # The _locked suffix is the held-lock calling contract.
        node = self._by_sid.get(sid)
        if node is None:
            return
        if self.tiers is not None:
            node.slot = None
            self._spilled_nodes += 1
            self.spilled += 1
            self._m_spill.inc()
            self._enforce_host_bound_locked()
            return
        self._drop_state_locked(node)
        self.invalidated += 1
        self._m_invalidate.inc()

    def _enforce_host_bound_locked(self) -> None:
        """Keep the spilled-node host footprint within ``host_bytes``:
        evict the coldest zero-ref SPILLED nodes (memory-only discard —
        no IO under the hot lock) until the bound holds."""
        while self._spilled_nodes * self.state_bytes > self.host_bytes:
            victim = None
            for node in self._stateful.values():
                if node.slot is None and node.refs == 0:
                    victim = node
                    break
            if victim is None:
                return
            self._evict_node_locked(victim)

    def clear(self) -> None:
        """Evict every node that is not mid-use (refs == 0) — the
        rollout controller's drained-replica reset, same contract as
        ``PrefixCache.clear``."""
        with self._lock:
            for node in list(self._stateful.values()):
                if node.refs == 0:
                    self._evict_node_locked(node)

    # ---- views ---------------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._stateful)

    def _structural_count_locked(self) -> int:
        count = 0
        stack = [self.root]
        while stack:
            node = stack.pop()
            if node is not self.root and node.sid is None:
                count += 1
            stack.extend(node.children.values())
        return count

    def stats(self) -> dict:
        with self._lock:
            spilled_nodes = self._spilled_nodes
            return {
                "mode": "trie",
                "entries": len(self._stateful),
                "stride": self.stride,
                "max_nodes": self.max_nodes,
                "nodes_device": len(self._stateful) - spilled_nodes,
                "nodes_spilled": spilled_nodes,
                "nodes_structural": self._structural_count_locked(),
                "host_bytes": self.host_bytes,
                "state_bytes": self.state_bytes,
                "spilled_bytes": spilled_nodes * self.state_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "inserts": self.inserts,
                "evictions": self.evictions,
                "invalidated": self.invalidated,
                "spilled": self.spilled,
                "promoted": self.promoted,
                "propagated_out": (0 if self._propagator is None
                                   else self._propagator.sent),
                "propagated_in": self.propagated_in,
                "propagation_dedup": self.propagation_dedup,
                "propagation_errors": (0 if self._propagator is None
                                       else self._propagator.errors),
            }


class PrefixPropagator:
    """Cross-replica prefix-node propagation worker.

    ``enqueue_locked`` (called by the trie under the shared cache lock)
    appends a job and returns — no device op, no IO. The daemon worker
    drains jobs in batches: it captures array REFERENCES + slot under
    the lock (zero device ops — jax arrays are immutable functional
    snapshots), performs the ONE designated device->host fetch
    (``StateCache.fetch_detached_batch``) outside it, and POSTs each
    node to every peer over the retrying :class:`PeerTransport` —
    skipping peers whose circuit is open or flap-damped (``suspect``),
    with ``replay_safe=True`` because the receiver dedups by token-hash
    (idempotent inserts over at-least-once delivery)."""

    BATCH = 16

    def __init__(self, trie: PrefixTrie, peers, *,
                 rpc_timeout: float = 5.0, max_queue: int = 256):
        self.trie = trie
        # peers: objects exposing ``transport`` (PeerTransport) and
        # ``suspect()`` — serve/remote.RemoteBatcher shims in production
        self.peers = list(peers)
        self.rpc_timeout = float(rpc_timeout)
        self.max_queue = int(max_queue)
        self._lock = trie._lock  # shared: enqueue fires mid-insert
        self._queue: deque = deque()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self.sent = 0        # node deliveries accepted by a peer
        self.errors = 0      # transport/peer failures (after retries)
        self.dropped = 0     # queue overflow (newest-first kept)

    def enqueue_locked(self, toks: tuple, sid: str) -> None:
        if not self.peers:
            return
        if len(self._queue) >= self.max_queue:
            self._queue.popleft()
            self.dropped += 1
        self._queue.append((toks, sid))
        self._ensure_worker_locked()

    def _ensure_worker_locked(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self.run, name="serve-prefix-propagate",
                daemon=True)
            self._thread.start()

    def close(self, timeout: float = 5.0) -> None:
        self._stop.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout)

    def run(self) -> None:
        """Worker loop (scheduler-closure discipline: the only blocking
        device call is the designated batched fetch)."""
        while not self._stop.is_set():
            jobs = []
            with self._lock:
                while self._queue and len(jobs) < self.BATCH:
                    toks, sid = self._queue.popleft()
                    node = self.trie._by_sid.get(sid)
                    if node is None or node.slot is None:
                        continue  # evicted/spilled before send: cold
                    jobs.append((toks, self.trie.cache.h,
                                 self.trie.cache.c, node.slot))
            if not jobs:
                # poll, batching bursts of inserts into one fetch
                self._stop.wait(0.05)
                continue
            states = StateCache.fetch_detached_batch(
                [(h, c, slot) for _, h, c, slot in jobs])
            for (toks, _, _, _), state in zip(jobs, states):
                self._send(toks, state)

    def _send(self, toks: tuple, state: DetachedState) -> None:
        from .transport import PeerHTTPError, TransportError

        body = {
            "tokens": list(toks),
            "hash": PrefixTrie.token_hash(toks),
            "layers": int(state.h.shape[0]),
            "hidden": int(state.h.shape[1]),
            "h": base64.b64encode(
                np.ascontiguousarray(state.h, np.float32).tobytes()
            ).decode("ascii"),
            "c": base64.b64encode(
                np.ascontiguousarray(state.c, np.float32).tobytes()
            ).decode("ascii"),
        }
        for peer in self.peers:
            if peer.suspect():
                continue  # circuit open / flap-damped: skip, not queue
            try:
                peer.transport.rpc_post(
                    "/replica/prefix", body, method="prefix",
                    timeout=self.rpc_timeout, replay_safe=True)
            except (TransportError, PeerHTTPError):
                self.errors += 1
                self.trie._m_prop.labels(direction="error").inc()
            else:
                self.sent += 1
                self.trie._m_prop.labels(direction="out").inc()


def decode_propagated_state(body: dict, *, num_layers: int,
                            hidden_size: int) -> DetachedState | None:
    """Decode + validate a ``/replica/prefix`` POST body into a
    :class:`DetachedState`; None when malformed or the hash does not
    match the token bytes (the idempotency key doubles as an integrity
    check). Runs on the HTTP handler thread, never under a hot lock."""
    try:
        toks = np.asarray(body["tokens"], np.int32).reshape(-1)
        layers = int(body["layers"])
        hidden = int(body["hidden"])
        if (layers, hidden) != (num_layers, hidden_size):
            return None
        want = hashlib.sha256(toks.tobytes()).hexdigest()
        if body.get("hash") != want:
            return None
        n = layers * hidden
        h = np.frombuffer(base64.b64decode(body["h"]),
                          np.float32)
        c = np.frombuffer(base64.b64decode(body["c"]),
                          np.float32)
        if h.size != n or c.size != n:
            return None
        return DetachedState(h=h.reshape(layers, hidden).copy(),
                             c=c.reshape(layers, hidden).copy())
    except (KeyError, ValueError, TypeError):
        return None


# time is imported for parity with the sibling modules' worker idiom;
# the propagator paces itself off the stop event's timed wait instead
# of wall-clock arithmetic (graftlint wallclock-timing).
_ = time
