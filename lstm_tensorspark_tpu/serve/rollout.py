"""Rollout controller: zero-downtime weight swaps and slot resizes.

The missing terminal stage of the training loop (ROADMAP item 3, the
pjit/TPUv4 production-training framing in PAPERS.md): ``supervise``
publishes each new best checkpoint into the :class:`~.registry.
ModelRegistry`, and this controller rolls it across the live fleet —
one replica at a time, through the router's drain machinery, so serving
capacity never drops below N−1 and no admitted request is lost.

One replica's roll is four phases, each counted in
``serve_rollout_total{phase,outcome}``:

1. **drain** — ``Router.begin_drain`` takes the replica out of fresh
   routing (continuations for its kept sessions migrate just-in-time in
   the router's pick; see ``_drain_affinity_locked``); its queued,
   not-yet-admitted work is requeued onto the peers with deadlines
   intact (``Router.requeue``); then the controller waits for in-flight
   work to finish (``Batcher.load() == 0``). A replica that never
   quiesces inside ``drain_timeout_s`` is returned to rotation and the
   rollout aborts with ``outcome="stuck"`` (the runbook row). Only then
   is the scheduler thread stopped — deliberately, which is why the
   router's death sweep skips draining replicas — and the remaining
   idle kept sessions move to peers via the PR 7 detach/restore path
   (``Router.migrate_from``: an uninterrupted-run-identical migration,
   the token-identity half of the gate drill).
2. **swap** — params come OUT OF THE REGISTRY (sha256-verified at load;
   a corrupt artifact quarantines and aborts the rollout, it is never
   served), with a config-fingerprint check against the engine's
   resident architecture (the version-skew guard). Same model id ⇒
   ``ServeEngine.swap_model``: params are traced ARGUMENTS to every
   compiled program, so same-shaped new weights reuse every compiled
   program — zero compiles. A new model id ⇒ ``add_model`` under its
   own compile-key namespace.
3. **warmup** — the batcher replays the server's remembered warmup spec
   off-path, so a NEW model id's programs (or a resize's new cache
   shapes) compile before traffic returns. ``BENCH_serve_r08.json``
   asserts zero mid-traffic compiles across the whole swap.
4. **rejoin** — a fresh scheduler thread starts and
   ``Router.end_drain`` returns the replica to rotation.

A replica whose scheduler DIES mid-drain (chaos ``replica_die``) is
handed back to the router's normal death path (end_drain + sweep →
retire: requeue/fail/migrate) and the rollout continues on the
survivors — the fleet still converges to the new version.

**Canary** (``canary_every > 0``, fleets of ≥ 2 local replicas): the
LAST local replica is rolled first, then a router hook shadows every
Nth stateless request onto it — a cloned best-effort request with
``use_prefix=False`` so the probe neither perturbs nor is flattered by
the shared prefix cache. Completed (primary, shadow) pairs are
token-diffed into ``serve_canary_diff_total{verdict}`` and the
TTFT distributions of both sides are summarised into a comparison
report BEFORE the remaining replicas promote. The report is
informational by default — new weights legitimately decode different
tokens; ``require_canary_match=True`` turns a diff into an abort (the
canary-diff-regression runbook row).

**Resize** (the PR 14 autotuner residual): device-slot count was frozen
at boot shape because the state arrays' shapes are baked into every
compiled program. ``request_resize`` runs the same drain → reshape
(``ServeEngine.resize_slots`` + ``Batcher.set_max_active``) → warmup →
rejoin move per replica, so the autotuner can ask for capacity instead
of being capped at boot.

Thread lifecycle is the AutoTuner contract: ``_run`` reads
``self._stop``; ``stop()`` sets it and joins ``self._thread`` (the
graftlint ``thread-lifecycle`` fixture pair ``viol_rollout`` /
``clean_rollout`` pins this shape).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque

from .batcher import Request
from .engine import GREEDY
from .registry import ModelRegistry, config_fingerprint

#: phases of one replica's roll, in order (metric label values)
PHASES = ("drain", "swap", "warmup", "rejoin")


class RolloutError(RuntimeError):
    """A rollout step failed; the fleet was left serving (the failing
    replica rejoined on its old weights, or retired through the normal
    death path)."""


class _ReplicaDied(RuntimeError):
    """The drainee's scheduler died mid-drain (chaos ``replica_die``) —
    handled by handing the corpse to the router's death path."""


def _pctl(values: list[float], q: float) -> float | None:
    if not values:
        return None
    xs = sorted(values)
    i = min(len(xs) - 1, int(q * (len(xs) - 1) + 0.5))
    return xs[i]


class RolloutController:
    """Drives rolling swaps/resizes over a :class:`~.server.ServeServer`
    (module docstring). ``start()``/``stop()`` manage the controller
    thread (the server's lifecycle drives them); ``run_rollout`` /
    ``run_resize`` execute one move synchronously (tests and the smoke
    drill call them directly); ``request_*`` enqueue for the thread."""

    def __init__(self, server, registry, *,
                 canary_every: int = 0,
                 canary_min_pairs: int = 8,
                 canary_timeout_s: float = 10.0,
                 require_canary_match: bool = False,
                 drain_timeout_s: float = 30.0,
                 interval_s: float = 0.25,
                 history: int = 32):
        if canary_every < 0:
            raise ValueError(
                f"canary_every must be >= 0, got {canary_every}")
        if drain_timeout_s <= 0:
            raise ValueError(
                f"drain_timeout_s must be > 0, got {drain_timeout_s}")
        self.server = server
        self.registry = (registry if isinstance(registry, ModelRegistry)
                         else ModelRegistry(registry))
        self.canary_every = int(canary_every)
        self.canary_min_pairs = int(canary_min_pairs)
        self.canary_timeout_s = float(canary_timeout_s)
        self.require_canary_match = bool(require_canary_match)
        self.drain_timeout_s = float(drain_timeout_s)
        self.interval_s = float(interval_s)
        reg = server.engine.metrics
        fam = reg.counter(
            "serve_rollout_total",
            "rollout-controller phase outcomes (phase=drain/swap/warmup/"
            "rejoin; outcome=ok/error/stuck — 'stuck' on drain is the "
            "stuck-drain runbook row)",
            labelnames=("phase", "outcome"))
        self._m_rollout = fam
        fam = reg.counter(
            "serve_canary_diff_total",
            "canary shadow-pair verdicts (match/diff/error); diff is "
            "informational unless require_canary_match is set",
            labelnames=("verdict",))
        self._m_canary = {v: fam.labels(verdict=v)
                         for v in ("match", "diff", "error")}
        # move queue + bookkeeping (guarded by _lock; the controller
        # thread pops, request_* and HTTP handlers push/read)
        self._lock = threading.Lock()
        self._queue: deque = deque()
        self._active: dict | None = None
        self._history: deque = deque(maxlen=history)
        self.rollouts = 0
        self.resizes = 0
        self.errors = 0
        self._last_error: str | None = None
        self.last_canary: dict | None = None
        # canary shadow state (its own lock: the router hook runs on
        # client threads while the controller thread collects)
        self._canary_lock = threading.Lock()
        self._pairs: list = []
        self._canary_counts = {"match": 0, "diff": 0, "error": 0,
                               "shadowed": 0, "skipped": 0}
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ---- lifecycle -----------------------------------------------------

    def start(self) -> "RolloutController":
        if self._thread is not None:
            raise RuntimeError("rollout controller already started")
        self._stop.clear()
        t = threading.Thread(target=self._run, name="serve-rollout",
                             daemon=True)
        self._thread = t
        t.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None

    def _run(self) -> None:
        # the wait IS the cadence: stop() parks the loop within one
        # interval (and aborts any in-progress drain wait)
        while not self._stop.wait(self.interval_s):
            with self._lock:
                move = self._queue.popleft() if self._queue else None
            if move is None:
                continue
            try:
                if move["kind"] == "rollout":
                    self.run_rollout(move["model"],
                                     version=move.get("version"))
                else:
                    self.run_resize(move["num_slots"])
            except Exception as e:
                # a failed move must degrade to "fleet keeps serving the
                # old version", never to a dead controller — recorded,
                # surfaced in /stats, the queue keeps draining
                with self._lock:
                    self.errors += 1
                    self._last_error = f"{type(e).__name__}: {e}"

    # ---- requests (async; the controller thread executes) ---------------

    def request_rollout(self, model_id: str,
                        version: int | None = None) -> dict:
        move = {"kind": "rollout", "model": str(model_id),
                "version": version}
        with self._lock:
            self._queue.append(move)
            return {**move, "queued": len(self._queue)}

    def request_resize(self, num_slots: int) -> dict:
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        move = {"kind": "resize", "num_slots": int(num_slots)}
        with self._lock:
            # collapse pending resizes — only the latest target matters
            self._queue = deque(m for m in self._queue
                                if m["kind"] != "resize")
            self._queue.append(move)
            return {**move, "queued": len(self._queue)}

    # ---- the moves (synchronous; tests/smoke call these directly) -------

    def _local_replicas(self) -> list:
        """The replicas this controller can swap: local engines only (a
        RemoteReplica's weights belong to its own host's controller)."""
        return [r for r in self.server.replicas
                if hasattr(getattr(r, "engine", None), "swap_model")]

    def run_rollout(self, model_id: str, version: int | None = None,
                    canary_every: int | None = None) -> dict:
        """Roll ``model_id`` (latest version by default) across every
        local replica. Returns the rollout record (also kept in
        ``stats()['history']``)."""
        locals_ = self._local_replicas()
        if not locals_:
            raise RolloutError("no local replicas to roll")
        # rescan first: the artifact being rolled was usually published
        # by ANOTHER process (supervise --registry-dir) after this
        # server's registry built its manifest at boot
        self.registry.scan()
        # decode ONCE against replica 0's param structure; each swap
        # re-places the host arrays onto its own replica's device/mesh
        meta, params = self.registry.load_params(
            model_id, locals_[0].engine.params, version)
        want = meta.get("config_hash")
        if want is not None:
            have = config_fingerprint(locals_[0].engine.cfg)
            if want != have:
                self._m_rollout.labels(phase="swap",
                                       outcome="error").inc()
                raise RolloutError(
                    f"{model_id} v{meta['version']} was trained on config "
                    f"{want}, the fleet serves {have} — refusing the swap "
                    "(version skew)")
        every = self.canary_every if canary_every is None else canary_every
        record = {"kind": "rollout", "model": meta["model"],
                  "version": meta["version"], "replicas": [],
                  "canary": None, "outcome": "ok",
                  # operator-facing record timestamps: wall clock intended
                  "t_start": time.time()}  # graftlint: disable=wallclock-timing
        with self._lock:
            self._active = record
        try:
            order = list(locals_)
            if every > 0 and len(order) > 1:
                # canary replica first: roll the LAST local replica, then
                # shadow-compare before the rest promote
                order = [order[-1]] + order[:-1]
                self._roll_one(order[0], meta, params, record)
                report = self._run_canary(order[0], meta, every)
                record["canary"] = report
                if (self.require_canary_match
                        and report["counts"]["diff"] > 0):
                    record["outcome"] = "canary_regression"
                    raise RolloutError(
                        f"canary diffed on {report['counts']['diff']} of "
                        f"{report['counts']['compared']} shadow pairs — "
                        "aborting promotion (the canary replica keeps the "
                        "new version for diagnosis)")
                order = order[1:]
            for rep in order:
                self._roll_one(rep, meta, params, record)
            with self._lock:
                self.rollouts += 1
        except Exception as e:
            if record["outcome"] == "ok":
                record["outcome"] = f"error: {e}"
            raise
        finally:
            record["t_end"] = time.time()  # graftlint: disable=wallclock-timing
            with self._lock:
                self._active = None
                self._history.append(record)
        return record

    def run_resize(self, num_slots: int) -> dict:
        """Drain-and-rejoin each local replica with ``num_slots`` device
        slots (the PR 14 residual: slot count is no longer a frozen boot
        shape). New cache shapes mean new programs — the warmup phase
        recompiles the lattice off-path before rejoin."""
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        record = {"kind": "resize", "num_slots": int(num_slots),
                  "replicas": [], "outcome": "ok",
                  # operator-facing record timestamps: wall clock intended
                  "t_start": time.time()}  # graftlint: disable=wallclock-timing
        with self._lock:
            self._active = record
        try:
            for rep in self._local_replicas():
                if rep.engine.cache.num_slots == num_slots:
                    continue  # already at target (idempotent requests)
                self._roll_one(rep, None, None, record,
                               num_slots=num_slots)
            with self._lock:
                self.resizes += 1
        except Exception as e:
            if record["outcome"] == "ok":
                record["outcome"] = f"error: {e}"
            raise
        finally:
            record["t_end"] = time.time()  # graftlint: disable=wallclock-timing
            with self._lock:
                self._active = None
                self._history.append(record)
        return record

    # ---- one replica's drain → swap/resize → warmup → rejoin ------------

    def _roll_one(self, rep, meta, params, record,
                  num_slots: int | None = None) -> None:
        entry = {"replica": rep.index, "phases": []}
        record["replicas"].append(entry)
        router = self.server.router
        try:
            self._phase(entry, "drain", self._drain, rep)
        except _ReplicaDied:
            # chaos mid-drain: hand the corpse to the normal death path
            # (requeue/fail/migrate) and keep rolling the survivors —
            # the fleet still converges to the new version
            router.end_drain(rep.index)
            router.sweep()
            return
        try:
            if num_slots is not None:
                self._phase(entry, "swap", self._resize_one, rep,
                            num_slots)
            else:
                self._phase(entry, "swap", rep.engine.swap_model, params,
                            model_id=meta["model"],
                            version=meta["version"])
            self._phase(entry, "warmup", self._warmup_one, rep)
        finally:
            # ALWAYS rejoin: even a failed swap leaves the engine on its
            # previous (or half-new, for a failed warmup) weights —
            # serving capacity comes back either way, and the phase
            # counters say which step to diagnose
            self._phase(entry, "rejoin", self._rejoin, rep)

    def _phase(self, entry: dict, phase: str, fn, *a, **kw):
        try:
            out = fn(*a, **kw)
        except _ReplicaDied:
            self._m_rollout.labels(phase=phase, outcome="error").inc()
            entry["phases"].append({"phase": phase, "outcome": "died"})
            raise
        except RolloutError as e:
            outcome = "stuck" if "quiesce" in str(e) else "error"
            self._m_rollout.labels(phase=phase, outcome=outcome).inc()
            entry["phases"].append({"phase": phase, "outcome": outcome,
                                    "error": str(e)})
            raise
        except Exception as e:
            self._m_rollout.labels(phase=phase, outcome="error").inc()
            entry["phases"].append({"phase": phase, "outcome": "error",
                                    "error": f"{type(e).__name__}: {e}"})
            raise
        self._m_rollout.labels(phase=phase, outcome="ok").inc()
        entry["phases"].append({"phase": phase, "outcome": "ok"})
        return out

    def _drain(self, rep) -> None:
        router = self.server.router
        router.begin_drain(rep.index)
        # requeue the not-yet-admitted backlog FIRST (deadlines ride
        # along), then wait for in-flight work to finish
        router.requeue(rep.batcher.drain_queue(), rep)
        deadline = time.monotonic() + self.drain_timeout_s
        while rep.batcher.load() > 0:
            if rep.thread is not None and not rep.thread.is_alive():
                raise _ReplicaDied(
                    f"replica {rep.index} died mid-drain")
            if time.monotonic() > deadline:
                router.end_drain(rep.index)
                raise RolloutError(
                    f"replica {rep.index} did not quiesce within "
                    f"{self.drain_timeout_s:g}s (load "
                    f"{rep.batcher.load()}) — returned to rotation")
            if self._stop.wait(0.005):
                router.end_drain(rep.index)
                raise RolloutError("controller stopped mid-drain")
            # late arrivals (continuations routed to the drainee while
            # it still owned their sessions) land in the queue — keep
            # requeueing them behind the migrating sessions
            router.requeue(rep.batcher.drain_queue(), rep)
        # quiesced: stop the scheduler (deliberate — the sweep skips
        # draining replicas) and move the idle kept sessions to peers
        self.server._stop_replica(rep)
        router.migrate_from(rep)

    def _resize_one(self, rep, num_slots: int) -> None:
        rep.engine.resize_slots(num_slots)
        rep.batcher.set_max_active(num_slots)

    def _warmup_one(self, rep) -> int:
        sampling, lens = getattr(self.server, "_warmup_spec",
                                 None) or (GREEDY, (1,))
        return rep.batcher.warmup(sampling, prompt_lens=lens)

    def _rejoin(self, rep) -> None:
        self.server._start_replica(rep)
        self.server.router.end_drain(rep.index)

    # ---- canary shadowing ------------------------------------------------

    def _run_canary(self, canary_rep, meta: dict, every: int) -> dict:
        """Shadow every ``every``-th stateless request onto the already-
        rolled canary replica until ``canary_min_pairs`` pairs compared
        (or ``canary_timeout_s``), then report. The hook clones the
        primary request — same prompt/sampling/model, ``use_prefix=False``
        (a probe must not perturb the shared prefix cache), best-effort
        class so shadows shed first under load — and submits it straight
        to the canary's batcher, off the router's books."""
        with self._canary_lock:
            self._pairs = []
            for k in self._canary_counts:
                self._canary_counts[k] = 0
        ttft = {"primary": [], "canary": []}
        router = self.server.router
        router.set_canary(self._make_hook(canary_rep, every))
        try:
            deadline = time.monotonic() + self.canary_timeout_s
            while time.monotonic() < deadline:
                if self._collect(ttft) >= self.canary_min_pairs:
                    break
                if self._stop.wait(0.02):
                    break
        finally:
            router.clear_canary()
        # grace: settle pairs whose shadow is still decoding
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            with self._canary_lock:
                outstanding = len(self._pairs)
            if not outstanding or self._stop.wait(0.02):
                break
            self._collect(ttft)
        self._collect(ttft)
        with self._canary_lock:
            counts = dict(self._canary_counts)
            self._pairs = []
        counts["compared"] = (counts["match"] + counts["diff"]
                              + counts["error"])
        report = {
            "model": meta["model"], "version": meta["version"],
            "replica": canary_rep.index, "every": every,
            "counts": counts,
            "verdict": ("diff" if counts["diff"] else
                        "match" if counts["match"] else "no_traffic"),
            # the SLO half of the comparison: TTFT of primaries vs their
            # shadows over the SAME prompts — a slower canary here is a
            # perf regression even when the tokens match
            "slo": {side: {
                "count": len(vals),
                "ttft_p50_ms": None if not vals
                else round(_pctl(vals, 0.50) * 1e3, 3),
                "ttft_p99_ms": None if not vals
                else round(_pctl(vals, 0.99) * 1e3, 3),
            } for side, vals in ttft.items()},
        }
        self.last_canary = report
        return report

    def _make_hook(self, canary_rep, every: int):
        counter = itertools.count(1)

        def hook(req: Request) -> None:
            if req.session_id is not None or req.keep_session:
                return  # stateful requests have affinity — never forked
            if req.replica == canary_rep.index:
                return  # already landed on the canary (or IS a shadow)
            if next(counter) % every:
                return
            shadow = Request(
                list(req.prompt), req.max_new_tokens,
                sampling=req.sampling, eos_id=req.eos_id,
                use_prefix=False, klass="best_effort", model=req.model)
            try:
                canary_rep.batcher.submit(shadow)
            except Exception:
                with self._canary_lock:
                    self._canary_counts["skipped"] += 1
                return
            with self._canary_lock:
                self._canary_counts["shadowed"] += 1
                self._pairs.append((req, shadow))

        return hook

    def _collect(self, ttft: dict) -> int:
        """Settle completed (primary, shadow) pairs into verdict counts
        + TTFT samples. Returns pairs compared so far."""
        with self._canary_lock:
            remaining = []
            for prim, shad in self._pairs:
                if not (prim.done.is_set() and shad.done.is_set()):
                    remaining.append((prim, shad))
                    continue
                if (prim.error is not None or shad.error is not None
                        or prim.timed_out or shad.timed_out):
                    verdict = "error"
                elif list(prim.tokens) == list(shad.tokens):
                    verdict = "match"
                else:
                    verdict = "diff"
                self._canary_counts[verdict] += 1
                self._m_canary[verdict].inc()
                for side, r in (("primary", prim), ("canary", shad)):
                    if r.t_first_token and r.t_submit:
                        ttft[side].append(r.t_first_token - r.t_submit)
            self._pairs = remaining
            return (self._canary_counts["match"]
                    + self._canary_counts["diff"]
                    + self._canary_counts["error"])

    # ---- views ----------------------------------------------------------

    def stats(self) -> dict:
        """The ``/stats`` ``rollout`` section."""
        with self._lock:
            return {
                "running": self._thread is not None,
                "registry": self.registry.stats(),
                "active": None if self._active is None
                else {k: v for k, v in self._active.items()
                      if k != "t_start"},
                "queued": [dict(m) for m in self._queue],
                "rollouts": self.rollouts,
                "resizes": self.resizes,
                "errors": self.errors,
                "last_error": self._last_error,
                "canary_every": self.canary_every,
                "last_canary": self.last_canary,
                "history": list(self._history),
            }
