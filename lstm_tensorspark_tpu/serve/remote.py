"""Remote-replica RPC transport: a peer serve PROCESS behind the router.

PR 7's replicas are threads in one process; this module generalises the
replica to a separate host. A :class:`RemoteReplica` satisfies the exact
Router-facing surface a local :class:`~.router.Replica` does —
``submit``/``queued``/``load``/``drain_queue``/``fail_inflight``/
``fail_request`` plus the scheduler heartbeat — over the stdlib
HTTP/JSON endpoint the peer already serves (serve/server.py): generate
RPCs ride ``POST /v1/generate`` verbatim, liveness and load ride the
lightweight ``GET /replica/heartbeat``, and session affinity probes ride
``GET /replica/has_session``. No new wire protocol, no new dependency —
the serve plane's public endpoint IS the replica transport.

Liveness is structural, not bolted on: the shim's heartbeat poller
thread is started by ``ServeServer.start()`` exactly like a local
scheduler thread (``RemoteBatcher.run(stop_event)``), and it EXITS when
``DEAD_AFTER`` consecutive heartbeats fail — so the router's existing
death sweep (thread-not-alive → retire exactly once) fires unchanged,
and replica-death handling generalises to HOST death for free:

- nothing is queued front-side (submits dispatch an RPC thread
  immediately), so ``drain_queue`` is empty by construction;
- in-flight RPCs ``fail_inflight`` honestly — the remote's decode
  position is indeterminate, the same verdict as a dead local scheduler;
- the dead host's KEPT sessions are NOT lost when the fleet shares a
  ``--session-dir``: the peer write-behind checkpointed every kept
  session at its request boundaries (PR 8), so a continuation re-routes
  to any live tiered replica and fills from the shared disk tier
  token-identically (tests/test_serve_mesh.py's 2-process kill drill;
  tools/chaos_serve.py ``host_die`` phase).

Affinity: the router probes ``sid in replica.engine.cache`` under its
lock; for a remote replica that is one bounded HTTP probe against the
peer's cache AND tiers (``ServeEngine.has_session``), so continuations
keep landing where their carries live. A dead/unreachable peer probes
False and the (shared-disk) fallback applies.

Error mapping keeps the client contract: a remote 429 settles the
request with the shed message, a remote ``deadline_exceeded`` settles it
as an honest timeout WITH the partial tokens, an unreachable host
mid-request settles it "state lost" — never a silent re-decode.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request

from .batcher import CLASSES, QueueFullError, Request
from .router import Replica

#: consecutive failed heartbeats before the poller declares the host
#: dead and exits (the router's sweep then retires the replica).
DEAD_AFTER = 4

#: batcher-stat counter keys mirrored from the remote heartbeat so
#: ServeServer.stats() can aggregate a mixed local/remote fleet.
_STAT_KEYS = (
    "submitted", "completed", "rejected", "failed", "timed_out",
    "queued", "active", "prefilling", "windows_pipelined",
    "tokens_generated", "prefill_chunks_dispatched", "prefix_resumed",
    "prefix_tokens_saved",
)


class _RemoteCache:
    """Affinity-probe view of the peer's session residency: membership
    is one bounded HTTP probe (device slots AND tiers — the peer can
    serve the session either way). Unreachable peer → False, and the
    router's shared-disk fallback takes over."""

    def __init__(self, shim: "RemoteBatcher"):
        self._shim = shim

    def __contains__(self, sid: str) -> bool:
        return self._shim.has_session(sid)

    def session_ids(self) -> list[str]:
        # retirement migration: a DEAD host's device state is gone by
        # definition — nothing to detach. Kept sessions survive through
        # the shared --session-dir disk tier instead.
        return []

    def stats(self) -> dict:
        return {"slots": 0, "live_sessions": 0, "pinned": 0, "free": 0,
                "evictions": 0, "generation": 0}


class _RemoteEngine:
    """The engine-shaped face of a remote replica: enough surface for
    the router (cache membership, tiers=None, metrics) and the server's
    stats/gauge collection — never a device owner."""

    def __init__(self, shim: "RemoteBatcher", registry):
        self.cache = _RemoteCache(shim)
        self.tiers = None
        self.prefix = None
        self.metrics = registry
        self._shim = shim

    def has_session(self, sid: str) -> bool:
        return self._shim.has_session(sid)

    def detach_session(self, sid: str):
        raise KeyError(f"session {sid!r} lives on a remote host — "
                       "detach is not part of the RPC surface")

    def restore_session(self, sid: str, state) -> int:
        raise RuntimeError("cannot restore a session into a remote "
                           "replica — route the continuation instead")

    def stats(self) -> dict:
        return {
            "remote_url": self._shim.url,
            "decode_kernel": None,
            "mesh_shards": None,
            "decode_window_scan_fallbacks": 0,
            "cache": self.cache.stats(),
            "prefix_cache": None,
            "tiers": None,
            "compiles": {},
            "heartbeat_age_s": self._shim.heartbeat_age(),
        }


class RemoteBatcher:
    """Batcher-shaped RPC shim for one remote serve host.

    ``run(stop_event)`` is the scheduler closure ServeServer drives on a
    thread (graftlint host-sync covers it like every scheduler loop —
    it never touches the device): poll ``/replica/heartbeat`` every
    ``poll_interval`` seconds, mirror the peer's queue/active counters,
    and EXIT after :data:`DEAD_AFTER` consecutive failures so the
    router's thread-liveness sweep retires the replica through the
    normal path. ``submit`` never blocks the router lock on the network:
    it dispatches a daemon RPC thread per request."""

    def __init__(self, url: str, *, replica: int = 0, queue_size: int = 64,
                 poll_interval: float = 0.5, rpc_timeout: float = 5.0,
                 registry=None):
        self.url = url.rstrip("/")
        self.replica = int(replica)
        self.queue_size = int(queue_size)
        self.poll_interval = float(poll_interval)
        self.rpc_timeout = float(rpc_timeout)
        self.last_heartbeat: float | None = None
        self._lock = threading.Lock()
        self._inflight: set[Request] = set()
        self._remote: dict = {}  # last heartbeat's batcher aggregate
        self._last_ok: float | None = None
        self.submitted = 0
        self.completed = 0
        self.failed = 0
        self._m_rpc = None
        if registry is not None:
            fam = registry.counter(
                "serve_remote_rpc_total",
                "remote-replica RPC outcomes (generate calls by result)",
                labelnames=("outcome", "replica"))
            rl = str(self.replica)
            self._m_rpc = {o: fam.labels(outcome=o, replica=rl)
                           for o in ("ok", "error", "unreachable")}

    # ---- HTTP plumbing -------------------------------------------------

    def _get(self, path: str, timeout: float | None = None) -> dict:
        with urllib.request.urlopen(
                self.url + path,
                timeout=self.rpc_timeout if timeout is None else timeout
        ) as r:
            return json.loads(r.read())

    def _post(self, path: str, body: dict, timeout: float) -> dict:
        req = urllib.request.Request(
            self.url + path, data=json.dumps(body).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read())

    # ---- liveness ------------------------------------------------------

    def heartbeat_age(self) -> float | None:
        hb = self.last_heartbeat
        return None if hb is None else round(time.monotonic() - hb, 3)

    def run(self, stop_event: threading.Event,
            idle_wait: float = 0.05) -> None:
        """Heartbeat poller — THE liveness proxy: this thread's exit is
        how the router learns the host died (sweep: thread-not-alive →
        retire). One initial probe runs immediately so a host that was
        already down is retired within ``DEAD_AFTER`` polls of start."""
        failures = 0
        while not stop_event.is_set():
            try:
                hb = self._get("/replica/heartbeat")
            except (urllib.error.URLError, OSError, ValueError):
                failures += 1
                if failures >= DEAD_AFTER:
                    return  # host dead: the sweep takes it from here
            else:
                failures = 0
                with self._lock:
                    self._remote = hb.get("batcher") or {}
                    self._last_ok = time.monotonic()
                if hb.get("status") != "down":
                    # a peer whose own schedulers are wedged reports
                    # "down": its thread lives but nothing serves — keep
                    # OUR heartbeat stale so the router stops routing
                    # fresh sessions there (the wedge semantics local
                    # replicas already have)
                    self.last_heartbeat = time.monotonic()
            stop_event.wait(self.poll_interval)

    def has_session(self, sid: str) -> bool:
        # the router calls this under its GLOBAL lock (affinity probe):
        # the probe is one bounded HTTP GET for a peer whose heartbeat
        # is FRESH, and a lock-free False for one that is not — a
        # silent/dying peer must not stall the whole admission plane
        # for a network timeout per continuation while the poller
        # counts down to declaring it dead. Routing the continuation
        # elsewhere is exactly right for an unhealthy peer: with a
        # shared --session-dir the survivor fills the last checkpointed
        # boundary from disk, and without one the honest "unknown
        # session" beats a submit plane frozen behind a corpse.
        with self._lock:
            last_ok = self._last_ok
        if (last_ok is None
                or time.monotonic() - last_ok > 3 * self.poll_interval):
            return False
        try:
            return bool(self._get(
                "/replica/has_session?sid="
                + urllib.parse.quote(sid, safe=""),
                timeout=min(self.rpc_timeout, 2.0)).get("has"))
        except (urllib.error.URLError, OSError, ValueError):
            return False

    # ---- router-facing surface -----------------------------------------

    def queued(self) -> int:
        # remote-reported queue depth PLUS the local in-flight RPCs:
        # the router's GLOBAL admission bound sums queued() across
        # replicas, and a burst routed here inside one heartbeat window
        # is invisible to the peer's last-reported number — counting it
        # locally makes the router's bound (with its shed accounting
        # and measured Retry-After) trip BEFORE the shim's own backstop
        # below. Slightly conservative in steady state (an in-flight
        # RPC the peer already admitted counts once here and once in
        # the peer's active set at the next poll) — early shedding
        # beats an unaccounted one.
        with self._lock:
            return (int(self._remote.get("queued", 0) or 0)
                    + len(self._inflight))

    def load(self) -> int:
        with self._lock:
            # in-flight RPCs cover the heartbeat staleness window (a
            # burst routed between polls must weigh on the next pick);
            # counted ONCE — queued() above uses the same accounting
            return (sum(int(self._remote.get(k, 0) or 0)
                        for k in ("queued", "active", "prefilling"))
                    + len(self._inflight))

    def submit(self, req: Request) -> None:
        """Dispatch the request to the peer on an RPC thread; returns
        immediately (the router holds its lock here — the network must
        never run under it). Backpressure: the local in-flight count is
        bounded at ``queue_size`` — the remote's own admission (and the
        router's global bound over ``queued()``) does the rest."""
        with self._lock:
            # 2x backstop only: the router's global bound (which counts
            # our in-flight RPCs via queued()) sheds with full
            # accounting first — this guards direct submit() callers
            # and pathological races, not normal overload
            if len(self._inflight) >= 2 * self.queue_size:
                raise QueueFullError(
                    f"remote replica {self.replica} has "
                    f"{2 * self.queue_size} RPCs in flight; "
                    "retry after 0.25s", retry_after_s=0.25)
            self._inflight.add(req)
            self.submitted += 1
        if req.t_submit is None:
            req.t_submit = time.perf_counter()
            if req.deadline_s is not None:
                req.deadline = req.t_submit + req.deadline_s
        threading.Thread(target=self._rpc_generate, args=(req,),
                         name=f"serve-remote-rpc-{self.replica}",
                         daemon=True).start()

    def _rpc_generate(self, req: Request) -> None:
        body = {
            "prompt": [int(t) for t in req.prompt],
            "max_new_tokens": req.max_new_tokens,
            "temperature": req.sampling.temperature,
            "top_k": req.sampling.top_k,
            "top_p": req.sampling.top_p,
            "greedy": req.sampling.greedy,
            "session_id": req.session_id,
            "keep_session": req.keep_session,
            "eos_id": req.eos_id,
            "use_prefix": req.use_prefix,
            "class": req.klass,
        }
        timeout = 120.0
        if req.deadline is not None:
            remaining = req.deadline - time.perf_counter()
            if remaining <= 0:
                self._settle(req, timeout_stage=True)
                return
            body["deadline_s"] = round(remaining, 3)
            timeout = remaining + self.rpc_timeout
        body["timeout"] = timeout
        try:
            reply = self._post("/v1/generate", body,
                               timeout=timeout + self.rpc_timeout)
        except urllib.error.HTTPError as e:
            try:
                err = json.loads(e.read())
            except Exception:
                err = {"error": f"HTTP {e.code}", "code": "internal"}
            if err.get("code") == "deadline_exceeded":
                # honest remote expiry WITH the partial tokens
                self._settle(req, tokens=err.get("tokens") or [],
                             timeout_stage=True)
            elif err.get("code") == "queue_full":
                # the peer SHED the request: it must reach the front's
                # client as a retryable 429 carrying the peer's measured
                # Retry-After — settling it as a plain error would turn
                # transient backpressure into a non-retryable 500 and
                # discard the honest drain estimate
                self._settle(req, error=(
                    f"remote replica {self.replica} shed the request: "
                    f"{err.get('error', 'queue full')}"),
                    shed_retry_after=float(
                        err.get("retry_after_s") or 0.25))
            else:
                self._settle(req, error=(
                    f"remote replica {self.replica} ({self.url}) "
                    f"rejected the request: "
                    f"{err.get('error', f'HTTP {e.code}')}"))
            return
        except (urllib.error.URLError, OSError, ValueError,
                TimeoutError) as e:
            # host unreachable mid-request: its decode position is
            # indeterminate — "state lost" is the truthful verdict,
            # exactly like a dead local scheduler's in-flight work
            self._settle(req, error=(
                f"remote replica {self.replica} ({self.url}) became "
                f"unreachable mid-request ({type(e).__name__}); its "
                "decode position is indeterminate (state lost — resend "
                "the request)"), unreachable=True)
            return
        self._settle(req, tokens=reply.get("tokens") or [],
                     session_id=reply.get("session_id"))

    def _settle(self, req: Request, *, tokens=None, session_id=None,
                error: str | None = None, timeout_stage: bool = False,
                unreachable: bool = False,
                shed_retry_after: float | None = None) -> None:
        # the whole settle — done-check, field writes, done.set() —
        # commits under the shim lock: an RPC thread finishing a
        # long-connected generate can race fail_inflight (host declared
        # dead on heartbeats while the socket still lives), and a
        # half-locked settle could hand the client a completed
        # request's tokens with a "state lost" error (or double-count
        # the outcome). Unlike the local Batcher, whose fail_inflight
        # only runs once its single scheduler thread is provably dead,
        # these RPC threads are independent and may still be live.
        now = time.perf_counter()
        with self._lock:
            self._inflight.discard(req)
            if req.done.is_set():
                return  # the racing settler won; this outcome is moot
            if error is None and not timeout_stage:
                self.completed += 1
            else:
                self.failed += 1
            if tokens:
                req.tokens.extend(int(t) for t in tokens)
                if req.t_first_token is None:
                    req.t_first_token = now
                req.t_tokens.extend([now] * len(tokens))
            if session_id is not None:
                req.session_id = session_id
            req.error = error
            req.timed_out = timeout_stage
            if shed_retry_after is not None:
                # marker ServeServer.generate re-raises as QueueFullError
                # (→ HTTP 429 + Retry-After), keeping the backpressure
                # contract across the RPC hop
                req.remote_shed_retry_after = shed_retry_after
            req.t_done = now
            req.done.set()
        if self._m_rpc is not None:
            self._m_rpc["unreachable" if unreachable else
                        "error" if (error or timeout_stage)
                        else "ok"].inc()

    # ---- retirement (router-driven, after run() exited) ----------------

    def drain_queue(self) -> list[Request]:
        return []  # nothing queues front-side: submits dispatch at once

    def fail_inflight(self, reason: str) -> int:
        # same locked-settle discipline as _settle: a still-live RPC
        # thread may be completing one of these requests concurrently,
        # and exactly one settler must win per request
        now = time.perf_counter()
        with self._lock:
            inflight = list(self._inflight)
            self._inflight.clear()
            n = 0
            for req in inflight:
                if req.done.is_set():
                    continue
                req.error = reason
                req.t_done = now
                req.done.set()
                n += 1
            self.failed += n
        return n

    def fail_request(self, req: Request, reason: str) -> None:
        with self._lock:
            if not req.done.is_set():
                req.error = reason
                req.t_done = time.perf_counter()
                req.done.set()

    # ---- views / warmup -------------------------------------------------

    def warmup(self, sampling=None, prompt_lens: tuple[int, ...] = (1,)):
        """Ask the peer to (re)warm its compile lattice for these prompt
        lengths. Best-effort: the peer already warmed at boot (cli
        _serve_http), so an unreachable peer costs a log line, not a
        failed start."""
        body = {"prompt_lens": [int(t) for t in prompt_lens]}
        if sampling is not None:
            body.update(temperature=sampling.temperature,
                        top_k=sampling.top_k, top_p=sampling.top_p,
                        greedy=sampling.greedy)
        try:
            return int(self._post("/replica/warmup", body,
                                  timeout=600.0).get("programs", 0))
        except (urllib.error.URLError, OSError, ValueError) as e:
            print(f"serve: remote replica {self.replica} warmup RPC "
                  f"failed ({type(e).__name__}) — relying on its own "
                  "boot-time warmup", flush=True)
            return 0

    def stats(self) -> dict:
        with self._lock:
            remote = dict(self._remote)
            submitted, completed = self.submitted, self.completed
            failed, inflight = self.failed, len(self._inflight)
        out = {k: int(remote.get(k, 0) or 0) for k in _STAT_KEYS}
        out.update({
            "replica": self.replica,
            "remote_url": self.url,
            "rpc_submitted": submitted,
            "rpc_completed": completed,
            "rpc_failed": failed,
            "rpc_inflight": inflight,
            # JSON stringified the K keys in flight; re-int them so the
            # server's cross-replica aggregation merges onto the local
            # batchers' integer rungs instead of duplicating "4" vs 4
            "windows_dispatched": {
                (int(k) if str(k).isdigit() else k): v
                for k, v in (remote.get("windows_dispatched")
                             or {}).items()},
            "queued_by_class": dict(remote.get("queued_by_class")
                                    or {c: 0 for c in CLASSES}),
            "class_weights": list(remote.get("class_weights") or []),
            "max_active": remote.get("max_active"),
            "queue_size": self.queue_size,
            "window_ladder": list(remote.get("window_ladder") or []),
            "prefill_chunk": remote.get("prefill_chunk"),
        })
        return out


class RemoteReplica(Replica):
    """A :class:`~.router.Replica` whose engine+scheduler live in
    another process. Plugs into ``ServeServer``/``Router`` unchanged:
    the heartbeat poller is the scheduler thread, the RPC shim is the
    batcher, and the engine view answers affinity probes."""

    def __init__(self, index: int, url: str, *, registry=None,
                 queue_size: int = 64, poll_interval: float = 0.5,
                 rpc_timeout: float = 5.0):
        shim = RemoteBatcher(url, replica=index, queue_size=queue_size,
                             poll_interval=poll_interval,
                             rpc_timeout=rpc_timeout, registry=registry)
        super().__init__(index, _RemoteEngine(shim, registry), shim)
        self.url = shim.url
