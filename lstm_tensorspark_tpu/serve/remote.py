"""Remote-replica RPC shim: a peer serve PROCESS behind the router.

PR 7's replicas are threads in one process; this module generalises the
replica to a separate host. A :class:`RemoteReplica` satisfies the exact
Router-facing surface a local :class:`~.router.Replica` does —
``submit``/``queued``/``load``/``drain_queue``/``fail_inflight``/
``fail_request`` plus the scheduler heartbeat — over the stdlib
HTTP/JSON endpoint the peer already serves (serve/server.py): generate
RPCs ride ``POST /v1/generate``, liveness, load AND session residency
ride the lightweight ``GET /replica/heartbeat``. No new wire protocol,
no new dependency — the serve plane's public endpoint IS the replica
transport, and all wire traffic flows through the shared
:class:`~.transport.PeerTransport` (ISSUE 17): pooled connections,
bounded ``backoff_delay`` retries, per-peer circuit breaker, and
deterministic network-fault injection.

Liveness distinguishes DEAD from PARTITIONED (circuit-open ≠ dead):

- a **refused** connection means no listener — the process provably
  exited. :data:`DEAD_AFTER` consecutive refused heartbeats make the
  poller thread exit, and the router's existing death sweep
  (thread-not-alive → retire exactly once) fires unchanged. Kept
  sessions survive through the shared ``--session-dir`` disk tier
  (tests/test_serve_mesh.py's 2-process kill drill).
- **timeouts/resets** (partition-shaped failures) never retire: they
  feed the per-peer :class:`~.transport.CircuitBreaker`. After
  ``circuit_open_after`` consecutive failures the circuit opens and the
  router routes around instantly (no request waits out ``rpc_timeout``
  against a blackhole); the heartbeat poller keeps probing as the
  half-open path, and ``circuit_rejoin_after`` consecutive successes
  close it — the peer REJOINS without a process restart.
- **flap damping**: in the closed regime one success resets the failure
  streak, so an alternating lossy link below the threshold never opens
  the circuit (and never retires — flap failures aren't refusals); once
  suspect, only consecutive successes rejoin (hysteresis).

Session residency (the affinity probe) is served from an async cache:
the heartbeat payload carries the peer's resident session ids, and
``has_session`` answers from that snapshot plus a front-side overlay of
recently settled kept sessions — ZERO network under the router's lock
(the old blocking GET per continuation is the exact bug the graftlint
``io-under-lock`` fixture pair ``viol/clean_remote_sync`` pins).

Generate RPCs are exactly-once over at-least-once delivery: the shim
mints a ``request_id`` per request, the peer deduplicates replays via
its settled cache, and the transport only retries indeterminate
failures under that replay guarantee. A failure that provably never
reached the peer (``executed is False``) re-enters routing through
``Router.reroute`` — with a shared session dir the survivor fills the
last checkpointed boundary token-identically; truly indeterminate
exhausted failures settle honestly ("state lost"), never a silent
re-decode.
"""

from __future__ import annotations

import threading
import time
import uuid

from .batcher import CLASSES, QueueFullError, Request
from .router import Replica
from .transport import CircuitBreaker, PeerHTTPError, PeerTransport, \
    TransportError

#: consecutive REFUSED heartbeats (no listener — the process provably
#: exited) before the poller declares the host dead and exits (the
#: router's sweep then retires the replica). Partition-shaped failures
#: (timeouts, resets) never count here — they open the circuit instead.
DEAD_AFTER = 4

#: default circuit thresholds: N consecutive transport failures open,
#: H consecutive heartbeat-probe successes close (rejoin hysteresis),
#: M consecutive failures mark cached residency suspect (M <= N).
CIRCUIT_OPEN_AFTER = 3
CIRCUIT_REJOIN_AFTER = 2
DAMP_AFTER = 2

#: batcher-stat counter keys mirrored from the remote heartbeat so
#: ServeServer.stats() can aggregate a mixed local/remote fleet.
_STAT_KEYS = (
    "submitted", "completed", "rejected", "failed", "timed_out",
    "queued", "active", "prefilling", "windows_pipelined",
    "tokens_generated", "prefill_chunks_dispatched", "prefix_resumed",
    "prefix_tokens_saved",
)


class _RemoteCache:
    """Affinity-probe view of the peer's session residency: membership
    reads the heartbeat-refreshed residency cache — in-memory only,
    never the network (the router probes under its global lock).
    Suspect/partitioned peer → False, and the router's shared-disk
    fallback takes over."""

    def __init__(self, shim: "RemoteBatcher"):
        self._shim = shim

    def __contains__(self, sid: str) -> bool:
        return self._shim.has_session(sid)

    def session_ids(self) -> list[str]:
        # retirement migration: a DEAD host's device state is gone by
        # definition — nothing to detach. Kept sessions survive through
        # the shared --session-dir disk tier instead.
        return []

    def stats(self) -> dict:
        return {"slots": 0, "live_sessions": 0, "pinned": 0, "free": 0,
                "evictions": 0, "generation": 0}


class _RemoteEngine:
    """The engine-shaped face of a remote replica: enough surface for
    the router (cache membership, tiers=None, metrics) and the server's
    stats/gauge collection — never a device owner."""

    def __init__(self, shim: "RemoteBatcher", registry):
        self.cache = _RemoteCache(shim)
        self.tiers = None
        self.prefix = None
        self.metrics = registry
        self._shim = shim

    def has_session(self, sid: str) -> bool:
        return self._shim.has_session(sid)

    def detach_session(self, sid: str):
        raise KeyError(f"session {sid!r} lives on a remote host — "
                       "detach is not part of the RPC surface")

    def restore_session(self, sid: str, state) -> int:
        raise RuntimeError("cannot restore a session into a remote "
                           "replica — route the continuation instead")

    def stats(self) -> dict:
        return {
            "remote_url": self._shim.url,
            "decode_kernel": None,
            "mesh_shards": None,
            "decode_window_scan_fallbacks": 0,
            "cache": self.cache.stats(),
            # the peer's real prefix-store section, mirrored off its
            # heartbeat (None until the first poll lands, or when the
            # peer runs without a prefix store) — a hardcoded None here
            # made /stats lie for remote hosts
            "prefix_cache": self._shim.remote_prefix(),
            "tiers": None,
            "compiles": {},
            "heartbeat_age_s": self._shim.heartbeat_age(),
            "circuit": self._shim.circuit.state(),
        }


class RemoteBatcher:
    """Batcher-shaped RPC shim for one remote serve host.

    ``run(stop_event)`` is the scheduler closure ServeServer drives on a
    thread (graftlint host-sync covers it like every scheduler loop —
    it never touches the device): poll ``/replica/heartbeat`` every
    ``poll_interval`` seconds through the retrying transport, mirror
    the peer's queue/active counters and session residency, feed the
    circuit breaker (the poller IS the half-open prober), and EXIT only
    after :data:`DEAD_AFTER` consecutive REFUSED connections so the
    router's thread-liveness sweep retires provably-dead hosts through
    the normal path while partitioned ones merely open the circuit.
    ``submit`` never blocks the router lock on the network: it
    dispatches a daemon RPC thread per request."""

    def __init__(self, url: str, *, replica: int = 0, queue_size: int = 64,
                 poll_interval: float = 0.5, rpc_timeout: float = 5.0,
                 generate_timeout_s: float | None = 120.0,
                 registry=None, circuit_open_after: int = CIRCUIT_OPEN_AFTER,
                 circuit_rejoin_after: int = CIRCUIT_REJOIN_AFTER,
                 damp_after: int = DAMP_AFTER, max_retries: int = 2,
                 retry_base_s: float = 0.05, transport=None):
        self.url = url.rstrip("/")
        self.replica = int(replica)
        self.queue_size = int(queue_size)
        self.poll_interval = float(poll_interval)
        self.rpc_timeout = float(rpc_timeout)
        if generate_timeout_s is not None:
            generate_timeout_s = float(generate_timeout_s)
            if generate_timeout_s < 0:
                raise ValueError(
                    f"generate_timeout_s must be >= 0 "
                    f"(0 = no client-side bound), got {generate_timeout_s}")
            if generate_timeout_s == 0:       # CLI convention: 0 = none
                generate_timeout_s = None
        self.generate_timeout_s = generate_timeout_s
        self.damp_after = int(damp_after)
        self.last_heartbeat: float | None = None
        self._lock = threading.Lock()
        self._inflight: set[Request] = set()
        self._remote: dict = {}  # last heartbeat's batcher aggregate
        self._remote_prefix: dict | None = None  # ... prefix-store section
        self._last_ok: float | None = None
        # residency cache: the last heartbeat's resident session ids
        # (None = peer didn't report / truncated list) plus an overlay
        # of kept sessions this front settled recently — covers the
        # window before the next heartbeat reflects them.
        self._residency: frozenset[str] | None = None
        self._recent: dict[str, float] = {}
        self.submitted = 0
        self.completed = 0
        self.failed = 0
        self.rerouted = 0
        self._reroute = None          # ServeServer wires Router.reroute
        gauge_child = None
        if registry is not None:
            gauge_child = registry.gauge(
                "serve_circuit_state",
                "per-peer circuit state (0=closed, 1=open, 2=half_open)",
                labelnames=("peer",)).labels(peer=str(self.replica))
        self.circuit = CircuitBreaker(open_after=circuit_open_after,
                                      rejoin_after=circuit_rejoin_after,
                                      gauge=gauge_child)
        if transport is None:
            transport = PeerTransport(
                self.url, peer=self.replica,
                connect_timeout=min(self.rpc_timeout, 1.0),
                max_retries=max_retries, retry_base_s=retry_base_s,
                circuit=self.circuit, registry=registry)
        self._transport = transport

    def set_reroute(self, fn) -> None:
        """Wire the router's reroute path (called by ServeServer after
        Router construction): ``fn(req) -> bool`` re-picks a replica
        for a request whose RPC provably never reached this peer."""
        self._reroute = fn

    # ---- liveness ------------------------------------------------------

    def heartbeat_age(self) -> float | None:
        hb = self.last_heartbeat
        return None if hb is None else round(time.monotonic() - hb, 3)

    def suspect(self) -> bool:
        """True while the peer's link is not trustworthy: circuit open,
        or ``damp_after`` consecutive transport failures accrued (the
        flap-damping threshold below full circuit-open)."""
        return self.circuit.suspect(self.damp_after)

    def remote_prefix(self) -> dict | None:
        """The peer's prefix-store stats section as of the last good
        heartbeat (None before the first poll, or when the peer serves
        without a prefix store)."""
        with self._lock:
            return self._remote_prefix

    @property
    def transport(self):
        """The peer's retrying :class:`PeerTransport` — the propagation
        plane (``PrefixPropagator``) posts fabric nodes through it so
        every delivery shares this peer's circuit breaker and retry
        provenance."""
        return self._transport

    def run(self, stop_event: threading.Event,
            idle_wait: float = 0.05) -> None:
        """Heartbeat poller — THE liveness proxy AND the circuit's
        half-open prober: this thread's exit is how the router learns
        the host provably DIED (sweep: thread-not-alive → retire), and
        its probes are how a partitioned-then-healed peer REJOINS (the
        transport records every outcome into the breaker; probes bypass
        the open-circuit fail-fast). One initial probe runs immediately
        so a host that was already down is retired within
        ``DEAD_AFTER`` polls of start."""
        refused = 0
        while not stop_event.is_set():
            try:
                hb = self._transport.rpc_get(
                    "/replica/heartbeat", method="heartbeat",
                    timeout=self.rpc_timeout, retries=0, probe=True)
            except TransportError as e:
                if e.kind == "refused":
                    refused += 1
                    if refused >= DEAD_AFTER:
                        return  # no listener: the sweep takes it
                else:
                    # partition-shaped (timeout/reset/blackhole): the
                    # breaker absorbed it — never a retirement signal
                    refused = 0
            except PeerHTTPError:
                refused = 0   # a listener answered: alive but unwell
            else:
                refused = 0
                now = time.monotonic()
                ids = hb.get("session_ids")
                with self._lock:
                    self._remote = hb.get("batcher") or {}
                    self._remote_prefix = hb.get("prefix_cache")
                    self._last_ok = now
                    if ids is None:
                        self._residency = None
                    else:
                        self._residency = frozenset(ids)
                        # overlay entries the snapshot now covers (or
                        # the peer evicted) are done shielding the gap
                        self._recent = {
                            s: t for s, t in self._recent.items()
                            if s not in self._residency
                            and now - t <= 3 * self.poll_interval}
                if hb.get("status") != "down":
                    # a peer whose own schedulers are wedged reports
                    # "down": its thread lives but nothing serves — keep
                    # OUR heartbeat stale so the router stops routing
                    # fresh sessions there (the wedge semantics local
                    # replicas already have)
                    self.last_heartbeat = now
            # the stop contract is is_set() only (server._ReplicaStop is
            # an OR-view, not an Event) — sleep in idle_wait slices so
            # stop/drain stays responsive at any poll_interval
            deadline = time.monotonic() + self.poll_interval
            while (not stop_event.is_set()
                   and time.monotonic() < deadline):
                time.sleep(min(idle_wait, self.poll_interval))

    def has_session(self, sid: str) -> bool:
        # the router calls this under its GLOBAL lock (affinity probe):
        # the answer comes from the heartbeat-refreshed residency cache
        # and the recent-settle overlay — NEVER the network (the old
        # blocking GET here stalled the whole admission plane for a
        # network timeout per continuation; graftlint io-under-lock now
        # pins the pattern). A suspect/stale peer probes False and the
        # shared-disk fallback routes the continuation to a survivor.
        if self.suspect():
            return False
        now = time.monotonic()
        with self._lock:
            if (self._last_ok is None
                    or now - self._last_ok > 3 * self.poll_interval):
                return False
            if self._residency is not None and sid in self._residency:
                return True
            t = self._recent.get(sid)
            return t is not None and now - t <= 3 * self.poll_interval

    # ---- router-facing surface -----------------------------------------

    def queued(self) -> int:
        # remote-reported queue depth PLUS the local in-flight RPCs:
        # the router's GLOBAL admission bound sums queued() across
        # replicas, and a burst routed here inside one heartbeat window
        # is invisible to the peer's last-reported number — counting it
        # locally makes the router's bound (with its shed accounting
        # and measured Retry-After) trip BEFORE the shim's own backstop
        # below. Slightly conservative in steady state (an in-flight
        # RPC the peer already admitted counts once here and once in
        # the peer's active set at the next poll) — early shedding
        # beats an unaccounted one.
        with self._lock:
            return (int(self._remote.get("queued", 0) or 0)
                    + len(self._inflight))

    def load(self) -> int:
        with self._lock:
            # in-flight RPCs cover the heartbeat staleness window (a
            # burst routed between polls must weigh on the next pick);
            # counted ONCE — queued() above uses the same accounting
            return (sum(int(self._remote.get(k, 0) or 0)
                        for k in ("queued", "active", "prefilling"))
                    + len(self._inflight))

    def submit(self, req: Request) -> None:
        """Dispatch the request to the peer on an RPC thread; returns
        immediately (the router holds its lock here — the network must
        never run under it). Backpressure: the local in-flight count is
        bounded at ``queue_size`` — the remote's own admission (and the
        router's global bound over ``queued()``) does the rest."""
        with self._lock:
            # 2x backstop only: the router's global bound (which counts
            # our in-flight RPCs via queued()) sheds with full
            # accounting first — this guards direct submit() callers
            # and pathological races, not normal overload
            if len(self._inflight) >= 2 * self.queue_size:
                raise QueueFullError(
                    f"remote replica {self.replica} has "
                    f"{2 * self.queue_size} RPCs in flight; "
                    "retry after 0.25s", retry_after_s=0.25)
            self._inflight.add(req)
            self.submitted += 1
        if req.rpc_request_id is None:
            # the idempotency key the peer deduplicates replays on —
            # minted ONCE per request so retries AND reroute-then-retry
            # chains can never double-decode on the same peer
            req.rpc_request_id = uuid.uuid4().hex
        if req.t_submit is None:
            req.t_submit = time.perf_counter()
            if req.deadline_s is not None:
                req.deadline = req.t_submit + req.deadline_s
        threading.Thread(target=self._rpc_generate, args=(req,),
                         name=f"serve-remote-rpc-{self.replica}",
                         daemon=True).start()

    def _rpc_generate(self, req: Request) -> None:
        body = {
            "prompt": [int(t) for t in req.prompt],
            "max_new_tokens": req.max_new_tokens,
            "temperature": req.sampling.temperature,
            "top_k": req.sampling.top_k,
            "top_p": req.sampling.top_p,
            "greedy": req.sampling.greedy,
            "session_id": req.session_id,
            "keep_session": req.keep_session,
            "eos_id": req.eos_id,
            "use_prefix": req.use_prefix,
            "class": req.klass,
            "request_id": req.rpc_request_id,
        }
        timeout = self.generate_timeout_s      # None = no client bound
        if req.deadline is not None:
            remaining = req.deadline - time.perf_counter()
            if remaining <= 0:
                self._settle(req, timeout_stage=True)
                return
            body["deadline_s"] = round(remaining, 3)
            timeout = remaining
        # the peer bounds its own wait on this; a day stands in for
        # "unbounded" because 0 means "expire immediately" server-side
        body["timeout"] = timeout if timeout is not None else 86400.0
        try:
            reply = self._transport.rpc_post(
                "/v1/generate", body, method="generate",
                timeout=None if timeout is None
                else timeout + self.rpc_timeout,
                replay_safe=True, deadline=req.deadline)
        except PeerHTTPError as e:
            err = e.body or {"error": f"HTTP {e.status}",
                             "code": "internal"}
            if err.get("code") == "deadline_exceeded":
                # honest remote expiry WITH the partial tokens
                self._settle(req, tokens=err.get("tokens") or [],
                             timeout_stage=True)
            elif err.get("code") == "queue_full":
                # the peer SHED the request: it must reach the front's
                # client as a retryable 429 carrying the peer's measured
                # Retry-After — settling it as a plain error would turn
                # transient backpressure into a non-retryable 500 and
                # discard the honest drain estimate
                self._settle(req, error=(
                    f"remote replica {self.replica} shed the request: "
                    f"{err.get('error', 'queue full')}"),
                    shed_retry_after=float(
                        err.get("retry_after_s") or 0.25))
            else:
                self._settle(req, error=(
                    f"remote replica {self.replica} ({self.url}) "
                    f"rejected the request: "
                    f"{err.get('error', f'HTTP {e.status}')}"))
            return
        except TransportError as e:
            if e.executed is False:
                # provably never delivered (connect-phase failure or
                # circuit fail-fast): re-routing is safe even for a
                # kept continuation — the shared disk tier fills the
                # last checkpointed boundary on the survivor
                if self._try_reroute(req):
                    return
                self._settle(req, error=(
                    f"remote replica {self.replica} ({self.url}) is "
                    f"unreachable ({e.kind}); the request was never "
                    "delivered (safe to resend)"), unreachable=True)
            else:
                # indeterminate after replay-safe retries exhausted:
                # the peer may have decoded — "state lost" is the
                # truthful verdict, exactly like a dead local
                # scheduler's in-flight work
                self._settle(req, error=(
                    f"remote replica {self.replica} ({self.url}) became "
                    f"unreachable mid-request ({e.kind}); its decode "
                    "position is indeterminate (state lost — resend "
                    "the request)"), unreachable=True)
            return
        except (ValueError, TypeError) as e:
            self._settle(req, error=(
                f"remote replica {self.replica} ({self.url}) sent an "
                f"unusable reply ({type(e).__name__}: {e})"))
            return
        sid = reply.get("session_id")
        self._settle(req, tokens=reply.get("tokens") or [],
                     session_id=sid)
        if req.keep_session and sid:
            # overlay: the next continuation's affinity probe must see
            # this session before the next heartbeat reflects it
            with self._lock:
                self._recent[sid] = time.monotonic()

    def _try_reroute(self, req: Request) -> bool:
        """Re-enter routing for a provably-undelivered request. The
        request leaves our in-flight set first (a racing fail_inflight
        must not settle what another replica now owns); exactly-one-
        settler stays true via the done-event check discipline."""
        reroute = self._reroute
        if reroute is None or req.done.is_set():
            return False
        with self._lock:
            self._inflight.discard(req)
        try:
            ok = bool(reroute(req))
        except Exception:
            ok = False
        if ok:
            with self._lock:
                self.rerouted += 1
            return True
        # nobody took it — restore accounting so the settle below and
        # fail_inflight keep seeing a consistent in-flight set
        with self._lock:
            if not req.done.is_set():
                self._inflight.add(req)
        return False

    def _settle(self, req: Request, *, tokens=None, session_id=None,
                error: str | None = None, timeout_stage: bool = False,
                unreachable: bool = False,
                shed_retry_after: float | None = None) -> None:
        # the whole settle — done-check, field writes, done.set() —
        # commits under the shim lock: an RPC thread finishing a
        # long-connected generate can race fail_inflight (host declared
        # dead on heartbeats while the socket still lives), and a
        # half-locked settle could hand the client a completed
        # request's tokens with a "state lost" error (or double-count
        # the outcome). Unlike the local Batcher, whose fail_inflight
        # only runs once its single scheduler thread is provably dead,
        # these RPC threads are independent and may still be live.
        now = time.perf_counter()
        with self._lock:
            self._inflight.discard(req)
            if req.done.is_set():
                return  # the racing settler won; this outcome is moot
            if error is None and not timeout_stage:
                self.completed += 1
            else:
                self.failed += 1
            if tokens:
                req.tokens.extend(int(t) for t in tokens)
                if req.t_first_token is None:
                    req.t_first_token = now
                req.t_tokens.extend([now] * len(tokens))
            if session_id is not None:
                req.session_id = session_id
            req.error = error
            req.timed_out = timeout_stage
            if shed_retry_after is not None:
                # marker ServeServer.generate re-raises as QueueFullError
                # (→ HTTP 429 + Retry-After), keeping the backpressure
                # contract across the RPC hop
                req.remote_shed_retry_after = shed_retry_after
            req.t_done = now
            req.done.set()

    # ---- retirement (router-driven, after run() exited) ----------------

    def drain_queue(self) -> list[Request]:
        return []  # nothing queues front-side: submits dispatch at once

    def fail_inflight(self, reason: str) -> int:
        # same locked-settle discipline as _settle: a still-live RPC
        # thread may be completing one of these requests concurrently,
        # and exactly one settler must win per request
        now = time.perf_counter()
        with self._lock:
            inflight = list(self._inflight)
            self._inflight.clear()
            n = 0
            for req in inflight:
                if req.done.is_set():
                    continue
                req.error = reason
                req.t_done = now
                req.done.set()
                n += 1
            self.failed += n
        return n

    def fail_request(self, req: Request, reason: str) -> None:
        with self._lock:
            if not req.done.is_set():
                req.error = reason
                req.t_done = time.perf_counter()
                req.done.set()

    # ---- views / warmup -------------------------------------------------

    def warmup(self, sampling=None, prompt_lens: tuple[int, ...] = (1,)):
        """Ask the peer to (re)warm its compile lattice for these prompt
        lengths. Best-effort: the peer already warmed at boot (cli
        _serve_http), so an unreachable peer costs a log line, not a
        failed start."""
        body = {"prompt_lens": [int(t) for t in prompt_lens]}
        if sampling is not None:
            body.update(temperature=sampling.temperature,
                        top_k=sampling.top_k, top_p=sampling.top_p,
                        greedy=sampling.greedy)
        try:
            return int(self._transport.rpc_post(
                "/replica/warmup", body, method="warmup",
                timeout=600.0, replay_safe=True).get("programs", 0))
        except (TransportError, PeerHTTPError, ValueError,
                TypeError) as e:
            print(f"serve: remote replica {self.replica} warmup RPC "
                  f"failed ({type(e).__name__}) — relying on its own "
                  "boot-time warmup", flush=True)
            return 0

    def stats(self) -> dict:
        with self._lock:
            remote = dict(self._remote)
            submitted, completed = self.submitted, self.completed
            failed, inflight = self.failed, len(self._inflight)
            rerouted = self.rerouted
        out = {k: int(remote.get(k, 0) or 0) for k in _STAT_KEYS}
        out.update({
            "replica": self.replica,
            "remote_url": self.url,
            "rpc_submitted": submitted,
            "rpc_completed": completed,
            "rpc_failed": failed,
            "rpc_inflight": inflight,
            "rpc_retries": self._transport.retries_total,
            "rpc_rerouted": rerouted,
            "circuit": self.circuit.state(),
            "circuit_opened": self.circuit.opened_total,
            "circuit_closed": self.circuit.closed_total,
            # JSON stringified the K keys in flight; re-int them so the
            # server's cross-replica aggregation merges onto the local
            # batchers' integer rungs instead of duplicating "4" vs 4
            "windows_dispatched": {
                (int(k) if str(k).isdigit() else k): v
                for k, v in (remote.get("windows_dispatched")
                             or {}).items()},
            "queued_by_class": dict(remote.get("queued_by_class")
                                    or {c: 0 for c in CLASSES}),
            "class_weights": list(remote.get("class_weights") or []),
            "max_active": remote.get("max_active"),
            "queue_size": self.queue_size,
            "window_ladder": list(remote.get("window_ladder") or []),
            "prefill_chunk": remote.get("prefill_chunk"),
        })
        return out


class RemoteReplica(Replica):
    """A :class:`~.router.Replica` whose engine+scheduler live in
    another process. Plugs into ``ServeServer``/``Router`` unchanged:
    the heartbeat poller is the scheduler thread, the RPC shim is the
    batcher, and the engine view answers affinity probes. Overrides
    ``circuit_open`` so the router treats a partitioned peer like a
    stale one (route around, don't retire) while it heals."""

    def __init__(self, index: int, url: str, *, registry=None,
                 queue_size: int = 64, poll_interval: float = 0.5,
                 rpc_timeout: float = 5.0,
                 generate_timeout_s: float | None = 120.0,
                 circuit_open_after: int = CIRCUIT_OPEN_AFTER,
                 circuit_rejoin_after: int = CIRCUIT_REJOIN_AFTER,
                 damp_after: int = DAMP_AFTER):
        shim = RemoteBatcher(url, replica=index, queue_size=queue_size,
                             poll_interval=poll_interval,
                             rpc_timeout=rpc_timeout,
                             generate_timeout_s=generate_timeout_s,
                             registry=registry,
                             circuit_open_after=circuit_open_after,
                             circuit_rejoin_after=circuit_rejoin_after,
                             damp_after=damp_after)
        super().__init__(index, _RemoteEngine(shim, registry), shim)
        self.url = shim.url

    def circuit_open(self) -> bool:
        return self.batcher.suspect()
