"""Slot-based device-resident cache of per-session recurrent state.

An LSTM session's entire decode state is ``(h, c)`` per layer — fixed-size,
independent of how many tokens the session has consumed (the O(1)
autoregressive cache; contrast a transformer's O(T) KV cache). The cache
stores it as two stacked device arrays ``[L, S+1, H]`` (layers x slots x
hidden, float32 — `lstm_step` computes carries in f32, so storage is exact)
plus a host-side session table:

- sessions map to integer **slots**; the jitted engine programs
  (serve/engine.py) gather carries by slot index, run the step, and
  scatter results back — the cache arrays are threaded through jit
  functionally and replaced via :meth:`swap`;
- slot ``S`` (the last row) is a **scratch slot**: decode batches padded
  up to a bucket size point their dead rows at it, so padding writes
  never corrupt a live session;
- **LRU eviction** frees the least-recently-used unpinned slot when the
  cache is full; the batcher pins slots while their session is active in
  a batch, so eviction only ever hits idle (kept-alive) sessions;
- **detach/restore**: `detach` pulls a session's carries to host numpy
  (releasing the slot), `restore` re-admits them later — the round trip
  is exact (tests/test_serve_cache.py proves continued decode is
  token-identical to an uninterrupted run).

Window-grain accounting: with windowed decode (serve/engine.py
`decode_window`) the cache arrays advance once per WINDOW, not per token,
and under the batcher's dispatch-ahead pipeline `swap` may install a
handle whose program has not finished (or started) executing — that is
safe because every consumer (the next window, a prefill, `detach`)
receives the handle and is therefore data-ordered after it on device.
``generation`` counts swaps (device programs applied to the cache), so
``stats()`` exposes how coarse the update grain actually is:
``tokens_generated / generation`` ≈ effective window size.

Host-side bookkeeping is lock-protected; device reads/writes are plain
jnp gather/scatter ops (one compile each per batch-shape, amortised).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import NamedTuple

import jax.numpy as jnp
import numpy as np


class CacheFullError(RuntimeError):
    """No free slot and every occupied slot is pinned."""


class DetachedState(NamedTuple):
    """Host-resident session state: h, c each ``[L, H]`` float32 numpy."""

    h: np.ndarray
    c: np.ndarray


class StateCache:
    def __init__(self, num_layers: int, num_slots: int, hidden_size: int):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        self.num_layers = num_layers
        self.num_slots = num_slots
        self.hidden_size = hidden_size
        # +1: the scratch slot for padded batch rows (index == num_slots)
        self.h = jnp.zeros((num_layers, num_slots + 1, hidden_size), jnp.float32)
        self.c = jnp.zeros((num_layers, num_slots + 1, hidden_size), jnp.float32)
        self._lock = threading.RLock()
        self._slots: OrderedDict[str, int] = OrderedDict()  # LRU: oldest first
        self._free: list[int] = list(range(num_slots))
        self._pinned: set[str] = set()
        self.evictions = 0
        self.generation = 0  # device programs applied via swap()

    @property
    def scratch_slot(self) -> int:
        return self.num_slots

    # ---- session table -------------------------------------------------

    def lookup(self, session_id: str) -> int | None:
        """Slot for a live session (refreshes LRU recency), else None."""
        with self._lock:
            if session_id not in self._slots:
                return None
            self._slots.move_to_end(session_id)
            return self._slots[session_id]

    def acquire(self, session_id: str) -> tuple[int, bool]:
        """Return ``(slot, fresh)`` for the session, allocating if needed.

        ``fresh`` is True when the slot holds no prior state for this
        session (new allocation) — the engine's prefill zeroes the initial
        carries for fresh rows instead of trusting the slot contents, so
        acquire never needs a device-side zeroing dispatch.
        """
        with self._lock:
            if session_id in self._slots:
                self._slots.move_to_end(session_id)
                return self._slots[session_id], False
            if self._free:
                slot = self._free.pop()
            else:
                slot = self._evict_lru_locked()
            self._slots[session_id] = slot
            return slot, True

    def _evict_lru_locked(self) -> int:
        for sid in self._slots:  # oldest-recency first
            if sid not in self._pinned:
                slot = self._slots.pop(sid)
                self.evictions += 1
                return slot
        raise CacheFullError(
            f"all {self.num_slots} slots pinned by active sessions"
        )

    def release(self, session_id: str) -> None:
        """Drop the session (its slot returns to the free list). No-op for
        unknown sessions — release after eviction must be safe."""
        with self._lock:
            self._pinned.discard(session_id)
            slot = self._slots.pop(session_id, None)
            if slot is not None:
                self._free.append(slot)

    def pin(self, session_id: str) -> None:
        with self._lock:
            if session_id not in self._slots:
                raise KeyError(f"cannot pin unknown session {session_id!r}")
            self._pinned.add(session_id)

    def unpin(self, session_id: str) -> None:
        with self._lock:
            self._pinned.discard(session_id)

    def __contains__(self, session_id: str) -> bool:
        with self._lock:
            return session_id in self._slots

    def __len__(self) -> int:
        with self._lock:
            return len(self._slots)

    # ---- device state --------------------------------------------------

    def swap(self, h: jnp.ndarray, c: jnp.ndarray) -> None:
        """Install updated cache arrays (the jitted step's outputs — may
        still be computing under async dispatch; consumers are
        data-ordered through the handles)."""
        self.h, self.c = h, c
        self.generation += 1

    def read_slots(self, slots) -> tuple[jnp.ndarray, jnp.ndarray]:
        """Gather carries for ``slots`` [B] → (h, c) each ``[L, B, H]``."""
        idx = jnp.asarray(slots, jnp.int32)
        return self.h[:, idx, :], self.c[:, idx, :]

    def write_slots(self, slots, h, c) -> None:
        """Scatter (h, c) each ``[L, B, H]`` into ``slots`` [B]."""
        idx = jnp.asarray(slots, jnp.int32)
        self.h = self.h.at[:, idx, :].set(h)
        self.c = self.c.at[:, idx, :].set(c)

    # ---- detach / restore ---------------------------------------------

    def detach(self, session_id: str) -> DetachedState:
        """Pull a session's carries to host and release its slot.

        The returned :class:`DetachedState` is exact (f32 both ways) —
        restoring it and continuing decode is bit-identical to never
        having detached.
        """
        with self._lock:
            if session_id not in self._slots:
                raise KeyError(f"cannot detach unknown session {session_id!r}")
            slot = self._slots[session_id]
            state = DetachedState(
                h=np.asarray(self.h[:, slot, :]),
                c=np.asarray(self.c[:, slot, :]),
            )
            self.release(session_id)
            return state

    def restore(self, session_id: str, state: DetachedState) -> int:
        """Re-admit a detached session; returns its (new) slot."""
        if state.h.shape != (self.num_layers, self.hidden_size):
            raise ValueError(
                f"detached state shape {state.h.shape} does not match cache "
                f"({self.num_layers}, {self.hidden_size})"
            )
        with self._lock:
            if session_id in self._slots:
                raise ValueError(f"session {session_id!r} already live")
            slot, _ = self.acquire(session_id)
            self.write_slots(
                np.asarray([slot]),
                jnp.asarray(state.h)[:, None, :],
                jnp.asarray(state.c)[:, None, :],
            )
            return slot

    def stats(self) -> dict:
        with self._lock:
            return {
                "slots": self.num_slots,
                "live_sessions": len(self._slots),
                "pinned": len(self._pinned),
                "free": len(self._free),
                "evictions": self.evictions,
                "generation": self.generation,
            }
