"""Slot-based device-resident cache of per-session recurrent state.

An LSTM session's entire decode state is ``(h, c)`` per layer — fixed-size,
independent of how many tokens the session has consumed (the O(1)
autoregressive cache; contrast a transformer's O(T) KV cache). The cache
stores it as two stacked device arrays ``[L, S+1, H]`` (layers x slots x
hidden, float32 — `lstm_step` computes carries in f32, so storage is exact)
plus a host-side session table:

- sessions map to integer **slots**; the jitted engine programs
  (serve/engine.py) gather carries by slot index, run the step, and
  scatter results back — the cache arrays are threaded through jit
  functionally and replaced via :meth:`swap`;
- slot ``S`` (the last row) is a **scratch slot**: decode batches padded
  up to a bucket size point their dead rows at it, so padding writes
  never corrupt a live session;
- **LRU eviction** frees the least-recently-used unpinned slot when the
  cache is full; the batcher pins slots while their session is active in
  a batch, so eviction only ever hits idle (kept-alive) sessions;
- **detach/restore**: `detach` pulls a session's carries to host numpy
  (releasing the slot), `restore` re-admits them later — the round trip
  is exact (tests/test_serve_cache.py proves continued decode is
  token-identical to an uninterrupted run).

Window-grain accounting: with windowed decode (serve/engine.py
`decode_window`) the cache arrays advance once per WINDOW, not per token,
and under the batcher's dispatch-ahead pipeline `swap` may install a
handle whose program has not finished (or started) executing — that is
safe because every consumer (the next window, a prefill, `detach`)
receives the handle and is therefore data-ordered after it on device.
``generation`` counts swaps (device programs applied to the cache), so
``stats()`` exposes how coarse the update grain actually is:
``tokens_generated / generation`` ≈ effective window size.

Host-side bookkeeping is lock-protected; device reads/writes are plain
jnp gather/scatter ops (one compile each per batch-shape, amortised).

:class:`PrefixCache` (same file) layers shared-prompt reuse on top: a
store of "state after token-prefix P" entries, each backed by a
state-cache slot under the reserved ``prefix/`` session namespace —
longest-match lookup, refcounted use, LRU eviction in both directions
(see its docstring).
"""

from __future__ import annotations

import json
import hashlib
import os
import threading
import time
from collections import OrderedDict, deque
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs
from ..resilience import faults as _faults
from ..train.checkpoint import CorruptCheckpointError, atomic_write, read_verified


class CacheFullError(RuntimeError):
    """No free slot and every occupied slot is pinned."""


# jitted h/c slot scatter: the eager ``.at[].set()`` pair costs ~1 ms of
# dispatch overhead per call on CPU (two un-jitted ops each tracing
# through the eager path) — measured as the dominant per-continuation
# fill cost in the BENCH_serve_r05 hot-set re-gate. One jitted program
# (cached per shape; fill batches are power-of-two padded so the shape
# set stays tiny) makes a warm fill dispatch sub-millisecond.
@jax.jit
def _scatter_slots(h, c, idx, hs, cs):
    return (h.at[:, idx, :].set(hs.astype(h.dtype)),
            c.at[:, idx, :].set(cs.astype(c.dtype)))


# jitted gather+scatter for pending-capture fills: rows gathered from an
# immutable captured snapshot and scattered into the live arrays as ONE
# program (the eager form paid two slice ops + two scatter ops of
# dispatch overhead per fill)
@jax.jit
def _gather_scatter_slots(h, c, src_h, src_c, src, dst):
    return (h.at[:, dst, :].set(src_h[:, src, :].astype(h.dtype)),
            c.at[:, dst, :].set(src_c[:, src, :].astype(c.dtype)))


#: session-id namespace for prefix-cache backing slots. Client-facing
#: layers (batcher Request) reject ids under it: a client naming a prefix
#: entry's session would inherit — and corrupt — the shared prefix state.
PREFIX_SID_NAMESPACE = "prefix/"

#: prefix-store stats() keys that are per-replica CONFIG (or mode
#: labels), not counters — cross-replica aggregation (loadgen
#: ``prefix_totals``, ServeServer's heartbeat fan-in) keeps replica 0's
#: value for these instead of summing. One constant shared by the
#: exact-match PrefixCache and the radix PrefixTrie so the two
#: aggregations can never drift.
PREFIX_STATS_CONFIG_KEYS = ("stride", "max_entries", "max_nodes",
                            "host_bytes", "state_bytes", "mode")


class DetachedState(NamedTuple):
    """Host-resident session state: h, c each ``[L, H]`` float32 numpy."""

    h: np.ndarray
    c: np.ndarray


class StateCache:
    def __init__(self, num_layers: int, num_slots: int, hidden_size: int,
                 registry=None, device=None, sharding=None):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if device is not None and sharding is not None:
            raise ValueError("pass device OR sharding, not both")
        self.num_layers = num_layers
        self.num_slots = num_slots
        self.hidden_size = hidden_size
        # remembered for resize(): a reallocated array pair must land
        # exactly where the originals did (committed device / mesh
        # sharding), or the engine's programs would recompile against a
        # different placement
        self._placement = device if device is not None else sharding
        # +1: the scratch slot for padded batch rows (index == num_slots)
        self.h = jnp.zeros((num_layers, num_slots + 1, hidden_size), jnp.float32)
        self.c = jnp.zeros((num_layers, num_slots + 1, hidden_size), jnp.float32)
        if device is not None:
            # device-per-replica serving: commit the cache arrays so every
            # program touching them (and their uncommitted host inputs)
            # runs on this replica's device
            self.h = jax.device_put(self.h, device)
            self.c = jax.device_put(self.c, device)
        elif sharding is not None:
            # mesh-per-replica serving (ServeEngine mesh_shards > 1): the
            # cache slots shard over the hidden axis like the params —
            # every gather/scatter/step program then runs sharded with
            # XLA deriving the collectives, and detach/device_get
            # assemble the full rows host-side
            self.h = jax.device_put(self.h, sharding)
            self.c = jax.device_put(self.c, sharding)
        self._lock = threading.RLock()
        self._slots: OrderedDict[str, int] = OrderedDict()  # LRU: oldest first
        self._free: list[int] = list(range(num_slots))
        self._pinned: set[str] = set()
        self.evictions = 0
        self.generation = 0  # device programs applied via swap()
        # registry counters feed /metrics; the per-instance ints above stay
        # the source for this instance's stats() (the registry aggregates
        # across every cache in the process — Prometheus semantics)
        reg = obs.REGISTRY if registry is None else registry
        self._m_evictions = reg.counter(
            "serve_state_cache_evictions_total",
            "LRU evictions of unpinned session slots")
        self._m_swaps = reg.counter(
            "serve_state_cache_swaps_total",
            "device programs applied to the cache arrays (generation)")
        # eviction listeners: called (under the cache lock) with the
        # ``(sid, slot)`` of every LRU-evicted session — the prefix cache
        # registers here so a slot eviction INVALIDATES (or, tiered,
        # SPILLS) the dependent prefix entry instead of leaving it
        # pointing at a slot another session now owns; SessionTiers
        # registers here to capture the evicted state's device handles
        # for the async host-tier spill
        self.evict_listeners: list = []

    @property
    def scratch_slot(self) -> int:
        # lock-free on the hot dispatch path: resize() only rebinds
        # num_slots with the cache drained (no sessions, no dispatches),
        # and a plain int rebind cannot tear
        return self.num_slots  # graftlint: disable=cross-thread-state

    # ---- session table -------------------------------------------------

    def lookup(self, session_id: str) -> int | None:
        """Slot for a live session (refreshes LRU recency), else None."""
        with self._lock:
            if session_id not in self._slots:
                return None
            self._slots.move_to_end(session_id)
            return self._slots[session_id]

    def acquire(self, session_id: str) -> tuple[int, bool]:
        """Return ``(slot, fresh)`` for the session, allocating if needed.

        ``fresh`` is True when the slot holds no prior state for this
        session (new allocation) — the engine's prefill zeroes the initial
        carries for fresh rows instead of trusting the slot contents, so
        acquire never needs a device-side zeroing dispatch.
        """
        with self._lock:
            if session_id in self._slots:
                self._slots.move_to_end(session_id)
                return self._slots[session_id], False
            if self._free:
                slot = self._free.pop()
            else:
                slot = self._evict_lru_locked()
            self._slots[session_id] = slot
            return slot, True

    def _evict_lru_locked(self) -> int:
        for sid in self._slots:  # oldest-recency first
            if sid not in self._pinned:
                slot = self._slots.pop(sid)
                self.evictions += 1
                self._m_evictions.inc()
                for listener in self.evict_listeners:
                    listener(sid, slot)
                return slot
        raise CacheFullError(
            f"all {self.num_slots} slots pinned by active sessions"
        )

    def release(self, session_id: str) -> None:
        """Drop the session (its slot returns to the free list). No-op for
        unknown sessions — release after eviction must be safe."""
        with self._lock:
            self._pinned.discard(session_id)
            slot = self._slots.pop(session_id, None)
            if slot is not None:
                self._free.append(slot)

    def acquire_pinned(self, session_id: str) -> tuple[int, bool]:
        """:meth:`acquire` + :meth:`pin` under ONE lock hold — with
        concurrent acquirers (the router's fill_ahead), a separate
        acquire→pin pair leaves a window where the fresh unpinned slot
        is LRU-evicted from under the caller and pin() raises. The
        batcher's admission uses this."""
        with self._lock:
            slot, fresh = self.acquire(session_id)
            self._pinned.add(session_id)
            return slot, fresh

    def pin(self, session_id: str) -> None:
        with self._lock:
            if session_id not in self._slots:
                raise KeyError(f"cannot pin unknown session {session_id!r}")
            self._pinned.add(session_id)

    def unpin(self, session_id: str) -> None:
        with self._lock:
            self._pinned.discard(session_id)

    def is_pinned(self, session_id: str) -> bool:
        """True while the session's slot is held by active work — the
        router's drain path must not detach a pinned session (its
        in-flight decode still writes the slot)."""
        with self._lock:
            return session_id in self._pinned

    def __contains__(self, session_id: str) -> bool:
        with self._lock:
            return session_id in self._slots

    def session_ids(self) -> list[str]:
        """Live session ids, LRU-oldest first (includes the ``prefix/``
        namespace — callers that only want client sessions filter it).
        The router's replica-retirement path enumerates these to migrate
        a dead replica's idle kept sessions via detach/restore."""
        with self._lock:
            return list(self._slots)

    def __len__(self) -> int:
        with self._lock:
            return len(self._slots)

    # ---- device state --------------------------------------------------

    def swap(self, h: jnp.ndarray, c: jnp.ndarray) -> None:
        """Install updated cache arrays (the jitted step's outputs — may
        still be computing under async dispatch; consumers are
        data-ordered through the handles). Handle installation takes the
        cache lock: the engine lock serialises dispatchers, but detach()
        reads ``h``/``c`` from client threads and must never observe the
        ``h``/``c`` pair mid-replacement."""
        with self._lock:
            self.h, self.c = h, c
            self.generation += 1
        self._m_swaps.inc()

    def read_slots(self, slots) -> tuple[jnp.ndarray, jnp.ndarray]:
        """Gather carries for ``slots`` [B] → (h, c) each ``[L, B, H]``."""
        idx = jnp.asarray(slots, jnp.int32)
        with self._lock:
            return self.h[:, idx, :], self.c[:, idx, :]

    def write_slots(self, slots, h, c) -> None:
        """Scatter (h, c) each ``[L, B, H]`` into ``slots`` [B] — one
        jitted program (see ``_scatter_slots``), so a tier fill on the
        admission path costs a cheap jit dispatch, not two eager ops."""
        idx = jnp.asarray(slots, jnp.int32)
        with self._lock:
            self.h, self.c = _scatter_slots(self.h, self.c, idx,
                                            jnp.asarray(h), jnp.asarray(c))

    def gather_scatter(self, dst_slots, src_h, src_c, src_slots) -> None:
        """Gather ``src_slots`` rows from a CAPTURED snapshot pair
        (``src_h``/``src_c`` — immutable functional snapshots, possibly
        generations old) and scatter them into ``dst_slots`` of the live
        arrays, as one jitted program (the pending-capture tier fill)."""
        src = jnp.asarray(src_slots, jnp.int32)
        dst = jnp.asarray(dst_slots, jnp.int32)
        with self._lock:
            self.h, self.c = _gather_scatter_slots(
                self.h, self.c, src_h, src_c, src, dst)

    def copy_slot(self, src: int, dst: int) -> None:
        """O(1) on-device copy of one slot's carries (src read, dst
        written) — how a prefix entry snapshots a session's state. Threads
        through the cache arrays, so it is data-ordered after any
        in-flight program that writes ``src``."""
        with self._lock:
            self.h = self.h.at[:, dst, :].set(self.h[:, src, :])
            self.c = self.c.at[:, dst, :].set(self.c[:, src, :])

    # ---- detach / restore ---------------------------------------------

    @staticmethod
    def fetch_detached(h_handle, c_handle) -> DetachedState:
        """Blocking device→host fetch of one session's sliced carries —
        the spill plane's ONE designated sync point (graftlint
        ``host-sync`` allow-list, like the batcher's ``fetch_window``).
        The handles are functional snapshots, so this may run long after
        the slot was reused and still reads the pre-eviction values."""
        return DetachedState(h=np.asarray(h_handle), c=np.asarray(c_handle))

    @staticmethod
    def fetch_detached_batch(captures) -> list[DetachedState]:
        """Batched form of :meth:`fetch_detached` for the spill worker:
        ``captures`` is a list of ``(h_array, c_array, slot)`` triples —
        FULL cache-array snapshots plus the slot to extract, or
        pre-sliced ``[L, H]`` handles with ``slot=None`` (the tiers'
        memory-pressure valve). One blocking ``device_get`` over the
        deduplicated arrays fetches everything (N spills cost one
        pipeline wait), and the per-slot extraction happens in numpy —
        ZERO per-job device ops on the fast path."""
        uniq: dict[int, object] = {}
        for h, c, slot in captures:
            uniq.setdefault(id(h), h)
            uniq.setdefault(id(c), c)
        fetched = jax.device_get(list(uniq.values()))
        by_id = dict(zip(uniq.keys(), fetched))
        out = []
        for h, c, slot in captures:
            fh, fc = by_id[id(h)], by_id[id(c)]
            if slot is None:  # pre-sliced capture: already [L, H]
                out.append(DetachedState(h=fh, c=fc))
            else:
                out.append(DetachedState(h=fh[:, slot, :].copy(),
                                         c=fc[:, slot, :].copy()))
        return out

    def detach(self, session_id: str) -> DetachedState:
        """Pull a session's carries to host and release its slot.

        The returned :class:`DetachedState` is exact (f32 both ways) —
        restoring it and continuing decode is bit-identical to never
        having detached.
        """
        with self._lock:
            if session_id not in self._slots:
                raise KeyError(f"cannot detach unknown session {session_id!r}")
            slot = self._slots[session_id]
            # slice the handles under the lock; the blocking host fetch
            # happens OUTSIDE it — holding the (scheduler-shared) lock
            # across a device drain would stall every dispatch behind
            # this client-thread call
            h_handle = self.h[:, slot, :]
            c_handle = self.c[:, slot, :]
            self.release(session_id)
        return DetachedState(h=np.asarray(h_handle), c=np.asarray(c_handle))

    def restore(self, session_id: str, state: DetachedState) -> int:
        """Re-admit a detached session; returns its (new) slot."""
        if state.h.shape != (self.num_layers, self.hidden_size):
            raise ValueError(
                f"detached state shape {state.h.shape} does not match cache "
                f"({self.num_layers}, {self.hidden_size})"
            )
        with self._lock:
            if session_id in self._slots:
                raise ValueError(f"session {session_id!r} already live")
            slot, _ = self.acquire(session_id)
            self.write_slots(
                np.asarray([slot]),
                jnp.asarray(state.h)[:, None, :],
                jnp.asarray(state.c)[:, None, :],
            )
            return slot

    def resize(self, num_slots: int) -> None:
        """Reallocate the slot arrays at a new slot count (the rollout
        controller's drained-replica resize move). Only legal while NO
        sessions are resident — live carries would not survive the
        reallocation, so the caller drains/migrates first. The new
        arrays keep the original placement (committed device or mesh
        sharding); the bucket programs themselves are slot-count
        agnostic (slots are a gather index, the array's slot axis is a
        shape), so a resize invalidates compiled programs exactly like
        any other shape change — warm up before rejoining traffic."""
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        with self._lock:
            if self._slots:
                raise RuntimeError(
                    f"cannot resize with {len(self._slots)} resident "
                    "sessions — drain and migrate them first")
            self.num_slots = num_slots
            h = jnp.zeros((self.num_layers, num_slots + 1,
                           self.hidden_size), jnp.float32)
            c = jnp.zeros((self.num_layers, num_slots + 1,
                           self.hidden_size), jnp.float32)
            if self._placement is not None:
                h = jax.device_put(h, self._placement)
                c = jax.device_put(c, self._placement)
            self.h, self.c = h, c
            self._free = list(range(num_slots))
            self._pinned.clear()
            self.generation += 1
        self._m_swaps.inc()

    def stats(self) -> dict:
        with self._lock:
            return {
                "slots": self.num_slots,
                "live_sessions": len(self._slots),
                "pinned": len(self._pinned),
                "free": len(self._free),
                "evictions": self.evictions,
                "generation": self.generation,
            }


class PrefixEntry:
    """One cached prefix: the exact token prefix, its backing state-cache
    session/slot, and a refcount of in-flight prefills reading it.
    ``slot`` is None while the entry is SPILLED (its backing slot was
    LRU-evicted under a tiered cache — the state lives in the host tier
    until a lookup promotes it back)."""

    __slots__ = ("key", "length", "sid", "slot", "refs")

    def __init__(self, key: bytes, length: int, sid: str, slot: int | None):
        self.key = key
        self.length = length
        self.sid = sid
        self.slot = slot
        self.refs = 0


class PrefixCache:
    """Shared-prompt prefix store over the :class:`StateCache`.

    An LSTM's state after ANY prefix is one O(1) ``(h, c)`` pair per layer,
    so exact prefix reuse is a slot copy — not a KV-cache re-plumb. Entries
    are keyed by the **exact token bytes** of the prefix (the dict hash IS
    the prefix hash; storing the bytes makes collisions impossible) and
    live at ``stride``-aligned lengths, so :meth:`lookup` probes the few
    distinct entry lengths longest-first. Each entry owns a state-cache
    slot under the reserved ``prefix/`` session namespace:

    - **refcounting**: ``lookup`` pins the backing slot and bumps ``refs``
      until the resumed prefill has been *dispatched* (`release`) — device
      data-ordering through the cache arrays makes it safe to release at
      dispatch, not completion;
    - **LRU eviction**: a full prefix cache evicts its own oldest
      zero-ref entry (releasing the backing slot); conversely a state-cache
      LRU eviction of a backing slot **invalidates** the dependent entry
      via the cache's eviction listener — an invalidated prefix is a miss,
      never a read of a slot someone else now owns;
    - a matched length is capped at ``len(prompt) - 1``: at least one real
      prompt token is always prefilled, so the first sampled token comes
      from the same head math as an uncached run (token-identical greedy
      parity, tests/test_serve_prefix.py).

    Synchronisation: shares the state cache's reentrant lock — the
    eviction listener fires under it, and a private lock here would ABBA
    with ``acquire``/``pin`` calls made from prefix methods.
    """

    def __init__(self, cache: StateCache, *, stride: int = 8,
                 max_entries: int = 16, registry=None, tiers=None):
        if stride < 1:
            raise ValueError(f"stride must be >= 1, got {stride}")
        if max_entries < 1:
            raise ValueError(f"max_entries must be >= 1, got {max_entries}")
        self.cache = cache
        self.stride = stride
        self.max_entries = max_entries
        # tiered spill/promote (SessionTiers): with tiers attached, a
        # state-cache eviction of a backing slot SPILLS the entry (state
        # survives in the host tier, slot=None) instead of invalidating
        # it — a later hit pays one host→device copy, not a re-prefill
        self.tiers: SessionTiers | None = tiers
        self._lock = cache._lock  # shared on purpose (see docstring)
        self._entries: OrderedDict[bytes, PrefixEntry] = OrderedDict()
        self._by_sid: dict[str, bytes] = {}
        # distinct entry lengths, maintained incrementally (descending
        # list + per-length entry counts) so lookup never re-sorts the
        # whole entry set under the shared lock on every admission
        self._lengths_desc: list[int] = []
        self._length_counts: dict[int, int] = {}
        self._sid_counter = 0
        self.hits = 0
        self.misses = 0
        self.inserts = 0
        self.evictions = 0     # own LRU (full prefix cache)
        self.invalidated = 0   # backing slot evicted under us, state lost
        self.spilled = 0       # backing slot evicted, state kept in a tier
        self.promoted = 0      # spilled entry restored to a device slot
        # /metrics mirror of the per-instance counters above (one registry
        # family per outcome; stats() keeps serving the instance's ints)
        reg = obs.REGISTRY if registry is None else registry
        self._m = reg.counter(
            "serve_prefix_cache_events_total",
            "prefix-cache outcomes (hit/miss/insert/evict/invalidate/"
            "spill/promote)",
            labelnames=("event",))
        self._m_hit = self._m.labels(event="hit")
        self._m_miss = self._m.labels(event="miss")
        self._m_insert = self._m.labels(event="insert")
        self._m_evict = self._m.labels(event="evict")
        self._m_invalidate = self._m.labels(event="invalidate")
        self._m_spill = self._m.labels(event="spill")
        self._m_promote = self._m.labels(event="promote")
        cache.evict_listeners.append(self._on_slot_evicted_locked)

    @staticmethod
    def _key(tokens) -> bytes:
        return np.asarray(tokens, np.int32).tobytes()

    def boundary(self, length: int) -> int:
        """Largest cacheable prefix length for a ``length``-token prompt:
        stride-aligned and <= length - 1 (>= 1 token must remain to
        prefill). 0 = prompt too short to cache."""
        k = ((length - 1) // self.stride) * self.stride
        return k if k >= self.stride else 0

    # ---- incremental distinct-length index (lookup's probe order) ------

    def _length_add_locked(self, n: int) -> None:
        count = self._length_counts.get(n, 0)
        self._length_counts[n] = count + 1
        if count == 0:
            # descending insert: bisect on the negated view keeps the
            # list sorted without a per-lookup re-sort
            lo, hi = 0, len(self._lengths_desc)
            while lo < hi:
                mid = (lo + hi) // 2
                if self._lengths_desc[mid] > n:
                    lo = mid + 1
                else:
                    hi = mid
            self._lengths_desc.insert(lo, n)

    def _length_drop_locked(self, n: int) -> None:
        count = self._length_counts.get(n, 0) - 1
        if count > 0:
            self._length_counts[n] = count
            return
        self._length_counts.pop(n, None)
        try:
            self._lengths_desc.remove(n)
        except ValueError:
            pass

    def lookup(self, prompt) -> tuple[PrefixEntry | None, int]:
        """Longest exact-prefix match for ``prompt`` with matched length
        <= len(prompt) - 1. A hit returns ``(entry, matched_len)`` with
        the entry ref-held and its slot pinned — the caller MUST
        :meth:`release` after dispatching the resumed prefill."""
        p = np.asarray(prompt, np.int32).reshape(-1)
        with self._lock:
            # the distinct-length probe order is maintained incrementally
            # on insert/evict (_length_add/drop_locked) — re-sorting the
            # entry set here would put an O(entries log entries) scan on
            # every fresh admission's hot path. list() snapshot: a probe
            # can drop an entry (_promote_locked loss) mid-iteration.
            for n in list(self._lengths_desc):
                if n > p.size - 1:
                    continue
                entry = self._entries.get(self._key(p[:n]))
                if entry is None:
                    continue
                if entry.slot is None and not self._promote_locked(entry):
                    # spilled entry whose state the tiers lost: the entry
                    # was dropped — keep probing shorter lengths
                    continue
                self._entries.move_to_end(entry.key)
                # refresh the BACKING slot's recency too — the state-cache
                # LRU must not evict the hottest prefix's slot first just
                # because pin/unpin never reorder it (reentrant RLock)
                self.cache.lookup(entry.sid)
                if entry.refs == 0:
                    self.cache.pin(entry.sid)
                entry.refs += 1
                self.hits += 1
                self._m_hit.inc()
                return entry, entry.length
            self.misses += 1
            self._m_miss.inc()
            return None, 0

    def _promote_locked(self, entry: PrefixEntry) -> bool:
        """Restore a SPILLED entry's state from the tiers into a fresh
        slot — the one host→device copy a tiered eviction costs instead
        of re-prefilling the shared prefix. Returns False (and drops the
        entry) when the tiered state is gone; False without dropping when
        no slot can be had right now (every slot pinned — transient)."""
        try:
            slot, fresh = self.cache.acquire(entry.sid)
        except CacheFullError:
            return False  # transient: entry stays spilled, miss this time
        # fill_memory, not fill: this runs with the shared cache lock
        # HELD (lookup's reentrant RLock), where fill()'s out-of-lock
        # disk read would silently re-enter the lock and stall every
        # admission behind the filesystem (graftlint io-under-lock).
        # Prefix states are host-only — the disk tier never holds them —
        # so the memory-only fill is semantically identical.
        if fresh and (self.tiers is None
                      or not self.tiers.fill_memory(entry.sid, slot)):
            self.cache.release(entry.sid)
            self._by_sid.pop(entry.sid, None)
            if self._entries.pop(entry.key, None) is not None:
                self._length_drop_locked(entry.length)
            self.invalidated += 1
            self._m_invalidate.inc()
            return False
        entry.slot = slot
        self.promoted += 1
        self._m_promote.inc()
        return True

    def release(self, entry: PrefixEntry) -> None:
        """Drop one ref; the last ref unpins the backing slot (making the
        entry LRU-evictable again). Safe after invalidation."""
        with self._lock:
            if entry.refs > 0:
                entry.refs -= 1
            if entry.refs == 0 and self._by_sid.get(entry.sid) == entry.key:
                self.cache.unpin(entry.sid)

    def insert(self, tokens, src_slot: int) -> bool:
        """Snapshot the state in ``src_slot`` (== the state after exactly
        ``tokens``) into a new prefix entry. Returns False — never raises —
        when the entry already exists, every entry is ref-held, or the
        state cache has no evictable slot left: prefix caching is an
        optimisation and must degrade, not fail requests."""
        key = self._key(tokens)
        length = int(np.asarray(tokens).size)
        with self._lock:
            if key in self._entries:
                # a dedup-hit is a hotness signal too: refresh the backing
                # slot's state-cache recency like the lookup path does
                self._entries.move_to_end(key)
                self.cache.lookup(self._entries[key].sid)
                return False
            while len(self._entries) >= self.max_entries:
                victim = next(
                    (e for e in self._entries.values() if e.refs == 0), None)
                if victim is None:
                    return False  # every entry is mid-use
                self._evict_entry_locked(victim)
            self._sid_counter += 1
            sid = f"{PREFIX_SID_NAMESPACE}{self._sid_counter}"
            try:
                slot, _ = self.cache.acquire(sid)
            except CacheFullError:
                return False
            self.cache.copy_slot(src_slot, slot)
            entry = PrefixEntry(key, length, sid, slot)
            self._entries[key] = entry
            self._by_sid[sid] = key
            self._length_add_locked(length)
            self.inserts += 1
            self._m_insert.inc()
            return True

    def _evict_entry_locked(self, entry: PrefixEntry) -> None:
        if self._entries.pop(entry.key, None) is not None:
            self._length_drop_locked(entry.length)
        self._by_sid.pop(entry.sid, None)
        self.cache.release(entry.sid)
        if self.tiers is not None:
            # drop any spilled copy too, or the tiers would hold state
            # for an entry that no longer exists. Memory tiers only:
            # this fires under the shared cache lock (insert's eviction
            # loop), and prefix states never reach the disk tier — the
            # full discard()'s file unlink would be IO under the hot
            # lock for a file that cannot exist (graftlint io-under-lock)
            self.tiers.discard_memory(entry.sid)
        self.evictions += 1
        self._m_evict.inc()

    def clear(self) -> None:
        """Evict every entry that is not mid-use (refs == 0), releasing
        its backing slot. The rollout controller calls this on a DRAINED
        replica before a slot-count resize — prefix entries are derived
        state (re-insertable from traffic), so dropping them is the
        cheap half of emptying the cache."""
        with self._lock:
            for entry in list(self._entries.values()):
                if entry.refs == 0:
                    self._evict_entry_locked(entry)

    def _on_slot_evicted_locked(self, sid: str, slot: int) -> None:
        # state-cache LRU took a backing slot. Untiered: the dependent
        # entry is now garbage — drop it so lookups miss instead of
        # reading a slot a live session owns. Tiered: the SessionTiers
        # listener captured the state's device handles, so the entry
        # survives SPILLED (slot=None) and a later hit promotes it back
        # for one host→device copy. The _locked suffix is the held-lock
        # calling contract (docs/LINT.md): eviction listeners fire under
        # the shared cache lock.
        key = self._by_sid.get(sid)
        if key is None:
            return
        entry = self._entries.get(key)
        if self.tiers is not None and entry is not None:
            entry.slot = None
            self.spilled += 1
            self._m_spill.inc()
            return
        self._by_sid.pop(sid, None)
        dropped = self._entries.pop(key, None)
        if dropped is not None:
            self._length_drop_locked(dropped.length)
        self.invalidated += 1
        self._m_invalidate.inc()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {
                "mode": "exact",
                "entries": len(self._entries),
                "stride": self.stride,
                "max_entries": self.max_entries,
                "hits": self.hits,
                "misses": self.misses,
                "inserts": self.inserts,
                "evictions": self.evictions,
                "invalidated": self.invalidated,
                "spilled": self.spilled,
                "promoted": self.promoted,
            }


def _pad_pow2(n: int) -> int:
    """Next power of two >= n — the fill-batch bucket lattice (a handful
    of compiled scatter shapes instead of one per distinct batch size)."""
    return 1 << max(0, n - 1).bit_length()


class _SpillJob:
    """A spill in flight: REFERENCES to the cache arrays captured (under
    the cache lock) at enqueue time plus the slot index — capturing is
    zero device ops (jax arrays are immutable functional snapshots;
    later writes to the slot create new arrays), and the actual slicing
    happens on the worker thread / at fill time, OFF the scheduler's
    admission path. ``in_queue`` tracks whether a worker queue entry
    still points here (a merged re-enqueue must not double-queue; an
    in-flight job must re-queue)."""

    __slots__ = ("h", "c", "slot", "sliced", "t0", "to_host", "to_disk",
                 "in_queue")

    def __init__(self, h, c, slot: int, t0: float, *, to_host: bool,
                 to_disk: bool, sliced: bool = False):
        self.h = h
        self.c = c
        self.slot = slot
        # sliced=True: h/c are already the [L, H] row handles (the
        # memory-pressure valve sliced at capture — see _enqueue_locked);
        # False: h/c are FULL cache-array snapshots to slice at ``slot``
        self.sliced = sliced
        self.t0 = t0
        self.to_host = to_host
        self.to_disk = to_disk
        self.in_queue = False


def session_file_path(directory: str, sid: str) -> str:
    """THE disk-tier session-file naming scheme, in one place: session
    ids are client-controlled strings, so the name is a digest
    (filesystem-safe, length-bounded) and the sid itself lives in the
    file's JSON header. Exposed module-level because the chaos drill
    and the host-kill tests probe checkpoint freshness by path — a
    private copy of the scheme would silently stop matching if it ever
    changed here."""
    digest = hashlib.sha256(sid.encode()).hexdigest()[:24]
    return os.path.join(directory, f"sess-{digest}{_DiskTier.SUFFIX}")


class _DiskTier:
    """Durable session files under one directory — the serve twin of the
    training checkpoint story (train/checkpoint.py): every file is
    written via the same fsync-before-rename ``atomic_write``, with the
    state's sha256 embedded IN the JSON header — ONE file, so
    ``os.replace`` alone decides atomically which complete payload wins
    even under concurrent same-path writers (a payload can never pair
    with another writer's stale sidecar). A file that fails its hash
    (or cannot be parsed) is QUARANTINED (renamed ``*.quarantined``,
    kept for forensics) and reported as state honestly lost — never
    served as wrong tokens.

    File name = ``sess-<sha256(sid)[:24]>.state`` (session ids are
    client-controlled strings — hashing keeps them filesystem-safe); the
    sid itself lives in the JSON header line, so a startup scan rebuilds
    the sid→file index and a restarted server can serve every session
    the previous process checkpointed.

    A private lock guards only the in-memory index; file IO runs outside
    it (and the spill worker writes files without holding the cache
    lock, so an fsync never stalls the scheduler)."""

    SUFFIX = ".state"

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        self._index: dict[str, str] = {}
        self._scan()

    def _scan(self) -> None:
        for name in sorted(os.listdir(self.directory)):
            if not name.endswith(self.SUFFIX):
                continue
            path = os.path.join(self.directory, name)
            try:
                with open(path, "rb") as f:
                    meta = json.loads(f.readline())
                sid = meta["sid"]
                if not isinstance(sid, str):
                    raise ValueError(f"bad sid {sid!r}")
            except (OSError, ValueError, KeyError, TypeError,
                    json.JSONDecodeError):
                # TypeError: header parsed as non-dict JSON — the same
                # corruption class, quarantined not crashed-on-boot
                self._quarantine(None, path)
                continue
            with self._lock:
                self._index[sid] = path

    def _path(self, sid: str) -> str:
        return session_file_path(self.directory, sid)

    def _quarantine(self, sid: str | None, path: str) -> None:
        for p in (path, path + ".sha256"):
            try:
                # no exists() pre-check: the file can vanish between the
                # stat and the rename (a peer replica quarantining the
                # same corrupt file) — FileNotFoundError lands in the
                # same best-effort OSError as every other race
                os.replace(p, p + ".quarantined")
            except OSError:
                pass  # best effort: a vanished file is already gone
        if sid is not None:
            with self._lock:
                self._index.pop(sid, None)

    def has_indexed(self, sid: str) -> bool:
        """Index-only probe — no filesystem IO, safe under hot locks
        (the eviction listener's to_disk decision; a false negative
        merely costs one redundant write)."""
        with self._lock:
            return sid in self._index

    def has(self, sid: str) -> bool:
        with self._lock:
            if sid in self._index:
                return True
        # shared-directory fallback: another replica (or a previous
        # process) may have written this session AFTER our startup scan —
        # the filename is deterministic from the sid, so one stat makes
        # peers' files visible without a rescan (the router's
        # evacuate-to-shared-disk migration depends on this)
        path = self._path(sid)
        if os.path.exists(path):
            with self._lock:
                self._index[sid] = path
            return True
        return False

    def sids(self) -> list[str]:
        with self._lock:
            return list(self._index)

    def __len__(self) -> int:
        with self._lock:
            return len(self._index)

    def put(self, sid: str, state: DetachedState) -> None:
        # chaos drills: an armed disk_write_err fault raises OSError here
        # — the same path a full/failing filesystem takes, so callers'
        # disk_error accounting (durability lost, correctness kept) is
        # exercised for real
        _faults.serve_disk_hook("write")
        body = (state.h.astype(np.float32).tobytes()
                + state.c.astype(np.float32).tobytes())
        # the sha256 lives IN the header, not a sidecar: a session file
        # is then ONE file whose os.replace alone decides, atomically,
        # which complete payload wins — concurrent same-path writers
        # (shared --session-dir retirement races) can never pair one
        # writer's payload with another's sidecar hash
        meta = {"sid": sid, "layers": int(state.h.shape[0]),
                "hidden": int(state.h.shape[1]), "dtype": "float32",
                "sha256": hashlib.sha256(body).hexdigest()}
        payload = json.dumps(meta).encode() + b"\n" + body
        path = self._path(sid)
        atomic_write(path, payload)
        # chaos drills: session_corrupt damages the COMPLETED file (the
        # bit-rot/torn-write class the embedded sha256 must catch at
        # fill time with a quarantine + honest "state lost")
        _faults.maybe_corrupt_session(path)
        with self._lock:
            self._index[sid] = path

    def get(self, sid: str, num_layers: int,
            hidden_size: int) -> DetachedState | None:
        """Read + verify one session file. None = not present; raises
        :class:`CorruptCheckpointError` AFTER quarantining the file when
        it exists but cannot be trusted."""
        with self._lock:
            path = self._index.get(sid)
        if path is None:
            # same shared-directory fallback as has(): a peer replica may
            # have written the file after our startup scan
            cand = self._path(sid)
            if not os.path.exists(cand):
                return None
            path = cand
            with self._lock:
                self._index[sid] = path
        try:
            # chaos drills: disk_read_err raises OSError inside this try
            # — the same honest-miss path a vanished/unreadable file
            # takes ("state lost", never wrong tokens)
            _faults.serve_disk_hook("read")
            data = read_verified(path)
        except CorruptCheckpointError:
            self._quarantine(sid, path)
            raise
        except OSError:
            # vanished/unreadable: a miss, not corruption — keep the file
            with self._lock:
                self._index.pop(sid, None)
            return None
        try:
            head, _, body = data.partition(b"\n")
            meta = json.loads(head)
            n = num_layers * hidden_size * 4
            if meta.get("sid") != sid or len(body) != 2 * n:
                raise ValueError(
                    f"session payload mismatch (sid {meta.get('sid')!r}, "
                    f"{len(body)} state bytes, expected {2 * n})")
            got = hashlib.sha256(body).hexdigest()
            if meta.get("sha256") != got:
                raise ValueError(
                    f"state sha256 mismatch (header "
                    f"{str(meta.get('sha256'))[:12]}…, got {got[:12]}…) — "
                    "truncated or corrupted write")
            h = np.frombuffer(body[:n], np.float32).reshape(
                num_layers, hidden_size).copy()
            c = np.frombuffer(body[n:], np.float32).reshape(
                num_layers, hidden_size).copy()
        except (ValueError, KeyError, TypeError, AttributeError,
                json.JSONDecodeError) as e:
            # TypeError/AttributeError: header parsed as non-dict JSON —
            # corruption, not a crash for the scheduler thread
            self._quarantine(sid, path)
            raise CorruptCheckpointError(f"{path}: {e}") from e
        return DetachedState(h=h, c=c)

    def discard(self, sid: str) -> None:
        with self._lock:
            path = self._index.pop(sid, None)
        if path is not None:
            for p in (path, path + ".sha256"):
                try:
                    # exists+remove is the TOCTOU the flush-vs-discard
                    # race exercises for real: just remove, a vanished
                    # file is already the desired state
                    os.remove(p)
                except OSError:
                    pass


class SessionTiers:
    """Host-RAM and disk tiers under the device :class:`StateCache`.

    Device slots stay tier 0. When the state cache LRU-evicts an idle
    session, the eviction listener (fired under the cache lock) captures
    REFERENCES to the current ``(h, c)`` cache arrays plus the slot
    index — zero device ops on the serving path; jax arrays are
    immutable functional snapshots, so the capture stays valid after the
    slot is reused — and enqueues an ASYNC spill: a background worker
    thread drains the queue in batches and performs the one designated
    device→host fetch (``StateCache.fetch_detached_batch`` — deduped
    full-snapshot ``device_get`` + numpy slot extraction, ONE pipeline
    wait per batch; graftlint ``host-sync`` covers this thread exactly
    like the batcher's scheduler loop) and stores the states in the host
    tier. Host-tier overflow cascades the oldest entry down to the disk
    tier (:class:`_DiskTier` — the PR 2 sha256/fsync checkpoint
    machinery applied to session files), or drops it honestly when no
    directory is configured.

    **Fill** is the reverse path: a continuation for a spilled session
    restores its state into a freshly acquired slot — from the pending
    spill's device handles (a device→device copy; the fetch never
    happened), the host tier (one host→device copy), or a verified disk
    read. Fills run inline under the shared cache lock (admission calls
    :meth:`fill`; the router's affinity probe calls :meth:`fill_ahead`
    before the continuation reaches the scheduler), so a session is
    either resident or honestly absent — there is no window where a
    racing eviction can hand a continuation someone else's slot.

    **Serve-session checkpointing**: :meth:`checkpoint` (called by the
    batcher when a ``keep_session`` request completes) write-behinds the
    session's request-boundary state to the disk tier. Because sessions
    are only evictable while idle, and idle state always equals the last
    request boundary, a disk file is never stale while its session is
    fillable — so a crashed-and-restarted server (supervise.py) resumes
    every checkpointed session token-identically from disk. The
    durability boundary is the last COMPLETED request whose write-behind
    flushed (``flush()``; a clean ``ServeServer.stop`` flushes).

    Synchronisation: shares the state cache's reentrant lock (the evict
    listener fires under it; a private lock would ABBA with the
    ``acquire``/``write_slots`` calls made from fill paths). The worker
    fetches and writes files OUTSIDE the lock."""

    def __init__(self, cache: StateCache, *, host_entries: int = 256,
                 directory: str | None = None, registry=None,
                 replica: int = 0):
        if host_entries < 1:
            raise ValueError(f"host_entries must be >= 1, got {host_entries}")
        self.cache = cache
        self.host_entries = host_entries
        self._lock = cache._lock  # shared on purpose (see docstring)
        self._work = threading.Condition(self._lock)
        self._pending: dict[str, _SpillJob] = {}
        self._queue: deque[str] = deque()
        self._host: OrderedDict[str, DetachedState] = OrderedDict()
        # host-overflow victims whose disk write is IN FLIGHT: they stay
        # fillable here until the write lands — without this, a
        # continuation arriving between the host-tier pop and the fsync
        # would spuriously fail "state lost"
        self._evacuating: dict[str, DetachedState] = {}
        # sids discarded WHILE a disk flush is running: the flusher
        # deletes any file it just wrote for them (a stale write landing
        # after an un-kept completion's discard must not resurrect the
        # session). Only populated during a flush; cleared after.
        self._dropped: set[str] = set()
        self._flushing = 0
        self._disk = _DiskTier(directory) if directory else None
        self._thread: threading.Thread | None = None
        self._closed = False  # close() parks the worker; enqueue revives
        self._in_flight = 0
        self.spills = {"host": 0, "disk": 0}
        self.fills = {"host": 0, "disk": 0}
        self.misses = 0
        self.corrupt = 0
        self.lost = 0  # host overflow dropped without a disk tier
        self.disk_errors = 0  # failed disk writes (state kept in RAM)
        self._registry = obs.REGISTRY if registry is None else registry
        self._bind_metrics(replica)
        cache.evict_listeners.append(self._on_slot_evicted_locked)

    def _bind_metrics(self, replica: int) -> None:
        """Resolve the labelled instruments for ``replica``. Plain
        attribute assignment on purpose (NOT under the lock): rebinding
        happens before traffic (construction / ServeServer wiring), and
        the record sites read these without holding the lock."""
        reg = self._registry
        rl = str(replica)
        fam = reg.counter(
            "serve_tier_spills_total",
            "session states spilled into a tier (host = RAM spill of an "
            "evicted slot; disk = durable session file written)",
            labelnames=("tier", "replica"))
        self._m_spill = {t: fam.labels(tier=t, replica=rl)
                         for t in ("host", "disk")}
        fam = reg.counter(
            "serve_tier_fills_total",
            "spilled session states restored into a device slot, by "
            "source tier",
            labelnames=("tier", "replica"))
        self._m_fill = {t: fam.labels(tier=t, replica=rl)
                        for t in ("host", "disk")}
        fam = reg.counter(
            "serve_tier_lost_total",
            "tier state trouble, by reason (miss = no tier holds it, "
            "corrupt = disk file quarantined, overflow = host tier full "
            "with no disk tier; disk_error = a disk write failed — state "
            "stays in RAM, durability lost, correctness kept)",
            labelnames=("reason", "replica"))
        self._m_lost = {r: fam.labels(reason=r, replica=rl)
                        for r in ("miss", "corrupt", "overflow",
                                  "disk_error")}
        self._m_spill_lat = reg.histogram(
            "serve_tier_spill_seconds",
            "eviction → spilled state stored (device fetch + optional "
            "disk write), per spill job",
            labelnames=("replica",)).labels(replica=rl)
        self._m_fill_lat = reg.histogram(
            "serve_tier_fill_seconds",
            "tier fill: probe → state written back into a device slot",
            labelnames=("replica",)).labels(replica=rl)

    def set_replica(self, replica: int) -> None:
        """Re-bind the metric children to a replica index (ServeServer
        wires this so tier metrics carry the right ``replica`` label even
        for engines built without one). Call before taking traffic."""
        self._bind_metrics(replica)

    # ---- spill capture (under the cache lock) --------------------------

    def _on_slot_evicted_locked(self, sid: str, slot: int) -> None:
        # fired by the state cache's LRU under the shared lock: capture
        # REFERENCES to the current cache arrays (zero device ops — the
        # functional snapshot means later writes to the slot create new
        # arrays) and let the worker slice + fetch them off-thread.
        # Evicted sids are idle kept sessions (active ones are pinned)
        # and prefix/ backing slots; prefix states stay host-only (their
        # entries die with the process anyway).
        # has_indexed (no filesystem stat): this fires on the scheduler's
        # admission path under the shared lock — a false negative only
        # costs one redundant disk write
        to_disk = (self._disk is not None
                   and not sid.startswith(PREFIX_SID_NAMESPACE)
                   and not self._disk.has_indexed(sid))
        self._enqueue_locked(sid, slot, to_host=True, to_disk=to_disk)

    def _enqueue_locked(self, sid: str, slot: int, *, to_host: bool,
                        to_disk: bool) -> None:
        h, c = self.cache.h, self.cache.c  # refs, not slices: zero ops
        sliced = False
        if len(self._pending) >= self.SPILL_BATCH:
            # memory-pressure valve: each full-array capture pins one
            # whole cache-array generation on device, so a backed-up
            # queue (e.g. a disk stall) must not hold O(pending x cache)
            # device memory. Under pressure, pay the two slice dispatches
            # here so the job holds only this session's [L, H] rows.
            h = h[:, slot, :]
            c = c[:, slot, :]
            sliced = True
        job = self._pending.get(sid)
        if job is not None:
            # merge: an existing job for this sid describes the same
            # request-boundary state (sessions are only spillable /
            # checkpointable while idle) — refresh the capture, OR the
            # destinations
            job.h, job.c, job.slot = h, c, slot
            job.sliced = sliced
            job.to_host = job.to_host or to_host
            job.to_disk = job.to_disk or to_disk
        else:
            job = _SpillJob(h, c, slot, time.perf_counter(),
                            to_host=to_host, to_disk=to_disk,
                            sliced=sliced)
            self._pending[sid] = job
        if not job.in_queue:
            job.in_queue = True
            self._queue.append(sid)
        # deliberately NO notify: enqueue fires on the scheduler's
        # admission path (evictions) and at every request finish
        # (checkpoints), and waking the worker per event makes it
        # contend for this very lock mid-admission. The worker POLLS
        # (short timed wait), so spills batch up and the serving path
        # pays a deque append, nothing more.
        self._ensure_worker_locked()

    def _ensure_worker_locked(self) -> None:
        self._closed = False
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self.run, name="serve-tier-spill", daemon=True)
            self._thread.start()

    def close(self) -> None:
        """Park the spill worker (ServeServer.stop calls flush() then
        this): without it, every retired serve stack would leak one
        forever-polling daemon thread pinning the engine's arrays. A
        later enqueue/flush lazily revives the worker, so restartable
        servers keep working."""
        with self._work:
            self._closed = True
            self._work.notify_all()

    def checkpoint(self, sid: str) -> bool:
        """Write-behind the session's current (request-boundary) state to
        the disk tier while it stays device-resident — the serve-session
        checkpoint a restarted server restores from. No-op without a
        disk tier or for unknown sids."""
        if self._disk is None:
            return False
        with self._lock:
            slot = self.cache.lookup(sid)
            if slot is None:
                return False
            self._enqueue_locked(sid, slot, to_host=False, to_disk=True)
            return True

    # ---- the spill worker (graftlint host-sync scheduler scope) --------

    #: max spill jobs fetched per worker batch (one blocking device_get
    #: per batch — bounds the latency any single flush() waits on)
    SPILL_BATCH = 64

    def run(self) -> None:
        """Worker loop: drain the spill queue forever, a BATCH at a time
        (one blocking device fetch per batch — N spills cost one
        pipeline wait, not N serialized ones). Daemon thread — started
        lazily on the first enqueue; ``flush()`` is the synchronisation
        point for callers that need durability."""
        while True:
            with self._work:
                while not self._queue:
                    if self._closed:
                        return  # close(): park until a revive
                    # timed wait: enqueues do NOT notify (see
                    # _enqueue_locked) — the poll is the worker's only
                    # wake-up for new work, and bounds the write-behind
                    # delay a spill can sit unfetched
                    self._work.wait(timeout=0.05)
                batch: list[tuple[str, _SpillJob]] = []
                while self._queue and len(batch) < self.SPILL_BATCH:
                    sid = self._queue.popleft()
                    job = self._pending.get(sid)
                    if job is None or not job.in_queue:
                        continue  # cancelled or superseded
                    job.in_queue = False
                    batch.append((sid, job))
                self._in_flight += len(batch)
            try:
                if batch:
                    self._spill_batch(batch)
            finally:
                # decremented HERE — after the disk writes — so flush()
                # is a real durability barrier, and decremented on EVERY
                # path (the finally covers the empty batch with -= 0
                # too, so the inc/dec pairing is unconditional — the
                # graftlint resource-pairing contract), so flush can
                # never wedge on a stuck in-flight count
                with self._work:
                    self._in_flight -= len(batch)
                    self._work.notify_all()

    def _spill_batch(self, batch: list[tuple[str, _SpillJob]]) -> None:
        # chaos drills: spill_stall delays this batch (runs on the worker
        # thread, OUTSIDE the shared lock) — the write-behind-delay drill:
        # flush() must still be a real barrier and fills must keep
        # finding the pending capture while the worker sleeps
        _faults.serve_spill_hook()
        # the ONE designated device→host fetch of the spill plane
        # (StateCache.fetch_detached_batch; graftlint host-sync
        # allow-list): full-snapshot fetch + numpy slot extraction —
        # no per-job device ops anywhere in the spill pipeline
        states = self.cache.fetch_detached_batch(
            [(job.h, job.c, None if job.sliced else job.slot)
             for _, job in batch])
        disk_writes: list[tuple[str, DetachedState]] = []
        stored: list[_SpillJob] = []
        dropped = 0
        with self._work:
            for (sid, job), state in zip(batch, states):
                cur = self._pending.get(sid)
                if cur is not job or job.in_queue:
                    continue  # superseded / re-queued while fetching
                del self._pending[sid]
                stored.append(job)
                if job.to_host:
                    self._host[sid] = state
                    self._host.move_to_end(sid)
                    self.spills["host"] += 1
                    self._m_spill["host"].inc()
                    dropped += self._cascade_overflow_locked(disk_writes)
                if job.to_disk:
                    disk_writes.append((sid, state))
        if dropped:
            self._m_lost["overflow"].inc(dropped)
        self._flush_disk_writes(disk_writes)
        # latency observed AFTER the disk writes (the histogram's help
        # promises "stored", fsync included) and only for jobs that
        # actually stored — superseded ones are not phantom spills
        end = time.perf_counter()
        for job in stored:
            self._m_spill_lat.observe(end - job.t0)

    def set_host_entries(self, n: int) -> None:
        """Resize the host-tier bound at runtime — the serve autotuner's
        capacity (autoscaler) knob. Growing is free; shrinking cascades
        overflow victims through the exact spill-time overflow path
        (disk-bound victims park in ``_evacuating`` until their write
        lands, the rest are dropped honestly and counted). The disk
        writes themselves run OUTSIDE the shared lock, like every other
        flush."""
        if n < 1:
            raise ValueError(f"host_entries must be >= 1, got {n}")
        disk_writes: list = []
        with self._lock:
            self.host_entries = int(n)
            dropped = self._cascade_overflow_locked(disk_writes)
        if dropped:
            self._m_lost["overflow"].inc(dropped)
        self._flush_disk_writes(disk_writes)

    def _cascade_overflow_locked(self, disk_writes: list) -> int:
        """Pop host-tier overflow victims. Disk-bound victims PARK in
        ``_evacuating`` (still fillable) until their write lands; the
        rest are dropped honestly. Returns the dropped count."""
        dropped = 0
        while len(self._host) > self.host_entries:
            vsid, vstate = self._host.popitem(last=False)
            if (self._disk is not None
                    and not vsid.startswith(PREFIX_SID_NAMESPACE)):
                self._evacuating[vsid] = vstate
                disk_writes.append((vsid, vstate))
            else:
                self.lost += 1
                dropped += 1
        return dropped

    def _flush_disk_writes(self, writes: list) -> None:
        """Write session files OUTSIDE the shared lock, with two honesty
        guards: a write is SKIPPED when its session no longer exists
        anywhere (discarded while queued — a stale file must not
        resurrect it), and a file written concurrently with a discard is
        deleted afterwards (``_dropped`` tombstones, alive only while a
        flush runs). A failed write keeps the state in RAM
        (``disk_error`` — durability lost, correctness kept)."""
        if not writes:
            return
        with self._lock:
            self._flushing += 1
        try:
            for sid, state in writes:
                with self._lock:
                    current = (sid in self._evacuating
                               or sid in self._pending
                               or sid in self._host or sid in self.cache)
                if not current:
                    continue  # discarded while queued: nothing to persist
                try:
                    self._write_disk(sid, state)
                except OSError as e:
                    # disk trouble loses durability, not correctness:
                    # keep the state in RAM and keep the worker alive
                    print(f"serve tiers: disk-tier write failed for "
                          f"{sid!r}: {e}", flush=True)
                    with self._lock:
                        self.disk_errors += 1
                        st = self._evacuating.pop(sid, None)
                        if st is not None:
                            self._host[sid] = st
                            self._host.move_to_end(sid)
                    self._m_lost["disk_error"].inc()
                    continue
                with self._lock:
                    self._evacuating.pop(sid, None)
                    undo = sid in self._dropped
                if undo:
                    # discard() raced the write: the file we just wrote
                    # describes a session that ended — remove it
                    self._disk.discard(sid)
        finally:
            with self._lock:
                self._flushing -= 1
                if not self._flushing:
                    self._dropped.clear()

    def _write_disk(self, sid: str, state: DetachedState) -> None:
        self._disk.put(sid, state)
        with self._lock:
            self.spills["disk"] += 1
        self._m_spill["disk"].inc()

    def flush(self, timeout: float | None = None) -> bool:
        """Block until every queued/in-flight spill has landed (True) or
        the timeout expired (False) — the durability barrier for clean
        shutdown and for tests/tools that must observe the disk tier."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._work:
            while self._queue or self._in_flight:
                self._ensure_worker_locked()
                if deadline is None:
                    self._work.wait(timeout=1.0)
                else:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        return False
                    self._work.wait(timeout=min(left, 1.0))
            return True

    # ---- fill (promote back to the device tier) ------------------------

    @property
    def disk_dir(self) -> str | None:
        """Disk-tier directory, or None — the router dedupes its
        disk-residency stats per distinct directory."""
        return None if self._disk is None else self._disk.directory

    def has(self, sid: str) -> bool:
        """Tier residency probe (the router's affinity extension): does
        any tier hold restorable state for ``sid``?"""
        with self._lock:
            return self._has_locked(sid)

    def has_memory(self, sid: str) -> bool:
        """MEMORY-tier residency only (pending capture / host RAM /
        evacuating overflow). The router prefers this over any disk
        match: the replica holding a memory copy is the session's owner
        with the freshest request boundary, while a shared disk file may
        be an older not-yet-overwritten boundary."""
        with self._lock:
            job = self._pending.get(sid)
            return ((job is not None and (job.to_host or job.to_disk))
                    or sid in self._host or sid in self._evacuating)

    def _has_locked(self, sid: str) -> bool:
        job = self._pending.get(sid)
        if job is not None and (job.to_host or job.to_disk):
            return True
        if sid in self._host or sid in self._evacuating:
            return True
        return self._disk is not None and self._disk.has(sid)

    def resident_tier(self, sid: str) -> str | None:
        """'pending' | 'host' | 'disk' | None — observability/tests."""
        with self._lock:
            if sid in self._pending and (self._pending[sid].to_host
                                         or self._pending[sid].to_disk):
                return "pending"
            if sid in self._host or sid in self._evacuating:
                return "host"
            if self._disk is not None and self._disk.has(sid):
                return "disk"
            return None

    def _fill_memory_locked(self, sid: str, idx, t0: float) -> bool:
        """Restore from the in-memory tiers (pending capture, host RAM,
        evacuating overflow) — called with the shared lock held."""
        job = self._pending.get(sid)
        if job is not None and (job.to_host or job.to_disk):
            # device→device: gather the captured snapshot's slot row
            # (index as an ARRAY so one gather program covers every
            # slot value) and scatter into the new slot. Any pending
            # capture is the freshest copy, whatever its destination
            # flags — a to_disk-only job's file may not be written yet
            if job.sliced:  # pressure-valve capture: already [L, H]
                self.cache.write_slots(idx, job.h[:, None, :],
                                       job.c[:, None, :])
            else:
                self.cache.gather_scatter(idx, job.h, job.c, [job.slot])
            job.to_host = False  # the disk leg (if any) still runs:
            # the file stays the valid request-boundary checkpoint
            if not job.to_disk and not job.in_queue:
                del self._pending[sid]
            self._host.pop(sid, None)
            return self._count_fill_locked("host", t0)
        state = self._host.pop(sid, None)
        if state is None:
            # overflow victim mid-evacuation: still RAM-resident (its
            # disk write — which stays valid — may even land after this)
            state = self._evacuating.get(sid)
        if state is not None:
            self.cache.write_slots(idx, state.h[:, None, :],
                                   state.c[:, None, :])
            return self._count_fill_locked("host", t0)
        return False

    def fill(self, sid: str, slot: int) -> bool:
        """Restore ``sid``'s spilled state into the (already acquired —
        and PINNED, so no concurrent eviction can reuse it) ``slot``:
        pending capture (device→device — the spill fetch never ran),
        host RAM, then disk. The disk read + sha256 verify runs OUTSIDE
        the shared lock (a slow filesystem must not stall the scheduler
        or the health probes). Returns False when no tier holds usable
        state (miss, or a corrupt disk file — quarantined and counted;
        the caller fails the continuation honestly)."""
        t0 = time.perf_counter()
        idx = np.asarray([slot])
        with self._lock:
            if self._fill_memory_locked(sid, idx, t0):
                return True
            if self._disk is None:
                self.misses += 1
                self._m_lost["miss"].inc()
                return False
        # disk branch: probe + read + verify all OUTSIDE the lock (get
        # returns None for absent — no separate stat-under-lock)
        try:
            state = self._disk.get(sid, self.cache.num_layers,
                                   self.cache.hidden_size)
        except CorruptCheckpointError as e:
            print(f"serve tiers: QUARANTINED corrupt session file "
                  f"for {sid!r}: {e}", flush=True)
            with self._lock:
                self.corrupt += 1
            self._m_lost["corrupt"].inc()
            state = None
        with self._lock:
            if state is None:
                self.misses += 1
                self._m_lost["miss"].inc()
                return False
            self.cache.write_slots(idx, state.h[:, None, :],
                                   state.c[:, None, :])
            return self._count_fill_locked("disk", t0)

    def _count_fill_locked(self, tier: str, t0: float) -> bool:
        self.fills[tier] += 1
        self._m_fill[tier].inc()
        self._m_fill_lat.observe(time.perf_counter() - t0)
        return True

    def fill_batch(self, pairs) -> dict[str, bool]:
        """Batched :meth:`fill`: restore MANY sessions' spilled states
        into their (already acquired AND PINNED) slots with ONE scatter
        program per source class, instead of one gather+scatter dispatch
        per session — the admission path's per-continuation device cost
        under session churn, which is exactly the hot-set-ratio gate's
        overhead (BENCH_serve_r05.json re-gate).

        ``pairs`` is ``[(sid, slot), ...]`` with UNIQUE sids (admission
        guarantees it — one in-flight request per session). Returns
        ``{sid: filled}``. Three phases:

        1. under the shared lock: classify each sid's freshest source
           (pending capture / host RAM / evacuating overflow / disk
           candidate) and do ALL the tier-dict bookkeeping — one lock
           hold for the whole batch, no device dispatch inside it (the
           per-session ``fill`` dispatched its scatter under the lock);
        2. outside the lock: disk reads + sha256 verify (per file, as
           before — the filesystem must never stall the scheduler);
        3. one stacked host→device scatter for every host/disk state,
           and one gather+scatter per distinct pending-capture array
           pair (usually one — jobs captured from the same cache
           generation share the arrays).

        Token-identity with per-session fills is pinned by
        tests/test_serve_tiers.py."""
        pairs = list(pairs)
        if not pairs:
            return {}
        t0 = time.perf_counter()
        results = {sid: False for sid, _ in pairs}
        host_fills: list[tuple[str, int, DetachedState]] = []
        dev_fills: list[tuple[str, int, object, object, int | None]] = []
        disk_cands: list[tuple[str, int]] = []
        misses = 0
        with self._lock:
            for sid, slot in pairs:
                job = self._pending.get(sid)
                if job is not None and (job.to_host or job.to_disk):
                    # freshest copy; the disk leg (if any) still runs —
                    # the file stays the valid request-boundary
                    # checkpoint (same bookkeeping as fill())
                    dev_fills.append((sid, slot, job.h, job.c,
                                      None if job.sliced else job.slot))
                    job.to_host = False
                    if not job.to_disk and not job.in_queue:
                        del self._pending[sid]
                    self._host.pop(sid, None)
                    continue
                state = self._host.pop(sid, None)
                if state is None:
                    # overflow victim mid-evacuation: still RAM-resident
                    # (its in-flight disk write stays valid — no pop)
                    state = self._evacuating.get(sid)
                if state is not None:
                    host_fills.append((sid, slot, state))
                elif self._disk is not None:
                    disk_cands.append((sid, slot))
                else:
                    misses += 1
        # phase 2: MEMORY-sourced fills complete first — their states are
        # already in RAM / captured on device, so they must never wait
        # behind batch-mates' filesystem IO (and their fill-latency
        # samples keep fill()'s per-source semantics: host-class numbers
        # never include a disk read). One stacked scatter for the host/
        # evacuating states, PADDED to a power-of-two bucket (extra rows
        # re-write row 0's state into the scratch slot — harmless by
        # definition): without the bucket, every distinct batch size N
        # would trace a fresh XLA scatter program MID-RUN, and the
        # compile (tens of ms) lands on exactly the admission latency
        # the batching exists to remove (measured: fill p99 0.76 s
        # unbucketed vs sub-ms warm).
        if host_fills:
            idx = [slot for _, slot, _ in host_fills]
            hs = [st.h for _, _, st in host_fills]
            cs = [st.c for _, _, st in host_fills]
            n = _pad_pow2(len(host_fills))
            idx += [self.cache.scratch_slot] * (n - len(host_fills))
            hs += [hs[0]] * (n - len(host_fills))
            cs += [cs[0]] * (n - len(host_fills))
            self.cache.write_slots(np.asarray(idx), np.stack(hs, axis=1),
                                   np.stack(cs, axis=1))
        # pending captures — one gather+scatter per distinct captured
        # array pair (immutable snapshots; usually ONE — jobs captured
        # from the same cache generation share the arrays), bucket-padded
        # the same way (src padding repeats src[0]; dst padding targets
        # the scratch slot). Sliced pressure-valve captures are [L, H]
        # handles, scattered individually.
        groups: dict[tuple[int, int], list] = {}
        for ent in dev_fills:
            groups.setdefault((id(ent[2]), id(ent[3])), []).append(ent)
        for ents in groups.values():
            full = [e for e in ents if e[4] is not None]
            if full:
                dst = [e[1] for e in full]
                src = [e[4] for e in full]
                n = _pad_pow2(len(full))
                dst += [self.cache.scratch_slot] * (n - len(full))
                src += [src[0]] * (n - len(full))
                self.cache.gather_scatter(np.asarray(dst), full[0][2],
                                          full[0][3], np.asarray(src))
            for sid, slot, h, c, _ in (e for e in ents if e[4] is None):
                self.cache.write_slots(np.asarray([slot]),
                                       h[:, None, :], c[:, None, :])
        end_mem = time.perf_counter()
        # phase 3: disk reads + sha256 verify OUTSIDE the lock, then the
        # disk states' own stacked scatter — disk-class latency samples
        # cover the read+verify, memory-class ones (above) do not
        disk_states: list[tuple[str, int, DetachedState]] = []
        for sid, slot in disk_cands:
            state = None
            try:
                state = self._disk.get(sid, self.cache.num_layers,
                                       self.cache.hidden_size)
            except CorruptCheckpointError as e:
                print(f"serve tiers: QUARANTINED corrupt session file "
                      f"for {sid!r}: {e}", flush=True)
                with self._lock:
                    self.corrupt += 1
                self._m_lost["corrupt"].inc()
            if state is None:
                misses += 1
            else:
                disk_states.append((sid, slot, state))
        if disk_states:
            idx = [slot for _, slot, _ in disk_states]
            hs = [st.h for _, _, st in disk_states]
            cs = [st.c for _, _, st in disk_states]
            n = _pad_pow2(len(disk_states))
            idx += [self.cache.scratch_slot] * (n - len(disk_states))
            hs += [hs[0]] * (n - len(disk_states))
            cs += [cs[0]] * (n - len(disk_states))
            self.cache.write_slots(np.asarray(idx), np.stack(hs, axis=1),
                                   np.stack(cs, axis=1))
        end_disk = time.perf_counter()
        n_host = len(host_fills) + len(dev_fills)
        n_disk = len(disk_states)
        with self._lock:
            self.fills["host"] += n_host
            self.fills["disk"] += n_disk
            self.misses += misses
        if n_host:
            self._m_fill["host"].inc(n_host)
        if n_disk:
            self._m_fill["disk"].inc(n_disk)
        if misses:
            self._m_lost["miss"].inc(misses)
        for sid, _, *_rest in (*host_fills, *dev_fills):
            results[sid] = True
            self._m_fill_lat.observe(end_mem - t0)
        for sid, _, _ in disk_states:
            results[sid] = True
            self._m_fill_lat.observe(end_disk - t0)
        return results

    def fill_memory(self, sid: str, slot: int) -> bool:
        """Memory-tiers-only :meth:`fill` (pending capture / host RAM /
        evacuating overflow — no disk leg). Safe to call with the shared
        cache lock already held: PrefixCache._promote_locked restores
        spilled prefix entries through this under the reentrant RLock,
        where fill()'s out-of-lock disk read would stall the scheduler
        behind the filesystem. Prefix states never reach the disk tier,
        so for them this is the whole fill."""
        t0 = time.perf_counter()
        with self._lock:
            if self._fill_memory_locked(sid, np.asarray([slot]), t0):
                return True
            self.misses += 1
            self._m_lost["miss"].inc()
            return False

    def discard_memory(self, sid: str) -> None:
        """Memory-tiers-only :meth:`discard` — drops pending/host/
        evacuating copies but never touches the disk tier (no file IO,
        safe under the shared cache lock). For sids that cannot have a
        disk file (prefix/ namespace) this is the whole discard."""
        with self._lock:
            job = self._pending.get(sid)
            if job is not None:
                job.to_host = job.to_disk = False
                if not job.in_queue:
                    del self._pending[sid]
            self._host.pop(sid, None)
            self._evacuating.pop(sid, None)

    def warmup_fills(self, max_batch: int) -> None:
        """Pre-compile the fill-path scatter lattice: one
        ``_scatter_slots`` + ``_gather_scatter_slots`` program per
        power-of-two batch size up to ``max_batch`` (fill batches are
        padded onto exactly these shapes). Called from
        ``ServeEngine.warmup`` so the first real continuation burst is
        never charged a mid-traffic XLA compile — the same discipline as
        the engine's program lattice (and what the BENCH_serve_r05
        re-gate measured as a 0.76 s fill p99 outlier without it). All
        writes target the scratch slot: harmless by definition."""
        L, H = self.cache.num_layers, self.cache.hidden_size
        scratch = self.cache.scratch_slot
        n = 1
        while True:
            idx = np.full((n,), scratch)
            z = np.zeros((L, n, H), np.float32)
            self.cache.write_slots(idx, z, z)
            with self._lock:
                h, c = self.cache.h, self.cache.c
            self.cache.gather_scatter(idx, h, c, idx)
            if n >= max(1, max_batch):
                break
            n *= 2

    def fill_ahead(self, sid: str) -> bool:
        """Router fill-ahead: on an affinity-probe tier hit, promote the
        session into a device slot NOW so the continuation's admission
        finds it resident (the device copy dispatches async — by the
        time the scheduler prefills, it is data-ordered anyway).
        MEMORY tiers only: this runs under the router's global lock, so
        a disk-resident session just routes home and admission does the
        (out-of-lock) disk fill."""
        with self._lock:
            if sid in self.cache:
                return True
            if not self._has_locked(sid):
                return False
            job = self._pending.get(sid)
            in_memory = ((job is not None and (job.to_host or job.to_disk))
                         or sid in self._host or sid in self._evacuating)
            if not in_memory:
                return True  # disk-resident: admission fills on arrival
            try:
                slot, fresh = self.cache.acquire(sid)
            except CacheFullError:
                return False  # every slot pinned: admission will retry
            if not fresh:
                return True
            if self._fill_memory_locked(sid, np.asarray([slot]),
                                        time.perf_counter()):
                return True
            self.cache.release(sid)
            return False

    def discard(self, sid: str) -> None:
        """Drop every tier's copy of ``sid`` (un-kept completion /
        prefix-entry eviction: the owner is gone, a stale copy must not
        resurrect it)."""
        with self._lock:
            job = self._pending.get(sid)
            if job is not None:
                job.to_host = job.to_disk = False
                if not job.in_queue:
                    del self._pending[sid]
            self._host.pop(sid, None)
            self._evacuating.pop(sid, None)
            if self._flushing:
                # a disk write for this sid may be mid-flight: tombstone
                # it so the flusher deletes whatever it lands
                self._dropped.add(sid)
        if self._disk is not None:
            self._disk.discard(sid)

    # ---- replica retirement (router-driven) ----------------------------

    def evacuate(self) -> tuple[int, list[tuple[str, DetachedState]]]:
        """Move every tier-held session off this (retired) replica:
        pending spills are fetched synchronously, then everything is
        persisted to the SHARED disk tier when one exists (any live
        replica can fill from it) or returned for the router to adopt
        into a live replica's host tier. Returns ``(persisted_count,
        homeless_entries)``. Prefix states are dropped — their entries
        die with the replica."""
        with self._lock:
            jobs = [(sid, job) for sid, job in self._pending.items()
                    if job.to_host or job.to_disk]
            self._pending.clear()
            self._queue.clear()
            host = list(self._host.items()) + list(self._evacuating.items())
            self._host.clear()
            self._evacuating.clear()
            self._work.notify_all()
        states: dict[str, DetachedState] = {}
        if jobs:
            fetched = self.cache.fetch_detached_batch(
                [(job.h, job.c, None if job.sliced else job.slot)
                 for _, job in jobs])
            states.update(
                (sid, st) for (sid, _), st in zip(jobs, fetched))
        states.update(host)  # same boundary where both exist
        persisted = 0
        homeless: list[tuple[str, DetachedState]] = []
        for sid, state in states.items():
            if sid.startswith(PREFIX_SID_NAMESPACE):
                continue
            if self._disk is not None:
                try:
                    self._write_disk(sid, state)
                    persisted += 1
                    continue
                except OSError as e:
                    # disk trouble mid-retirement must not abort the
                    # router's requeue of the dead replica's work: the
                    # session becomes HOMELESS (adopted into a live
                    # replica's host tier) instead of crashing _retire
                    print(f"serve tiers: evacuate disk write failed for "
                          f"{sid!r}: {e}", flush=True)
                    with self._lock:
                        self.disk_errors += 1
                    self._m_lost["disk_error"].inc()
            homeless.append((sid, state))
        return persisted, homeless

    def adopt(self, sid: str, state: DetachedState) -> None:
        """Insert a migrated session's state into this replica's host
        tier (router retirement of a diskless peer)."""
        disk_writes: list[tuple[str, DetachedState]] = []
        dropped = 0
        with self._lock:
            self._host[sid] = state
            self._host.move_to_end(sid)
            self.spills["host"] += 1
            dropped += self._cascade_overflow_locked(disk_writes)
        self._m_spill["host"].inc()
        if dropped:
            self._m_lost["overflow"].inc(dropped)
        self._flush_disk_writes(disk_writes)

    # ---- views ---------------------------------------------------------

    def session_ids(self) -> list[str]:
        """Sids with restorable tier state (host + pending + disk)."""
        with self._lock:
            out = {sid for sid, j in self._pending.items()
                   if j.to_host or j.to_disk}
            out.update(self._host)
            out.update(self._evacuating)
            if self._disk is not None:
                out.update(self._disk.sids())
            return sorted(out)

    def stats(self) -> dict:
        with self._lock:
            return {
                "host_entries_max": self.host_entries,
                "entries": {
                    "pending": sum(1 for j in self._pending.values()
                                   if j.to_host or j.to_disk),
                    # evacuating overflow victims are still RAM-resident
                    "host": len(self._host) + len(self._evacuating),
                    "disk": 0 if self._disk is None else len(self._disk),
                },
                "disk_dir": None if self._disk is None
                else self._disk.directory,
                "spills": dict(self.spills),
                "fills": dict(self.fills),
                "misses": self.misses,
                "corrupt": self.corrupt,
                "lost": self.lost,
                "disk_errors": self.disk_errors,
            }
