"""Slot-based device-resident cache of per-session recurrent state.

An LSTM session's entire decode state is ``(h, c)`` per layer — fixed-size,
independent of how many tokens the session has consumed (the O(1)
autoregressive cache; contrast a transformer's O(T) KV cache). The cache
stores it as two stacked device arrays ``[L, S+1, H]`` (layers x slots x
hidden, float32 — `lstm_step` computes carries in f32, so storage is exact)
plus a host-side session table:

- sessions map to integer **slots**; the jitted engine programs
  (serve/engine.py) gather carries by slot index, run the step, and
  scatter results back — the cache arrays are threaded through jit
  functionally and replaced via :meth:`swap`;
- slot ``S`` (the last row) is a **scratch slot**: decode batches padded
  up to a bucket size point their dead rows at it, so padding writes
  never corrupt a live session;
- **LRU eviction** frees the least-recently-used unpinned slot when the
  cache is full; the batcher pins slots while their session is active in
  a batch, so eviction only ever hits idle (kept-alive) sessions;
- **detach/restore**: `detach` pulls a session's carries to host numpy
  (releasing the slot), `restore` re-admits them later — the round trip
  is exact (tests/test_serve_cache.py proves continued decode is
  token-identical to an uninterrupted run).

Window-grain accounting: with windowed decode (serve/engine.py
`decode_window`) the cache arrays advance once per WINDOW, not per token,
and under the batcher's dispatch-ahead pipeline `swap` may install a
handle whose program has not finished (or started) executing — that is
safe because every consumer (the next window, a prefill, `detach`)
receives the handle and is therefore data-ordered after it on device.
``generation`` counts swaps (device programs applied to the cache), so
``stats()`` exposes how coarse the update grain actually is:
``tokens_generated / generation`` ≈ effective window size.

Host-side bookkeeping is lock-protected; device reads/writes are plain
jnp gather/scatter ops (one compile each per batch-shape, amortised).

:class:`PrefixCache` (same file) layers shared-prompt reuse on top: a
store of "state after token-prefix P" entries, each backed by a
state-cache slot under the reserved ``prefix/`` session namespace —
longest-match lookup, refcounted use, LRU eviction in both directions
(see its docstring).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import obs


class CacheFullError(RuntimeError):
    """No free slot and every occupied slot is pinned."""


#: session-id namespace for prefix-cache backing slots. Client-facing
#: layers (batcher Request) reject ids under it: a client naming a prefix
#: entry's session would inherit — and corrupt — the shared prefix state.
PREFIX_SID_NAMESPACE = "prefix/"


class DetachedState(NamedTuple):
    """Host-resident session state: h, c each ``[L, H]`` float32 numpy."""

    h: np.ndarray
    c: np.ndarray


class StateCache:
    def __init__(self, num_layers: int, num_slots: int, hidden_size: int,
                 registry=None, device=None):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        self.num_layers = num_layers
        self.num_slots = num_slots
        self.hidden_size = hidden_size
        # +1: the scratch slot for padded batch rows (index == num_slots)
        self.h = jnp.zeros((num_layers, num_slots + 1, hidden_size), jnp.float32)
        self.c = jnp.zeros((num_layers, num_slots + 1, hidden_size), jnp.float32)
        if device is not None:
            # device-per-replica serving: commit the cache arrays so every
            # program touching them (and their uncommitted host inputs)
            # runs on this replica's device
            self.h = jax.device_put(self.h, device)
            self.c = jax.device_put(self.c, device)
        self._lock = threading.RLock()
        self._slots: OrderedDict[str, int] = OrderedDict()  # LRU: oldest first
        self._free: list[int] = list(range(num_slots))
        self._pinned: set[str] = set()
        self.evictions = 0
        self.generation = 0  # device programs applied via swap()
        # registry counters feed /metrics; the per-instance ints above stay
        # the source for this instance's stats() (the registry aggregates
        # across every cache in the process — Prometheus semantics)
        reg = obs.REGISTRY if registry is None else registry
        self._m_evictions = reg.counter(
            "serve_state_cache_evictions_total",
            "LRU evictions of unpinned session slots")
        self._m_swaps = reg.counter(
            "serve_state_cache_swaps_total",
            "device programs applied to the cache arrays (generation)")
        # eviction listeners: called (under the cache lock) with the sid of
        # every LRU-evicted session — the prefix cache registers here so a
        # slot eviction INVALIDATES the dependent prefix entry instead of
        # leaving it pointing at a slot another session now owns
        self.evict_listeners: list = []

    @property
    def scratch_slot(self) -> int:
        return self.num_slots

    # ---- session table -------------------------------------------------

    def lookup(self, session_id: str) -> int | None:
        """Slot for a live session (refreshes LRU recency), else None."""
        with self._lock:
            if session_id not in self._slots:
                return None
            self._slots.move_to_end(session_id)
            return self._slots[session_id]

    def acquire(self, session_id: str) -> tuple[int, bool]:
        """Return ``(slot, fresh)`` for the session, allocating if needed.

        ``fresh`` is True when the slot holds no prior state for this
        session (new allocation) — the engine's prefill zeroes the initial
        carries for fresh rows instead of trusting the slot contents, so
        acquire never needs a device-side zeroing dispatch.
        """
        with self._lock:
            if session_id in self._slots:
                self._slots.move_to_end(session_id)
                return self._slots[session_id], False
            if self._free:
                slot = self._free.pop()
            else:
                slot = self._evict_lru_locked()
            self._slots[session_id] = slot
            return slot, True

    def _evict_lru_locked(self) -> int:
        for sid in self._slots:  # oldest-recency first
            if sid not in self._pinned:
                slot = self._slots.pop(sid)
                self.evictions += 1
                self._m_evictions.inc()
                for listener in self.evict_listeners:
                    listener(sid)
                return slot
        raise CacheFullError(
            f"all {self.num_slots} slots pinned by active sessions"
        )

    def release(self, session_id: str) -> None:
        """Drop the session (its slot returns to the free list). No-op for
        unknown sessions — release after eviction must be safe."""
        with self._lock:
            self._pinned.discard(session_id)
            slot = self._slots.pop(session_id, None)
            if slot is not None:
                self._free.append(slot)

    def pin(self, session_id: str) -> None:
        with self._lock:
            if session_id not in self._slots:
                raise KeyError(f"cannot pin unknown session {session_id!r}")
            self._pinned.add(session_id)

    def unpin(self, session_id: str) -> None:
        with self._lock:
            self._pinned.discard(session_id)

    def __contains__(self, session_id: str) -> bool:
        with self._lock:
            return session_id in self._slots

    def session_ids(self) -> list[str]:
        """Live session ids, LRU-oldest first (includes the ``prefix/``
        namespace — callers that only want client sessions filter it).
        The router's replica-retirement path enumerates these to migrate
        a dead replica's idle kept sessions via detach/restore."""
        with self._lock:
            return list(self._slots)

    def __len__(self) -> int:
        with self._lock:
            return len(self._slots)

    # ---- device state --------------------------------------------------

    def swap(self, h: jnp.ndarray, c: jnp.ndarray) -> None:
        """Install updated cache arrays (the jitted step's outputs — may
        still be computing under async dispatch; consumers are
        data-ordered through the handles). Handle installation takes the
        cache lock: the engine lock serialises dispatchers, but detach()
        reads ``h``/``c`` from client threads and must never observe the
        ``h``/``c`` pair mid-replacement."""
        with self._lock:
            self.h, self.c = h, c
            self.generation += 1
        self._m_swaps.inc()

    def read_slots(self, slots) -> tuple[jnp.ndarray, jnp.ndarray]:
        """Gather carries for ``slots`` [B] → (h, c) each ``[L, B, H]``."""
        idx = jnp.asarray(slots, jnp.int32)
        with self._lock:
            return self.h[:, idx, :], self.c[:, idx, :]

    def write_slots(self, slots, h, c) -> None:
        """Scatter (h, c) each ``[L, B, H]`` into ``slots`` [B]."""
        idx = jnp.asarray(slots, jnp.int32)
        with self._lock:
            self.h = self.h.at[:, idx, :].set(h)
            self.c = self.c.at[:, idx, :].set(c)

    def copy_slot(self, src: int, dst: int) -> None:
        """O(1) on-device copy of one slot's carries (src read, dst
        written) — how a prefix entry snapshots a session's state. Threads
        through the cache arrays, so it is data-ordered after any
        in-flight program that writes ``src``."""
        with self._lock:
            self.h = self.h.at[:, dst, :].set(self.h[:, src, :])
            self.c = self.c.at[:, dst, :].set(self.c[:, src, :])

    # ---- detach / restore ---------------------------------------------

    def detach(self, session_id: str) -> DetachedState:
        """Pull a session's carries to host and release its slot.

        The returned :class:`DetachedState` is exact (f32 both ways) —
        restoring it and continuing decode is bit-identical to never
        having detached.
        """
        with self._lock:
            if session_id not in self._slots:
                raise KeyError(f"cannot detach unknown session {session_id!r}")
            slot = self._slots[session_id]
            # slice the handles under the lock; the blocking host fetch
            # happens OUTSIDE it — holding the (scheduler-shared) lock
            # across a device drain would stall every dispatch behind
            # this client-thread call
            h_handle = self.h[:, slot, :]
            c_handle = self.c[:, slot, :]
            self.release(session_id)
        return DetachedState(h=np.asarray(h_handle), c=np.asarray(c_handle))

    def restore(self, session_id: str, state: DetachedState) -> int:
        """Re-admit a detached session; returns its (new) slot."""
        if state.h.shape != (self.num_layers, self.hidden_size):
            raise ValueError(
                f"detached state shape {state.h.shape} does not match cache "
                f"({self.num_layers}, {self.hidden_size})"
            )
        with self._lock:
            if session_id in self._slots:
                raise ValueError(f"session {session_id!r} already live")
            slot, _ = self.acquire(session_id)
            self.write_slots(
                np.asarray([slot]),
                jnp.asarray(state.h)[:, None, :],
                jnp.asarray(state.c)[:, None, :],
            )
            return slot

    def stats(self) -> dict:
        with self._lock:
            return {
                "slots": self.num_slots,
                "live_sessions": len(self._slots),
                "pinned": len(self._pinned),
                "free": len(self._free),
                "evictions": self.evictions,
                "generation": self.generation,
            }


class PrefixEntry:
    """One cached prefix: the exact token prefix, its backing state-cache
    session/slot, and a refcount of in-flight prefills reading it."""

    __slots__ = ("key", "length", "sid", "slot", "refs")

    def __init__(self, key: bytes, length: int, sid: str, slot: int):
        self.key = key
        self.length = length
        self.sid = sid
        self.slot = slot
        self.refs = 0


class PrefixCache:
    """Shared-prompt prefix store over the :class:`StateCache`.

    An LSTM's state after ANY prefix is one O(1) ``(h, c)`` pair per layer,
    so exact prefix reuse is a slot copy — not a KV-cache re-plumb. Entries
    are keyed by the **exact token bytes** of the prefix (the dict hash IS
    the prefix hash; storing the bytes makes collisions impossible) and
    live at ``stride``-aligned lengths, so :meth:`lookup` probes the few
    distinct entry lengths longest-first. Each entry owns a state-cache
    slot under the reserved ``prefix/`` session namespace:

    - **refcounting**: ``lookup`` pins the backing slot and bumps ``refs``
      until the resumed prefill has been *dispatched* (`release`) — device
      data-ordering through the cache arrays makes it safe to release at
      dispatch, not completion;
    - **LRU eviction**: a full prefix cache evicts its own oldest
      zero-ref entry (releasing the backing slot); conversely a state-cache
      LRU eviction of a backing slot **invalidates** the dependent entry
      via the cache's eviction listener — an invalidated prefix is a miss,
      never a read of a slot someone else now owns;
    - a matched length is capped at ``len(prompt) - 1``: at least one real
      prompt token is always prefilled, so the first sampled token comes
      from the same head math as an uncached run (token-identical greedy
      parity, tests/test_serve_prefix.py).

    Synchronisation: shares the state cache's reentrant lock — the
    eviction listener fires under it, and a private lock here would ABBA
    with ``acquire``/``pin`` calls made from prefix methods.
    """

    def __init__(self, cache: StateCache, *, stride: int = 8,
                 max_entries: int = 16, registry=None):
        if stride < 1:
            raise ValueError(f"stride must be >= 1, got {stride}")
        if max_entries < 1:
            raise ValueError(f"max_entries must be >= 1, got {max_entries}")
        self.cache = cache
        self.stride = stride
        self.max_entries = max_entries
        self._lock = cache._lock  # shared on purpose (see docstring)
        self._entries: OrderedDict[bytes, PrefixEntry] = OrderedDict()
        self._by_sid: dict[str, bytes] = {}
        self._sid_counter = 0
        self.hits = 0
        self.misses = 0
        self.inserts = 0
        self.evictions = 0     # own LRU (full prefix cache)
        self.invalidated = 0   # backing slot evicted under us
        # /metrics mirror of the per-instance counters above (one registry
        # family per outcome; stats() keeps serving the instance's ints)
        reg = obs.REGISTRY if registry is None else registry
        self._m = reg.counter(
            "serve_prefix_cache_events_total",
            "prefix-cache outcomes (hit/miss/insert/evict/invalidate)",
            labelnames=("event",))
        self._m_hit = self._m.labels(event="hit")
        self._m_miss = self._m.labels(event="miss")
        self._m_insert = self._m.labels(event="insert")
        self._m_evict = self._m.labels(event="evict")
        self._m_invalidate = self._m.labels(event="invalidate")
        cache.evict_listeners.append(self._on_slot_evicted_locked)

    @staticmethod
    def _key(tokens) -> bytes:
        return np.asarray(tokens, np.int32).tobytes()

    def boundary(self, length: int) -> int:
        """Largest cacheable prefix length for a ``length``-token prompt:
        stride-aligned and <= length - 1 (>= 1 token must remain to
        prefill). 0 = prompt too short to cache."""
        k = ((length - 1) // self.stride) * self.stride
        return k if k >= self.stride else 0

    def lookup(self, prompt) -> tuple[PrefixEntry | None, int]:
        """Longest exact-prefix match for ``prompt`` with matched length
        <= len(prompt) - 1. A hit returns ``(entry, matched_len)`` with
        the entry ref-held and its slot pinned — the caller MUST
        :meth:`release` after dispatching the resumed prefill."""
        p = np.asarray(prompt, np.int32).reshape(-1)
        with self._lock:
            lengths = sorted({e.length for e in self._entries.values()},
                             reverse=True)
            for n in lengths:
                if n > p.size - 1:
                    continue
                entry = self._entries.get(self._key(p[:n]))
                if entry is None:
                    continue
                self._entries.move_to_end(entry.key)
                # refresh the BACKING slot's recency too — the state-cache
                # LRU must not evict the hottest prefix's slot first just
                # because pin/unpin never reorder it (reentrant RLock)
                self.cache.lookup(entry.sid)
                if entry.refs == 0:
                    self.cache.pin(entry.sid)
                entry.refs += 1
                self.hits += 1
                self._m_hit.inc()
                return entry, entry.length
            self.misses += 1
            self._m_miss.inc()
            return None, 0

    def release(self, entry: PrefixEntry) -> None:
        """Drop one ref; the last ref unpins the backing slot (making the
        entry LRU-evictable again). Safe after invalidation."""
        with self._lock:
            if entry.refs > 0:
                entry.refs -= 1
            if entry.refs == 0 and self._by_sid.get(entry.sid) == entry.key:
                self.cache.unpin(entry.sid)

    def insert(self, tokens, src_slot: int) -> bool:
        """Snapshot the state in ``src_slot`` (== the state after exactly
        ``tokens``) into a new prefix entry. Returns False — never raises —
        when the entry already exists, every entry is ref-held, or the
        state cache has no evictable slot left: prefix caching is an
        optimisation and must degrade, not fail requests."""
        key = self._key(tokens)
        length = int(np.asarray(tokens).size)
        with self._lock:
            if key in self._entries:
                # a dedup-hit is a hotness signal too: refresh the backing
                # slot's state-cache recency like the lookup path does
                self._entries.move_to_end(key)
                self.cache.lookup(self._entries[key].sid)
                return False
            while len(self._entries) >= self.max_entries:
                victim = next(
                    (e for e in self._entries.values() if e.refs == 0), None)
                if victim is None:
                    return False  # every entry is mid-use
                self._evict_entry_locked(victim)
            self._sid_counter += 1
            sid = f"{PREFIX_SID_NAMESPACE}{self._sid_counter}"
            try:
                slot, _ = self.cache.acquire(sid)
            except CacheFullError:
                return False
            self.cache.copy_slot(src_slot, slot)
            entry = PrefixEntry(key, length, sid, slot)
            self._entries[key] = entry
            self._by_sid[sid] = key
            self.inserts += 1
            self._m_insert.inc()
            return True

    def _evict_entry_locked(self, entry: PrefixEntry) -> None:
        self._entries.pop(entry.key, None)
        self._by_sid.pop(entry.sid, None)
        self.cache.release(entry.sid)
        self.evictions += 1
        self._m_evict.inc()

    def _on_slot_evicted_locked(self, sid: str) -> None:
        # state-cache LRU took a backing slot: the dependent entry is now
        # garbage — drop it so lookups miss instead of reading a slot a
        # live session owns. The _locked suffix is the held-lock calling
        # contract (docs/LINT.md): eviction listeners fire under the
        # shared cache lock.
        key = self._by_sid.pop(sid, None)
        if key is not None:
            self._entries.pop(key, None)
            self.invalidated += 1
            self._m_invalidate.inc()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            return {
                "entries": len(self._entries),
                "stride": self.stride,
                "max_entries": self.max_entries,
                "hits": self.hits,
                "misses": self.misses,
                "inserts": self.inserts,
                "evictions": self.evictions,
                "invalidated": self.invalidated,
            }
