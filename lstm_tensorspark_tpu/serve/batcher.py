"""Continuous-batching scheduler over the serve engine.

One scheduler iteration (:meth:`Batcher.step`) does three things, in order:

1. **admission** — pop queued requests FIFO (same sampling config; capped
   by ``max_active`` and the engine's batch bucket), allocate/pin their
   cache slots, look up the **prefix cache** (``engine.prefix``, when
   enabled): a fresh prompt sharing a cached prefix resumes prefill at
   the matched offset from the prefix entry's slot instead of re-running
   the shared tokens — O(1) reuse of e.g. a system prompt thousands of
   sessions share;
2. **prefill** — dispatch prefill work for admitted sessions. Without
   ``prefill_chunk`` the whole remaining prompt runs now (one program,
   plus one head-less chunk when a prefix-insert split is due). With
   ``prefill_chunk=C`` at most ONE bounded program (<= C tokens per row)
   is dispatched per iteration, so a bucket-128 prompt's prefill
   interleaves with decode instead of stalling every running session
   behind one monolithic program (head-of-line ITL);
3. **decode** — advance EVERY active session, packed into bucketed decode
   batches grouped by sampling config. In steady state (empty queue, no
   prefill in flight, one sampling group that fits one batch bucket) the
   advance is a **decode window**: K tokens in one XLA program
   (``window_ladder``, K chosen adaptively), dispatched ahead of the
   previous window's readback.

Prefix-cache discipline: lookups ref-hold the matched entry (its backing
slot is pinned) until the resumed prefill is DISPATCHED — device data
ordering through the cache arrays covers the rest. Insertion is canonical:
a fresh prompt passing its stride boundary ``k`` snapshots the state after
``prompt[:k]`` into a new entry (one O(1) slot copy) exactly once; session
continuations (``session_id`` reuse) neither match nor insert, since their
prompt fragments are not absolute prefixes. Greedy output is
token-identical with the cache on (cold or hot), off, or chunked
(tests/test_serve_prefix.py).

**Adaptive windowing + async readback** (the per-token host-round-trip
killer): K falls back to 1 whenever the submit queue is non-empty or any
session is within K tokens of its budget — so a late request is still
admitted within one scheduler iteration and nobody decodes padding —
and grows to the ladder's largest rung in steady-state decode. A
dispatched window is held as ``_pending`` device handles; the NEXT
iteration dispatches window i+1 straight from those handles (the engine's
``decode_window_next``) *before* calling ``fetch_window`` on window i, so
host readback and Python token distribution overlap device compute. Rows
that hit EOS or their budget latch dead ON DEVICE (frozen carries, PAD
output), which is what makes running ahead safe. Greedy windowed output
is token-identical to the K=1 path (tests/test_serve_window.py).

Because step 2 covers all active sessions each iteration, fairness is
structural (no session can starve another; within a steady-state burst
every session advances by the same window), and because step 1 runs every
iteration, a short request submitted late finishes while longer earlier
sessions are still decoding — the continuous-batching property
(tests/test_serve_batcher.py).

Backpressure: the submit queue is bounded; a full queue raises
:class:`QueueFullError` immediately (the HTTP layer maps it to 429). The
active set is bounded by ``max_active`` (≤ cache slots, so admission can
always pin a slot without evicting another active session).

**Admission classes + deadlines** (the serve robustness plane): every
request carries an admission class (``priority`` default /
``best_effort``) and an optional deadline. The class queues are served
by weighted round-robin (``class_weights``, default 4:1 — FIFO within a
class, and exactly the old FIFO when only one class waits), so a
best-effort flood cannot starve priority traffic; the router above
additionally sheds best-effort at a smaller queue bound with an honest
``Retry-After``. Deadlines are enforced where they can still save work:
expired queued requests are REAPED before consuming a slot or a prefill
dispatch, mid-prefill expiry stops burning chunks, and decode honors
the deadline at window boundaries — settling the request with the
partial output under its own ``timeout`` outcome
(``serve_requests_total{outcome="timeout"}`` +
``serve_deadline_expired_total{stage=}``), never a wedged client
(tests/test_serve_deadline.py).

The scheduler is single-threaded by design — `step()` is driven either by
the server's background thread (`run`) or directly by tests (`drain`);
`submit` may be called from any thread.

Telemetry (obs/, via ``engine.metrics``): queue depth/wait, scheduler
iteration time, server-side TTFT and inter-token-latency histograms
(same timestamp definitions as loadgen's — the two views must agree),
window-K / prefill-chunk / readback-latency counters, and per-request
phase timelines (``Request.phases`` → the Chrome tracer under
``--trace`` + ``phases_ms`` in the HTTP reply). Instruments are resolved
once at construction; each record site costs a lock + an add.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque

import numpy as np

from ..resilience import faults as _faults
from ..utils import tracing
from .engine import (GREEDY, PAD_TOKEN, DecodeWindow, SamplingParams,
                     ServeEngine, UnknownModelError)
from .state_cache import PREFIX_SID_NAMESPACE

#: admission classes, in dequeue-priority order. "priority" is the
#: default (a class-less client gets the old FIFO behavior and the
#: stricter SLO); "best_effort" is shed first under overload and served
#: at the smaller weighted-dequeue share.
CLASSES = ("priority", "best_effort")


def retry_after_from_p99(p99, fullness: float) -> float:
    """The ONE Retry-After policy, shared by the router's shed path and
    the batcher's own queue bound: the measured queue-wait p99 (the
    drain-time evidence) scaled by how full the queue is (0.5 + fullness
    — 1.5x at a full queue), clamped to [0.05 s, 30 s], with a
    conservative 0.25 s floor when no samples exist yet (cold server) or
    the estimate is NaN."""
    base = (float(p99) if isinstance(p99, (int, float)) and p99 == p99
            else 0.0)
    if base <= 0:
        base = 0.25
    return float(min(max(base * (0.5 + fullness), 0.05), 30.0))


def register_shed_instruments(reg):
    """Resolve the shed instruments both admission layers record into —
    one registration site, so the name/labels/help can never drift
    between the router and the batcher (metrics-consistency). Returns
    ``(shed_by_class, tenant_shed_by_class, retry_after_histogram)`` —
    ``tenant_limited="yes"`` children count the router's per-tenant
    token-bucket 429s, ``"no"`` the capacity sheds."""
    fam = reg.counter(
        "serve_shed_total",
        "429 sheds by admission class (best_effort sheds at its "
        "smaller queue bound while priority keeps the headroom); "
        "tenant_limited=yes marks per-tenant token-bucket rejections",
        labelnames=("class", "tenant_limited"))
    # "class" is a Python keyword, so the kwarg must go through ** —
    # which the analyzer cannot resolve against the registration
    # graftlint: disable=metrics-consistency
    shed = {c: fam.labels(**{"class": c, "tenant_limited": "no"})
            for c in CLASSES}
    # graftlint: disable=metrics-consistency
    tenant_shed = {c: fam.labels(**{"class": c, "tenant_limited": "yes"})
                   for c in CLASSES}
    retry_hist = reg.histogram(
        "serve_retry_after_seconds",
        "Retry-After hints attached to 429 sheds, computed from the "
        "live queue-wait p99 (drain estimate, not a fixed constant)")
    return shed, tenant_shed, retry_hist


class QueueFullError(RuntimeError):
    """Admission control: the bounded submit queue is full, or the
    shedding policy rejected this class (HTTP 429). ``retry_after_s``
    (when set by the router) is the server's live drain estimate from
    the queue-wait p99 histogram — the client's honest retry hint."""

    def __init__(self, msg: str, retry_after_s: float | None = None):
        super().__init__(msg)
        self.retry_after_s = retry_after_s


class DeadlineExceededError(RuntimeError):
    """The request's deadline lapsed server-side. ``request`` carries
    whatever partial output was generated before expiry — the HTTP layer
    returns it with an honest ``deadline_exceeded`` body instead of
    wedging the client until its own timeout."""

    def __init__(self, request: "Request"):
        super().__init__(
            f"request {request.id} deadline exceeded after "
            f"{len(request.tokens)} token(s)")
        self.request = request


class Request:
    """One generation request; the result fields are filled by the
    scheduler and published by setting ``done``."""

    _ids = itertools.count()

    def __init__(
        self,
        prompt,
        max_new_tokens: int,
        *,
        sampling: SamplingParams = GREEDY,
        session_id: str | None = None,
        keep_session: bool = False,
        eos_id: int | None = None,
        use_prefix: bool = True,
        klass: str = "priority",
        deadline_s: float | None = None,
        tenant: str | None = None,
        model: str | None = None,
    ):
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        if self.prompt.size < 1:
            raise ValueError("prompt must contain at least one token")
        if max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {max_new_tokens}")
        self.max_new_tokens = int(max_new_tokens)
        self.sampling = sampling
        if session_id is not None and session_id.startswith(PREFIX_SID_NAMESPACE):
            # the prefix cache's backing slots live in this namespace — a
            # client naming one would inherit (and corrupt) shared state
            raise ValueError(
                f"session_id namespace {PREFIX_SID_NAMESPACE!r} is reserved")
        self.session_id = session_id
        self.keep_session = keep_session
        self.eos_id = eos_id
        # opt-out of prefix-cache lookup AND insert for this request —
        # measurement probes must not perturb (or be flattered by) the
        # shared cache
        self.use_prefix = use_prefix
        if klass not in CLASSES:
            raise ValueError(
                f"unknown admission class {klass!r} (classes: "
                f"{', '.join(CLASSES)})")
        self.klass = klass
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        self.deadline_s = deadline_s
        # per-tenant rate limiting (serve/router.py): the token-bucket
        # identity. None = untenanted traffic, never rate-limited.
        if tenant is not None:
            tenant = str(tenant)
            if not tenant or len(tenant) > 256:
                raise ValueError(
                    "tenant must be a non-empty string of <= 256 chars")
        self.tenant = tenant
        # multi-model multiplexing (serve/engine.py residents): which
        # resident model serves this request. None = the replica's
        # default model — the single-model fleet's behavior, unchanged.
        # One dispatched batch is one model (like sampling configs), so
        # the scheduler groups by it everywhere it groups by sampling.
        if model is not None:
            model = str(model)
            if not model or len(model) > 256:
                raise ValueError(
                    "model must be a non-empty string of <= 256 chars")
        self.model = model
        # absolute perf_counter deadline, stamped at FIRST submission so
        # the budget covers queue wait; a requeued request (replica
        # death) keeps its original deadline — the client's budget does
        # not reset because a replica died
        self.deadline: float | None = None
        # honest server-side expiry: the request settled with whatever
        # tokens were already generated (partial output), counted under
        # serve_requests_total{outcome="timeout"}
        self.timed_out = False
        self.id = next(Request._ids)
        # replica index this request was routed to (serve/router.py) —
        # None until routed (or forever, for a direct Batcher.submit).
        # Surfaced in the HTTP reply and loadgen's per-replica counts.
        self.replica: int | None = None
        # network-resilience bookkeeping (serve/remote.py): the client-
        # minted idempotency key the remote transport replays under
        # (minted once, at first remote submit), and how many times a
        # provably-undelivered RPC re-entered routing (Router.reroute
        # bounds this by fleet size)
        self.rpc_request_id: str | None = None
        self.reroutes = 0
        self.tokens: list[int] = []
        self.error: str | None = None
        self.cancelled = False  # set by an abandoning client (timeout)
        self.done = threading.Event()
        self.t_submit: float | None = None
        self.t_admit: float | None = None
        self.t_first_token: float | None = None
        self.t_done: float | None = None
        # phase timeline: (name, start, end) perf_counter intervals the
        # scheduler appends as the request moves admit → queue → prefill
        # chunk(s) → decode window(s) → readback. Cheap (tuple appends);
        # at completion the batcher emits them into the installed Chrome
        # tracer (one synthetic row per request) and the HTTP reply
        # carries phase_summary_ms().
        self.phases: list[tuple[str, float, float]] = []
        # host-side arrival time of each token (one entry per token):
        # consecutive deltas are the request's inter-token latencies. A
        # decode window delivers its K tokens in one burst, so these make
        # the latency cost of windowing measurable (loadgen p50/p99 ITL)
        # instead of guessed.
        self.t_tokens: list[float] = []

    def expired(self, now: float | None = None) -> bool:
        """True once the (submit-stamped) deadline has lapsed."""
        if self.deadline is None:
            return False
        return (time.perf_counter() if now is None else now) > self.deadline

    def itl_gaps(self) -> list[float]:
        """Inter-token latencies (seconds): gaps between consecutive
        token arrivals — the ONE definition shared by the HTTP reply's
        ``max_itl_ms`` and loadgen's pooled percentiles. TTFT is not a
        gap (reported separately); a window's burst contributes 0.0s
        gaps between its tokens."""
        return [b - a for a, b in zip(self.t_tokens, self.t_tokens[1:])]

    def phase_summary_ms(self) -> dict[str, float]:
        """Total host-side time per phase (ms) — the per-request breakdown
        the HTTP reply returns. Decode windows fold into ``decode_ms``
        (the sync per-token path records ``decode`` directly);
        ``readback_ms`` is fetch-blocked time. Per phase the spans are
        UNION-merged, not summed: pipelined decode windows overlap in time
        (window i+1 is dispatched before window i's fetch), and a plain
        sum would report decode_ms larger than the request's own
        latency. Each value is therefore <= the request latency, but
        DIFFERENT phases still overlap each other under pipelining
        (window i's readback runs inside window i+1's decode span — the
        overlap IS the pipeline), so the values don't add up to the
        latency either."""
        spans: dict[str, list[tuple[float, float]]] = {}
        for name, a, b in self.phases:
            key = "decode" if name == "decode_window" else name
            spans.setdefault(key, []).append((a, b))
        out = {}
        for key, ivs in spans.items():
            ivs.sort()
            total, cur_a, cur_b = 0.0, ivs[0][0], ivs[0][1]
            for a, b in ivs[1:]:
                if a > cur_b:
                    total += cur_b - cur_a
                    cur_a, cur_b = a, b
                else:
                    cur_b = max(cur_b, b)
            total += cur_b - cur_a
            out[f"{key}_ms"] = round(total * 1e3, 3)
        return out


class _Session:
    __slots__ = ("req", "sid", "slot", "remaining", "last_token")

    def __init__(self, req: Request, sid: str, slot: int):
        self.req = req
        self.sid = sid
        self.slot = slot
        self.remaining = req.max_new_tokens
        self.last_token = 0


class _Prefilling:
    """An admitted session whose prompt is not fully consumed yet.

    ``pos`` counts consumed prompt tokens; ``entry`` is the ref-held
    prefix-cache entry the FIRST dispatch gathers from (released, and set
    to None, once that dispatch is in flight); ``was_fresh`` records
    whether the session started stateless — only such sessions' prompts
    are absolute prefixes eligible for prefix-cache insertion."""

    __slots__ = ("sess", "pos", "entry", "was_fresh", "draft_started")

    def __init__(self, sess: _Session, pos: int, entry, was_fresh: bool):
        self.sess = sess
        self.pos = pos
        self.entry = entry
        self.was_fresh = was_fresh
        # speculative serving: True once the DRAFT model consumed this
        # session's first fragment — the first draft dispatch always
        # starts from zero (the draft has no prefix entries and no tier
        # copies to resume from; starting cold is lossless, it only
        # lowers acceptance until the draft catches context)
        self.draft_started = False

    def src(self) -> tuple[int, bool]:
        """(src_slot, fresh) for the next prefill dispatch."""
        if self.entry is not None:
            return self.entry.slot, False
        return self.sess.slot, self.was_fresh and self.pos == 0


class Batcher:
    #: default decode-window ladder: every K is a compile key, so the
    #: lattice stays tiny; (1,) disables windowing (pure K=1 path).
    DEFAULT_WINDOW_LADDER = (1, 4, 8)

    #: default weighted-dequeue shares (priority, best_effort): out of
    #: every 5 admissions with both classes waiting, 4 are priority.
    DEFAULT_CLASS_WEIGHTS = (4, 1)

    #: default speculative K_draft ladder: each K > 0 is a compile key
    #: (("spec_window", bucket, K)); rung 0 is ALWAYS present — it is
    #: the plain-decode fallback the autotuner retreats to when the
    #: draft stops paying for itself.
    DEFAULT_SPEC_LADDER = (0, 2, 4)

    def __init__(
        self,
        engine: ServeEngine,
        *,
        replica: int = 0,
        max_active: int = 16,
        queue_size: int = 64,
        window_ladder: tuple[int, ...] = DEFAULT_WINDOW_LADDER,
        prefill_chunk: int | None = None,
        prefill_chunk_choices: tuple[int, ...] | None = None,
        class_weights: tuple[int, int] = DEFAULT_CLASS_WEIGHTS,
        speculative: bool = False,
        spec_ladder: tuple[int, ...] = DEFAULT_SPEC_LADDER,
        spec_k: int | None = None,
    ):
        if max_active < 1:
            raise ValueError(f"max_active must be >= 1, got {max_active}")
        if max_active > engine.cache.num_slots:
            raise ValueError(
                f"max_active {max_active} exceeds the cache's "
                f"{engine.cache.num_slots} slots — active sessions must "
                "always be able to hold a pinned slot"
            )
        if queue_size < 1:
            raise ValueError(f"queue_size must be >= 1, got {queue_size}")
        if not window_ladder or any(k < 1 for k in window_ladder):
            raise ValueError(
                f"window_ladder needs positive window sizes, got "
                f"{window_ladder!r}")
        self._validate_chunk(prefill_chunk, engine)
        if prefill_chunk_choices:
            if prefill_chunk is None:
                # the choice set is the autotuner's movement range for an
                # ALREADY-chunked scheduler; flipping None↔int at runtime
                # would also flip submit()'s prompt-length admission rule
                # under a client's feet
                raise ValueError(
                    "prefill_chunk_choices needs prefill_chunk set (the "
                    "knob moves among chunk sizes, it cannot turn "
                    "chunking on or off)")
            for c in prefill_chunk_choices:
                self._validate_chunk(int(c), engine)
        if (len(class_weights) != len(CLASSES)
                or any(int(w) < 1 for w in class_weights)):
            raise ValueError(
                f"class_weights needs one positive weight per class "
                f"{CLASSES}, got {class_weights!r}")
        if any(int(k) < 0 for k in spec_ladder):
            raise ValueError(
                f"spec_ladder needs K_draft >= 0, got {spec_ladder!r}")
        if speculative and not engine.has_draft:
            raise ValueError(
                "speculative=True needs a draft model attached to the "
                "engine (attach_draft) — there is nothing to propose "
                "tokens with")
        # rung 1 is always present: _pick_window falls back to it (near
        # budget end, pipelined tails), and warmup(windows=ladder) must
        # precompile every size the scheduler can dispatch
        ladder = tuple(sorted({1} | set(window_ladder)))
        # rung 0 is always present in the spec ladder: the autotuner's
        # K_draft=0 fallback must be selectable even when the operator
        # configured only positive rungs
        self.spec_ladder = tuple(sorted({0} | {int(k) for k in spec_ladder}))
        self.speculative = bool(speculative)
        if not self.speculative:
            self.spec_k = 0
        elif spec_k is None:
            self.spec_k = self.spec_ladder[-1]
        else:
            if spec_k not in self.spec_ladder:
                raise ValueError(
                    f"spec_k {spec_k} is not a spec_ladder rung "
                    f"{self.spec_ladder}")
            self.spec_k = int(spec_k)
        self.engine = engine
        # identity within a replicated server (serve/router.py): labels
        # this scheduler's metric children and names it in /healthz —
        # a standalone batcher is replica 0 of a one-replica stack
        self.replica = int(replica)
        self.max_active = max_active
        self.queue_size = queue_size
        self.window_ladder = ladder
        # live ceiling on the adaptive window pick — the serve
        # autotuner's K knob. Always a ladder rung (set_window_cap
        # validates), so every reachable window size is warmup-covered;
        # the default (the top rung) is exactly the pre-knob behavior.
        self.window_cap = ladder[-1]
        self.prefill_chunk = prefill_chunk
        # warmed chunk sizes the autotuner may move prefill_chunk among
        # (set_prefill_chunk refuses anything else; warmup() replays the
        # stop sequence for EVERY choice so no pick compiles mid-traffic)
        self.prefill_chunk_choices = (
            tuple(sorted({int(c) for c in prefill_chunk_choices}
                         | {prefill_chunk}))
            if prefill_chunk_choices else ())
        # admitted sessions still consuming their prompt (FIFO; owned by
        # the scheduler thread — the lock only covers reads from stats())
        self._prefilling: list[_Prefilling] = []
        # the in-flight decode window: (DecodeWindow handles, its rows'
        # sessions in packed order). Owned by the scheduler thread only.
        self._pending: tuple[DecodeWindow, list[_Session]] | None = None
        # one bounded queue PER admission class; dequeue is weighted
        # round-robin over the non-empty ones (the wrr sequence below),
        # so a best-effort flood can no longer starve priority traffic
        # the way the old single FIFO did. The queue_size bound covers
        # the SUM — the router's class-aware shed policy sits above.
        self.class_weights = tuple(int(w) for w in class_weights)
        self._queues: dict[str, deque[Request]] = {
            c: deque() for c in CLASSES}
        self._wrr_seq: tuple[str, ...] = tuple(
            c for c, w in zip(CLASSES, self.class_weights)
            for _ in range(w))
        self._wrr_idx = 0
        # True while any queued request MAY carry a deadline — gates the
        # per-iteration queue reap so deadline-less workloads never pay
        # the scan (set by submit, cleared when a scan finds none left)
        self._deadlines_queued = False
        self._active: list[_Session] = []
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._sid_counter = itertools.count()
        # auto-minted session ids must be unique across the FLEET, not
        # just this scheduler: with remote replicas (serve/remote.py)
        # every serve process has a replica 0, and two processes minting
        # "s0-0" for different clients would cross their affinity probes
        # AND alias each other's session files on a shared --session-dir
        # (hash(sid) names the file — a collision silently decodes the
        # other conversation's state). A per-process random component
        # makes the namespace collision-free without any coordination.
        self._sid_prefix = f"s{self.replica}.{os.urandom(3).hex()}"
        self.submitted = 0
        self.completed = 0
        self.rejected = 0
        self.failed = 0
        self.timed_out = 0  # deadline expiries (queue/prefill/decode)
        self.tokens_generated = 0
        self.windows_dispatched: dict[int, int] = {}  # K -> dispatch count
        self.windows_pipelined = 0  # dispatched ahead of a pending fetch
        self.prefill_chunks_dispatched = 0  # head-less chunk programs
        self.prefix_resumed = 0  # sessions that resumed from a prefix hit
        self.prefix_tokens_saved = 0  # prompt tokens skipped via the cache
        self.prefill_tokens_computed = 0  # prompt tokens actually run
        # speculative accounting: spec windows dispatched per K_draft,
        # and the accepted-proposal total (emitted = accepted + 1 per
        # live row per window — the correction token always rides along)
        self.spec_windows_dispatched: dict[int, int] = {}
        self.spec_accepted_tokens = 0
        self.draft_prefills_dispatched = 0
        self.draft_prefill_failures = 0
        # liveness heartbeat for /healthz: monotonic timestamp of the last
        # scheduler pass (run-loop cycle or direct step()); None until the
        # scheduler first runs. A dead/stuck scheduler thread stops
        # advancing it — the honest signal a wedged server must emit.
        self.last_heartbeat: float | None = None
        # telemetry (obs/): instruments resolved ONCE here — the per-event
        # cost at the record sites is a lock + an add. The registry comes
        # from the engine so one constructor argument scopes the whole
        # serve stack (and NULL_REGISTRY turns all of this into no-ops).
        # Every family carries a `replica` label: a replicated server's
        # schedulers share the registry, and their children must stay
        # separable (summaries() exports the cross-replica aggregate
        # under the bare family name).
        reg = engine.metrics
        rl = str(self.replica)
        self._m_queue_depth = reg.gauge(
            "serve_queue_depth", "requests waiting in the submit queue",
            labelnames=("replica",)).labels(replica=rl)
        self._m_active = reg.gauge(
            "serve_active_sessions", "sessions in active decode",
            labelnames=("replica",)).labels(replica=rl)
        self._m_prefilling = reg.gauge(
            "serve_prefilling_sessions", "admitted sessions mid-prefill",
            labelnames=("replica",)).labels(replica=rl)
        self._m_queue_wait = reg.histogram(
            "serve_queue_wait_seconds", "submit → admission wait",
            labelnames=("replica",)).labels(replica=rl)
        self._m_ttft = reg.histogram(
            "serve_ttft_seconds", "submit → first token (server-side)",
            labelnames=("replica",)).labels(replica=rl)
        self._m_itl = reg.histogram(
            "serve_itl_seconds",
            "inter-token gaps, host arrival times (0 within a window burst)",
            labelnames=("replica",)).labels(replica=rl)
        self._m_iteration = reg.histogram(
            "serve_scheduler_iteration_seconds",
            "duration of scheduler iterations that did work",
            labelnames=("replica",)).labels(replica=rl)
        self._m_readback = reg.histogram(
            "serve_readback_seconds",
            "decode-window dispatch → tokens on host (fetch latency)",
            labelnames=("replica",)).labels(replica=rl)
        self._m_chunks = reg.counter(
            "serve_prefill_chunks_total",
            "head-less bounded prefill chunk programs dispatched",
            labelnames=("replica",)).labels(replica=rl)
        fam = reg.counter("serve_decode_windows_total",
                          "decode windows dispatched by window size K",
                          labelnames=("k", "replica"))
        self._m_window_k = {k: fam.labels(k=str(k), replica=rl)
                            for k in self.window_ladder}
        # speculative telemetry: per-row accepted length per verify
        # window (what the autotuner's spec_k knob watches), and verify
        # outcomes — "full" = every proposal accepted, "partial" = some,
        # "reject" = none (the row still emitted its correction token)
        self._m_spec_accept = reg.histogram(
            "serve_spec_accept_len",
            "draft proposals accepted per speculative verify window, "
            "per live row",
            labelnames=("replica",),
            buckets=(0.0, 1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0),
        ).labels(replica=rl)
        fam = reg.counter(
            "serve_spec_verify_total",
            "speculative verify windows by per-row outcome",
            labelnames=("outcome", "replica"))
        self._m_spec_outcome = {o: fam.labels(outcome=o, replica=rl)
                                for o in ("full", "partial", "reject")}
        fam = reg.counter("serve_requests_total",
                          "requests by final outcome",
                          labelnames=("outcome", "replica"))
        self._m_req_completed = fam.labels(outcome="completed", replica=rl)
        self._m_req_failed = fam.labels(outcome="failed", replica=rl)
        self._m_req_rejected = fam.labels(outcome="rejected", replica=rl)
        # honest deadline expiry is its OWN outcome (partial output,
        # never "failed" — the client got every token that was ready)
        self._m_req_timeout = fam.labels(outcome="timeout", replica=rl)
        fam = reg.counter(
            "serve_deadline_expired_total",
            "request deadlines that lapsed, by the pipeline stage that "
            "reaped them (queue = before any slot/prefill was spent)",
            labelnames=("stage", "replica"))
        self._m_deadline = {s: fam.labels(stage=s, replica=rl)
                            for s in ("queue", "prefill", "decode")}
        # the batcher-level bound can fire too (direct submits; a wedged
        # replica's own queue filling on the affinity path while the
        # router's non-stale sum stays low) — those 429s must carry the
        # same Retry-After + shed accounting as the router's (one shared
        # registration + one shared policy, so the layers cannot drift).
        # The tenant-limited children are the router's (rate limiting
        # lives above routing); the batcher only sheds on capacity.
        self._m_shed, _, self._m_retry_after = register_shed_instruments(reg)

    # ---- client side ---------------------------------------------------

    def submit(self, req: Request) -> None:
        """Enqueue a request, or raise :class:`QueueFullError` (bounded
        queue — the backpressure boundary)."""
        with self._lock:
            # under the lock: prefill_chunk is a live knob
            # (set_prefill_chunk) — though only its None-ness matters
            # here, and the autotuner can never flip that
            if (self.prefill_chunk is None
                    and req.prompt.size > self.engine.max_prompt_len):
                # chunked prefill lifts this cap: any prompt length is
                # consumed prefill_chunk tokens per dispatch, so no
                # single program ever exceeds the bucket lattice
                raise ValueError(
                    f"prompt length {req.prompt.size} exceeds the "
                    f"engine's largest prefill bucket "
                    f"{self.engine.max_prompt_len} "
                    "(enable prefill_chunk to serve longer prompts)"
                )
            if not self.engine.has_model(req.model):
                # reject at the admission boundary, not at dispatch time:
                # a request naming a non-resident model would otherwise
                # consume a slot, reach _dispatch_prefill, and fail a
                # whole co-batched dispatch with it
                raise UnknownModelError(
                    f"model {req.model!r} is not resident on replica "
                    f"{self.replica}")
            if self._qlen_locked() >= self.queue_size:
                # same honest-429 contract as the router's shed path:
                # Retry-After from the measured queue wait, counted under
                # serve_shed_total — a 429 from THIS layer (direct
                # submits; a wedged replica's own queue filling while the
                # router's non-stale sum stays low) must not be a
                # second-class reply clients cannot back off from
                retry = self._retry_after_locked()
                self.rejected += 1
                self._m_req_rejected.inc()
                self._m_shed[req.klass].inc()
                self._m_retry_after.observe(retry)
                raise QueueFullError(
                    f"submit queue full ({self.queue_size} pending); "
                    f"retry after {retry:.2f}s", retry_after_s=retry
                )
            if req.t_submit is None:
                # first submission; a REQUEUED request (router: replica
                # death) arrives with t_submit already stamped and is
                # neither re-stamped nor re-counted — queue-wait/TTFT
                # must cover the time spent on the dead replica's queue,
                # and the dead replica already counted the submission
                # (the cross-replica `submitted` sum stays one per
                # client request; the serving replica's per-replica
                # count undercounts by the requeues, which the router's
                # `requeued` counter makes explicit)
                req.t_submit = time.perf_counter()
                self.submitted += 1
                if req.deadline_s is not None:
                    # the absolute deadline starts at FIRST submission
                    # (covers queue wait); requeues keep the original
                    req.deadline = req.t_submit + req.deadline_s
            if req.deadline is not None:
                self._deadlines_queued = True  # arms the _admit reap
            self._queues[req.klass].append(req)
            self._work.notify()

    def _qlen_locked(self) -> int:
        return sum(len(q) for q in self._queues.values())

    def _retry_after_locked(self) -> float:
        """Honest Retry-After for a full-queue 429 at THIS layer: this
        scheduler's queue-wait p99 through the shared policy
        (:func:`retry_after_from_p99`) at fullness 1.0 — the bound only
        fires when the queue IS full."""
        s = self._m_queue_wait.summary() or {}
        return retry_after_from_p99(s.get("p99"), 1.0)

    def queued(self) -> int:
        """Requests waiting for admission, summed over the class queues
        (the router sums this across replicas for the GLOBAL bound)."""
        with self._lock:
            return self._qlen_locked()

    def load(self) -> int:
        """Routing weight: queued + admitted work on this scheduler, read
        under one lock hold (the router's least-loaded pick)."""
        with self._lock:
            return (self._qlen_locked() + len(self._active)
                    + len(self._prefilling))

    # ---- live knobs (serve/autotune.py; bounded by the warmed lattice) -

    @staticmethod
    def _validate_chunk(chunk: int | None, engine: ServeEngine) -> None:
        if chunk is None:
            return
        if chunk < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1 or None, got {chunk}")
        if chunk > engine.max_prompt_len:
            raise ValueError(
                f"prefill_chunk {chunk} exceeds the largest prefill "
                f"bucket {engine.max_prompt_len} — each chunk is one "
                "bucketed program")
        if (engine.prefix is not None
                and chunk % engine.prefix.stride != 0
                and engine.prefix.stride % chunk != 0):
            # _stop_from stride-aligns every pre-boundary stop, so an
            # incompatible chunk is silently truncated each dispatch —
            # the operator gets a smaller effective chunk than configured
            raise ValueError(
                f"prefill_chunk {chunk} is not a multiple or divisor "
                f"of prefix stride {engine.prefix.stride} — chunks would "
                "be truncated to stride alignment; pick a compatible "
                "chunk or disable the prefix cache")

    def set_window_cap(self, k: int) -> None:
        """Move the decode-window ceiling to ladder rung ``k`` (the
        autotuner's K knob). Only warmed rungs are accepted — the
        controller can NEVER select a window size that would compile
        mid-traffic. Takes effect at the next ``_pick_window``."""
        if k not in self.window_ladder:
            raise ValueError(
                f"window cap {k} is not a warmed ladder rung "
                f"{self.window_ladder} — an off-ladder window would "
                "compile mid-traffic")
        with self._lock:
            self.window_cap = int(k)

    def set_max_active(self, n: int) -> None:
        """Move the active-set bound (the rollout controller's
        slot-resize move resizes the device cache first, then raises or
        lowers this to match). Bounded by the CURRENT slot count — the
        same invariant __init__ enforces: admission must always be able
        to pin a slot."""
        if n < 1:
            raise ValueError(f"max_active must be >= 1, got {n}")
        if n > self.engine.cache.num_slots:
            raise ValueError(
                f"max_active {n} exceeds the cache's "
                f"{self.engine.cache.num_slots} slots — resize the slot "
                "pool first (rollout controller resize move)")
        with self._lock:
            self.max_active = int(n)

    def set_prefill_chunk(self, chunk: int) -> None:
        """Move the prefill chunk size to ``chunk`` (the autotuner's
        chunk knob). Only members of the warmed ``prefill_chunk_choices``
        set are accepted — warmup() replayed the stop sequence for every
        choice, so no pick dispatches an uncompiled program."""
        if chunk not in self.prefill_chunk_choices:
            raise ValueError(
                f"prefill_chunk {chunk} is not in the warmed choice set "
                f"{self.prefill_chunk_choices} — an unwarmed chunk would "
                "compile mid-traffic")
        with self._lock:
            self.prefill_chunk = int(chunk)

    def set_spec_k(self, k: int) -> None:
        """Move the speculative K_draft to spec-ladder rung ``k`` (the
        autotuner's spec knob). Rung 0 is the plain-decode fallback —
        speculation off until the knob moves back up. Only warmed rungs
        are accepted, so no pick ever compiles mid-traffic; takes effect
        at the next ``_pick_spec_k``."""
        if not self.speculative:
            raise ValueError(
                "set_spec_k on a non-speculative scheduler — boot with "
                "speculative=True (and an attached draft) first")
        if k not in self.spec_ladder:
            raise ValueError(
                f"spec_k {k} is not a warmed spec-ladder rung "
                f"{self.spec_ladder} — an off-ladder K_draft would "
                "compile mid-traffic")
        with self._lock:
            self.spec_k = int(k)

    # ---- replica retirement (router-driven; see serve/router.py) -------
    #
    # These are called by the admission router ONLY after this scheduler's
    # thread has exited — they mutate scheduler-owned state from another
    # thread, which is safe precisely because the owner is gone (and every
    # guarded structure is still snapshotted under the lock, so a stats()
    # or health reader racing the retirement sees consistent views).

    def drain_queue(self) -> list[Request]:
        """Remove and return every not-yet-admitted request (the router
        requeues them onto live replicas), oldest-submitted first so the
        requeue preserves rough arrival order across the class queues."""
        with self._lock:
            out = [r for q in self._queues.values() for r in q]
            for q in self._queues.values():
                q.clear()
        out.sort(key=lambda r: (r.t_submit if r.t_submit is not None
                                else float("inf"), r.id))
        return out

    def fail_inflight(self, reason: str) -> int:
        """Fail every admitted (prefilling or decoding) request with
        ``reason`` and release its slot/prefix refs. Under dispatch-ahead
        windowed decode the host cannot know how many tokens an
        un-fetched window already consumed, so a dead scheduler's
        in-flight sessions cannot be resumed elsewhere without risking
        silent double-decode — honest failure is the only correct
        outcome. Returns the number of requests failed."""
        with self._lock:
            prefilling = list(self._prefilling)
            self._prefilling.clear()
            active = list(self._active)
            self._active.clear()
        self._pending = None  # scheduler-owned; the owner thread is dead
        for p in prefilling:
            if p.entry is not None:
                self.engine.prefix.release(p.entry)
                p.entry = None
            self.engine.cache.release(p.sess.sid)
            self._fail(p.sess.req, reason)
        for s in active:
            self.engine.cache.release(s.sid)
            self._fail(s.req, reason)
        return len(prefilling) + len(active)

    def fail_request(self, req: Request, reason: str) -> None:
        """Settle a request this batcher owns with an error (router use:
        a drained request that could not be requeued anywhere)."""
        self._fail(req, reason)

    # ---- scheduler side ------------------------------------------------

    def step(self) -> bool:
        """One scheduler iteration (admission + bounded prefill progress +
        a decode advance for every active session). Returns True when any
        work was done."""
        self.last_heartbeat = time.monotonic()
        # chaos drills: an armed replica_die/replica_wedge fault fires
        # here — the InjectedFault propagates out of run() and kills this
        # scheduler thread (death the router must retire), or the wedge
        # blocks with the heartbeat stale (the /healthz wedge case)
        _faults.serve_step_hook(self.replica)
        t0 = time.perf_counter()
        did = self._admit()
        did = self._prefill_step() or did
        did = self._decode_all() or did
        with self._lock:
            queued, active = self._qlen_locked(), len(self._active)
            prefilling = len(self._prefilling)
        self._m_queue_depth.set(queued)
        self._m_active.set(active)
        self._m_prefilling.set(prefilling)
        if did:
            # idle passes are excluded: the histogram answers "how long
            # does a WORKING iteration hold the scheduler", not "how often
            # does the idle loop spin"
            self._m_iteration.observe(time.perf_counter() - t0)
        # beat AGAIN on completion: a step that spends its whole budget
        # inside one long dispatch (first-shape compile, big window)
        # must not leave the heartbeat aged by that dispatch — a fresh
        # pick racing it would misread this replica as wedged and fall
        # back onto genuinely stale ones. A step that truly never
        # returns (the wedge) never reaches this line, so staleness
        # still means stuck, not slow.
        self.last_heartbeat = time.monotonic()
        return did

    def _admit(self) -> bool:
        admit: list[Request] = []
        dropped: list[Request] = []
        reaped: list[Request] = []
        now = time.perf_counter()
        with self._lock:
            # deadline reap across the WHOLE queue first: an expired
            # request must be settled here — never allowed to consume a
            # state-cache slot or burn a prefill dispatch further down.
            # One rebuild pass per class (not remove() per victim —
            # O(k·n) under the submit-shared lock during exactly the
            # mass-expiry bursts deadlines exist for), gated so
            # deadline-less workloads never pay the scan at all.
            if self._deadlines_queued:
                still_armed = False
                for q in self._queues.values():
                    keep: list[Request] = []
                    for r in q:
                        if r.expired(now):
                            reaped.append(r)
                        else:
                            keep.append(r)
                            still_armed = (still_armed
                                           or r.deadline is not None)
                    if len(keep) != len(q):
                        q.clear()
                        q.extend(keep)
                self._deadlines_queued = still_armed
            busy_sids = {s.sid for s in self._active}
            busy_sids.update(p.sess.sid for p in self._prefilling)
            capacity = min(
                self.max_active - len(self._active) - len(self._prefilling),
                self.engine.max_batch,
            )
            nwrr = len(self._wrr_seq)
            while len(admit) < capacity:
                # weighted round-robin over the non-empty class queues:
                # within a class the order stays FIFO, and with one class
                # waiting this degrades to exactly the old FIFO
                cls = jpos = None
                for i in range(nwrr):
                    j = (self._wrr_idx + i) % nwrr
                    if self._queues[self._wrr_seq[j]]:
                        cls, jpos = self._wrr_seq[j], j
                        break
                if cls is None:
                    break
                head = self._queues[cls][0]
                if head.cancelled:
                    # abandoned by its client (timeout): drop instead of
                    # spending decode steps on tokens nobody reads. A
                    # drop is not a service — the wrr cursor stays put.
                    self._queues[cls].popleft()
                    dropped.append(head)
                    continue
                # one prefill batch = one sampling config AND one model
                # (both are compile/dispatch keys); FIFO at the picked
                # head keeps admission starvation-free
                if admit and (head.sampling.key(), head.model) != (
                        admit[0].sampling.key(), admit[0].model):
                    break
                self._queues[cls].popleft()
                self._wrr_idx = (jpos + 1) % nwrr
                admit.append(head)
        for r in dropped:
            self._fail(r, "cancelled before admission")
        for r in reaped:
            # queue-only lifetime: the phase timeline records exactly the
            # submit→reap span, nothing else (tests pin this)
            if r.t_submit is not None:
                r.phases.append(("queue", r.t_submit, now))
            self._settle_timeout(r, "queue")
        if not admit:
            return bool(dropped or reaped)

        now = time.perf_counter()
        # admitted requests that need a tier fill (continuation whose
        # session is no longer device-resident): collected through the
        # loop and restored in ONE batched gather+scatter program
        # (SessionTiers.fill_batch) instead of a per-session dispatch —
        # the per-continuation admission cost under session churn
        records: list[list] = []  # [req, sid, slot, fresh, needs_fill]
        for req in admit:
            req.t_admit = now
            if req.t_submit is not None:
                self._m_queue_wait.observe(now - req.t_submit)
                req.phases.append(("queue", req.t_submit, now))
            sid = req.session_id
            if sid is None:
                # auto ids share a namespace with client-chosen ones:
                # skip any id the cache already holds, or an anonymous
                # request could silently inherit (and overwrite) a kept
                # session's carries. The prefix bakes in the replica
                # index AND a per-process random component so the ids
                # are unique across a replicated server and across the
                # fleet's processes (see __init__ — the router and the
                # shared disk tier both key on the sid).
                sid = f"{self._sid_prefix}-{next(self._sid_counter)}"
                while sid in self.engine.cache:
                    sid = f"{self._sid_prefix}-{next(self._sid_counter)}"
            if sid in busy_sids:
                # two in-flight requests on one session would share a cache
                # slot and corrupt each other's carries — reject the
                # newcomer loudly; the client serialises its own session
                self._fail(req, f"session {sid!r} is busy (another request "
                                "on it is still decoding)")
                continue
            busy_sids.add(sid)
            try:
                # acquire+pin ATOMICALLY: a tier fill (below) may read
                # the disk outside the cache lock, and a concurrent
                # fill_ahead's acquire must never evict this
                # just-acquired slot — neither mid-restore nor in the
                # window before a separate pin() call (release() on the
                # failure paths clears the pin along with the slot)
                slot, fresh = self.engine.cache.acquire_pinned(sid)
            except Exception as e:  # cache exhausted by pinned slots
                self._fail(req, f"{type(e).__name__}: {e}")
                continue
            # explicit continuation of a session no longer in a device
            # slot: a tiered engine restores the spilled state (pending
            # spill capture / host RAM / verified disk read) into the
            # fresh PINNED slot — the exact pre-eviction carries, so the
            # continuation decodes token-identically. The restore itself
            # is deferred to ONE fill_batch call below. Nothing
            # restorable (never created, spilled copy lost, corrupt disk
            # file quarantined): silently decoding from zero state would
            # return wrong tokens — fail loudly.
            needs_fill = req.session_id is not None and fresh
            if needs_fill and self.engine.tiers is None:
                self.engine.cache.release(sid)
                self._fail(req, f"unknown session {sid!r} (expired, "
                                "never created, or its spilled state "
                                "was lost; re-send the full prompt)")
                continue
            records.append([req, sid, slot, fresh, needs_fill])
        fill_res = {}
        if any(r[4] for r in records):
            fill_res = self.engine.tiers.fill_batch(
                [(sid, slot) for _, sid, slot, _, nf in records if nf])
        for req, sid, slot, fresh, needs_fill in records:
            if needs_fill:
                if not fill_res.get(sid):
                    self.engine.cache.release(sid)
                    self._fail(req, f"unknown session {sid!r} (expired, "
                                    "never created, or its spilled state "
                                    "was lost; re-send the full prompt)")
                    continue
                fresh = False
            sess = _Session(req, sid, slot)
            # prefix-cache lookup: fresh sessions only (a continuation's
            # prompt is a fragment, not an absolute prefix). The hit is
            # ref-held until its resumed prefill is dispatched.
            entry, matched = None, 0
            if fresh and req.use_prefix and self.engine.prefix is not None:
                entry, matched = self.engine.prefix.lookup(req.prompt)
            with self._lock:
                self._prefilling.append(
                    _Prefilling(sess, matched, entry, fresh))
        # dispatching happens in _prefill_step — same step() iteration, so
        # an unchunked admission still prefills (and gets TTFT) right here
        return True

    # ---- prefill scheduling (chunked + prefix-resumed; see module doc) --

    def _next_stop(self, p: _Prefilling,
                   chunk: int | None = None) -> int:
        """Prompt position the next dispatch advances ``p`` to: the prompt
        end, capped by the chunk size. With the prefix cache on, stops are
        stride-ALIGNED: every stop is a potential (deduped) insert point,
        so chunked prefill caches a shared prefix at block granularity —
        and without chunking, the single split lands at the largest stride
        boundary (the state after ``prompt[:k]`` must exist in the
        session's own slot for the one-copy insert). ``chunk`` pins the
        chunk size for one scheduler iteration — a live knob move
        (set_prefill_chunk) must land BETWEEN iterations, never between
        a batch's dispatch and its ``pos`` bookkeeping."""
        # opt-out requests never insert, so never pay the insert-boundary
        # split either — their prefill is the plain monolithic/chunked one
        return self._stop_from(p.pos, p.sess.req.prompt.size,
                               p.was_fresh and p.sess.req.use_prefix,
                               chunk=(self.prefill_chunk if chunk is None
                                      else chunk))

    def _stop_from(self, pos: int, total: int, fresh: bool,
                   chunk: int | None = None) -> int:
        """Pure arithmetic core of :meth:`_next_stop` — also replayed by
        :meth:`warmup` to enumerate the exact program lengths this
        scheduler will dispatch for a prompt length. ``chunk`` overrides
        the live ``prefill_chunk`` (warmup replays the stop sequence for
        every entry of the autotuner's choice set)."""
        if chunk is None:
            chunk = self.prefill_chunk
        stop = total
        if chunk is not None:
            stop = min(stop, pos + chunk)
        if self.engine.prefix is not None and fresh:
            k = self.engine.prefix.boundary(total)
            if pos < k:
                # never run past the last insertable boundary in one
                # dispatch, and keep chunk stops stride-aligned — every
                # stop is then an insert point
                stop = min(stop, k)
                if chunk is not None:
                    aligned = (stop // self.engine.prefix.stride
                               ) * self.engine.prefix.stride
                    if aligned > pos:
                        stop = aligned
        return stop

    def warmup(self, sampling: SamplingParams = GREEDY,
               prompt_lens: tuple[int, ...] = (1,)) -> int:
        """Pre-compile every program this scheduler can dispatch for the
        given prompt lengths. ``engine.warmup`` alone cannot know the
        chunk and prefix-insert split lengths — those are scheduler
        policy — so this replays :meth:`_stop_from`'s stop sequence per
        length (a cold fresh prompt, a fresh prompt resumed from a full
        prefix hit, and a continuation fragment) and warms the union of
        (phase, length) programs plus the window ladder. Callers should
        use this — or :meth:`ServeServer.warmup` — instead of calling
        the engine directly, or first traffic gets charged mid-run XLA
        compiles for the split programs."""
        finals: set[int] = set()
        chunks: set[int] = set()
        prefix = self.engine.prefix
        # every chunk size the scheduler can EVER run with: the live one
        # (read under the lock — it is a knob now) plus the autotuner's
        # whole choice set. The walk is a CLOSURE over choice MIXES, not
        # a per-choice replay: a knob move lands between scheduler
        # iterations, so one prompt's chunks may use different sizes —
        # e.g. chunk 16 then 32 on a 48-token prompt dispatches a
        # 32-length FINAL that neither pure-16 nor pure-32 replay ever
        # produces. Every position reachable under ANY mix is expanded
        # with EVERY choice, or the first mid-prompt knob move compiles
        # mid-traffic (caught by the bench's zero-compile assert).
        with self._lock:
            live_chunk = self.prefill_chunk
        chunk_values = sorted({live_chunk} | set(self.prefill_chunk_choices),
                              key=lambda c: (c is None, c))
        for t in prompt_lens:
            t = max(1, int(t))
            # (start position, was_fresh) dispatch sequences to replay —
            # longest-match lookup can resume from ANY stride multiple
            # up to boundary(t), not just the full boundary, so every
            # such start must be replayed or a partial hit's remainder
            # length dispatches an unwarmed program
            stack = [(0, True), (0, False)]
            if prefix is not None:
                for k in range(prefix.stride, prefix.boundary(t) + 1,
                               prefix.stride):
                    stack.append((k, True))
            # _stop_from is pure in (pos, fresh, chunk) for a given t,
            # so the BFS visits each (pos, fresh) once — bounded by
            # t/min_chunk * |choices| expansions
            seen: set[tuple[int, bool]] = set()
            while stack:
                pos, fresh = stack.pop()
                if pos >= t or (pos, fresh) in seen:
                    continue
                seen.add((pos, fresh))
                for chunk in chunk_values:
                    stop = self._stop_from(pos, t, fresh, chunk=chunk)
                    (finals if stop >= t else chunks).add(stop - pos)
                    if stop < t:
                        stack.append((stop, fresh))
        return self.engine.warmup(
            sampling, prompt_lens=tuple(sorted(finals)),
            windows=self.window_ladder,
            chunk_lens=tuple(sorted(chunks)),
            spec_windows=(tuple(k for k in self.spec_ladder if k > 0)
                          if self.speculative else ()))

    def _select_prefill_batch(
            self, chunk: int | None) -> tuple[list[_Prefilling], bool]:
        """FIFO-fair batch selection: the HEAD of the prefilling list
        always progresses (a stream of short prompts cannot starve a long
        prompt's chunks); compatible rows ride along — same phase
        (final/intermediate), and for finals the same sampling config
        (intermediate chunks are sampling-free programs)."""
        head = self._prefilling[0]
        final = self._next_stop(head, chunk) >= head.sess.req.prompt.size
        skey = head.sess.req.sampling.key()
        mdl = head.sess.req.model
        batch = []
        for p in self._prefilling:
            if len(batch) >= self.engine.max_batch:
                break
            if (self._next_stop(p, chunk)
                    >= p.sess.req.prompt.size) != final:
                continue
            if final and p.sess.req.sampling.key() != skey:
                continue
            # one dispatch is one model's params — intermediate chunks
            # included (the chunk program is sampling-free but not
            # model-free)
            if p.sess.req.model != mdl:
                continue
            batch.append(p)
        return batch, final

    def _prefill_step(self) -> bool:
        """Advance prompt consumption. Unchunked: run every pending
        prefill to completion now. Chunked: dispatch exactly ONE bounded
        program (<= prefill_chunk tokens per row) and return — decode
        interleaves between chunks, so a long prompt can only delay
        running sessions by one chunk's latency per token."""
        if not self._prefilling:
            return False
        # ONE chunk-size read per scheduler iteration: selection, the
        # dispatched slice, and the pos bookkeeping below must all agree
        # even while the autotuner moves the knob from its own thread —
        # a move lands between iterations, never inside one
        chunk = self.prefill_chunk
        now = time.perf_counter()
        for p in list(self._prefilling):
            if p.sess.req.cancelled:
                self._abort_prefilling(p, "cancelled during prefill")
            elif p.sess.req.expired(now):
                # mid-prefill expiry (chunked prefills span iterations):
                # stop burning chunk dispatches on a dead deadline
                self._abort_prefilling(p, None, timeout=True)
        while self._prefilling:
            batch, final = self._select_prefill_batch(chunk)
            self._dispatch_prefill(batch, final, chunk)
            if chunk is not None:
                break  # one bounded dispatch per scheduler iteration
        return True

    def _dispatch_prefill(self, batch: list[_Prefilling], final: bool,
                          chunk: int | None = None) -> None:
        prefix = self.engine.prefix
        items = []
        draft_items = []
        computed = 0  # prompt tokens this dispatch runs through the model
        # the draft is distilled against the DEFAULT model only — other
        # residents' sessions never speculate, so their prefills are not
        # mirrored either
        mirror = self.speculative and (
            batch[0].sess.req.model is None
            or batch[0].sess.req.model == self.engine.model_id)
        for p in batch:
            stop = self._next_stop(p, chunk)
            # stride-aligned insert point: the state after prompt[:pos]
            # sits in the session's own slot — one O(1) device copy caches
            # it for every future sharer (insert() dedups existing keys
            # itself, refreshing their LRU recency; rows resuming FROM an
            # entry this dispatch have p.entry set and skip)
            if (prefix is not None and p.was_fresh and p.entry is None
                    and p.sess.req.use_prefix
                    and p.pos >= prefix.stride
                    and p.pos % prefix.stride == 0):
                prefix.insert(p.sess.req.prompt[: p.pos], p.sess.slot)
            src_slot, fresh = p.src()
            items.append((p.sess.slot, src_slot, fresh,
                          p.sess.req.prompt[p.pos: stop]))
            computed += stop - p.pos
            if mirror:
                # mirror every target dispatch so the draft's slot state
                # tracks the consumed context. The draft's FIRST fragment
                # always starts from zero — it has no prefix entries or
                # tier copies to resume from (prefix-resumed and
                # tier-restored rows rebuild draft context from the
                # fragment alone: lossless, lower acceptance until the
                # draft catches up)
                draft_items.append((p.sess.slot, not p.draft_started,
                                    p.sess.req.prompt[p.pos: stop]))
        t0 = time.perf_counter()
        try:
            if final:
                first = self.engine.prefill(items, batch[0].sess.req.sampling,
                                            model=batch[0].sess.req.model)
            else:
                self.engine.prefill_chunk(items,
                                          model=batch[0].sess.req.model)
                self.prefill_chunks_dispatched += 1
                self._m_chunks.inc()
        except Exception as e:
            for p in batch:
                self._abort_prefilling(
                    p, f"prefill failed: {type(e).__name__}: {e}")
            return
        # count AFTER the dispatch lands: the compute-savings gate
        # (saved vs computed) must not credit work an aborted batch
        # never did
        self.prefill_tokens_computed += computed
        if draft_items:
            try:
                self.engine.draft_prefill(draft_items)
                self.draft_prefills_dispatched += 1
                for p in batch:
                    p.draft_started = True
            except Exception:
                # draft state is acceptance-only — a failed mirror can
                # never corrupt output (the verify window is teacher-
                # forced by the TARGET), so the session proceeds with a
                # stale draft instead of failing a healthy prefill; the
                # counter is the failure's only surface (stats/bench)
                self.draft_prefill_failures += 1
        now = time.perf_counter()
        phase = "prefill" if final else "prefill_chunk"
        for p in batch:
            # final prefill syncs on the first token (np.asarray), so its
            # span covers device compute; a chunk's span is dispatch only
            p.sess.req.phases.append((phase, t0, now))
        for i, p in enumerate(batch):
            # the gather from a prefix slot is in flight and data-ordered:
            # the ref can drop now — and only now did the resume actually
            # happen (an aborted session must not count as savings)
            if p.entry is not None:
                self.prefix_resumed += 1
                self.prefix_tokens_saved += p.pos
                prefix.release(p.entry)
                p.entry = None
            if not final:
                p.pos = self._next_stop(p, chunk)
                continue
            with self._lock:
                self._prefilling.remove(p)
            s = p.sess
            s.req.t_first_token = now
            if s.req.t_submit is not None:
                self._m_ttft.observe(now - s.req.t_submit)
            self._append_token(s, int(first[i]))
            if s.remaining == 0:
                self._finish(s)
            else:
                with self._lock:
                    self._active.append(s)

    def _abort_prefilling(self, p: _Prefilling, error: str | None,
                          *, timeout: bool = False) -> None:
        with self._lock:
            try:
                self._prefilling.remove(p)
            except ValueError:
                return  # already settled
        if p.entry is not None:
            self.engine.prefix.release(p.entry)
            p.entry = None
        self.engine.cache.release(p.sess.sid)
        if timeout:
            self._settle_timeout(p.sess.req, "prefill")
        else:
            self._fail(p.sess.req, error)

    def _decode_all(self) -> bool:
        did = False
        if self._pending is not None:
            self._resolve_pending()
            did = True
            if self._pending is not None:
                # pipelined: window i+1 is already in flight — it IS this
                # iteration's decode work
                return True
        with self._lock:
            active = list(self._active)
        if not active:
            return did
        now = time.perf_counter()
        for s in active:
            if s.req.cancelled:  # abandoned mid-decode: free the slot now
                self._retire(s)
                self.engine.cache.release(s.sid)
                self._fail(s.req, "cancelled mid-decode")
            elif s.req.expired(now):
                # deadline at a decode boundary: settle with the tokens
                # already delivered (honest partial output). The session
                # is NOT kept even under keep_session — dispatch-ahead
                # windows may have advanced the device state past the
                # returned tokens, and a continuation from an
                # indeterminate position could silently double-decode.
                self._retire(s)
                self._release_timed_out_session(s)
                self._settle_timeout(s.req, "decode")
        active = [s for s in active if not s.req.done.is_set()]
        if not active:
            return True
        # pack by (sampling config, model) — both are dispatch keys;
        # chunk to the engine's largest batch bucket; iteration order ==
        # admission order (fairness: every active session advances
        # exactly one token per step)
        groups: dict[tuple, list[_Session]] = {}
        for s in active:
            groups.setdefault((s.req.sampling.key(), s.req.model),
                              []).append(s)
        # steady-state fast path: the whole active set is one sampling
        # group in one batch bucket and nobody is waiting to be admitted —
        # advance K tokens in one program and let the NEXT iteration fetch
        # them (possibly after dispatching the window after that)
        if len(groups) == 1 and len(active) <= self.engine.max_batch:
            with self._lock:
                # a non-empty prefilling set pins K=1 like a non-empty
                # queue: decode must yield to the next prefill chunk every
                # iteration, or chunking's bounded-stall guarantee dies
                queue_empty = (not self._qlen_locked()
                               and not self._prefilling)
            if queue_empty:
                min_rem = min(s.remaining for s in active)
                kd = self._spec_k_for(active, min_rem)
                if kd > 0:
                    self._dispatch_spec_window(active, kd)
                    return True
                k = self._pick_window(min_rem)
                if k > 1:
                    self._dispatch_window(active, k)
                    return True
        for group in groups.values():
            for i in range(0, len(group), self.engine.max_batch):
                chunk = group[i : i + self.engine.max_batch]
                slots = [s.slot for s in chunk]
                toks = [s.last_token for s in chunk]
                t0 = time.perf_counter()
                try:
                    nxt = self.engine.decode(slots, toks,
                                             chunk[0].req.sampling,
                                             model=chunk[0].req.model)
                except Exception as e:
                    self._fail_chunk(
                        chunk, f"decode failed: {type(e).__name__}: {e}")
                    continue
                t1 = time.perf_counter()
                for s, tok in zip(chunk, nxt):
                    s.req.phases.append(("decode", t0, t1))
                    self._append_token(s, int(tok), t1)
                    if s.remaining == 0:
                        self._retire(s)
                        self._finish(s)
        return True

    # ---- windowed decode (see module docstring) ------------------------

    def _pick_window(self, min_remaining: int) -> int:
        """Largest ladder rung no session would overshoot (a session
        within K tokens of its budget forces a smaller K — the on-device
        budget latch makes overshoot SAFE, this just keeps windows from
        decoding padding and delaying completion), additionally capped
        by ``window_cap`` — the autotuner's live K ceiling (default: the
        top rung, i.e. exactly the uncapped pick)."""
        k = 1
        cap = self.window_cap
        for w in self.window_ladder:
            if w <= min_remaining and w <= cap:
                k = max(k, w)
        return k

    def _spec_k_for(self, sessions: list[_Session],
                    min_remaining: int) -> int:
        """K_draft for a speculative window over ``sessions``, or 0 when
        plain decode is the right call. Speculation applies only to
        greedy default-model groups (the verify pass is pure argmax and
        the draft pairs the default model); the rung is the largest
        warmed ladder entry under the autotuner's ``spec_k`` cap whose
        window W=K+1 no session would overshoot — mirroring
        ``_pick_window``'s no-padding rule. ``min_remaining`` < 2 means
        at most one token is wanted, where speculation cannot win."""
        if not self.speculative:
            return 0
        cap = self.spec_k
        if cap <= 0 or min_remaining < 2:
            return 0
        s0 = sessions[0]
        if not s0.req.sampling.greedy:
            return 0
        if s0.req.model is not None and s0.req.model != self.engine.model_id:
            return 0
        k = 0
        for r in self.spec_ladder:
            if 0 < r <= cap and r + 1 <= min_remaining:
                k = max(k, r)
        return k

    def _dispatch_spec_window(self, sessions: list[_Session],
                              kd: int) -> None:
        """Dispatch a speculative verify window (draft proposes ``kd``
        tokens, target verifies all of them plus one correction in ONE
        pass); handles park in ``_pending`` like a plain window."""
        try:
            win = self.engine.spec_window(
                [s.slot for s in sessions],
                [s.last_token for s in sessions],
                [s.remaining for s in sessions],
                [-1 if s.req.eos_id is None else s.req.eos_id
                 for s in sessions],
                k_draft=kd, model=sessions[0].req.model,
            )
        except Exception as e:
            self._fail_chunk(sessions, f"decode failed: {type(e).__name__}: {e}")
            return
        self.spec_windows_dispatched[kd] = (
            self.spec_windows_dispatched.get(kd, 0) + 1)
        self._pending = (win, list(sessions))

    def _dispatch_window(self, sessions: list[_Session], k: int) -> None:
        """Dispatch a K-token window for ``sessions`` from host state; the
        handles park in ``_pending`` for the next iteration's fetch."""
        try:
            win = self.engine.decode_window(
                [s.slot for s in sessions],
                [s.last_token for s in sessions],
                [s.remaining for s in sessions],
                [-1 if s.req.eos_id is None else s.req.eos_id
                 for s in sessions],
                sessions[0].req.sampling, window=k,
                model=sessions[0].req.model,
            )
        except Exception as e:
            self._fail_chunk(sessions, f"decode failed: {type(e).__name__}: {e}")
            return
        self.windows_dispatched[k] = self.windows_dispatched.get(k, 0) + 1
        self._count_window(k)
        self._pending = (win, list(sessions))

    def _count_window(self, k: int) -> None:
        m = self._m_window_k.get(k)
        if m is not None:  # ladder rungs are pre-resolved; others skipped
            m.inc()

    def _resolve_pending(self, pipeline: bool = True) -> None:
        """Resolve the in-flight window: if steady state still holds,
        dispatch its successor FROM ITS DEVICE HANDLES first (async
        dispatch — the fetch below then overlaps that window's compute),
        then fetch and distribute the tokens."""
        win, sessions = self._pending
        self._pending = None
        with self._lock:
            queue_empty = (not self._qlen_locked()
                           and not self._prefilling)
            same_rows = self._active == sessions
        now0 = time.perf_counter()
        # an expired (or cancelled/settled) row stops the pipeline: its
        # window boundary is where the deadline is honored, not deferred
        # behind yet another dispatched window
        stop = any(s.req.cancelled or s.req.done.is_set()
                   or s.req.expired(now0) for s in sessions)
        if pipeline and queue_empty and same_rows and not stop:
            # remaining budgets as of AFTER the unfetched window, assuming
            # full consumption (rows that EOS'd early are latched frozen on
            # device, so overestimating their budget is harmless)
            proj = [s.remaining - win.window for s in sessions]
            live = [r for r in proj if r > 0]
            if live and win.spec:
                # pipeline a speculative successor only while speculation
                # still picks a rung; a 0 pick falls through WITHOUT a
                # successor and the next _decode_all tick dispatches plain
                # (spec<->plain transitions always happen at a tick, never
                # inside the pipeline — the window types' device programs
                # differ)
                kd = self._spec_k_for(sessions, min(live))
                if kd > 0:
                    try:
                        nxt = self.engine.spec_window_next(win, k_draft=kd)
                    except Exception as e:
                        self._fail_chunk(
                            sessions,
                            f"decode failed: {type(e).__name__}: {e}")
                        return
                    self.spec_windows_dispatched[kd] = (
                        self.spec_windows_dispatched.get(kd, 0) + 1)
                    self.windows_pipelined += 1
                    self._pending = (nxt, list(sessions))
            elif live:
                try:
                    nxt = self.engine.decode_window_next(
                        win, window=self._pick_window(min(live)))
                except Exception as e:
                    self._fail_chunk(
                        sessions, f"decode failed: {type(e).__name__}: {e}")
                    return
                self.windows_dispatched[nxt.window] = (
                    self.windows_dispatched.get(nxt.window, 0) + 1)
                self._count_window(nxt.window)
                self.windows_pipelined += 1
                self._pending = (nxt, list(sessions))
        # the pipeline's only sync point: blocks on window i while window
        # i+1 (if dispatched above) runs on device. Chaos drills inject
        # slow-readback latency here (the scheduler must absorb it as
        # latency, never as wrong tokens).
        _faults.serve_readback_hook()
        t_fetch = time.perf_counter()
        # ONE transfer for the token block AND the per-row summary the
        # window program latched on device (remaining budget + liveness):
        # the scheduler tick trusts the device latches instead of
        # re-deriving them per token host-side — with the fused Pallas
        # kernel those latches lived in VMEM for the whole window
        toks, dev_rem, dev_alive = self.engine.fetch_window_summary(win)
        now = time.perf_counter()
        # dispatch→fetch-complete: how long the window's tokens took to
        # reach the host after its program was dispatched (device compute
        # + readback, minus whatever the scheduler overlapped)
        self._m_readback.observe(now - win.t_dispatch)
        for i, (s, row) in enumerate(zip(sessions, toks)):
            if s.req.cancelled or s.req.done.is_set():
                continue  # the cancel sweep / a prior window settled it
            s.req.phases.append((
                "spec_window" if win.spec else "decode_window",
                win.t_dispatch, t_fetch))
            s.req.phases.append(("readback", t_fetch, now))
            if win.spec:
                # accept accounting: a spec window emits accepted+1
                # tokens per live row (the verify step that detects the
                # first disagreement emits the target's own correction
                # token). emitted == 0 means the row was dead at window
                # entry — not a rejection, so it doesn't skew the
                # histogram the autotuner steers by.
                emitted = 0
                for tok in row:
                    if tok == PAD_TOKEN:
                        break
                    emitted += 1
                if emitted > 0:
                    accepted = emitted - 1
                    self.spec_accepted_tokens += accepted
                    self._m_spec_accept.observe(float(accepted))
                    if accepted >= win.window - 1:
                        outcome = "full"
                    elif accepted > 0:
                        outcome = "partial"
                    else:
                        outcome = "reject"
                    self._m_spec_outcome[outcome].inc()
            for tok in row:
                if tok == PAD_TOKEN:
                    break
                self._append_token(s, int(tok), now)
                if s.remaining == 0:
                    break
            if not dev_alive[i] or dev_rem[i] <= 0:
                # the device latch is the liveness authority (EOS hit or
                # budget exhausted inside the window); the host token
                # walk above agrees by construction — _append_token's
                # bookkeeping mirrors the same latch rules
                s.remaining = 0
            if s.remaining == 0:
                self._retire(s)
                self._finish(s)
            elif s.req.expired(now):
                # window boundary = deadline boundary: this window's
                # tokens were delivered above, the request settles now
                # with that partial output (see the _decode_all sweep
                # for why the session is never kept)
                self._retire(s)
                self._release_timed_out_session(s)
                self._settle_timeout(s.req, "decode")

    def _fail_chunk(self, sessions: list[_Session], error: str) -> None:
        for s in sessions:
            self._retire(s)
            self.engine.cache.release(s.sid)
            self._fail(s.req, error)

    def _append_token(self, s: _Session, tok: int,
                      t: float | None = None) -> None:
        if t is None:
            t = time.perf_counter()
        if s.req.t_tokens:
            # server-side inter-token latency: same gap definition as
            # Request.itl_gaps()/loadgen (host arrival deltas; a window's
            # burst contributes 0.0 gaps), so the two views must agree
            self._m_itl.observe(t - s.req.t_tokens[-1])
        s.req.tokens.append(tok)
        s.req.t_tokens.append(t)
        s.last_token = tok
        s.remaining -= 1
        self.tokens_generated += 1
        if s.req.eos_id is not None and tok == s.req.eos_id:
            s.remaining = 0

    def _release_timed_out_session(self, s: _Session) -> None:
        """Release a deadline-expired session's slot AND its tier copies.
        The client received PARTIAL tokens this turn, so a tier copy from
        the LAST COMPLETED boundary would resurrect the conversation
        WITHOUT them — a later continuation would silently decode a
        context inconsistent with what the client already displayed.
        Discarding makes that continuation fail "unknown session"
        loudly instead (the client re-sends its full history, exactly
        like after an un-kept completion). Contrast the FAILURE paths,
        which deliberately keep tier copies: a failed request delivered
        nothing, so the last completed boundary IS its token-identical
        recovery point."""
        self.engine.cache.release(s.sid)
        if self.engine.tiers is not None:
            self.engine.tiers.discard(s.sid)

    def _retire(self, s: _Session) -> None:
        with self._lock:
            try:
                self._active.remove(s)
            except ValueError:
                pass

    def _finish(self, s: _Session) -> None:
        if s.req.keep_session:
            # keep the carries cached (unpinned → LRU-evictable) so a
            # follow-up request with this session_id continues in place
            self.engine.cache.unpin(s.sid)
            s.req.session_id = s.sid
            if self.engine.tiers is not None:
                # durable serve-session checkpoint at the request
                # boundary (async write-behind to the disk tier): a
                # crashed-and-restarted server resumes this session
                # token-identically from the last completed request
                self.engine.tiers.checkpoint(s.sid)
        else:
            self.engine.cache.release(s.sid)
            if self.engine.tiers is not None:
                # the conversation ended un-kept: stale tier copies from
                # earlier boundaries must not resurrect it — a later fill
                # would decode from BEFORE this request's tokens, i.e.
                # wrong output. (Failure paths deliberately keep tier
                # copies: resuming a failed continuation from the last
                # completed boundary is the token-identical recovery.)
                self.engine.tiers.discard(s.sid)
        s.req.t_done = time.perf_counter()
        self.completed += 1
        self._m_req_completed.inc()
        self._emit_timeline(s.req)
        s.req.done.set()

    def _fail(self, req: Request, error: str) -> None:
        req.error = error
        req.t_done = time.perf_counter()
        self.failed += 1
        self._m_req_failed.inc()
        self._emit_timeline(req)
        req.done.set()

    def _settle_timeout(self, req: Request, stage: str) -> None:
        """Settle a deadline-expired request: its own outcome family
        (never "failed" — the client gets every token that was ready as
        a partial reply), counted by the stage that reaped it."""
        req.timed_out = True
        req.t_done = time.perf_counter()
        self.timed_out += 1
        self._m_req_timeout.inc()
        m = self._m_deadline.get(stage)
        if m is not None:
            m.inc()
        self._emit_timeline(req)
        req.done.set()

    @staticmethod
    def _emit_timeline(req: Request) -> None:
        """Emit the request's phase timeline into the installed Chrome
        tracer (``--trace``): one complete event per phase on a synthetic
        per-request row, so Perfetto shows each request's
        admit→queue→prefill→decode→readback lane. No tracer → free."""
        t = tracing.get_tracer()
        if t is None or not req.phases:
            return
        tid = req.id  # request ids are tiny; pthread idents are huge —
        t.set_tid_name(tid, f"request {req.id}")  # no collision in practice
        for name, a, b in req.phases:
            t.complete(name, a, b, tid=tid, request=req.id)
        if req.error is not None:
            t.complete("failed", req.phases[-1][2], req.t_done, tid=tid,
                       request=req.id, error=req.error)

    # ---- drivers -------------------------------------------------------

    def drain(self) -> None:
        """Drive the scheduler until no work remains (test/offline use)."""
        while self.step():
            pass

    def run(self, stop_event: threading.Event, idle_wait: float = 0.05) -> None:
        """Scheduler loop for the server's background thread: step while
        there is work, block on the submit condition when idle."""
        while not stop_event.is_set():
            if self.step():
                continue
            with self._work:
                if not self._qlen_locked() and not self._active:
                    self._work.wait(timeout=idle_wait)
            # idle cycles beat the heartbeat too: "no traffic" and "thread
            # stuck" must look different to /healthz
            self.last_heartbeat = time.monotonic()
        if self._pending is not None:
            # graceful shutdown: the in-flight window's tokens are already
            # paid for — deliver them instead of hanging their requests
            # until client timeout (no follow-up dispatch: queue clients
            # waiting on THOSE must fail fast at stop, not decode on)
            self._resolve_pending(pipeline=False)
        # same fail-fast rule for mid-prefill sessions: a chunked prefill
        # spans many iterations, and nothing else settles its request
        for p in list(self._prefilling):
            self._abort_prefilling(p, "server stopped during prefill")

    def stats(self) -> dict:
        # one lock hold for the whole snapshot: submitted/rejected are
        # written under the lock by submit(), so reading them outside it
        # from this (client-thread) path is a data race — and a snapshot
        # whose fields come from different instants lies under load
        with self._lock:
            queued, active = self._qlen_locked(), len(self._active)
            queued_by_class = {c: len(q) for c, q in self._queues.items()}
            prefilling = len(self._prefilling)
            submitted, rejected = self.submitted, self.rejected
            window_cap, prefill_chunk = self.window_cap, self.prefill_chunk
            max_active = self.max_active
            spec_k = self.spec_k
        return {
            "replica": self.replica,
            "submitted": submitted,
            "completed": self.completed,
            "rejected": rejected,
            "failed": self.failed,
            "timed_out": self.timed_out,
            "queued_by_class": queued_by_class,
            "class_weights": list(self.class_weights),
            "tokens_generated": self.tokens_generated,
            "queued": queued,
            "active": active,
            "prefilling": prefilling,
            "max_active": max_active,
            "queue_size": self.queue_size,
            "window_ladder": list(self.window_ladder),
            "window_cap": window_cap,
            "windows_dispatched": dict(self.windows_dispatched),
            "windows_pipelined": self.windows_pipelined,
            "prefill_chunk": prefill_chunk,
            "prefill_chunk_choices": list(self.prefill_chunk_choices),
            "prefill_chunks_dispatched": self.prefill_chunks_dispatched,
            "prefix_resumed": self.prefix_resumed,
            "prefix_tokens_saved": self.prefix_tokens_saved,
            "prefill_tokens_computed": self.prefill_tokens_computed,
            "speculative": self.speculative,
            "spec_ladder": list(self.spec_ladder),
            "spec_k": spec_k,
            "spec_windows_dispatched": dict(self.spec_windows_dispatched),
            "spec_accepted_tokens": self.spec_accepted_tokens,
            "draft_prefills_dispatched": self.draft_prefills_dispatched,
            "draft_prefill_failures": self.draft_prefill_failures,
        }
