"""Serving front-ends: in-process synchronous client + stdlib HTTP server.

:class:`ServeServer` owns N replicas (one engine + batcher + scheduler
thread each) behind an admission :class:`~.router.Router` — a single
engine is the classic one-replica stack, a list of engines is the
data-parallel ``--replicas N`` stack (session→replica affinity, global
bounded admission, honest replica-death handling; serve/router.py).
:meth:`ServeServer.generate` is the synchronous request path used by
both front-ends:

- :class:`InprocessClient` — the test/loadgen client: same admission,
  batching and backpressure semantics as HTTP, no sockets;
- :func:`make_http_server` — a stdlib ``ThreadingHTTPServer`` JSON
  endpoint (no new dependencies):

  - ``POST /v1/generate``  body ``{"prompt": [ids], "max_new_tokens": N,
    "greedy": true, "temperature": t, "top_k": k, "top_p": p,
    "session_id": "...", "keep_session": false, "eos_id": null,
    "use_prefix": true}`` →
    ``{"tokens": [...], "session_id": "...", "latency_ms": ...,
    "ttft_ms": ..., "max_itl_ms": ...}`` (time-to-first-token and the
    request's worst inter-token gap — windowed decode delivers K tokens
    per burst, and a client deciding whether to pin ``--decode-window 1``
    needs to SEE that, not guess it);
  - ``GET /healthz`` → honest liveness fanned in across replicas:
    ``status`` is ``ok`` / ``degraded`` (some replicas dead or wedged —
    still 200, survivors are serving) / ``down`` (503), with per-replica
    alive/stale/heartbeat-age detail (a wedged server must fail probes,
    not smile at them);
    ``GET /stats`` (alias ``/v1/stats``) → batcher/engine/cache counters:
    per-key compile counts, prefix-cache hit/miss/evict/invalidate,
    state-cache swap generation, prefill-chunk/window dispatch counts,
    plus ``metrics`` — histogram summaries (p50/p99) and counter/gauge
    values from the telemetry registry (obs/);
  - ``GET /metrics`` → Prometheus text exposition of the same registry
    (histograms as cumulative buckets): server-side TTFT,
    inter-token-latency and queue-wait distributions, scheduler
    iteration time, readback latency, compile/cache/prefix counters —
    the live-server view of what loadgen could only measure offline.

  Each generate reply also carries ``phases_ms`` — the request's own
  queue/prefill/decode/readback host-time breakdown (the per-request
  trace timeline, summarised; the full timeline goes to ``--trace``).

  Backpressure maps to HTTP: full queue → 429, bad request → 400,
  scheduler failure → 500, timeout → 504.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .batcher import (
    CLASSES,
    Batcher,
    DeadlineExceededError,
    QueueFullError,
    Request,
)
from .engine import GREEDY, SamplingParams, ServeEngine, UnknownModelError
from .router import Replica, Router
from .state_cache import PREFIX_SID_NAMESPACE, PREFIX_STATS_CONFIG_KEYS


class _ReplicaStop:
    """Per-replica stop signal layered over the server-wide one: the
    rollout controller stops ONE scheduler (drain → swap → rejoin)
    without touching its peers. ``Batcher.run`` only polls
    ``is_set()``; ``wait()`` completes the Event-shaped surface for
    code that parks on the stop signal (the wedged-scheduler test
    stub) — without it such a thread dies with AttributeError and the
    liveness sweep retires a replica that was merely stuck."""

    __slots__ = ("server_stop", "local")

    def __init__(self, server_stop: threading.Event):
        self.server_stop = server_stop
        self.local = threading.Event()

    def is_set(self) -> bool:
        return self.server_stop.is_set() or self.local.is_set()

    def wait(self, timeout: float | None = None) -> bool:
        # OR over two Events with no shared condition to block on:
        # park on the server-wide one in short slices, re-checking the
        # local flag each wake (≤50 ms extra latency on a local stop)
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        while not self.is_set():
            step = 0.05
            if deadline is not None:
                step = min(step, deadline - time.monotonic())
                if step <= 0:
                    return False
            self.server_stop.wait(step)
        return True

#: aggregated batcher counters summed across replicas in stats(); config
#: fields (window ladder etc.) are taken from replica 0 instead
_SUMMED_BATCHER_KEYS = (
    "submitted", "completed", "rejected", "failed", "timed_out",
    "queued", "active", "prefilling", "windows_pipelined",
    "tokens_generated",
    "prefill_chunks_dispatched", "prefix_resumed", "prefix_tokens_saved",
    "prefill_tokens_computed",
)


class ServeServer:
    """N replicas (engine + batcher + scheduler thread each) behind an
    admission router, with a synchronous submit path.

    ``engine`` may be a single :class:`ServeEngine` (the classic
    one-replica stack — every existing call site) or a list of engines
    (``cli serve --replicas N``): one :class:`Batcher` is built per
    engine and the :class:`Router` spreads fresh sessions by load while
    keeping session continuations replica-affine. ``queue_size`` is the
    GLOBAL admission bound, enforced at the router.

    ``health_stale_after``: seconds of scheduler-heartbeat silence before
    a replica counts unhealthy even though its thread is alive — the
    wedged-dispatch case (thread stuck inside a device call that never
    returns) where ``is_alive()`` stays true forever. An idle scheduler
    beats the heartbeat every ``idle_wait`` (~0.05 s), so any healthy
    server sits far below the default."""

    def __init__(self, engine, batcher: Batcher | None = None,
                 health_stale_after: float = 60.0,
                 best_effort_queue_frac: float = 0.5,
                 deadline_defaults: dict | None = None,
                 sweep_interval: float | None = None,
                 remote_replicas: tuple[str, ...] = (),
                 remote_timeout_s: float | None = 120.0,
                 remote_rpc_timeout_s: float = 5.0,
                 remote_poll_interval_s: float = 0.5,
                 autotune=None,
                 tenant_rate: float | None = None,
                 tenant_burst: float = 5.0,
                 model_registry=None,
                 rollout_kw: dict | None = None, **batcher_kw):
        engines = (list(engine) if isinstance(engine, (list, tuple))
                   else [engine])
        if not engines:
            # remote-only fleets are deliberately unsupported: replica 0
            # anchors the registry, the back-compat engine/batcher views,
            # and the shared-session-dir failover target host death
            # depends on — a front with zero local capacity would also
            # lose every kept session with its last remote host
            raise ValueError(
                "ServeServer needs at least one LOCAL engine (remote "
                "replicas ride behind it via remote_replicas=)")
        if sweep_interval is not None and sweep_interval <= 0:
            raise ValueError(
                f"sweep_interval must be > 0 or None, got {sweep_interval}")
        # per-class default deadlines (seconds): applied in generate()
        # when the request names none — the serve plane's promise that
        # NO admitted request can wait/decode forever. None per class =
        # no default (the shipped default, back-compat).
        self.deadline_defaults = {c: None for c in CLASSES}
        if deadline_defaults:
            for c, v in deadline_defaults.items():
                if c not in CLASSES:
                    raise ValueError(f"unknown admission class {c!r}")
                if v is not None and v < 0:
                    raise ValueError(
                        f"deadline_defaults[{c!r}] must be >= 0 or None, "
                        f"got {v}")
                # 0 normalizes to None (the CLI's 0-means-none
                # convention) HERE, at construction — otherwise every
                # request of the class would fail Request validation at
                # runtime with a client-blaming 400
                self.deadline_defaults[c] = v if v else None
        if batcher is not None and len(engines) > 1:
            raise ValueError(
                "an explicit batcher only makes sense for a single-replica "
                "server; pass batcher_kw for replicated stacks")
        self.replicas: list[Replica] = []
        for i, eng in enumerate(engines):
            b = batcher if (batcher is not None and i == 0) else Batcher(
                eng, replica=i, **batcher_kw)
            if eng.tiers is not None:
                # tier metrics carry the replica label like every other
                # serve family — rebinding here covers engines built
                # without an explicit replica index
                eng.tiers.set_replica(i)
            self.replicas.append(Replica(i, eng, b))
        # remote replicas (serve/remote.py): peer serve PROCESSES behind
        # this router — the RPC shim satisfies the same replica surface,
        # its heartbeat poller is the scheduler thread start() drives,
        # and host death retires through the exact replica-death path.
        # Indexed after the locals, so replica 0 (the engine/batcher
        # back-compat views, the registry anchor) stays in-process.
        remotes = []
        for url in remote_replicas:
            from .remote import RemoteReplica

            rep = RemoteReplica(
                len(self.replicas), url, registry=engines[0].metrics,
                queue_size=self.replicas[0].batcher.queue_size,
                poll_interval=remote_poll_interval_s,
                rpc_timeout=remote_rpc_timeout_s,
                generate_timeout_s=remote_timeout_s)
            self.replicas.append(rep)
            remotes.append(rep)
        # the global admission bound == the per-replica queue bound, so
        # the router's check is the only one that ever fires
        self.router = Router(
            self.replicas, queue_size=self.replicas[0].batcher.queue_size,
            stale_after=health_stale_after,
            best_effort_frac=best_effort_queue_frac,
            registry=engines[0].metrics,
            tenant_rate=tenant_rate, tenant_burst=tenant_burst)
        # wire the provably-undelivered reroute path: a remote RPC that
        # failed before delivery (connect refused/timed out, circuit
        # fail-fast) re-enters routing instead of settling "state lost"
        for rep in remotes:
            rep.batcher.set_reroute(
                lambda req, _r=rep: self.router.reroute(req, _r))
        # prefix-state fabric propagation (serve/prefix_trie.py): every
        # LOCAL trie pushes its hot inserts to every remote peer through
        # that peer's OWN transport/circuit (RemoteBatcher.transport), so
        # one replica's cold prefill warms the fleet. Exact-match
        # PrefixCache stores have no adopt path and are left alone.
        self._propagators = []
        if remotes:
            from .prefix_trie import PrefixPropagator

            peer_shims = [rep.batcher for rep in remotes]
            for r in self.replicas:
                trie = getattr(r.engine, "prefix", None)
                if trie is not None and hasattr(trie, "attach_propagator"):
                    prop = PrefixPropagator(
                        trie, peer_shims, rpc_timeout=remote_rpc_timeout_s)
                    trie.attach_propagator(prop)
                    self._propagators.append(prop)
        # peer-side replay dedup for the generate POST: remote fronts
        # mint a request_id per request; a retried delivery whose first
        # attempt executed replays the settled reply instead of
        # double-decoding (exactly-once effect; serve/transport.py)
        from .transport import SettledCache

        self.settled = SettledCache(registry=engines[0].metrics)
        self.health_stale_after = health_stale_after
        # online autotuner (serve/autotune.py): built over the finished
        # stack so it sees every replica/tier/router surface; its
        # controller thread is started by start() and JOINED by stop()
        # (the thread-lifecycle contract lives inside AutoTuner itself).
        # None (the default) is byte-identical pre-autotuner behavior —
        # no thread, no knob ever moves.
        self.autotuner = None
        if autotune is not None:
            from .autotune import AutoTuner

            self.autotuner = AutoTuner(self, autotune)
        # rollout controller (serve/rollout.py): registry-backed rolling
        # weight swaps and slot resizes over this stack. None (the
        # default) = no registry, no controller thread, no new behavior.
        # ``model_registry`` is a ModelRegistry or a directory path.
        self.rollout = None
        if model_registry is not None:
            from .rollout import RolloutController

            self.rollout = RolloutController(
                self, model_registry, **(rollout_kw or {}))
        # the last warmup spec, remembered so the rollout controller can
        # replay the full compile-key lattice off-path before a swapped/
        # resized replica rejoins (None until warmup() runs)
        self._warmup_spec: tuple | None = None
        self._replica_stops: dict[int, _ReplicaStop] = {}
        self._model_info_seen: set[tuple[str, str]] = set()
        # optional periodic death sweep: the sweep normally piggybacks on
        # submits and health probes, so a dead replica on a QUIET server
        # is only retired when the next probe lands — an interval makes
        # retirement (requeue/migrate) happen within sweep_interval even
        # with no traffic and no prober
        self.sweep_interval = sweep_interval
        self._sweep_thread: threading.Thread | None = None
        self._stop = threading.Event()

    # ---- single-replica views (back-compat + convenience) --------------

    @property
    def engine(self) -> ServeEngine:
        """Replica 0's engine (THE engine of a single-replica server)."""
        return self.replicas[0].engine

    @property
    def batcher(self) -> Batcher:
        """Replica 0's batcher (THE batcher of a single-replica server)."""
        return self.replicas[0].batcher

    @property
    def _thread(self) -> threading.Thread | None:
        return self.replicas[0].thread

    # ---- lifecycle -----------------------------------------------------

    def start(self) -> "ServeServer":
        if any(r.thread is not None for r in self.replicas):
            raise RuntimeError("server already started")
        self._stop.clear()
        for r in self.replicas:
            # a stop()/start() restart revives retired replicas: their
            # death cleanup (requeue/fail/migrate) already ran, and the
            # fresh scheduler thread below serves again — leaving the
            # flag set would make the router refuse them forever while
            # health reports the new thread alive
            r.retired = False
            self._start_replica(r)
        # re-arm the death sweep only once every thread is RUNNING: a
        # concurrent probe/submit sweeping between `r.thread = t` and
        # `t.start()` would see a not-yet-alive thread and retire a
        # replica that is about to serve
        self.router.set_stopping(False)
        if self.sweep_interval is not None:
            t = threading.Thread(target=self._sweep_loop,
                                 name="serve-death-sweeper", daemon=True)
            self._sweep_thread = t
            t.start()
        if self.autotuner is not None:
            self.autotuner.start()
        if self.rollout is not None:
            self.rollout.start()
        return self

    def _start_replica(self, r: Replica) -> None:
        """Start (or restart, after a rollout drain) one replica's
        scheduler thread under a fresh per-replica stop signal. Target
        resolved at start time so tests can monkeypatch replica
        batchers' run/step before (or between) starts."""
        stop = _ReplicaStop(self._stop)
        self._replica_stops[r.index] = stop
        t = threading.Thread(
            target=r.batcher.run, args=(stop,),
            name=f"serve-scheduler-{r.index}", daemon=True,
        )
        r.thread = t
        t.start()

    def _stop_replica(self, r: Replica, timeout: float = 10.0) -> None:
        """Stop ONE replica's scheduler (the rollout controller's drain
        step — the replica must already be out of rotation and idle;
        the run loop's exit path would fail anything still pending)."""
        stop = self._replica_stops.get(r.index)
        if stop is not None:
            stop.local.set()
        if r.thread is not None:
            r.thread.join(timeout=timeout)

    def _sweep_loop(self) -> None:
        # stop() sets self._stop, which this loop's wait reads — the
        # thread parks within one interval of a shutdown
        while not self._stop.wait(self.sweep_interval):
            self.router.sweep()

    def stop(self) -> None:
        # the controllers park FIRST: knobs must not move and no drain
        # may start while the schedulers are being joined (both threads
        # are joined here — the thread-lifecycle contract)
        if self.rollout is not None:
            self.rollout.stop()
        if self.autotuner is not None:
            self.autotuner.stop()
        # mark the stop BEFORE joining: the router's death sweep must not
        # mistake deliberately-joined scheduler threads for crashes and
        # start requeueing a shutting-down server's work
        self.router.set_stopping(True)
        self._stop.set()
        if self._sweep_thread is not None:
            self._sweep_thread.join(timeout=10.0)
            self._sweep_thread = None
        for r in self.replicas:
            if r.thread is not None:
                r.thread.join(timeout=10.0)
                r.thread = None
        for r in self.replicas:
            if r.engine.tiers is not None:
                # durability barrier: a clean stop lands every kept
                # session's write-behind checkpoint on the disk tier, so
                # stop → start resumes them all (tests/test_serve_tiers);
                # close() then parks the spill worker (a later start's
                # first enqueue revives it) so stopped stacks don't leak
                # polling threads
                r.engine.tiers.flush(timeout=10.0)
                r.engine.tiers.close()
        for prop in self._propagators:
            # park the fabric's propagation workers: undelivered queue
            # entries are best-effort warmth, not durable state
            prop.close()

    def warmup(self, sampling: SamplingParams = GREEDY,
               prompt_lens: tuple[int, ...] = (1,)) -> int:
        """Pre-compile everything the schedulers can dispatch for these
        prompt lengths, on EVERY replica (each engine owns its compiled
        programs). Delegates to each batcher, which derives the chunk /
        prefix-insert split and window-ladder programs from its own
        policy — the one warmup entry point front-ends should use.
        Returns the total number of cached programs across replicas.

        The spec is remembered: the rollout controller replays it on a
        swapped/resized replica before that replica rejoins rotation, so
        a rollout never reintroduces mid-traffic compiles."""
        self._warmup_spec = (sampling, tuple(prompt_lens))
        return sum(r.batcher.warmup(sampling, prompt_lens=prompt_lens)
                   for r in self.replicas)

    def __enter__(self) -> "ServeServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ---- request path --------------------------------------------------

    def generate(
        self,
        prompt,
        *,
        max_new_tokens: int,
        sampling: SamplingParams = GREEDY,
        session_id: str | None = None,
        keep_session: bool = False,
        eos_id: int | None = None,
        use_prefix: bool = True,
        timeout: float = 120.0,
        klass: str = "priority",
        deadline_s: float | None = None,
        tenant: str | None = None,
        model: str | None = None,
    ) -> Request:
        """Submit and block until the request completes; returns the filled
        :class:`Request` (``.tokens``, ``.session_id``, ``.replica``,
        timestamps). Raises :class:`QueueFullError` (backpressure/shed —
        carries ``retry_after_s``), :class:`DeadlineExceededError` (the
        server-side deadline lapsed; ``.request`` holds the partial
        output), ``TimeoutError`` (client-side wait bound), or
        ``RuntimeError`` on a scheduler-side failure.

        ``deadline_s`` defaults to the server's per-class policy
        (``deadline_defaults``); an EXPLICIT ``deadline_s <= 0`` opts out
        of that default (the CLI's documented 0-means-none semantics —
        without it a client on a defaulted server could never request an
        unbounded run). The absolute deadline is stamped at submission
        and enforced at admission, in the queue, and at every
        decode-window boundary."""
        if deadline_s is None:
            deadline_s = self.deadline_defaults.get(klass)
        elif deadline_s <= 0:
            deadline_s = None  # explicit opt-out of the per-class default
        req = Request(
            prompt, max_new_tokens, sampling=sampling,
            session_id=session_id, keep_session=keep_session, eos_id=eos_id,
            use_prefix=use_prefix, klass=klass, deadline_s=deadline_s,
            tenant=tenant, model=model,
        )
        self.router.submit(req)
        if not req.done.wait(timeout):
            # tell the scheduler to stop working for a client that left —
            # otherwise abandoned requests hold queue/slot capacity and
            # decode tokens nobody reads (504 + retry = load amplification)
            req.cancelled = True
            raise TimeoutError(
                f"request {req.id} not completed within {timeout:.0f}s"
            )
        if req.timed_out:
            # honest server-side expiry: the partial output rides on the
            # exception — the HTTP layer returns it, never a wedged client
            raise DeadlineExceededError(req)
        if req.error is not None:
            retry = getattr(req, "remote_shed_retry_after", None)
            if retry is not None:
                # a REMOTE replica shed this request after routing
                # (serve/remote.py): re-raise as the same retryable 429
                # a local shed produces, with the peer's measured
                # Retry-After — not a hard RuntimeError/500
                raise QueueFullError(req.error, retry_after_s=retry)
            raise RuntimeError(req.error)
        return req

    def has_session(self, session_id: str) -> bool:
        """Fleet-wide session residency (device slots OR tiers on any
        replica) — the ``/replica/has_session`` affinity probe a FRONT
        router's RPC shim asks before routing a continuation here."""
        return any(r.engine.has_session(session_id)
                   for r in self.replicas
                   if hasattr(r.engine, "has_session"))

    @staticmethod
    def _aggregate_batcher(snapshots: list[dict]) -> dict:
        """THE cross-replica batcher aggregation — one implementation
        for ``stats()`` and ``replica_heartbeat()``, so a counter added
        to ``_SUMMED_BATCHER_KEYS`` (or a new merged dict) can never
        diverge between the two views. Seeds from the first snapshot
        (config fields ride along; merged dicts deep-copied so summing
        never mutates replica 0's reported view), sums the counter
        keys, and merges the per-K / per-class dicts."""
        agg: dict = {}
        for b in snapshots:
            if not agg:
                agg = dict(b)
                agg["windows_dispatched"] = dict(
                    b.get("windows_dispatched") or {})
                agg["queued_by_class"] = dict(
                    b.get("queued_by_class") or {})
                continue
            for k in _SUMMED_BATCHER_KEYS:
                agg[k] += b.get(k, 0)
            for k, v in (b.get("windows_dispatched") or {}).items():
                agg["windows_dispatched"][k] = (
                    agg["windows_dispatched"].get(k, 0) + v)
            for k, v in (b.get("queued_by_class") or {}).items():
                agg["queued_by_class"][k] = (
                    agg["queued_by_class"].get(k, 0) + v)
        agg.pop("replica", None)  # the aggregate is not one replica's view
        return agg

    def replica_heartbeat(self) -> dict:
        """Lightweight liveness + load payload for a front-of-fleet
        router's RPC shim (``GET /replica/heartbeat``): the health
        verdict plus the summed batcher counters — deliberately WITHOUT
        the metrics summaries /stats carries, because the shim polls
        this every ~0.5 s."""
        health = self.health()
        agg = self._aggregate_batcher(
            [r.batcher.stats() for r in self.replicas])
        return {
            "ok": health["ok"],
            "status": health["status"],
            "queued": health["queued"],
            "active": health["active"],
            "replicas_healthy": health["replicas_healthy"],
            "replicas_total": health["replicas_total"],
            "sessions": sum(len(r.engine.cache)
                            for r in self.replicas
                            if hasattr(r.engine.cache, "__len__")),
            # resident session ids (device slots AND tiers): the front's
            # RPC shim answers affinity probes from this snapshot so the
            # admission plane never blocks on a per-continuation GET.
            # None = truncated (a fleet past the cap falls back to the
            # shared-disk probe front-side — correct, just less warm).
            "session_ids": self._resident_session_ids(),
            "batcher": agg,
            # the prefix-store section a polling front mirrors into its
            # _RemoteEngine.stats() (None when no local replica runs a
            # prefix store) — keeps /stats honest fleet-wide
            "prefix_cache": self._aggregate_prefix(),
        }

    def _aggregate_prefix(self) -> dict | None:
        """Sum prefix-store counters across local replicas; config keys
        (:data:`PREFIX_STATS_CONFIG_KEYS`) keep the first store's value
        — stride/max/mode are fleet-uniform by construction (one CLI
        builds every replica). Works for both store modes: the stats
        contract is a FLAT dict of ints plus config scalars."""
        stats_list = [r.engine.prefix.stats() for r in self.replicas
                      if getattr(r.engine, "prefix", None) is not None]
        if not stats_list:
            return None
        agg = dict(stats_list[0])
        for s in stats_list[1:]:
            for k, v in s.items():
                if k in PREFIX_STATS_CONFIG_KEYS:
                    continue
                agg[k] = agg.get(k, 0) + v
        return agg

    #: heartbeat residency-list cap: past this the payload reports None
    #: (truncated) instead of shipping an unbounded id list every poll
    MAX_HEARTBEAT_SESSIONS = 4096

    def _resident_session_ids(self) -> list[str] | None:
        ids: set[str] = set()
        for r in self.replicas:
            cache = r.engine.cache
            if hasattr(cache, "session_ids"):
                ids.update(s for s in cache.session_ids()
                           if not s.startswith(PREFIX_SID_NAMESPACE))
            tiers = getattr(r.engine, "tiers", None)
            if tiers is not None and hasattr(tiers, "session_ids"):
                ids.update(tiers.session_ids())
            if len(ids) > self.MAX_HEARTBEAT_SESSIONS:
                return None
        return sorted(ids)

    def stats(self) -> dict:
        """Aggregate view + per-replica detail. Top-level ``batcher`` sums
        counters across replicas (identical to replica 0's stats on a
        single-replica server); top-level engine fields stay replica 0's
        for back-compat; ``replicas`` carries each replica's full
        batcher/engine stats and ``router`` the routing/requeue/migration
        counters."""
        per = []
        for r in self.replicas:
            # ONE stats() call per replica: the aggregate and this
            # replica's detail in one reply describe the same instant
            per.append({"replica": r.index, "batcher": r.batcher.stats(),
                        **r.engine.stats()})
        agg = self._aggregate_batcher([p["batcher"] for p in per])
        rt = self.router.stats()
        # router-level 429s are THE backpressure count of the replicated
        # stack (per-replica bounds never fire; see Router docstring)
        agg["rejected"] += rt["rejected"]
        return {"batcher": agg, **self.engine.stats(), "router": rt,
                "replicas": per, "metrics": self.metrics_summary(),
                # controller decisions + the last windowed (recent-
                # biased) signal deltas; None = autotuning off
                "autotune": (None if self.autotuner is None
                             else self.autotuner.stats()),
                # registry/rollout state; None = no registry attached
                "rollout": (None if self.rollout is None
                            else self.rollout.stats()),
                # fleet-wide model residency {model: {version: replica
                # count}} — two versions of one model nonzero at once
                # OUTSIDE an active rollout is the version-skew runbook
                # signature
                "models": self.resident_models()}

    def resident_models(self) -> dict:
        """{model: {version: replica_count}} across local replicas."""
        models: dict = {}
        for r in self.replicas:
            resident = getattr(r.engine, "resident_models", None)
            if resident is None:
                continue
            for mid, ver in resident().items():
                by_ver = models.setdefault(mid, {})
                by_ver[str(ver)] = by_ver.get(str(ver), 0) + 1
        return models

    def _collect_gauges(self) -> None:
        """Refresh poll-style gauges at scrape time — an idle server's
        schedulers may not have run since the last change, and cache
        occupancy is cheapest read on demand. One child per replica."""
        reg = self.engine.metrics
        live = dead = 0
        for r in self.replicas:
            rl = str(r.index)
            b = r.batcher.stats()
            reg.gauge("serve_queue_depth", labelnames=("replica",)).labels(
                replica=rl).set(b["queued"])
            reg.gauge("serve_active_sessions",
                      labelnames=("replica",)).labels(
                replica=rl).set(b["active"])
            reg.gauge("serve_prefilling_sessions",
                      labelnames=("replica",)).labels(
                replica=rl).set(b["prefilling"])
            c = r.engine.cache.stats()
            fam = reg.gauge("serve_state_cache_slots",
                            "state-cache slot occupancy",
                            labelnames=("replica", "state"))
            fam.labels(replica=rl, state="live").set(c["live_sessions"])
            fam.labels(replica=rl, state="pinned").set(c["pinned"])
            fam.labels(replica=rl, state="free").set(c["free"])
            if r.engine.prefix is not None:
                ps = r.engine.prefix.stats()
                reg.gauge("serve_prefix_cache_entries",
                          "live prefix-cache entries",
                          labelnames=("replica",)).labels(replica=rl).set(
                    ps["entries"])
                if "nodes_device" in ps:
                    # fabric mode: node population by residency kind —
                    # device (slot-backed), spilled (host tier, within
                    # the byte bound), structural (stateless radix
                    # splits)
                    fam = reg.gauge(
                        "serve_prefix_trie_nodes",
                        "prefix-trie nodes by residency kind",
                        labelnames=("replica", "kind"))
                    fam.labels(replica=rl, kind="device").set(
                        ps["nodes_device"])
                    fam.labels(replica=rl, kind="spilled").set(
                        ps["nodes_spilled"])
                    fam.labels(replica=rl, kind="structural").set(
                        ps["nodes_structural"])
            if r.engine.tiers is not None:
                ts = r.engine.tiers.stats()
                fam = reg.gauge("serve_tier_entries",
                                "spilled session states held per tier "
                                "(pending = spill captured, fetch not "
                                "done)",
                                labelnames=("tier", "replica"))
                for tier in ("pending", "host", "disk"):
                    fam.labels(tier=tier, replica=rl).set(
                        ts["entries"][tier])
            if r.alive():
                live += 1
            else:
                dead += 1
        fam = reg.gauge("serve_replicas",
                        "replica schedulers by liveness state",
                        labelnames=("state",))
        fam.labels(state="live").set(live)
        fam.labels(state="dead").set(dead)
        # model residency: replicas hosting each (model, version). Pairs
        # that vanish (a completed rollout's old version) are pinned to
        # 0, not dropped — a flatlined-to-zero child is how the scrape
        # side SEES the cutover complete
        fam = reg.gauge(
            "serve_model_info",
            "replicas hosting each resident model version (two versions "
            "of one model nonzero at once outside a rollout = version "
            "skew; see docs/OPERATIONS.md)",
            labelnames=("model", "version"))
        current = {}
        for mid, by_ver in self.resident_models().items():
            for ver, count in by_ver.items():
                current[(mid, ver)] = count
        for key in self._model_info_seen - set(current):
            fam.labels(model=key[0], version=key[1]).set(0)
        for (mid, ver), count in current.items():
            fam.labels(model=mid, version=ver).set(count)
        self._model_info_seen |= set(current)

    def metrics_text(self) -> str:
        """Prometheus text exposition of the serve stack's registry
        (``GET /metrics``)."""
        self._collect_gauges()
        return self.engine.metrics.render_prometheus()

    def metrics_summary(self) -> dict:
        """JSON-ready registry view (histograms as {count,sum,p50,p99})
        — embedded in ``/stats`` and the loadgen/bench reports so
        server-side and loadgen-side percentiles sit next to each other.
        ``replica``-labelled families export per-child entries plus one
        cross-replica aggregate under the bare name."""
        self._collect_gauges()
        return self.engine.metrics.summaries()

    def health(self) -> dict:
        """Honest liveness, fanned in across replicas. A replica is
        healthy when its scheduler THREAD is alive AND its heartbeat is
        fresher than ``health_stale_after`` (a wedged thread — stuck
        inside a dispatch that never returns — stays is_alive() forever,
        so the heartbeat age is the real signal). The aggregate
        ``status`` is ``ok`` (all healthy), ``degraded`` (some dead or
        wedged, survivors still serving — HTTP 200, because an
        orchestrator kill-looping a half-healthy server would destroy
        the surviving capacity too) or ``down`` (nothing serving —
        HTTP 503). The probe also triggers the router's death sweep, so
        a dead replica's queued work is requeued by the next probe even
        on an otherwise idle server."""
        self.router.sweep()
        now = time.monotonic()
        reps = []
        healthy = 0
        for r in self.replicas:
            alive = r.thread is not None and r.thread.is_alive()
            hb = r.batcher.last_heartbeat
            age = None if hb is None else max(now - hb, 0.0)
            stale = age is not None and age > self.health_stale_after
            ok = bool(alive and not stale)
            healthy += ok
            st = r.batcher.stats()
            reps.append({
                "replica": r.index,
                "ok": ok,
                "alive": bool(alive),
                "stale": bool(stale),
                "retired": bool(r.retired),
                # mid-rollout: out of rotation on purpose — a "degraded"
                # verdict while this is set is the planned N-1 window
                "draining": bool(getattr(r, "draining", False)),
                "seconds_since_last_iteration":
                    None if age is None else round(age, 3),
                "queued": st["queued"],
                "active": st["active"],
            })
        status = ("ok" if healthy == len(reps)
                  else "degraded" if healthy else "down")
        ages = [x["seconds_since_last_iteration"] for x in reps
                if x["seconds_since_last_iteration"] is not None]
        return {
            "ok": status == "ok",
            "status": status,
            "replicas_healthy": healthy,
            "replicas_total": len(reps),
            "replicas": reps,
            # legacy flat fields: the single-replica view generalised —
            # alive only when EVERY scheduler thread lives, stale when any
            # heartbeat is, worst-case heartbeat age, summed depths
            "batcher_alive": all(x["alive"] for x in reps),
            "batcher_stale": any(x["stale"] for x in reps),
            "seconds_since_last_iteration": max(ages) if ages else None,
            "queued": sum(x["queued"] for x in reps),
            "active": sum(x["active"] for x in reps),
        }


class InprocessClient:
    """Synchronous in-process client: the HTTP semantics without sockets."""

    def __init__(self, server: ServeServer):
        self._server = server

    def generate(self, prompt, *, max_new_tokens: int,
                 sampling: SamplingParams = GREEDY, **kw) -> list[int]:
        req = self._server.generate(
            prompt, max_new_tokens=max_new_tokens, sampling=sampling, **kw
        )
        return list(req.tokens)

    def stats(self) -> dict:
        return self._server.stats()


def _sampling_from_body(body: dict) -> SamplingParams:
    # sampling params are COMPILE KEYS (engine.py): quantize the floats so
    # clients sending temperature=0.70000001 vs 0.7 share one compiled
    # program; the engine's max_sampling_configs bounds the rest
    top_k = body.get("top_k")
    top_p = body.get("top_p")
    return SamplingParams(
        temperature=round(float(body.get("temperature", 1.0)), 2),
        top_k=None if top_k is None else int(top_k),
        top_p=None if top_p is None else round(float(top_p), 2),
        greedy=bool(body.get("greedy", False)),
    )


class _Handler(BaseHTTPRequestHandler):
    server_version = "lstm-tsp-serve/1.0"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # keep serving logs structured
        pass

    @property
    def _serve(self) -> ServeServer:
        return self.server.serve  # type: ignore[attr-defined]

    def _reply(self, code: int, payload: dict,
               headers: dict | None = None) -> None:
        data = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    @staticmethod
    def _error_parts(code: str, message: str, *, retryable: bool,
                     retry_after_s: float | None = None,
                     **extra) -> tuple[dict, dict | None]:
        """ONE error shape for every non-200 reply, so clients can branch
        on a stable contract instead of parsing prose: ``error`` (the
        human message — the key every pre-existing client reads),
        ``code`` (stable machine token), ``retryable``, and
        ``retry_after_s`` where the server has an honest estimate (also
        sent as the standard ``Retry-After`` header on 429s). Returns
        ``(body, headers)`` so the generate path can settle the payload
        into the replay cache before writing it to the wire."""
        body = {"error": message, "code": code, "retryable": bool(retryable),
                "retry_after_s": retry_after_s, **extra}
        headers = None
        if retry_after_s is not None:
            # delta-seconds per RFC 9110 (integer, rounded up — the body
            # keeps the precise float)
            headers = {"Retry-After": str(max(1, int(-(-retry_after_s // 1))))}
        return body, headers

    def _error(self, http_status: int, code: str, message: str, *,
               retryable: bool, retry_after_s: float | None = None,
               **extra) -> None:
        body, headers = self._error_parts(
            code, message, retryable=retryable,
            retry_after_s=retry_after_s, **extra)
        self._reply(http_status, body, headers)

    def do_GET(self) -> None:
        if self.path == "/healthz":
            # per-replica fan-in: 200 while ANY replica serves ("ok" or
            # "degraded" — kill-looping a half-healthy server would take
            # out the surviving capacity too), 503 only when "down"
            health = self._serve.health()
            self._reply(200 if health["status"] != "down" else 503, health)
        elif self.path in ("/stats", "/v1/stats"):
            # one payload, two routes: per-key compile counts, prefix-cache
            # hit/miss/evict/invalidate counters, state-cache swap
            # generation, batcher chunk/window counters + registry
            # histogram summaries (p50/p99)
            self._reply(200, self._serve.stats())
        elif self.path == "/metrics":
            # Prometheus text exposition (server-side TTFT/ITL/queue-wait
            # histograms as cumulative buckets; see docs/OPERATIONS.md for
            # the scrape config and runbook)
            data = self._serve.metrics_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
        elif self.path == "/replica/heartbeat":
            # the remote-replica transport's liveness+load poll
            # (serve/remote.py RemoteBatcher.run): health verdict +
            # summed batcher counters, no metrics summaries — cheap
            # enough for a sub-second poll cadence
            self._reply(200, self._serve.replica_heartbeat())
        elif self.path.startswith("/replica/has_session"):
            # affinity probe from a front-of-fleet router: is this
            # session device- or tier-resident on ANY local replica?
            q = urllib.parse.parse_qs(
                urllib.parse.urlparse(self.path).query)
            sid = (q.get("sid") or [None])[0]
            if not sid:
                self._error(400, "bad_request",
                            "has_session needs ?sid=", retryable=False)
            else:
                self._reply(200, {"has": self._serve.has_session(sid)})
        elif self.path == "/rollout":
            # rollout-controller state: active move, queue, history,
            # last canary report, registry manifest
            if self._serve.rollout is None:
                self._error(404, "not_found",
                            "no model registry attached (start the "
                            "server with --registry-dir)",
                            retryable=False)
            else:
                self._reply(200, self._serve.rollout.stats())
        else:
            self._error(404, "not_found", f"no route {self.path}",
                        retryable=False)

    def do_POST(self) -> None:
        if self.path == "/replica/warmup":
            # front-of-fleet warmup pass-through: compile the lattice
            # for the front's prompt lengths/sampling before traffic
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                lens = tuple(int(t) for t in body.get("prompt_lens", (1,)))
                sampling = _sampling_from_body(body)
            except (ValueError, TypeError, json.JSONDecodeError) as e:
                self._error(400, "bad_request", f"bad request: {e}",
                            retryable=False)
                return
            try:
                n = self._serve.warmup(sampling, prompt_lens=lens)
            except (ValueError, RuntimeError) as e:
                self._error(500, "internal",
                            f"{type(e).__name__}: {e}", retryable=False)
                return
            self._reply(200, {"programs": n})
            return
        if self.path == "/replica/prefix":
            # fabric propagation receiver: a peer pushes one trie node
            # (token path + carry snapshot). Idempotent by token-hash —
            # the retrying transport may deliver twice (replay_safe) and
            # a replay answers dedup, not a double insert. Applied to
            # every LOCAL replica running a fabric trie so a
            # multi-replica host warms uniformly.
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
            except (ValueError, json.JSONDecodeError) as e:
                self._error(400, "bad_request", f"bad request: {e}",
                            retryable=False)
                return
            from .prefix_trie import decode_propagated_state

            applied = dedup = rejected = 0
            tries = [r.engine.prefix for r in self._serve.replicas
                     if hasattr(getattr(r.engine, "prefix", None),
                                "adopt_remote")]
            if not tries:
                self._error(404, "not_found",
                            "no prefix fabric on this host (boot with "
                            "--prefix-fabric on)", retryable=False)
                return
            for trie in tries:
                state = decode_propagated_state(
                    body, num_layers=trie.cache.num_layers,
                    hidden_size=trie.cache.hidden_size)
                if state is None:
                    rejected += 1
                    continue
                outcome = trie.adopt_remote(body.get("tokens", ()), state,
                                            body.get("hash"))
                if outcome == "applied":
                    applied += 1
                elif outcome == "dedup":
                    dedup += 1
                else:
                    rejected += 1
            if applied == dedup == 0 and rejected:
                self._error(400, "bad_request",
                            "malformed or rejected fabric node "
                            "(hash/shape/stride mismatch, or store "
                            "full of pinned nodes)", retryable=False)
                return
            self._reply(200, {"applied": applied, "dedup": dedup,
                              "rejected": rejected})
            return
        if self.path == "/rollout":
            # enqueue a rolling swap ({"model": ..., "version": N?}) or
            # a slot resize ({"slots": N}) for the controller thread;
            # 202 — the roll happens replica-by-replica off this request
            if self._serve.rollout is None:
                self._error(404, "not_found",
                            "no model registry attached (start the "
                            "server with --registry-dir)",
                            retryable=False)
                return
            try:
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                slots = body.get("slots")
                if slots is not None:
                    move = self._serve.rollout.request_resize(int(slots))
                else:
                    version = body.get("version")
                    move = self._serve.rollout.request_rollout(
                        str(body["model"]),
                        None if version is None else int(version))
            except (KeyError, ValueError, TypeError,
                    json.JSONDecodeError) as e:
                self._error(400, "bad_request", f"bad request: {e}",
                            retryable=False)
                return
            self._reply(202, {"accepted": True, **move})
            return
        if self.path != "/v1/generate":
            self._error(404, "not_found", f"no route {self.path}",
                        retryable=False)
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(length) or b"{}")
            prompt = body["prompt"]
            max_new = int(body.get("max_new_tokens", 16))
            sampling = _sampling_from_body(body)
            timeout = float(body.get("timeout", 120.0))
            # deadline: body field wins, the X-Deadline-S header is the
            # proxy-friendly alternative; absent both, the server's
            # per-class default applies (ServeServer.deadline_defaults)
            deadline_s = body.get("deadline_s")
            if deadline_s is None:
                hdr = self.headers.get("X-Deadline-S")
                deadline_s = None if hdr is None else float(hdr)
            deadline_s = None if deadline_s is None else float(deadline_s)
            klass = str(body.get("class", "priority"))
            # per-tenant rate limiting (serve/router.py): the token-
            # bucket identity; absent = untenanted, never rate-limited
            tenant = body.get("tenant")
            tenant = None if tenant is None else str(tenant)
            # multi-model multiplexing: absent = the default model —
            # the single-model fleet's behavior, unchanged
            model = body.get("model")
            model = None if model is None else str(model)
        except (KeyError, ValueError, TypeError, json.JSONDecodeError) as e:
            # TypeError included: {"max_new_tokens": null} etc. must be a
            # 400, not a handler crash that resets the connection
            self._error(400, "bad_request", f"bad request: {e}",
                        retryable=False)
            return
        rid = body.get("request_id")
        rid = None if rid is None else str(rid)
        if rid is not None:
            # idempotent replay (serve/transport.py SettledCache): a
            # remote front retries delivery under this client-minted id
            # — a replay of an attempt that already executed returns
            # the settled reply verbatim instead of double-decoding
            state, cached = self._serve.settled.begin(
                rid, wait_timeout=timeout)
            if state == "hit":
                status, payload = cached
                self._reply(status, dict(payload, replayed=True))
                return
            if state == "timeout":
                self._error(504, "client_timeout",
                            f"request_id {rid!r} is still executing its "
                            "first delivery", retryable=True)
                return
            # "mine": first delivery — every outcome below settles or
            # abandons the id before the reply hits the wire
        status, payload, headers = self._generate_outcome(
            body, prompt, max_new, sampling, timeout, klass, deadline_s,
            tenant, model)
        if rid is not None:
            if status == 200 or payload.get("code") == "deadline_exceeded":
                # only outcomes that decoded tokens are worth replaying;
                # transient errors (shed, bad request, internal) abandon
                # so a retried delivery re-executes
                self._serve.settled.settle(rid, status, payload)
            else:
                self._serve.settled.abandon(rid)
        self._reply(status, payload, headers)

    def _generate_outcome(self, body, prompt, max_new, sampling, timeout,
                          klass, deadline_s, tenant, model):
        """Execute one generate call and return ``(status, payload,
        headers)`` instead of writing the wire directly — the replay
        cache records the settled outcome before the reply is sent."""
        t0 = time.perf_counter()
        err = self._error_parts
        try:
            req = self._serve.generate(
                prompt, max_new_tokens=max_new, sampling=sampling,
                session_id=body.get("session_id"),
                keep_session=bool(body.get("keep_session", False)),
                eos_id=body.get("eos_id"),
                use_prefix=bool(body.get("use_prefix", True)),
                timeout=timeout, klass=klass, deadline_s=deadline_s,
                tenant=tenant, model=model,
            )
        except UnknownModelError as e:
            # the model is not resident anywhere in the fleet: the
            # client named a thing that does not exist — 404, like an
            # unknown route, not a capacity condition
            return (404, *err("unknown_model", str(e), retryable=False))
        except QueueFullError as e:
            # the shed path: retryable by definition, with the router's
            # live drain estimate as the honest Retry-After
            return (429, *err("queue_full", str(e), retryable=True,
                              retry_after_s=getattr(e, "retry_after_s",
                                                    None)))
        except DeadlineExceededError as e:
            # server-side deadline expiry: an honest timeout WITH the
            # partial output — the client keeps every token that was
            # ready, and can branch on code="deadline_exceeded"
            r = e.request
            return (504, *err("deadline_exceeded", str(e), retryable=True,
                              tokens=list(r.tokens),
                              deadline_s=r.deadline_s,
                              phases_ms=r.phase_summary_ms()))
        except (ValueError, TypeError, RuntimeError) as e:
            # TypeError: a null/wrong-typed prompt surfaces from
            # np.asarray inside Request — still the client's fault
            if isinstance(e, RuntimeError):
                return (500, *err("internal", f"{type(e).__name__}: {e}",
                                  retryable=False))
            return (400, *err("bad_request", f"{type(e).__name__}: {e}",
                              retryable=False))
        except TimeoutError as e:
            # the client-side wait bound (distinct from the server-side
            # deadline): the request was CANCELLED, nothing useful to
            # return, but retrying re-sends the work — mark retryable
            return (504, *err("client_timeout", str(e), retryable=True))
        gaps = req.itl_gaps()
        return (200, {
            "tokens": list(req.tokens),
            "session_id": req.session_id,
            "replica": req.replica,
            "class": req.klass,
            "latency_ms": round((time.perf_counter() - t0) * 1e3, 3),
            "ttft_ms": round((req.t_first_token - req.t_submit) * 1e3, 3)
            if req.t_first_token and req.t_submit else None,
            "max_itl_ms": round(max(gaps) * 1e3, 3) if gaps else None,
            # per-request phase breakdown (queue/prefill/decode/readback
            # host time) — the trace timeline, summarised into the reply
            "phases_ms": req.phase_summary_ms(),
        }, None)


def make_http_server(serve: ServeServer, host: str = "127.0.0.1",
                     port: int = 0) -> ThreadingHTTPServer:
    """Bind the JSON endpoint (port 0 → ephemeral; see
    ``httpd.server_address``). Caller drives ``serve_forever`` (typically
    on a thread) and pairs it with ``serve.start()``/``serve.stop()``."""
    httpd = ThreadingHTTPServer((host, port), _Handler)
    httpd.serve = serve  # type: ignore[attr-defined]
    return httpd
