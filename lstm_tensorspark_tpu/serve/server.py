"""Serving front-ends: in-process synchronous client + stdlib HTTP server.

:class:`ServeServer` owns the engine + batcher and a background scheduler
thread; :meth:`ServeServer.generate` is the synchronous request path used
by both front-ends:

- :class:`InprocessClient` — the test/loadgen client: same admission,
  batching and backpressure semantics as HTTP, no sockets;
- :func:`make_http_server` — a stdlib ``ThreadingHTTPServer`` JSON
  endpoint (no new dependencies):

  - ``POST /v1/generate``  body ``{"prompt": [ids], "max_new_tokens": N,
    "greedy": true, "temperature": t, "top_k": k, "top_p": p,
    "session_id": "...", "keep_session": false, "eos_id": null,
    "use_prefix": true}`` →
    ``{"tokens": [...], "session_id": "...", "latency_ms": ...,
    "ttft_ms": ..., "max_itl_ms": ...}`` (time-to-first-token and the
    request's worst inter-token gap — windowed decode delivers K tokens
    per burst, and a client deciding whether to pin ``--decode-window 1``
    needs to SEE that, not guess it);
  - ``GET /healthz`` → honest liveness: 200 with the scheduler thread's
    heartbeat age while the batcher thread lives, 503 once it is dead or
    never started (a wedged server must fail probes, not smile at them);
    ``GET /stats`` (alias ``/v1/stats``) → batcher/engine/cache counters:
    per-key compile counts, prefix-cache hit/miss/evict/invalidate,
    state-cache swap generation, prefill-chunk/window dispatch counts,
    plus ``metrics`` — histogram summaries (p50/p99) and counter/gauge
    values from the telemetry registry (obs/);
  - ``GET /metrics`` → Prometheus text exposition of the same registry
    (histograms as cumulative buckets): server-side TTFT,
    inter-token-latency and queue-wait distributions, scheduler
    iteration time, readback latency, compile/cache/prefix counters —
    the live-server view of what loadgen could only measure offline.

  Each generate reply also carries ``phases_ms`` — the request's own
  queue/prefill/decode/readback host-time breakdown (the per-request
  trace timeline, summarised; the full timeline goes to ``--trace``).

  Backpressure maps to HTTP: full queue → 429, bad request → 400,
  scheduler failure → 500, timeout → 504.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from .batcher import Batcher, QueueFullError, Request
from .engine import GREEDY, SamplingParams, ServeEngine


class ServeServer:
    """Engine + batcher + scheduler thread, with a synchronous submit path.

    ``health_stale_after``: seconds of scheduler-heartbeat silence before
    ``health()`` reports not-ok even though the thread is alive — the
    wedged-dispatch case (thread stuck inside a device call that never
    returns) where ``is_alive()`` stays true forever. An idle scheduler
    beats the heartbeat every ``idle_wait`` (~0.05 s), so any healthy
    server sits far below the default."""

    def __init__(self, engine: ServeEngine, batcher: Batcher | None = None,
                 health_stale_after: float = 60.0, **batcher_kw):
        self.engine = engine
        self.batcher = batcher or Batcher(engine, **batcher_kw)
        self.health_stale_after = health_stale_after
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # ---- lifecycle -----------------------------------------------------

    def start(self) -> "ServeServer":
        if self._thread is not None:
            raise RuntimeError("server already started")
        self._stop.clear()
        self._thread = threading.Thread(
            target=self.batcher.run, args=(self._stop,),
            name="serve-scheduler", daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def warmup(self, sampling: SamplingParams = GREEDY,
               prompt_lens: tuple[int, ...] = (1,)) -> int:
        """Pre-compile everything the scheduler can dispatch for these
        prompt lengths. Delegates to the batcher, which derives the
        chunk / prefix-insert split and window-ladder programs from its
        own policy — the one warmup entry point front-ends should use."""
        return self.batcher.warmup(sampling, prompt_lens=prompt_lens)

    def __enter__(self) -> "ServeServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ---- request path --------------------------------------------------

    def generate(
        self,
        prompt,
        *,
        max_new_tokens: int,
        sampling: SamplingParams = GREEDY,
        session_id: str | None = None,
        keep_session: bool = False,
        eos_id: int | None = None,
        use_prefix: bool = True,
        timeout: float = 120.0,
    ) -> Request:
        """Submit and block until the request completes; returns the filled
        :class:`Request` (``.tokens``, ``.session_id``, timestamps).
        Raises :class:`QueueFullError` (backpressure), ``TimeoutError``, or
        ``RuntimeError`` on a scheduler-side failure."""
        req = Request(
            prompt, max_new_tokens, sampling=sampling,
            session_id=session_id, keep_session=keep_session, eos_id=eos_id,
            use_prefix=use_prefix,
        )
        self.batcher.submit(req)
        if not req.done.wait(timeout):
            # tell the scheduler to stop working for a client that left —
            # otherwise abandoned requests hold queue/slot capacity and
            # decode tokens nobody reads (504 + retry = load amplification)
            req.cancelled = True
            raise TimeoutError(
                f"request {req.id} not completed within {timeout:.0f}s"
            )
        if req.error is not None:
            raise RuntimeError(req.error)
        return req

    def stats(self) -> dict:
        return {"batcher": self.batcher.stats(), **self.engine.stats(),
                "metrics": self.metrics_summary()}

    def _collect_gauges(self) -> None:
        """Refresh poll-style gauges at scrape time — an idle server's
        scheduler may not have run since the last change, and cache
        occupancy is cheapest read on demand."""
        reg = self.engine.metrics
        b = self.batcher.stats()
        reg.gauge("serve_queue_depth").set(b["queued"])
        reg.gauge("serve_active_sessions").set(b["active"])
        reg.gauge("serve_prefilling_sessions").set(b["prefilling"])
        c = self.engine.cache.stats()
        fam = reg.gauge("serve_state_cache_slots",
                        "state-cache slot occupancy", labelnames=("state",))
        fam.labels(state="live").set(c["live_sessions"])
        fam.labels(state="pinned").set(c["pinned"])
        fam.labels(state="free").set(c["free"])
        if self.engine.prefix is not None:
            reg.gauge("serve_prefix_cache_entries",
                      "live prefix-cache entries").set(
                self.engine.prefix.stats()["entries"])

    def metrics_text(self) -> str:
        """Prometheus text exposition of the serve stack's registry
        (``GET /metrics``)."""
        self._collect_gauges()
        return self.engine.metrics.render_prometheus()

    def metrics_summary(self) -> dict:
        """JSON-ready registry view (histograms as {count,sum,p50,p99})
        — embedded in ``/stats`` and the loadgen/bench reports so
        server-side and loadgen-side percentiles sit next to each other."""
        self._collect_gauges()
        return self.engine.metrics.summaries()

    def health(self) -> dict:
        """Honest liveness: ``ok`` requires the scheduler THREAD to be
        alive AND its heartbeat fresher than ``health_stale_after`` — a
        crashed batcher fails probes (HTTP 503), and so does a WEDGED one
        (thread alive but stuck inside a dispatch that never returns: the
        is_alive() check alone would smile through that forever). Reports
        ``seconds_since_last_iteration`` (scheduler heartbeat age; idle
        cycles count as iterations, so a healthy idle server stays near
        its poll interval) plus queue depth for probe-side context."""
        thread = self._thread
        alive = thread is not None and thread.is_alive()
        hb = self.batcher.last_heartbeat
        age = None if hb is None else max(time.monotonic() - hb, 0.0)
        stale = age is not None and age > self.health_stale_after
        st = self.batcher.stats()
        return {
            "ok": bool(alive and not stale),
            "batcher_alive": bool(alive),
            "batcher_stale": bool(stale),
            "seconds_since_last_iteration":
                None if age is None else round(age, 3),
            "queued": st["queued"],
            "active": st["active"],
        }


class InprocessClient:
    """Synchronous in-process client: the HTTP semantics without sockets."""

    def __init__(self, server: ServeServer):
        self._server = server

    def generate(self, prompt, *, max_new_tokens: int,
                 sampling: SamplingParams = GREEDY, **kw) -> list[int]:
        req = self._server.generate(
            prompt, max_new_tokens=max_new_tokens, sampling=sampling, **kw
        )
        return list(req.tokens)

    def stats(self) -> dict:
        return self._server.stats()


def _sampling_from_body(body: dict) -> SamplingParams:
    # sampling params are COMPILE KEYS (engine.py): quantize the floats so
    # clients sending temperature=0.70000001 vs 0.7 share one compiled
    # program; the engine's max_sampling_configs bounds the rest
    top_k = body.get("top_k")
    top_p = body.get("top_p")
    return SamplingParams(
        temperature=round(float(body.get("temperature", 1.0)), 2),
        top_k=None if top_k is None else int(top_k),
        top_p=None if top_p is None else round(float(top_p), 2),
        greedy=bool(body.get("greedy", False)),
    )


class _Handler(BaseHTTPRequestHandler):
    server_version = "lstm-tsp-serve/1.0"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # keep serving logs structured
        pass

    @property
    def _serve(self) -> ServeServer:
        return self.server.serve  # type: ignore[attr-defined]

    def _reply(self, code: int, payload: dict) -> None:
        data = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self) -> None:
        if self.path == "/healthz":
            health = self._serve.health()
            self._reply(200 if health["ok"] else 503, health)
        elif self.path in ("/stats", "/v1/stats"):
            # one payload, two routes: per-key compile counts, prefix-cache
            # hit/miss/evict/invalidate counters, state-cache swap
            # generation, batcher chunk/window counters + registry
            # histogram summaries (p50/p99)
            self._reply(200, self._serve.stats())
        elif self.path == "/metrics":
            # Prometheus text exposition (server-side TTFT/ITL/queue-wait
            # histograms as cumulative buckets; see docs/OPERATIONS.md for
            # the scrape config and runbook)
            data = self._serve.metrics_text().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4; charset=utf-8")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)
        else:
            self._reply(404, {"error": f"no route {self.path}"})

    def do_POST(self) -> None:
        if self.path != "/v1/generate":
            self._reply(404, {"error": f"no route {self.path}"})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(length) or b"{}")
            prompt = body["prompt"]
            max_new = int(body.get("max_new_tokens", 16))
            sampling = _sampling_from_body(body)
            timeout = float(body.get("timeout", 120.0))
        except (KeyError, ValueError, TypeError, json.JSONDecodeError) as e:
            # TypeError included: {"max_new_tokens": null} etc. must be a
            # 400, not a handler crash that resets the connection
            self._reply(400, {"error": f"bad request: {e}"})
            return
        t0 = time.perf_counter()
        try:
            req = self._serve.generate(
                prompt, max_new_tokens=max_new, sampling=sampling,
                session_id=body.get("session_id"),
                keep_session=bool(body.get("keep_session", False)),
                eos_id=body.get("eos_id"),
                use_prefix=bool(body.get("use_prefix", True)),
                timeout=timeout,
            )
        except QueueFullError as e:
            self._reply(429, {"error": str(e)})
            return
        except (ValueError, TypeError, RuntimeError) as e:
            # TypeError: a null/wrong-typed prompt surfaces from
            # np.asarray inside Request — still the client's fault
            code = 500 if isinstance(e, RuntimeError) else 400
            self._reply(code, {"error": f"{type(e).__name__}: {e}"})
            return
        except TimeoutError as e:
            self._reply(504, {"error": str(e)})
            return
        gaps = req.itl_gaps()
        self._reply(200, {
            "tokens": list(req.tokens),
            "session_id": req.session_id,
            "latency_ms": round((time.perf_counter() - t0) * 1e3, 3),
            "ttft_ms": round((req.t_first_token - req.t_submit) * 1e3, 3)
            if req.t_first_token and req.t_submit else None,
            "max_itl_ms": round(max(gaps) * 1e3, 3) if gaps else None,
            # per-request phase breakdown (queue/prefill/decode/readback
            # host time) — the trace timeline, summarised into the reply
            "phases_ms": req.phase_summary_ms(),
        })


def make_http_server(serve: ServeServer, host: str = "127.0.0.1",
                     port: int = 0) -> ThreadingHTTPServer:
    """Bind the JSON endpoint (port 0 → ephemeral; see
    ``httpd.server_address``). Caller drives ``serve_forever`` (typically
    on a thread) and pairs it with ``serve.start()``/``serve.stop()``."""
    httpd = ThreadingHTTPServer((host, port), _Handler)
    httpd.serve = serve  # type: ignore[attr-defined]
    return httpd
