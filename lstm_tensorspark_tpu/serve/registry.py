"""Model registry: sha256-verified artifacts behind the serve fleet.

The registry is the hand-off point between training and serving
(ROADMAP item 3): ``supervise`` publishes each new best checkpoint
here, and the rollout controller (serve/rollout.py) pulls versions out
to roll across replicas without a restart. It deliberately reuses the
PR 2/8 durability core (train/checkpoint.py ``atomic_write`` /
``read_verified``) instead of inventing a second torn-write story:
every artifact is fsync'd, renamed into place, and carries a
``.sha256`` sidecar that is checked on every read.

Layout — one flat directory, three files per artifact:

- ``<model>__v<version>.msgpack``            payload bytes
- ``<model>__v<version>.msgpack.sha256``     integrity sidecar
- ``<model>__v<version>.json``               metadata record

The metadata record holds {model, version, kind, config_hash, parent,
sha256, payload_bytes}: enough for an operator (or the version-skew
runbook row) to answer "what is v7 and where did it come from" without
deserializing the payload. ``parent`` names the checkpoint artifact the
weights were promoted from (e.g. ``best.msgpack @ step 1200``).

The in-memory manifest is an INDEX, not a source of truth: it is
rebuilt from the directory on every :meth:`scan`, so a registry shared
by a publishing supervisor and a serving fleet (or two fleets) needs no
coordination beyond the filesystem's atomic rename. A payload that
fails its checksum — truncation, bit rot, a torn copy — is QUARANTINED
(all three files renamed ``*.quarantined``, kept for forensics) and
drops out of the manifest: a corrupt artifact can be diagnosed but
never served.

Payload kinds:

- ``"params"``     ``flax.serialization.to_bytes(params)`` — decoded
  against the engine's params template via ``from_bytes``.
- ``"best_state"`` the raw ``best.msgpack`` artifact a train run's
  Checkpointer wrote (msgpack dict with ``step``/``value``/``state``) —
  ``supervise`` publishes these bytes VERBATIM, so promotion never
  deserializes multi-MB weights in the supervisor process; the serve
  side extracts ``state["params"]`` against its template on load.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import re
import threading

from flax import serialization

from ..train.checkpoint import (
    CorruptCheckpointError,
    atomic_write,
    read_verified,
)


class RegistryError(RuntimeError):
    """Lookup failure: unknown model id / version, or an artifact that
    was quarantined out from under the request."""


# one naming authority for artifact files; version is zero-padded so a
# plain directory listing sorts in version order for operators
_ARTIFACT_PAT = re.compile(r"^(?P<model>[A-Za-z0-9._\-]+)__v(?P<ver>\d+)"
                           r"\.msgpack$")


def artifact_name(model_id: str, version: int) -> str:
    return f"{model_id}__v{version:06d}.msgpack"


def config_fingerprint(cfg) -> str:
    """Stable short hash of a model config (dataclass or mapping) —
    stored with every artifact so a rollout can refuse weights whose
    architecture does not match the engine's resident config (the
    "version skew" runbook row's third signature)."""
    if dataclasses.is_dataclass(cfg) and not isinstance(cfg, type):
        payload = dataclasses.asdict(cfg)
    elif isinstance(cfg, dict):
        payload = cfg
    else:
        payload = {"repr": repr(cfg)}
    blob = json.dumps(payload, sort_keys=True, default=repr)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


class ModelRegistry:
    """sha256-verified model artifact store (module docstring).

    Thread-safe: ``publish``/``scan``/``load`` may be called from the
    supervisor loop, the rollout controller's thread and HTTP handlers
    concurrently — the lock only guards the manifest index; payload IO
    runs outside it (the filesystem rename is the real arbiter)."""

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        self._manifest: dict[str, dict[int, dict]] = {}
        self.quarantined = 0  # artifacts set aside across this process
        self.scan()

    # ---- publishing -----------------------------------------------------

    def publish(self, model_id: str, payload: bytes, *,
                version: int | None = None, kind: str = "params",
                config_hash: str | None = None,
                parent: str | None = None) -> dict:
        """Write one artifact atomically and index it. ``version=None``
        allocates the next version for the model (max + 1, starting at
        1). Returns the metadata record. The payload lands with its
        sidecar BEFORE the metadata record: a crash between the two
        leaves an unindexed-but-valid payload the next scan adopts
        (metadata reconstructed minimally), never a record pointing at
        missing bytes."""
        if not model_id or "__v" in model_id or "/" in model_id:
            raise ValueError(
                f"invalid model id {model_id!r} (must be non-empty, no "
                "'__v' or '/')")
        if kind not in ("params", "best_state"):
            raise ValueError(f"unknown artifact kind {kind!r}")
        with self._lock:
            if version is None:
                have = self._manifest.get(model_id, {})
                version = max(have, default=0) + 1
            version = int(version)
            if version < 1:
                raise ValueError(f"version must be >= 1, got {version}")
            if version in self._manifest.get(model_id, {}):
                raise ValueError(
                    f"{model_id} v{version} already published — versions "
                    "are immutable, publish a new one")
        name = artifact_name(model_id, version)
        path = os.path.join(self.directory, name)
        atomic_write(path, payload, checksum=True)
        meta = {
            "model": model_id,
            "version": version,
            "kind": kind,
            "config_hash": config_hash,
            "parent": parent,
            "sha256": hashlib.sha256(payload).hexdigest(),
            "payload_bytes": len(payload),
        }
        atomic_write(self._meta_path(path),
                     json.dumps(meta, sort_keys=True).encode())
        with self._lock:
            self._manifest.setdefault(model_id, {})[version] = meta
        return dict(meta)

    # ---- index ----------------------------------------------------------

    def scan(self) -> dict[str, list[int]]:
        """Rebuild the manifest from the directory (the only source of
        truth — a peer process may have published or quarantined since
        the last scan). Verifies every payload against its sidecar and
        quarantines failures HERE, at index time, so a corrupt artifact
        is out of the manifest before anything can pick it. Returns
        {model_id: sorted versions}."""
        manifest: dict[str, dict[int, dict]] = {}
        quarantined = 0
        for fname in sorted(os.listdir(self.directory)):
            m = _ARTIFACT_PAT.match(fname)
            if m is None:
                continue
            path = os.path.join(self.directory, fname)
            try:
                payload = read_verified(path)
            except (CorruptCheckpointError, OSError) as e:
                print(f"registry: QUARANTINING {fname}: {e}", flush=True)
                self._quarantine(path)
                quarantined += 1
                continue
            meta = self._read_meta(path, m, payload)
            manifest.setdefault(meta["model"], {})[meta["version"]] = meta
        with self._lock:
            self._manifest = manifest
            self.quarantined += quarantined
            return {mid: sorted(v) for mid, v in manifest.items()}

    def _read_meta(self, path: str, m: re.Match, payload: bytes) -> dict:
        try:
            with open(self._meta_path(path)) as f:
                return json.loads(f.read())
        except (OSError, ValueError):
            # publish crashed between payload and record (or the record
            # was lost): the payload is verified-good, so adopt it with
            # a reconstructed minimal record instead of stranding it
            return {
                "model": m.group("model"),
                "version": int(m.group("ver")),
                "kind": "params",
                "config_hash": None,
                "parent": None,
                "sha256": hashlib.sha256(payload).hexdigest(),
                "payload_bytes": len(payload),
            }

    def _meta_path(self, payload_path: str) -> str:
        return payload_path[:-len(".msgpack")] + ".json"

    def _quarantine(self, path: str) -> None:
        for p in (path, path + ".sha256", self._meta_path(path)):
            try:
                os.replace(p, p + ".quarantined")
            except OSError:
                pass  # best effort; the next scan retries what remains

    def models(self) -> dict[str, list[int]]:
        with self._lock:
            return {mid: sorted(vers)
                    for mid, vers in self._manifest.items()}

    def latest(self, model_id: str) -> dict | None:
        """Newest version's metadata record, or None."""
        with self._lock:
            vers = self._manifest.get(model_id)
            if not vers:
                return None
            return dict(vers[max(vers)])

    def meta(self, model_id: str, version: int | None = None) -> dict:
        with self._lock:
            vers = self._manifest.get(model_id)
            if not vers:
                raise RegistryError(
                    f"unknown model {model_id!r} (registry has "
                    f"{sorted(self._manifest) or 'no models'})")
            if version is None:
                version = max(vers)
            if version not in vers:
                raise RegistryError(
                    f"{model_id} has no version {version} "
                    f"(have {sorted(vers)})")
            return dict(vers[version])

    # ---- loading --------------------------------------------------------

    def load_bytes(self, model_id: str,
                   version: int | None = None) -> tuple[dict, bytes]:
        """Verified payload bytes + metadata. A checksum failure at THIS
        point (corruption after the indexing scan) quarantines the
        artifact, drops it from the manifest and raises
        :class:`RegistryError` — a corrupt artifact is never served."""
        meta = self.meta(model_id, version)
        path = os.path.join(self.directory,
                            artifact_name(meta["model"], meta["version"]))
        try:
            payload = read_verified(path)
        except (CorruptCheckpointError, OSError) as e:
            print(f"registry: QUARANTINING {os.path.basename(path)} at "
                  f"load: {e}", flush=True)
            self._quarantine(path)
            with self._lock:
                vers = self._manifest.get(meta["model"], {})
                vers.pop(meta["version"], None)
                if not vers:  # no versions left — drop the model entirely
                    self._manifest.pop(meta["model"], None)
                self.quarantined += 1
            raise RegistryError(
                f"{meta['model']} v{meta['version']} failed verification "
                f"and was quarantined: {e}") from e
        return meta, payload

    def load_params(self, model_id: str, template,
                    version: int | None = None) -> tuple[dict, object]:
        """Decode an artifact into a params pytree shaped like
        ``template`` (the serving engine's resident params — host copies
        are fine; the engine re-places on device at swap). Dispatch on
        the record's ``kind``; see the module docstring."""
        meta, payload = self.load_bytes(model_id, version)
        if meta.get("kind") == "best_state":
            best = serialization.msgpack_restore(payload)
            state_sd = serialization.msgpack_restore(best["state"])
            params = serialization.from_state_dict(
                template, state_sd["params"])
        else:
            params = serialization.from_bytes(template, payload)
        return meta, params

    # ---- views ----------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "directory": self.directory,
                "models": {mid: sorted(vers)
                           for mid, vers in self._manifest.items()},
                "artifacts": sum(len(v)
                                 for v in self._manifest.values()),
                "quarantined": self.quarantined,
            }
