"""Stacked-direction bi-LSTM kernel (ops/pallas_bilstm.py): interpret-mode
parity on CPU against the two-call reference (`lstm_scan` forward +
reverse), gradients through the custom VJP, masked variable-length
batches, lane padding, and the `bidir_lstm_scan` dispatch gate."""

import jax
import jax.numpy as jnp
import numpy as np

from lstm_tensorspark_tpu.ops import init_lstm_params, lstm_scan
from lstm_tensorspark_tpu.ops.pallas_bilstm import (
    bilstm_supported, pallas_bilstm_scan,
)
from lstm_tensorspark_tpu.ops.scan import bidir_lstm_scan

B, T, D, H = 8, 10, 16, 128


def _setup(h=H, d=D):
    pf = init_lstm_params(jax.random.PRNGKey(0), d, h)
    pb = init_lstm_params(jax.random.PRNGKey(1), d, h)
    xs = jax.random.normal(jax.random.PRNGKey(2), (B, T, d))
    return pf, pb, xs


def _reference(pf, pb, xs, mask=None):
    out_f = lstm_scan(pf, xs, mask=mask)
    out_b = lstm_scan(pb, xs, mask=mask, reverse=True)
    return out_f, out_b


def _assert_pair_close(got, want, **kw):
    (gc, gys), (wc, wys) = got[0], want[0]
    np.testing.assert_allclose(gys, wys, **kw)
    np.testing.assert_allclose(gc[0], wc[0], **kw)
    np.testing.assert_allclose(gc[1], wc[1], **kw)
    (gc, gys), (wc, wys) = got[1], want[1]
    np.testing.assert_allclose(gys, wys, **kw)
    np.testing.assert_allclose(gc[0], wc[0], **kw)
    np.testing.assert_allclose(gc[1], wc[1], **kw)


def test_forward_parity():
    pf, pb, xs = _setup()
    got = pallas_bilstm_scan(pf, pb, xs, interpret=True)
    want = _reference(pf, pb, xs)
    _assert_pair_close(got, want, rtol=1e-5, atol=1e-5)


def test_masked_parity():
    """Right-padded variable lengths: the reverse rows walk padding first
    with a frozen zero carry — final states must equal the two-call
    reference's reversed masked scan."""
    pf, pb, xs = _setup()
    lengths = jnp.array([10, 7, 3, 1, 10, 5, 8, 2])
    mask = jnp.arange(T)[None, :] < lengths[:, None]
    got = pallas_bilstm_scan(pf, pb, xs, mask=mask, interpret=True)
    want = _reference(pf, pb, xs, mask=mask)
    _assert_pair_close(got, want, rtol=1e-5, atol=1e-5)


def test_grad_parity():
    """All cotangent paths at once — ys of both directions, final carries
    of both directions — through the stacked custom VJP vs the reference
    BPTT, for BOTH directions' params and xs."""
    pf, pb, xs = _setup()
    lengths = jnp.array([10, 7, 3, 1, 10, 5, 8, 2])
    mask = jnp.arange(T)[None, :] < lengths[:, None]

    def loss(run):
        def f(pf, pb, xs):
            ((hf, cf), ysf), ((hb, cb), ysb) = run(pf, pb, xs)
            return (jnp.mean(ysf ** 2) + 2.0 * jnp.mean(ysb ** 2)
                    + jnp.mean(hf * 0.5) + jnp.mean(cf ** 2)
                    + jnp.mean(hb ** 2) + jnp.mean(cb * 0.25))
        return f

    run_p = lambda pf, pb, xs: pallas_bilstm_scan(  # noqa: E731
        pf, pb, xs, mask=mask, interpret=True)
    run_r = lambda pf, pb, xs: _reference(pf, pb, xs, mask=mask)  # noqa: E731
    g1 = jax.grad(loss(run_p), argnums=(0, 1, 2))(pf, pb, xs)
    g2 = jax.grad(loss(run_r), argnums=(0, 1, 2))(pf, pb, xs)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5),
        g1, g2,
    )


def test_unmasked_grad_parity():
    pf, pb, xs = _setup()

    def loss(run):
        def f(pf, pb, xs):
            ((hf, _), ysf), ((_, cb), ysb) = run(pf, pb, xs)
            return jnp.mean(ysf ** 2) + jnp.mean(ysb ** 2) + jnp.mean(hf + cb)
        return f

    run_p = lambda pf, pb, xs: pallas_bilstm_scan(  # noqa: E731
        pf, pb, xs, interpret=True)
    run_r = lambda pf, pb, xs: _reference(pf, pb, xs)  # noqa: E731
    g1 = jax.grad(loss(run_p), argnums=(0, 1, 2))(pf, pb, xs)
    g2 = jax.grad(loss(run_r), argnums=(0, 1, 2))(pf, pb, xs)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5),
        g1, g2,
    )


def test_lane_padded_hidden():
    """H=100 pads to 128 internally; outputs slice back exactly."""
    pf, pb, xs = _setup(h=100)
    got = pallas_bilstm_scan(pf, pb, xs, interpret=True)
    want = _reference(pf, pb, xs)
    _assert_pair_close(got, want, rtol=1e-5, atol=1e-5)


def test_bf16_compute_parity():
    """bf16 matmuls, f32 state — matches the reference scan at the same
    compute dtype to bf16-scale tolerance."""
    pf, pb, xs = _setup()
    got = pallas_bilstm_scan(pf, pb, xs, compute_dtype=jnp.bfloat16,
                             interpret=True)
    out_f = lstm_scan(pf, xs, compute_dtype=jnp.bfloat16)
    out_b = lstm_scan(pb, xs, compute_dtype=jnp.bfloat16, reverse=True)
    _assert_pair_close(got, (out_f, out_b), rtol=2e-2, atol=2e-2)


def test_supported_gating():
    # CPU: never (real kernel path only; interpret is explicit in tests)
    assert not bilstm_supported(64, 256, 256, 400, platform="cpu",
                                param_dtype_bytes=2, has_mask=True)
    # config 2's exact shape on TPU: supported
    assert bilstm_supported(64, 256, 256, 400, platform="tpu",
                            param_dtype_bytes=2, has_mask=True)
    # short sequences keep the single-direction hoisted-xproj kernels
    assert not bilstm_supported(64, 256, 256, 64, platform="tpu",
                                param_dtype_bytes=2, has_mask=True)
    # sublane misalignment
    assert not bilstm_supported(7, 256, 256, 400, platform="tpu",
                                param_dtype_bytes=2, has_mask=True)


def test_dispatch_falls_back_off_tpu():
    """On the CPU mesh `bidir_lstm_scan` must take the two-call fallback
    (bilstm_supported is platform-gated) and agree with the reference."""
    pf, pb, xs = _setup()
    got = bidir_lstm_scan(pf, pb, xs, use_pallas=True)
    want = _reference(pf, pb, xs)
    _assert_pair_close(got, want, rtol=1e-6, atol=1e-6)


def test_env_disable_lever(monkeypatch):
    """LSTM_TSP_NO_BIDIR_FUSE=1 must short-circuit the stacked path even
    where it would be supported (A/B lever). Exercised by making
    bilstm_supported explode if consulted."""
    import lstm_tensorspark_tpu.ops.scan as scan_mod

    monkeypatch.setenv("LSTM_TSP_NO_BIDIR_FUSE", "1")

    def boom(*a, **k):  # pragma: no cover - would fail the test if called
        raise AssertionError("stacked path consulted despite disable lever")

    monkeypatch.setattr(
        "lstm_tensorspark_tpu.ops.pallas_bilstm.bilstm_supported", boom)
    pf, pb, xs = _setup()
    got = scan_mod.bidir_lstm_scan(pf, pb, xs, use_pallas=True)
    want = _reference(pf, pb, xs)
    _assert_pair_close(got, want, rtol=1e-6, atol=1e-6)


def test_classifier_training_with_stacked_kernel_interpret(monkeypatch):
    """Full-model integration: classifier training (embed -> bi-layer ->
    concat -> head -> xent) with the stacked-direction kernel forced past
    the platform gate (interpret mode) must reproduce the plain-scan
    trajectory step for step."""
    import functools

    import lstm_tensorspark_tpu.ops.pallas_bilstm as bilstm_mod
    from lstm_tensorspark_tpu.models import (
        ClassifierConfig, classifier_loss, init_classifier,
    )
    from lstm_tensorspark_tpu.train import make_optimizer, make_train_step
    from lstm_tensorspark_tpu.train.loop import init_train_state

    V, Bc, Tc = 20, 8, 12
    rng = np.random.RandomState(0)
    batch = {
        "tokens": rng.randint(0, V, (Bc, Tc)).astype(np.int32),
        "lengths": rng.randint(3, Tc + 1, (Bc,)).astype(np.int32),
        "labels": rng.randint(0, 2, (Bc,)).astype(np.int32),
        "valid": np.ones((Bc,), np.float32),
    }

    def run(use_pallas):
        cfg = ClassifierConfig(vocab_size=V, hidden_size=16, num_layers=2,
                               use_pallas=use_pallas)
        params = init_classifier(jax.random.PRNGKey(3), cfg)
        opt = make_optimizer("adam", 1e-2)
        step = make_train_step(
            lambda p, b, r: classifier_loss(p, b, cfg), opt)
        state = init_train_state(params, opt, jax.random.PRNGKey(4))
        losses = []
        for _ in range(3):
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        return losses

    plain = run(False)
    monkeypatch.setattr(bilstm_mod, "bilstm_supported",
                        lambda *a, **k: True)
    monkeypatch.setattr(
        bilstm_mod, "pallas_bilstm_scan",
        functools.partial(bilstm_mod.pallas_bilstm_scan, interpret=True),
    )
    got = run(True)
    np.testing.assert_allclose(got, plain, rtol=1e-4, atol=1e-5)
