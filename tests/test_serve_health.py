"""Serving resilience: /healthz degrades honestly (503 on a dead scheduler
thread) and an injected serve-engine exception mid-decode fails only the
affected requests — the server keeps serving and keeps reporting healthy."""

import os
import json
import threading
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from lstm_tensorspark_tpu.models import LMConfig, init_lm
from lstm_tensorspark_tpu.resilience import faults
from lstm_tensorspark_tpu.serve import InprocessClient, ServeEngine, ServeServer
from lstm_tensorspark_tpu.serve.server import make_http_server

_CFG = LMConfig(vocab_size=29, hidden_size=16, num_layers=1)


@pytest.fixture(autouse=True)
def _clean_plane(monkeypatch):
    monkeypatch.delenv(faults.ENV_VAR, raising=False)
    faults.disarm()
    yield
    # explicit pop, not monkeypatch: the CLI EXPORTS the var mid-test
    # (--faults -> env for children) and delenv-on-absent records no undo
    os.environ.pop(faults.ENV_VAR, None)
    faults.disarm()


@pytest.fixture(scope="module")
def engine():
    """ONE engine for the whole file: the compiled prefill/decode programs
    are the expensive part and the fault hook is read at CALL time, so
    every test (armed or not) can share them."""
    params = init_lm(jax.random.PRNGKey(3), _CFG)
    return ServeEngine(params, _CFG, num_slots=4,
                       prefill_buckets=(4, 8), batch_buckets=(1, 2))


def _server(engine, **kw):
    return ServeServer(engine, max_active=2, queue_size=8, **kw)


def test_health_alive_and_heartbeat(engine):
    server = _server(engine)
    with server:
        client = InprocessClient(server)
        client.generate(np.array([1, 2, 3], np.int32), max_new_tokens=3)
        h = server.health()
        assert h["ok"] and h["batcher_alive"]
        assert h["seconds_since_last_iteration"] is not None
        assert h["seconds_since_last_iteration"] < 30.0
    # after stop(): the scheduler thread is gone — health must say so
    h = server.health()
    assert not h["ok"]


def test_health_not_ok_before_start(engine):
    assert _server(engine).health()["ok"] is False


def test_stale_heartbeat_flips_not_ok_while_thread_alive(engine):
    """The wedge case: the scheduler thread is stuck inside a dispatch that
    never returns — is_alive() stays true forever, so health must gate on
    heartbeat AGE too, or probes would smile at a wedged server."""
    import time

    server = _server(engine, health_stale_after=0.2)

    def wedged_run(stop_event, idle_wait=0.05):
        server.batcher.last_heartbeat = time.monotonic()
        stop_event.wait()  # "inside a device call that never returns"

    server.batcher.run = wedged_run  # type: ignore[method-assign]
    with server:
        time.sleep(0.5)
        h = server.health()
        assert h["batcher_alive"] is True   # thread alive...
        assert h["batcher_stale"] is True   # ...but silent too long
        assert h["ok"] is False             # → probe sees 503


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_healthz_returns_503_when_batcher_thread_dies(engine):
    """Kill the scheduler thread with an unexpected error: the HTTP probe
    must flip to 503 instead of smiling at a wedged server."""
    server = _server(engine)
    boom = RuntimeError("scheduler bug")
    server.batcher.step = lambda: (_ for _ in ()).throw(boom)  # type: ignore
    httpd = make_http_server(server, "127.0.0.1", 0)
    host, port = httpd.server_address[:2]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        with server:
            server._thread.join(timeout=10)  # run() dies on first step()
            assert not server._thread.is_alive()
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://{host}:{port}/healthz", timeout=10)
            assert ei.value.code == 503
            body = json.loads(ei.value.read())
            assert body["ok"] is False and body["batcher_alive"] is False
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_warmup_neither_consumes_nor_fires_serve_fault(engine):
    """warmup()'s dummy decodes must not advance the serve_error counter
    (or a loadgen drill dies at startup): the first REAL decode after
    warmup is still call 1 and fires."""
    faults.arm("serve_error@1")
    engine.warmup()  # would raise here without the bypass
    with pytest.raises(faults.InjectedFault):
        engine.decode([engine.cache.scratch_slot], [0])


def test_injected_decode_error_fails_only_that_request(engine):
    """serve_error@2: the second decode call of the plane raises inside
    the engine. The batcher retires+fails the affected session, releases
    its slot, and later requests (and the server's health) are unharmed."""
    faults.arm("serve_error@2")
    server = _server(engine)
    with server:
        client = InprocessClient(server)
        prompt = np.array([1, 2, 3], np.int32)
        with pytest.raises(RuntimeError) as ei:
            client.generate(prompt, max_new_tokens=6)
        assert "InjectedFault" in str(ei.value)
        # the engine healed: a fresh request decodes to completion
        toks = client.generate(prompt, max_new_tokens=6)
        assert len(toks) == 6
        h = server.health()
        assert h["ok"] and h["active"] == 0  # no leaked slots/sessions
        assert server.batcher.failed == 1 and server.batcher.completed == 1
