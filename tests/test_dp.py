"""Data-parallel backend tests on the virtual 8-device CPU mesh — the
`local[N]` equivalent (SURVEY.md §4): 1-device vs N-device loss parity at
equal global batch, the reference's synchronous grad-averaging semantics
(SURVEY.md §3.3)."""

import jax
import jax.numpy as jnp
import numpy as np

from lstm_tensorspark_tpu.models import LMConfig, init_lm, lm_loss
from lstm_tensorspark_tpu.parallel import (
    make_dp_eval_step,
    make_dp_train_step,
    make_mesh,
    shard_batch,
)
from lstm_tensorspark_tpu.parallel.data_parallel import replicate
from lstm_tensorspark_tpu.train import make_optimizer, make_train_step
from lstm_tensorspark_tpu.train.loop import init_train_state

V, H, B, T = 11, 16, 8, 12


def _setup():
    cfg = LMConfig(vocab_size=V, hidden_size=H)

    def loss_fn(params, batch, rng):
        return lm_loss(params, batch, cfg)

    opt = make_optimizer("sgd", 0.3)
    params = init_lm(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(0)
    batches = [
        {
            "inputs": rng.randint(0, V, (B, T)).astype(np.int32),
            "targets": rng.randint(0, V, (B, T)).astype(np.int32),
        }
        for _ in range(5)
    ]
    return cfg, loss_fn, opt, params, batches


def test_dp_matches_single_device():
    cfg, loss_fn, opt, params, batches = _setup()

    single = make_train_step(loss_fn, opt)
    s1 = init_train_state(params, opt, jax.random.PRNGKey(1))
    losses1 = []
    for b in batches:
        s1, m = single(s1, b)
        losses1.append(float(m["loss"]))

    mesh = make_mesh(dp=8)
    dp = make_dp_train_step(loss_fn, opt, mesh)
    s2 = init_train_state(params, opt, jax.random.PRNGKey(1))
    s2 = s2._replace(params=replicate(s2.params, mesh),
                     opt_state=replicate(s2.opt_state, mesh))
    losses2 = []
    for b in batches:
        s2, m = dp(s2, shard_batch(b, mesh))
        losses2.append(float(m["loss"]))

    np.testing.assert_allclose(losses1, losses2, rtol=1e-5, atol=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5),
        jax.device_get(s1.params),
        jax.device_get(s2.params),
    )


def test_dp_eval_matches_single():
    cfg, loss_fn, opt, params, batches = _setup()
    mesh = make_mesh(dp=8)
    ev = make_dp_eval_step(loss_fn, mesh)
    p = replicate(params, mesh)
    got = float(ev(p, shard_batch(batches[0], mesh))["loss"])
    want = float(loss_fn(params, batches[0], None)[0])
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_dp_smaller_mesh():
    """--num-partitions < device count: a 4-device data axis also works."""
    cfg, loss_fn, opt, params, batches = _setup()
    mesh = make_mesh(dp=4, devices=np.asarray(jax.devices()[:4]))
    dp = make_dp_train_step(loss_fn, opt, mesh)
    s = init_train_state(params, opt, jax.random.PRNGKey(1))
    s = s._replace(params=replicate(s.params, mesh),
                   opt_state=replicate(s.opt_state, mesh))
    s, m = dp(s, shard_batch(batches[0], mesh))
    assert np.isfinite(float(m["loss"]))


def test_stateful_dp_matches_single():
    """Stateful TBPTT: carries thread across windows identically on the
    single-chip and DP paths (carries sharded over the data axis)."""
    cfg = LMConfig(vocab_size=V, hidden_size=H)
    from lstm_tensorspark_tpu.models.lstm_lm import init_carries

    def loss_fn(params, batch, rng, carries):
        return lm_loss(params, batch, cfg, carries=carries)

    opt = make_optimizer("sgd", 0.3)
    params = init_lm(jax.random.PRNGKey(0), cfg)
    rng = np.random.RandomState(0)
    batches = [
        {
            "inputs": rng.randint(0, V, (B, T)).astype(np.int32),
            "targets": rng.randint(0, V, (B, T)).astype(np.int32),
        }
        for _ in range(4)
    ]

    single = make_train_step(loss_fn, opt, stateful=True)
    s1 = init_train_state(params, opt, jax.random.PRNGKey(1),
                          carries=init_carries(cfg, B))
    losses1 = []
    for b in batches:
        s1, m = single(s1, b)
        losses1.append(float(m["loss"]))
    # carries actually moved away from zero
    assert float(jnp.abs(s1.carries[0][0]).max()) > 0

    mesh = make_mesh(dp=8)
    dp = make_dp_train_step(loss_fn, opt, mesh, stateful=True)
    s2 = init_train_state(params, opt, jax.random.PRNGKey(1),
                          carries=init_carries(cfg, B))
    s2 = s2._replace(params=replicate(s2.params, mesh),
                     opt_state=replicate(s2.opt_state, mesh),
                     carries=shard_batch(s2.carries, mesh))
    losses2 = []
    for b in batches:
        s2, m = dp(s2, shard_batch(b, mesh))
        losses2.append(float(m["loss"]))
    np.testing.assert_allclose(losses1, losses2, rtol=1e-5, atol=1e-6)

    # stateful must differ from stateless after the first window
    def loss_fn_sl(params, batch, rng):
        return lm_loss(params, batch, cfg)
    stateless = make_train_step(loss_fn_sl, opt)
    s3 = init_train_state(params, opt, jax.random.PRNGKey(1))
    sl_losses = []
    for b in batches:
        s3, m = stateless(s3, b)
        sl_losses.append(float(m["loss"]))
    assert abs(sl_losses[1] - losses1[1]) > 1e-8
