"""Async checkpointing (train/checkpoint.py async_save): background writes
must produce byte-identical restorable checkpoints, serialize one-in-flight,
keep N, and surface writer errors at the next save()/wait()."""

import json
import os

import jax
import numpy as np
import pytest

from lstm_tensorspark_tpu.models import LMConfig, init_lm, lm_loss
from lstm_tensorspark_tpu.train import make_optimizer, make_train_step
from lstm_tensorspark_tpu.train.checkpoint import Checkpointer
from lstm_tensorspark_tpu.train.loop import init_train_state

V, H, B, T = 13, 16, 8, 12


def _setup():
    cfg = LMConfig(vocab_size=V, hidden_size=H, num_layers=1)

    def loss_fn(p, b, r):
        return lm_loss(p, b, cfg)

    opt = make_optimizer("adam", 1e-2)
    params = init_lm(jax.random.PRNGKey(0), cfg)
    state = init_train_state(params, opt, jax.random.PRNGKey(1))
    rng = np.random.RandomState(0)
    batch = {
        "inputs": rng.randint(0, V, (B, T)).astype(np.int32),
        "targets": rng.randint(0, V, (B, T)).astype(np.int32),
    }
    return loss_fn, opt, state, batch


def test_async_save_restores_identically(tmp_path):
    loss_fn, opt, state, batch = _setup()
    step = make_train_step(loss_fn, opt)
    state, _ = step(state, batch)

    sync_dir, async_dir = str(tmp_path / "s"), str(tmp_path / "a")
    Checkpointer(sync_dir).save(state)
    ca = Checkpointer(async_dir, async_save=True)
    ca.save(state)
    ca.wait()
    # byte-identical files → identical restores
    with open(os.path.join(sync_dir, "step_1.msgpack"), "rb") as f:
        want = f.read()
    with open(os.path.join(async_dir, "step_1.msgpack"), "rb") as f:
        got = f.read()
    assert want == got

    template = init_train_state(
        init_lm(jax.random.PRNGKey(9), LMConfig(vocab_size=V, hidden_size=H,
                                                num_layers=1)),
        opt, jax.random.PRNGKey(10),
    )
    restored = ca.restore_latest(template)
    assert int(restored.step) == 1
    for a, b in zip(jax.tree.leaves(restored.params),
                    jax.tree.leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a),
                                      np.asarray(jax.device_get(b)))


def test_async_snapshot_is_immune_to_later_updates(tmp_path):
    """The host snapshot happens at save() time: training steps taken while
    the write is in flight must NOT leak into the checkpoint."""
    loss_fn, opt, state, batch = _setup()
    step = make_train_step(loss_fn, opt)
    state, _ = step(state, batch)
    want = jax.device_get(state.params)

    ck = Checkpointer(str(tmp_path), async_save=True)
    ck.save(state)
    for _ in range(3):  # keep training immediately
        state, _ = step(state, batch)
    ck.wait()
    template = init_train_state(
        init_lm(jax.random.PRNGKey(9), LMConfig(vocab_size=V, hidden_size=H,
                                                num_layers=1)),
        opt, jax.random.PRNGKey(10),
    )
    restored = ck.restore_latest(template)
    assert int(restored.step) == 1
    for a, b in zip(jax.tree.leaves(restored.params), jax.tree.leaves(want)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_async_keep_n_and_one_in_flight(tmp_path):
    loss_fn, opt, state, batch = _setup()
    step = make_train_step(loss_fn, opt)
    ck = Checkpointer(str(tmp_path), keep=2, async_save=True)
    for _ in range(4):
        state, _ = step(state, batch)
        ck.save(state)  # each save waits for the previous write
    ck.wait()
    names = sorted(n for n in os.listdir(tmp_path) if n.endswith(".msgpack"))
    assert names == ["step_3.msgpack", "step_4.msgpack"]


def test_async_write_error_surfaces_on_next_save(tmp_path, monkeypatch):
    loss_fn, opt, state, batch = _setup()
    step = make_train_step(loss_fn, opt)
    state, _ = step(state, batch)
    ck = Checkpointer(str(tmp_path), async_save=True)

    def boom(host_state):
        raise OSError("disk full (synthetic)")

    monkeypatch.setattr(ck, "_save_single", boom)
    ck.save(state)
    with pytest.raises(OSError, match="disk full"):
        ck.wait()
    # the error is consumed: the checkpointer stays usable
    monkeypatch.undo()
    state, _ = step(state, batch)
    ck.save(state)
    ck.wait()
    assert ck.has_checkpoint()


def test_cli_async_checkpoint_resume(tmp_path):
    """CLI e2e: --async-checkpoint run, then a --resume run continues from
    the restored step."""
    from lstm_tensorspark_tpu.cli import main

    ckpt = str(tmp_path / "ck")
    jsonl = tmp_path / "m.jsonl"
    argv = [
        "--dataset", "ptb_char", "--hidden-units", "16", "--num-layers", "1",
        "--batch-size", "8", "--seq-len", "16", "--log-every", "2",
        "--backend", "single", "--checkpoint-dir", ckpt,
        "--checkpoint-every", "2", "--async-checkpoint",
    ]
    assert main(argv + ["--num-steps", "4"]) == 0
    assert main(argv + ["--num-steps", "8", "--resume",
                        "--jsonl", str(jsonl)]) == 0
    records = [json.loads(l) for l in open(jsonl)]
    notes = [r for r in records if "resumed at step" in str(r.get("note", ""))]
    # the LAST checkpoint (step 4) must be the resume point — a stale
    # restore (in-flight final write) would resume at step 2
    assert notes and "resumed at step 4" in notes[0]["note"], records


def test_save_best_and_restore_best(tmp_path):
    loss_fn, opt, state, batch = _setup()
    step = make_train_step(loss_fn, opt)
    state, _ = step(state, batch)
    ck = Checkpointer(str(tmp_path))
    ck.save_best(state, 3.14)
    meta = json.load(open(os.path.join(tmp_path, "best.json")))
    assert meta == {"step": 1, "value": 3.14}
    template = init_train_state(
        init_lm(jax.random.PRNGKey(9), LMConfig(vocab_size=V, hidden_size=H,
                                                num_layers=1)),
        opt, jax.random.PRNGKey(10),
    )
    restored = ck.restore_best(template)
    assert int(restored.step) == 1
    for a, b in zip(jax.tree.leaves(restored.params),
                    jax.tree.leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a),
                                      np.asarray(jax.device_get(b)))
    # best.msgpack lives OUTSIDE the keep-N rotation
    for _ in range(5):
        state, _ = step(state, batch)
        ck.save(state)
    assert os.path.exists(os.path.join(tmp_path, "best.msgpack"))


def test_cli_keep_best_tracks_best_eval(tmp_path):
    """--keep-best: best.json records the step whose eval metric is the
    minimum of all eval records in the run's own JSONL."""
    from lstm_tensorspark_tpu.cli import main

    ckpt = str(tmp_path / "ck")
    jsonl = tmp_path / "m.jsonl"
    rc = main([
        "--dataset", "ptb_char", "--hidden-units", "16", "--num-layers", "1",
        "--batch-size", "8", "--seq-len", "16", "--num-steps", "8",
        "--log-every", "2", "--eval-every", "2", "--backend", "single",
        "--checkpoint-dir", ckpt, "--checkpoint-every", "4",
        "--keep-best", "--jsonl", str(jsonl),
    ])
    assert rc == 0
    meta = json.load(open(os.path.join(ckpt, "best.json")))
    records = [json.loads(l) for l in open(jsonl)]
    evals = {r["step"]: r["eval_loss"] for r in records
             if "eval_loss" in r and r.get("note") is None}
    best_step = min(evals, key=evals.get)
    assert meta["step"] == best_step
    np.testing.assert_allclose(meta["value"], evals[best_step], rtol=1e-6)


def test_cli_keep_best_requires_dir_and_cadence():
    import pytest

    from lstm_tensorspark_tpu.cli import main

    with pytest.raises(SystemExit):
        main(["--dataset", "ptb_char", "--num-steps", "2", "--keep-best"])


def test_keep_best_survives_resume(tmp_path):
    """A resumed run whose evals are WORSE than the stored best must not
    overwrite best.msgpack (best-so-far is seeded from the saved best)."""
    from lstm_tensorspark_tpu.cli import main

    ckpt = str(tmp_path / "ck")
    argv = [
        "--dataset", "ptb_char", "--hidden-units", "16", "--num-layers", "1",
        "--batch-size", "8", "--seq-len", "16", "--log-every", "2",
        "--eval-every", "2", "--backend", "single",
        "--checkpoint-dir", ckpt, "--checkpoint-every", "2", "--keep-best",
    ]
    assert main(argv + ["--num-steps", "4", "--learning-rate", "1.0"]) == 0
    before = json.load(open(os.path.join(ckpt, "best.json")))
    # resume with a divergent learning rate: evals only get worse
    assert main(argv + ["--num-steps", "8", "--resume",
                        "--learning-rate", "50.0"]) == 0
    after = json.load(open(os.path.join(ckpt, "best.json")))
    assert after == before, (before, after)

    ck = Checkpointer(ckpt)
    assert ck.best_meta() == before


def test_cli_resume_best(tmp_path):
    """--resume-best restarts from best.msgpack's step, not the latest."""
    from lstm_tensorspark_tpu.cli import main

    ckpt = str(tmp_path / "ck")
    argv = [
        "--dataset", "ptb_char", "--hidden-units", "16", "--num-layers", "1",
        "--batch-size", "8", "--seq-len", "16", "--log-every", "2",
        "--eval-every", "2", "--backend", "single",
        "--checkpoint-dir", ckpt, "--checkpoint-every", "2", "--keep-best",
    ]
    # run 1: healthy to step 4, then a divergent continuation to step 8 —
    # best stays at an early step while the LATEST checkpoint is step 8
    assert main(argv + ["--num-steps", "4", "--learning-rate", "1.0"]) == 0
    assert main(argv + ["--num-steps", "8", "--resume",
                        "--learning-rate", "50.0"]) == 0
    best = json.load(open(os.path.join(ckpt, "best.json")))
    assert best["step"] < 8

    jsonl = tmp_path / "m.jsonl"
    rc = main(argv + ["--num-steps", str(best["step"] + 2), "--resume-best",
                      "--learning-rate", "0.1", "--jsonl", str(jsonl)])
    assert rc == 0
    records = [json.loads(l) for l in open(jsonl)]
    note = [r for r in records if "BEST" in str(r.get("note", ""))][0]
    assert f"step {best['step']}" in note["note"]


def test_best_tracking_ignores_nan():
    """A NaN eval must never become (and pin) the best."""
    from lstm_tensorspark_tpu.train.loop import train_loop

    saved = []
    evals = iter([float("nan"), 2.0, 1.5])

    def train_step(state, batch):
        return state, {"loss": 0.0, "grad_norm": 0.0}

    loss_fn, opt, state, batch = _setup()
    train_loop(
        state, train_step, iter([batch] * 3), num_steps=3, log_every=0,
        eval_fn=lambda p: {"eval_loss": next(evals)}, eval_every=1,
        best_fn=lambda s, v: saved.append(v),
    )
    assert saved == [2.0, 1.5]


def test_resume_best_fences_abandoned_lineage(tmp_path):
    """--resume-best deletes the abandoned lineage's newer checkpoints, so
    a later --resume continues the NEW lineage."""
    from lstm_tensorspark_tpu.cli import main

    ckpt = str(tmp_path / "ck")
    argv = [
        "--dataset", "ptb_char", "--hidden-units", "16", "--num-layers", "1",
        "--batch-size", "8", "--seq-len", "16", "--log-every", "2",
        "--eval-every", "2", "--backend", "single",
        "--checkpoint-dir", ckpt, "--checkpoint-every", "2", "--keep-best",
    ]
    assert main(argv + ["--num-steps", "4", "--learning-rate", "1.0"]) == 0
    assert main(argv + ["--num-steps", "8", "--resume",
                        "--learning-rate", "50.0"]) == 0
    best = json.load(open(os.path.join(ckpt, "best.json")))
    assert best["step"] < 8
    # rewind: fine-tune from best for 2 more steps
    assert main(argv + ["--num-steps", str(best["step"] + 2),
                        "--resume-best", "--learning-rate", "0.1"]) == 0
    steps = sorted(int(n.split("_")[1].split(".")[0])
                   for n in os.listdir(ckpt) if n.startswith("step_"))
    assert all(s <= best["step"] + 2 for s in steps), steps
    # a plain --resume now continues the fine-tune lineage, not step 8
    jsonl = tmp_path / "m.jsonl"
    assert main(argv + ["--num-steps", str(best["step"] + 4), "--resume",
                        "--learning-rate", "0.1",
                        "--jsonl", str(jsonl)]) == 0
    records = [json.loads(l) for l in open(jsonl)]
    note = [r for r in records if "resumed at step" in str(r.get("note", ""))]
    assert note and f"step {best['step'] + 2}" in note[0]["note"], note


def test_resume_best_requires_dir_and_best():
    import pytest

    from lstm_tensorspark_tpu.cli import main

    with pytest.raises(SystemExit):  # no --checkpoint-dir
        main(["--dataset", "ptb_char", "--num-steps", "2", "--resume-best"])


def test_resume_best_fails_fast_without_best(tmp_path):
    import pytest

    from lstm_tensorspark_tpu.cli import main

    with pytest.raises(SystemExit):  # dir exists but never had --keep-best
        main(["--dataset", "ptb_char", "--num-steps", "2", "--resume-best",
              "--checkpoint-dir", str(tmp_path)])


def test_best_artifact_kinds_never_shadow(tmp_path):
    """A stale single-process best.msgpack must not shadow a newer
    sharded best, and vice versa: each save deletes the other kind, and
    the crash-window arbitration picks the newer step (code-review r4).

    `_save_best_sharded` degenerates cleanly at process_count()==1 (the
    sync barriers no-op, pid 0 writes everything), standing in for the
    multi-process writer."""
    loss_fn, opt, state, batch = _setup()
    step = make_train_step(loss_fn, opt)
    state1, _ = step(state, batch)    # step 1
    state2, _ = step(state1, batch)   # step 2
    state3, _ = step(state2, batch)   # step 3

    ck = Checkpointer(str(tmp_path))
    # 1-process best at step 1, then a "multi-process" best at step 2:
    ck.save_best(state1, 3.0)
    assert os.path.exists(os.path.join(str(tmp_path), "best.msgpack"))
    ck._save_best_sharded(state2, 0.5)
    ck._best_meta_cache = None
    # the old best.msgpack is gone; meta and restore follow the shards
    assert not os.path.exists(os.path.join(str(tmp_path), "best.msgpack"))
    assert ck.best_meta() == {"step": 2, "value": 0.5}
    restored = ck.restore_best(jax.device_get(state2))
    np.testing.assert_array_equal(np.asarray(restored.step), 2)

    # and back: a newer single-process best removes the sharded set
    ck.save_best(state3, 0.25)
    ck._best_meta_cache = None
    assert ck.best_meta() == {"step": 3, "value": 0.25}
    left = [n for n in os.listdir(str(tmp_path))
            if n.startswith("best_") or n == "best.complete"]
    assert left == [], left

    # crash-window arbitration: both kinds on disk at once (a crash
    # between writing one and unlinking the other) -> newer step wins
    with open(os.path.join(str(tmp_path), "best.complete"), "w") as f:
        json.dump({"writers": 1, "step": 1, "value": 9.9}, f)
    ck._best_meta_cache = None
    assert ck._best_artifact()[0] == "single"   # single step 3 > sharded 1
    assert ck.best_meta() == {"step": 3, "value": 0.25}
    with open(os.path.join(str(tmp_path), "best.complete"), "w") as f:
        json.dump({"writers": 1, "step": 7, "value": 0.1}, f)
    ck._best_meta_cache = None
    assert ck._best_artifact()[0] == "sharded"  # sharded step 7 > single 3
    assert ck.best_meta() == {"step": 7, "value": 0.1}
