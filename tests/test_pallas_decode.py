"""Fused Pallas decode-window kernel (ops/pallas_decode.py +
serve/engine.py ``decode_kernel="pallas"``), CPU interpreter mode.

The contract under test:

- greedy AND temperature-sampled decode through the Pallas window is
  TOKEN-IDENTICAL to the `lax.scan` window and to `models/generate.py`,
  across batch buckets, the K ladder, EOS-in-window and budget-latch
  edges (off-TPU the kernel runs interpreted — same kernel body, same
  tokens; `tests_tpu/test_pallas_decode_tpu.py` is the compiled gate);
- the compile lattice stays bounded: ≤1 trace per
  ``("decode_window_pallas", bucket, K, sampling)``, covered by warmup;
- sampling configs the kernel cannot reproduce bit-exactly (top-k /
  top-p need an in-kernel sort) fall back to the scan window, counted;
- the window readback contract is kernel-independent: PAD_TOKEN rows,
  ``fetch_window``/``fetch_window_summary`` and the request phase
  timeline behave identically for both kernels (the regression pin for
  the readback/phase-timeline path).
"""

import threading

import jax
import numpy as np
import pytest

from lstm_tensorspark_tpu.models import LMConfig, init_lm, make_generate_fn
from lstm_tensorspark_tpu.ops import pallas_decode
from lstm_tensorspark_tpu.serve import (
    PAD_TOKEN,
    Batcher,
    Request,
    ServeEngine,
    ServeServer,
    InprocessClient,
)
from lstm_tensorspark_tpu.serve.engine import GREEDY, SamplingParams

_CFG = LMConfig(vocab_size=37, hidden_size=16, num_layers=2)


@pytest.fixture(scope="module")
def params():
    return init_lm(jax.random.PRNGKey(11), _CFG)


def _engine(params, kernel="pallas", **kw):
    kw.setdefault("num_slots", 8)
    kw.setdefault("prefill_buckets", (4, 8))
    kw.setdefault("batch_buckets", (1, 2, 4))
    return ServeEngine(params, _CFG, decode_kernel=kernel, **kw)


def _prompt(n, seed=0):
    return np.random.RandomState(seed).randint(0, 37, size=n).astype(np.int32)


def _window_stream(engine, prompt, sampling, *, budget, window, eos_id=None):
    """prefill + decode_window chain through the engine's public path;
    returns (tokens incl. the prefill token, last summary)."""
    sid = f"s{engine.decode_kernel}{np.random.randint(1 << 30)}"
    slot, _ = engine.cache.acquire(sid)
    first = engine.prefill([(slot, True, prompt)], sampling)
    out = [int(first[0])]
    remaining = budget
    last = int(first[0])
    summary = None
    while remaining > 0:
        win = engine.decode_window(
            [slot], [last], [remaining],
            eos_ids=None if eos_id is None else [eos_id],
            sampling=sampling, window=window)
        toks, rem, alive = engine.fetch_window_summary(win)
        summary = (rem.copy(), alive.copy())
        emitted = [int(t) for t in toks[0] if t != PAD_TOKEN]
        out.extend(emitted)
        remaining -= len(emitted)
        if not alive[0]:
            break
        last = out[-1]
    engine.cache.release(sid)
    return out, summary


# ---- engine resolution ---------------------------------------------------


def test_kernel_resolution_and_auto(params):
    assert _engine(params, "pallas").decode_kernel == "pallas"
    assert _engine(params, "scan").decode_kernel == "scan"
    # auto stays on scan off-TPU: interpreted pallas is a correctness
    # path, not a fast one
    auto = _engine(params, "auto")
    if jax.default_backend() != "tpu":
        assert auto.decode_kernel == "scan"
    with pytest.raises(ValueError):
        _engine(params, "mosaic")


# ---- token parity: pallas vs scan vs models/generate ---------------------


@pytest.mark.parametrize("window", [1, 4, 8])
def test_greedy_parity_across_k_ladder(params, window):
    ep = _engine(params)
    es = _engine(params, "scan")
    for seed, plen, budget in ((1, 3, 10), (2, 6, 13), (3, 8, 5)):
        p = _prompt(plen, seed)
        got_p, _ = _window_stream(ep, p, GREEDY, budget=budget,
                                  window=window)
        got_s, _ = _window_stream(es, p, GREEDY, budget=budget,
                                  window=window)
        gen = make_generate_fn(_CFG, max_new_tokens=budget + 1, greedy=True)
        ref = np.asarray(gen(params, p[None, :], jax.random.PRNGKey(0)))[
            0, p.size:]
        assert got_p == got_s == list(ref)
    # the pallas engine really compiled pallas window programs
    assert any(k[0] == "decode_window_pallas" for k in ep.compile_counts)
    assert not any(k[0] == "decode_window_pallas" for k in es.compile_counts)


def test_greedy_parity_across_batch_buckets(params):
    """Packed multi-row windows (bucket 2 and 4, with padding rows) —
    every row token-identical to the scan window."""
    for kernel in ("pallas", "scan"):
        e = _engine(params, kernel)
        slots = []
        prompts = [_prompt(3, 21), _prompt(5, 22), _prompt(4, 23)]
        for i, p in enumerate(prompts):
            slot, _ = e.cache.acquire(f"b{i}")
            slots.append(slot)
        first = e.prefill([(s, True, p) for s, p in zip(slots, prompts)])
        win = e.decode_window(slots, [int(t) for t in first],
                              [6] * 3, window=8)
        toks = e.fetch_window(win)
        if kernel == "pallas":
            got_pallas = toks.tolist()
        else:
            assert toks.tolist() == got_pallas


def test_sampled_parity_temperature(params):
    """Temperature sampling through the Pallas kernel is bit-identical
    to the scan window: same engine rng chain, same Gumbel draws, same
    argmax — token for token."""
    samp = SamplingParams(temperature=0.7)
    ep = _engine(params, rng_seed=9)
    es = _engine(params, "scan", rng_seed=9)
    p = _prompt(5, 31)
    got_p, _ = _window_stream(ep, p, samp, budget=12, window=4)
    got_s, _ = _window_stream(es, p, samp, budget=12, window=4)
    assert got_p == got_s
    assert len(got_p) == 13
    # a second stream continues both rng chains in lockstep
    got_p2, _ = _window_stream(ep, p, samp, budget=8, window=8)
    got_s2, _ = _window_stream(es, p, samp, budget=8, window=8)
    assert got_p2 == got_s2


# ---- EOS / budget latch edges --------------------------------------------


def test_eos_latch_inside_window(params):
    ep = _engine(params)
    p = _prompt(4, 6)
    probe, _ = _window_stream(ep, p, GREEDY, budget=12, window=8)
    stream = probe[1:]  # post-prefill continuation
    eos, first_idx = None, None
    for idx in range(1, 6):
        if stream[idx] not in stream[:idx]:
            eos, first_idx = stream[idx], idx
            break
    if eos is None:
        pytest.skip("greedy stream has no unique mid-window token")
    es = _engine(params, "scan")
    got_p, sum_p = _window_stream(ep, p, GREEDY, budget=12, window=8,
                                  eos_id=int(eos))
    got_s, sum_s = _window_stream(es, p, GREEDY, budget=12, window=8,
                                  eos_id=int(eos))
    assert got_p == got_s == probe[: first_idx + 2]
    # the on-device summary latched the row dead in both kernels
    assert not sum_p[1][0] and not sum_s[1][0]


@pytest.mark.parametrize("budget", [1, 3, 7, 8])
def test_budget_latch_edges(params, budget):
    """Budgets straddling the window size: the row latches dead ON
    DEVICE exactly at the budget, PAD after, summary remaining == 0."""
    ep = _engine(params)
    es = _engine(params, "scan")
    p = _prompt(5, 40)
    for e in (ep, es):
        slot, _ = e.cache.acquire("s")
        first = e.prefill([(slot, True, p)])
        win = e.decode_window([slot], [int(first[0])], [budget], window=8)
        toks, rem, alive = e.fetch_window_summary(win)
        row = [int(t) for t in toks[0]]
        assert all(t != PAD_TOKEN for t in row[:budget])
        assert all(t == PAD_TOKEN for t in row[budget:])
        assert rem[0] == 0 and not alive[0]
        e.cache.release("s")


def test_pipelined_followup_window_stays_frozen(params):
    """decode_window_next from an EOS-latched pallas window (dispatch-
    ahead, pre-fetch): the latched row stays frozen — all PAD."""
    e = _engine(params)
    slot, _ = e.cache.acquire("s")
    first = e.prefill([(slot, True, _prompt(3, 7))])
    probe = e.decode_window([slot], [int(first[0])], [8], window=8)
    stream = [int(t) for t in ServeEngine.fetch_window(probe)[0]]
    eos = stream[2]
    slot2, _ = e.cache.acquire("s2")
    f2 = e.prefill([(slot2, True, _prompt(3, 7))])
    win = e.decode_window([slot2], [int(f2[0])], [8], eos_ids=[eos],
                          window=8)
    nxt = e.decode_window_next(win)  # dispatch-ahead, pre-fetch
    first_idx = stream.index(eos)
    row = ServeEngine.fetch_window(win)[0]
    assert [int(t) for t in row[: first_idx + 1]] == stream[: first_idx + 1]
    assert all(int(t) == PAD_TOKEN for t in row[first_idx + 1:])
    assert all(int(t) == PAD_TOKEN for t in ServeEngine.fetch_window(nxt)[0])


# ---- warmup coverage + bounded lattice -----------------------------------


def test_warmup_covers_pallas_lattice_and_replay(params):
    e = _engine(params, batch_buckets=(1, 2))
    n = e.warmup(prompt_lens=(3,), windows=(1, 8))
    counts = dict(e.compile_counts)
    assert all(v == 1 for v in counts.values())
    pkeys = [k for k in counts if k[0] == "decode_window_pallas"]
    assert len(pkeys) == 2 * 2  # buckets x ladder — all pallas, no scan
    assert not any(k[0] == "decode_window" for k in counts)
    assert e.warmup(prompt_lens=(3,), windows=(1, 8)) == n
    assert dict(e.compile_counts) == counts


def test_server_end_to_end_pallas_matches_generate(params):
    """Full server path (batcher ladder, pipelining, readback) on the
    pallas kernel: concurrent sessions token-identical to generate()."""
    server = ServeServer(_engine(params), max_active=4, queue_size=16)
    prompts = [_prompt(2, 3), _prompt(7, 5)]
    n_new = 11
    got = [None] * len(prompts)
    with server:
        client = InprocessClient(server)

        def run_one(i):
            got[i] = client.generate(prompts[i], max_new_tokens=n_new)

        threads = [threading.Thread(target=run_one, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    gen = make_generate_fn(_CFG, max_new_tokens=n_new, greedy=True)
    for i, p in enumerate(prompts):
        ref = np.asarray(gen(params, p[None, :], jax.random.PRNGKey(0)))[
            0, p.size:]
        np.testing.assert_array_equal(np.asarray(got[i], np.int32), ref)
    assert any(k > 1 for k in server.batcher.windows_dispatched)
    assert any(k[0] == "decode_window_pallas"
               for k in server.engine.compile_counts)


# ---- unsupported-sampling fallback ---------------------------------------


def test_topk_topp_fall_back_to_scan_window(params):
    samp = SamplingParams(temperature=1.0, top_k=5)
    ep = _engine(params, rng_seed=4)
    es = _engine(params, "scan", rng_seed=4)
    p = _prompt(5, 50)
    got_p, _ = _window_stream(ep, p, samp, budget=8, window=4)
    got_s, _ = _window_stream(es, p, samp, budget=8, window=4)
    assert got_p == got_s  # the fallback IS the scan window
    assert ep.decode_window_scan_fallbacks > 0
    assert ep.stats()["decode_window_scan_fallbacks"] > 0
    assert not any(k[0] == "decode_window_pallas" for k in ep.compile_counts)
    assert not pallas_decode.sampling_supported(1.0, 5, None, False)
    assert not pallas_decode.sampling_supported(1.0, None, 0.9, False)
    assert pallas_decode.sampling_supported(0.5, None, None, False)


def test_vmem_plan_gate(params):
    """A shape whose working set cannot fit VMEM refuses the kernel (the
    engine would fall back); a tiny one fits."""
    assert pallas_decode.plan_fits(2, 8, 2, 16, 16, 37, sampled=True)
    assert not pallas_decode.plan_fits(16, 8, 2, 1024, 1024, 65536,
                                      sampled=True)


# ---- the window readback contract, pinned for BOTH kernels ---------------


@pytest.mark.parametrize("kernel", ["pallas", "scan"])
def test_window_readback_contract_both_kernels(params, kernel):
    """Regression pin (the fetch_window PAD_TOKEN round-trip): whatever
    kernel produced the window, (a) fetch_window returns PAD-padded rows
    that stop the host walk, (b) fetch_window_summary agrees with the
    PAD structure, and (c) the request phase timeline still records the
    decode_window + readback spans — the phase-timeline path must not
    care which kernel filled the handles."""
    e = _engine(params, kernel)
    server = ServeServer(e, max_active=2, queue_size=8)
    with server:
        client = InprocessClient(server)
        probe = client.generate(_prompt(4, 6), max_new_tokens=12)
        eos = None
        for idx in range(2, 7):
            if probe[idx] not in probe[:idx]:
                eos, first_idx = probe[idx], idx
                break
        if eos is None:
            pytest.skip("greedy stream has no unique mid-window token")
        req = server.generate(_prompt(4, 6), max_new_tokens=12,
                              eos_id=int(eos))
    # EOS stops the stream exactly where the eos-free stream first
    # emitted that token — the PAD tail never leaked into the output
    assert list(req.tokens) == probe[: first_idx + 1]
    assert PAD_TOKEN not in req.tokens
    phases = [name for name, _, _ in req.phases]
    assert "decode_window" in phases and "readback" in phases
    # engine-level: the raw window rows carry PAD after the latch and
    # the summary matches, for this kernel
    slot, _ = e.cache.acquire("pin")
    first = e.prefill([(slot, True, _prompt(4, 6))])
    win = e.decode_window([slot], [int(first[0])], [12],
                          eos_ids=[int(eos)], window=8)
    row = ServeEngine.fetch_window(win)[0]
    toks, rem, alive = e.fetch_window_summary(win)
    np.testing.assert_array_equal(row, toks[0])
    pad_idx = [i for i, t in enumerate(row) if t == PAD_TOKEN]
    if pad_idx:  # eos landed inside this window
        assert not alive[0]
        assert all(int(t) == PAD_TOKEN for t in row[pad_idx[0]:])
    e.cache.release("pin")
